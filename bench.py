"""Benchmark: flagship-model training throughput on the local chip(s).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "tokens/s/chip", "vs_baseline": N}

North star (BASELINE.json): framework throughput >= 90% of single-process
JAX on the same hardware. ``vs_baseline`` is therefore measured directly:
framework train step (ray_tpu.parallel.make_train_step — the same compiled
path the JaxTrainer drives) vs a plain hand-rolled jax.jit train step
written inline below with no framework imports in the loop. >= 0.9 meets
the target; ~1.0 means the framework adds no overhead over raw JAX.

Diagnostics (MFU, step times) go to stderr; stdout stays one JSON line.
"""
from __future__ import annotations

import functools
import json
import sys
import time


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def main():
    cpu_mode = "--cpu" in sys.argv
    # The end-to-end trainer bench must run FIRST: its worker process owns
    # the chip, so this process must not have initialized the TPU backend
    # yet (import jax alone is safe; device_count() is not).
    e2e_step_time = None
    if not cpu_mode and "--no-e2e" not in sys.argv:
        try:
            e2e_step_time = _bench_trainer_e2e(log)
        except Exception as e:  # noqa: BLE001 — e2e must not kill the bare metric
            log(f"trainer e2e bench failed: {e!r}")

    import jax

    if cpu_mode:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import optax

    from ray_tpu.models import transformer as tf
    from ray_tpu.parallel import MeshPlan, build_mesh, make_train_state, make_train_step
    from ray_tpu.parallel import mesh as mesh_lib
    from ray_tpu.parallel.train_step import make_optimizer

    n_dev = jax.device_count()
    platform = jax.devices()[0].platform
    log(f"devices: {n_dev} x {platform}")

    if cpu_mode:
        cfg = tf.TransformerConfig.tiny(dtype=jnp.float32)
        batch_size, seq, steps, warmup = 4, 64, 20, 3
    else:
        # ~750M-param model — the largest llama-shaped config that fits
        # one v5e chip's 16GB HBM with f32 master params + f32 Adam
        # moments (12 bytes/param states + f32 grads) and remat. The 7B
        # config is dryrun-compiled sharded by benchmarks/compile_7b.py.
        # Shape picked by benchmarks/tune_flash.py sweep: wide-shallow
        # (2304×10, head_dim 128) at batch 12 beats the round-2 1536×24
        # at batch 8 by ~16% tokens/s at equal params — bigger matmuls
        # feed the MXU better.
        cfg = tf.TransformerConfig(
            vocab_size=32000,
            d_model=2304,
            n_layers=10,
            n_heads=18,
            n_kv_heads=18,
            d_ff=5760,
            max_seq_len=2048,
            dtype=jnp.bfloat16,
            remat=True,
        )
        batch_size, seq, steps, warmup = 12, 2048, 8, 2

    plan = MeshPlan(dp=n_dev)
    mesh = build_mesh(plan)
    opt = make_optimizer(lr=3e-4, warmup=10)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch_size, seq + 1), 0, cfg.vocab_size)
    batch = {"tokens": jax.device_put(tokens, mesh_lib.batch_sharding(mesh, plan))}

    # ---- framework path -------------------------------------------------
    params, opt_state, _ = make_train_state(cfg, plan, mesh, opt)
    step = make_train_step(cfg, plan, mesh, opt)

    # ---- plain JAX baseline (no framework in the loop) ------------------
    def plain_loss(params, batch):
        return tf.loss_fn(params, batch, cfg)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def plain_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(plain_loss)(params, batch)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, {"loss": loss}

    # Same placement a plain-JAX user would pick on this mesh: replicated
    # params, batch-sharded data (single-device this is a no-op).
    from jax.sharding import NamedSharding, PartitionSpec as P

    rep = NamedSharding(mesh, P())

    def plain_state():
        p = jax.jit(lambda k: tf.init_params(k, cfg), out_shardings=rep)(jax.random.PRNGKey(0))
        return p, jax.jit(opt.init, out_shardings=rep)(p)

    if cpu_mode:
        # Interleaved medians: alternating measurement blocks cancel the
        # thermal/cache drift that biases whichever path is timed first on
        # CPU. Holds both states — fine at tiny scale.
        params2, opt_state2 = plain_state()
        fw_time, pj_time = _time_interleaved(
            [(step, params, opt_state), (plain_step, params2, opt_state2)],
            batch,
            steps,
            warmup,
            log,
            ("framework", "plain-jax"),
        )
    else:
        # On TPU both states at once would double HBM use; measure
        # sequentially and free each state in between (steps are long and
        # thermally stable there, so ordering bias is negligible).
        fw_time = _time_steps(step, params, opt_state, batch, steps, warmup, log, "framework")
        del params, opt_state
        params2, opt_state2 = plain_state()
        pj_time = _time_steps(plain_step, params2, opt_state2, batch, steps, warmup, log, "plain-jax")
        del params2, opt_state2

    tokens_per_step = batch_size * seq
    value = tokens_per_step / fw_time / n_dev
    vs_baseline = pj_time / fw_time  # >1 → framework faster than plain JAX

    # Peak per-device HBM at the end of the train measurement (telemetry
    # leg of the perf trajectory: memory regressions show up in BENCH_*
    # next to throughput). None on backends without memory_stats (CPU).
    from ray_tpu.core.node_telemetry import peak_device_hbm_gb

    train_peak_hbm = peak_device_hbm_gb()

    flops_tok = tf.flops_per_token(cfg, seq)
    peak = {"tpu": 197e12, "cpu": 1e12}.get(platform, 100e12)  # v5e bf16 peak
    mfu = (flops_tok * tokens_per_step / fw_time) / (peak * n_dev)
    log(f"step: framework {fw_time*1e3:.1f}ms, plain-jax {pj_time*1e3:.1f}ms")
    log(f"tokens/s/chip {value:.0f}  MFU~{mfu:.2%} (peak {peak/1e12:.0f}TF)")

    extra = {}
    if e2e_step_time is not None:
        e2e_value = tokens_per_step / e2e_step_time / n_dev
        extra["e2e_tokens_per_sec_per_chip"] = round(e2e_value, 1)
        # ≥0.97 target: the framework loop (init→PG→WorkerGroup→session)
        # must not tax the compiled step (reference e2e parity claim:
        # doc/source/train/benchmarks.rst:49-83)
        extra["e2e_vs_bare_step"] = round(fw_time / e2e_step_time, 4)
        log(
            f"e2e (JaxTrainer loop): {e2e_value:.0f} tokens/s/chip "
            f"({extra['e2e_vs_bare_step']:.4f}x bare step)"
        )
    if not cpu_mode:
        try:
            extra["decode_7b_bf16_tok_s"] = _bench_decode_7b(log)
        except Exception as e:  # noqa: BLE001 — decode bench must not kill the train metric
            log(f"7B decode bench failed: {e!r}")
        try:
            serve_res = _bench_serving_7b(log)
            extra["serve_7b_tok_s"] = serve_res
            if "prefix_hit_rate" in serve_res:
                extra["serve_prefix_hit_rate"] = serve_res["prefix_hit_rate"]
            b1 = extra.get("decode_7b_bf16_tok_s")
            if b1 and "c16" in serve_res:
                extra["serve_c16_vs_batch1"] = round(serve_res["c16"] / b1, 2)
        except Exception as e:  # noqa: BLE001 — serving bench must not kill the train metric
            log(f"7B serving bench failed: {e!r}")
    else:
        try:
            tiny_serve = _bench_serving_tiny_cpu(log, cfg)
            extra["serve_tiny_cpu"] = tiny_serve
            extra["serve_prefix_hit_rate"] = tiny_serve["prefix_hit_rate"]
        except Exception as e:  # noqa: BLE001 — smoke bench must not kill the metric
            log(f"cpu serve bench failed: {e!r}")
        try:
            extra["ingest_cpu"] = _bench_ingest_cpu(log)
            extra["profiling_overhead_pct"] = extra["ingest_cpu"][
                "profiling_overhead_pct"
            ]
        except Exception as e:  # noqa: BLE001 — ingest bench must not kill the metric
            log(f"cpu ingest bench failed: {e!r}")
        try:
            extra["rl_ppo_cpu"] = _bench_rl_ppo_cpu(log)
            extra["rl_ppo_env_steps_per_sec"] = extra["rl_ppo_cpu"][
                "podracer_env_steps_per_s"
            ]
        except Exception as e:  # noqa: BLE001 — RL bench must not kill the metric
            log(f"cpu rl ppo bench failed: {e!r}")

    record = {
        "metric": "train_tokens_per_sec_per_chip_750m_bf16" if not cpu_mode else "train_tokens_per_sec_per_chip_tiny_cpu",
        "value": round(value, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(vs_baseline, 4),
    }
    if train_peak_hbm is not None:
        record["train_peak_hbm_gb"] = train_peak_hbm
    record.update(extra)
    print(json.dumps(record))


def _bench_trainer_e2e(log):
    """The flagship config driven through the WHOLE framework on the real
    chip: ray_tpu.init → placement group → WorkerGroup → _TrainSession
    report (VERDICT r3 #4 — the reference's Train parity claim is
    end-to-end, doc/source/train/benchmarks.rst:49-83). Returns the
    measured per-step time from inside the training loop; the driver
    process never touches the chip (the train WORKER owns it)."""
    import ray_tpu
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    def train_fn(config):
        import time as _t

        import jax
        import jax.numpy as jnp

        from ray_tpu import train
        from ray_tpu.models import transformer as tf
        from ray_tpu.parallel import (
            MeshPlan,
            build_mesh,
            make_train_state,
            make_train_step,
        )
        from ray_tpu.parallel import mesh as mesh_lib
        from ray_tpu.parallel.train_step import make_optimizer

        cfg = tf.TransformerConfig(
            vocab_size=32000, d_model=2304, n_layers=10, n_heads=18,
            n_kv_heads=18, d_ff=5760, max_seq_len=2048,
            dtype=jnp.bfloat16, remat=True,
        )
        batch_size, seq, steps, warmup = 12, 2048, 8, 3
        plan = MeshPlan(dp=jax.device_count())
        mesh = build_mesh(plan)
        opt = make_optimizer(lr=3e-4, warmup=10)
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (batch_size, seq + 1), 0, cfg.vocab_size
        )
        batch = {"tokens": jax.device_put(tokens, mesh_lib.batch_sharding(mesh, plan))}
        params, opt_state, _ = make_train_state(cfg, plan, mesh, opt)
        step = make_train_step(cfg, plan, mesh, opt)
        # float() forces completion; block_until_ready is NOT a sync
        # point for the tunneled-TPU backend inside a worker thread
        # (measured: it returns in µs while float() waits the full step).
        # 3 warmups: the 3rd step still re-autotunes on this backend.
        for _ in range(warmup):
            params, opt_state, m = step(params, opt_state, batch)
            float(m["loss"])
        t0 = _t.perf_counter()
        for _ in range(steps):
            params, opt_state, m = step(params, opt_state, batch)
        float(m["loss"])
        dt = (_t.perf_counter() - t0) / steps
        train.report({"step_time_s": dt, "devices": jax.device_count()})

    ray_tpu.init(num_cpus=4, num_tpus=1)
    try:
        trainer = JaxTrainer(
            train_fn,
            scaling_config=ScalingConfig(num_workers=1, use_tpu=True),
            run_config=RunConfig(name="bench_e2e"),
        )
        result = trainer.fit()
        if result.error is not None:
            raise result.error
        dt = result.metrics["step_time_s"]
        log(f"e2e trainer step {dt*1e3:.1f}ms on {result.metrics['devices']} device(s)")
        return dt
    finally:
        ray_tpu.shutdown()


def _bench_decode_7b(log):
    """Largest-single-chip inference: Llama-2-7B bf16 (~13.5 GB weights)
    decoding on ONE v5e chip — the memory-bandwidth-bound regime
    (~13.5 GB of weights read per token; v5e HBM ~819 GB/s puts the roof
    near 60 tok/s at batch 1). The VERDICT's second measured metric."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import generate as gen
    from ray_tpu.models import transformer as tf

    cfg = tf.TransformerConfig.llama7b(
        max_seq_len=2048, dtype=jnp.bfloat16, remat=False
    )

    # bf16 init directly on device — a fp32 7B tree (27 GB) never exists
    @jax.jit
    def init_bf16(key):
        return jax.tree.map(
            lambda x: x.astype(jnp.bfloat16), tf.init_params(key, cfg)
        )

    params = init_bf16(jax.random.PRNGKey(0))
    jax.block_until_ready(jax.tree.leaves(params)[0])
    n_params = sum(x.size for x in jax.tree.leaves(params))
    log(f"7B decode: {n_params/1e9:.2f}B params bf16 on one chip")

    prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 128), 0, cfg.vocab_size)
    max_len = 128 + 96
    prefill_j = jax.jit(
        lambda p, t: gen.prefill(p, cfg, t, max_len=max_len)
    )
    decode_j = jax.jit(
        lambda p, t, c, pos: gen.decode_step(p, cfg, t, c, pos)
    )
    logits, cache = prefill_j(params, prompt)
    tok = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)  # [b]
    # warmup the decode program
    lg, cache = decode_j(params, tok, cache, jnp.int32(128))
    jax.block_until_ready(lg)
    steps = 64
    pos = 129
    t0 = time.perf_counter()
    for i in range(steps):
        tok = jnp.argmax(lg, axis=-1).astype(jnp.int32)
        lg, cache = decode_j(params, tok, cache, jnp.int32(pos + i))
    jax.block_until_ready(lg)
    dt = (time.perf_counter() - t0) / steps
    tok_s = 1.0 / dt
    log(f"7B decode: {tok_s:.1f} tok/s (batch 1, {dt*1e3:.1f} ms/token)")
    del params, cache
    return round(tok_s, 1)


def rng_prompt(cfg, n, _state=[0]):
    import numpy as np

    _state[0] += 1
    return np.random.default_rng(_state[0]).integers(0, cfg.vocab_size, n).tolist()


def _bench_serving_7b(log):
    """Continuous-batching 7B serving: aggregate tok/s at concurrency
    1/4/8/16 through the paged-KV engine (VERDICT r4 #1 — the reference
    serves via vLLM-on-Ray; this is the native replacement). Batch-1
    decode is HBM-bound reading ~13.5 GB of weights per token; batching
    shares that read across slots, so aggregate throughput should scale
    near-linearly until the KV-gather bandwidth bites."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models import transformer as tf
    from ray_tpu.models.paged import PagedConfig
    from ray_tpu.serve.llm_engine import LLMEngine

    cfg = tf.TransformerConfig.llama7b(max_seq_len=2048, dtype=jnp.bfloat16, remat=False)

    def init_bf16():
        return jax.tree.map(
            lambda x: x.astype(jnp.bfloat16),
            tf.init_params(jax.random.PRNGKey(0), cfg),
        )

    t0 = time.perf_counter()
    # KV pool sized to HBM: the decode program's working set is ~2x the
    # pool (in-place scan carry + one live intermediate at window seams)
    # on top of the 13.5 GB weights; 144 usable 8-token blocks (1152
    # cache tokens, ~0.6 GB) keeps the compiled program inside the 16 GB
    # chip, and the small block size keeps the per-step gather narrow
    # (W*bs = 72 positions/slot).
    pcfg = PagedConfig(block_size=8, num_blocks=145, max_batch=16, max_blocks_per_seq=9)
    # decode_window=10: one host sync per 10 tokens — the tunneled
    # chip's ~170 ms dispatch RTT would otherwise dominate (measured:
    # synced steps 136 ms vs 38 ms chained at batch 16). overlap=True
    # double-buffers the window (host consumes window N while the device
    # runs N+1) and dirty-slot shipping drops the 4 per-window h2d
    # uploads; prefix cache + bucket warmup serve the shared-prefix
    # scenario below. Params passed as an INIT CALLABLE: the engine
    # materializes the 13.5 GB weights directly in its decode program's
    # preferred layout (no relayout copy — see LLMEngine docstring).
    eng = LLMEngine(init_bf16, cfg, pcfg, decode_window=10, overlap=True,
                    enable_prefix_cache=True, warmup_buckets=True)
    log(
        f"7B serve: engine built, params in layout "
        f"({time.perf_counter()-t0:.0f}s, warmup "
        f"{eng.stats.get('warmup_s', 0):.1f}s x{eng.stats.get('warmup_compiles', 0)})"
    )
    t0 = time.perf_counter()
    eng.generate_batch([rng_prompt(cfg, 16)], 3)  # warm the serve loop
    log(f"7B serve: warmup/compile done ({time.perf_counter()-t0:.0f}s)")
    results = {}
    # 16+36+19 overlap overshoot (2*window-1) = 71 tokens -> 9 blocks per
    # slot; 16 slots = 144 blocks = the whole usable pool.
    gen_tokens = 36
    for c in (1, 4, 8, 16):
        prompts = [rng_prompt(cfg, 16) for _ in range(c)]
        t0 = time.perf_counter()
        outs = eng.generate_batch(prompts, gen_tokens)
        dt = time.perf_counter() - t0
        agg = sum(len(o) for o in outs) / dt
        results[f"c{c}"] = round(agg, 1)
        log(f"7B serve: concurrency {c}: {agg:.1f} tok/s aggregate ({dt:.2f}s)")
    results.update(_serve_prefix_scenario(eng, cfg, log, tag="7B serve"))
    from ray_tpu.core.node_telemetry import peak_device_hbm_gb

    peak = peak_device_hbm_gb()
    if peak is not None:
        results["peak_hbm_gb"] = peak
    log(f"7B serve engine stats: {eng.stats}")
    return results


def _serve_prefix_scenario(eng, cfg, log, *, tag, n_req=8, shared_len=32,
                           uniq_len=8, gen_tokens=12):
    """Shared-prefix serving: ``n_req`` requests sharing a ``shared_len``
    system prompt with distinct tails, submitted twice. The second (warm)
    pass must serve the shared blocks from the prefix cache — reported as
    hit-rate over the scenario plus cold/warm TTFT."""
    import statistics

    shared = rng_prompt(cfg, shared_len)
    prompts = [shared + rng_prompt(cfg, uniq_len) for _ in range(n_req)]
    h0 = eng.stats["prefix_hit_tokens"]
    l0 = eng.stats["prefix_lookup_tokens"]
    ttft = {}
    for phase in ("cold", "warm"):
        reqs = [eng.add_request(p, gen_tokens) for p in prompts]
        if eng._thread is None:
            while eng.active_count() or eng.waiting:
                eng.step()
        for r in reqs:
            list(r.tokens(timeout=300.0))
        samples = [(r.first_token_ts - r.submit_ts) * 1000.0 for r in reqs]
        # Only the first request of the first pass is guaranteed a full
        # cold prefill — later cold-pass admissions may already map
        # blocks an earlier request of the SAME pass registered (that
        # concurrent sharing is part of the feature, but it must not
        # masquerade as the cold baseline). Warm pass: median.
        ttft[phase] = samples[0] if phase == "cold" else statistics.median(samples)
    hit = eng.stats["prefix_hit_tokens"] - h0
    lookup = eng.stats["prefix_lookup_tokens"] - l0
    rate = hit / max(1, lookup)
    log(
        f"{tag}: shared-prefix hit rate {rate:.2f} ({hit}/{lookup} tokens, "
        f"incl. within-pass sharing), TTFT cold(first) {ttft['cold']:.1f} ms "
        f"-> warm p50 {ttft['warm']:.1f} ms"
    )
    return {
        "prefix_hit_rate": round(rate, 3),
        "prefix_ttft_cold_ms": round(ttft["cold"], 1),
        "prefix_ttft_warm_ms": round(ttft["warm"], 1),
    }


def _bench_serving_tiny_cpu(log, cfg):
    """CPU smoke of the serving perf suite (tiny model): engine with
    prefix cache + chunked prefill + overlap, shared-prefix hit rate and
    TTFT, plus a small aggregate-throughput number. Keeps `--cpu` runs
    emitting the same serve fields the TPU bench reports."""
    import jax

    from ray_tpu.models import transformer as tf
    from ray_tpu.models.paged import PagedConfig
    from ray_tpu.serve.llm_engine import LLMEngine

    params = tf.init_params(jax.random.PRNGKey(0), cfg)
    pcfg = PagedConfig(block_size=8, num_blocks=65, max_batch=8,
                       max_blocks_per_seq=12)
    eng = LLMEngine(params, cfg, pcfg, decode_window=4, overlap=True,
                    enable_prefix_cache=True, prefill_chunk=16,
                    warmup_buckets=True)
    res = {"warmup_s": eng.stats.get("warmup_s")}
    prompts = [rng_prompt(cfg, 16) for _ in range(8)]
    t0 = time.perf_counter()
    outs = eng.generate_batch(prompts, 24)
    dt = time.perf_counter() - t0
    res["c8_tok_s"] = round(sum(len(o) for o in outs) / dt, 1)
    log(f"tiny cpu serve: c8 {res['c8_tok_s']} tok/s aggregate")
    res.update(_serve_prefix_scenario(eng, cfg, log, tag="tiny cpu serve"))
    res["overlap_occupancy"] = round(
        eng.stats["spec_windows"] / max(1, eng.stats["steps"]), 3
    )
    from ray_tpu.core.node_telemetry import peak_device_hbm_gb

    peak = peak_device_hbm_gb()
    if peak is not None:  # CPU backends report no memory_stats
        res["peak_hbm_gb"] = peak
    log(f"tiny cpu serve engine stats: {eng.stats}")
    return res


def _bench_ingest_cpu(log):
    """Ingest-bound A/B for the pipelined data→device path (ISSUE 5):
    materialized columnar blocks → iter_jax_batches, consumed by a
    simulated device step sized to the measured host batch-prep cost —
    the regime where fetch/rebatch/H2D either serialize with the step
    (pipeline off) or hide behind it (pipeline on). Reports batches/s
    off vs on, the speedup, and the zero-copy hit count."""
    import numpy as np

    import ray_tpu
    from ray_tpu.data.metrics import data_metrics

    ray_tpu.init(num_cpus=4)
    try:
        # 24 blocks x ~2MB (8192 rows x 64 f32) — shm-tier, zero-copy eligible
        arr = np.arange(24 * 8192 * 64, dtype=np.float32).reshape(-1, 64)
        ds = ray_tpu.data.from_numpy({"x": arr}, parallelism=24).materialize()
        m = data_metrics()

        def run(prefetch_blocks, prefetch_to_device, step_s):
            it = ds.iterator().iter_jax_batches(
                batch_size=4096,
                dtypes={"x": np.float32},
                prefetch_blocks=prefetch_blocks,
                prefetch_to_device=prefetch_to_device,
            )
            n = 0
            t0 = time.perf_counter()
            for _ in it:
                if step_s:
                    time.sleep(step_s)
                n += 1
            return n / (time.perf_counter() - t0)

        hits0 = m.counts.get("zero_copy_hits", 0)
        run(0, 0, 0.0)  # warm: page-fault the mappings, first transfers
        base = run(0, 0, 0.0)  # calibrate host prep cost per batch
        step_s = 1.0 / base
        # Interleaved best-of-2 per arm (scheduler-noise control, same
        # practice as the CPU train A/B above): off/on alternate so load
        # drift biases neither arm.
        off = on = 0.0
        for _ in range(2):
            off = max(off, run(0, 0, step_s))
            on = max(on, run(2, 2, step_s))
        hits = m.counts.get("zero_copy_hits", 0) - hits0
        # Continuous-profiler overhead A/B (ISSUE 9): the same pipelined
        # ingest arm UNPACED (pure host throughput — no device-step sleep
        # to hide the sampler behind), interleaved with the incident-ring
        # sampler on at 19 Hz vs off. Budget: < 3%.
        from ray_tpu.util import profiling

        prof_off = prof_on = 0.0
        for _ in range(3):
            prof_off = max(prof_off, run(2, 2, 0.0))
            sampler = profiling.ContinuousSampler(hz=19.0).start()
            try:
                prof_on = max(prof_on, run(2, 2, 0.0))
            finally:
                sampler.stop()
        overhead_pct = round(max(0.0, (prof_off - prof_on) / prof_off) * 100.0, 2)
        res = {
            "batches_per_s_off": round(off, 1),
            "batches_per_s_on": round(on, 1),
            "pipeline_speedup": round(on / off, 2),
            "data_zero_copy_hits": hits,
            "profiling_overhead_pct": overhead_pct,
            "profiling_overhead_ok": overhead_pct < 3.0,
        }
        log(
            f"cpu ingest: {off:.1f} -> {on:.1f} batches/s "
            f"({res['pipeline_speedup']}x, step {step_s*1e3:.2f}ms, "
            f"zero-copy hits {hits})"
        )
        log(
            f"continuous-profiler overhead (19 Hz, unpaced ingest): "
            f"{prof_off:.1f} -> {prof_on:.1f} batches/s = {overhead_pct}% "
            f"({'OK' if overhead_pct < 3.0 else 'OVER'} vs 3% budget)"
        )
        return res
    finally:
        ray_tpu.shutdown()


def _bench_rl_ppo_cpu(log):
    """RLlib PPO CartPole env-steps/sec (the BASELINE.json north-star
    metric): synchronous driver loop vs the podracer async pipeline
    (ISSUE 8, ray_tpu.rllib.podracer), 4 CPU env-runner actors in both
    arms. Mid-run one podracer runner is KILLED to prove the bench
    completes through an actor restart (queue keeps flowing, restart
    recorded in the control-plane lifecycle events)."""
    import ray_tpu
    from ray_tpu.rllib import PPOConfig
    from ray_tpu.util import state

    def base():
        # kl_target high = KL early-stop off, so BOTH arms do the exact
        # same learner work per batch (a clean A/B: the podracer win is
        # sampling/update overlap, not a shorter epoch cycle).
        return (
            PPOConfig()
            .environment("CartPole-v1")
            .training(train_batch_size=2048, minibatch_size=256,
                      num_epochs=4, lr=1e-3, kl_target=10.0)
            .debugging(seed=0)
        )

    iters = 6
    ray_tpu.init(num_cpus=8)
    try:
        # -- arm 1: synchronous driver loop (sample -> update -> sync) ----
        cfg = base().env_runners(
            num_env_runners=4, num_envs_per_env_runner=2,
            rollout_fragment_length=256,
        )
        algo = cfg.build()
        algo.train()  # warmup: jit compiles on every runner + the learner
        t0 = time.perf_counter()
        steps = 0
        for _ in range(iters):
            r = algo.train()
            steps += r["env_steps_this_iter"]
        sync_rate = steps / (time.perf_counter() - t0)
        log(f"rl ppo: sync {sync_rate:.0f} env-steps/s "
            f"(return {r['episode_return_mean']:.1f})")
        algo.stop()

        # -- arm 2: podracer async pipeline -------------------------------
        cfg = base().env_runners(
            num_envs_per_env_runner=2, rollout_fragment_length=256
        ).podracer(num_async_runners=4, sample_queue_size=16)
        algo = cfg.build()
        algo.train()  # warmup
        t0 = time.perf_counter()
        steps = 0
        for i in range(iters):
            if i == iters // 2:
                # kill a runner mid-run: the bench must complete anyway
                ray_tpu.kill(algo._podracer.manager.actors[0])
                log("rl ppo: killed runner 0 mid-run")
            r = algo.train()
            steps += r["env_steps_this_iter"]
        pod_rate = steps / (time.perf_counter() - t0)
        # The measured window can end within the ~0.5s crash-detection
        # latency; give the pipeline a bounded beat to register the
        # restart so it is visible in the report and lifecycle events.
        deadline = time.time() + 15
        while algo._podracer.num_restarts == 0 and time.time() < deadline:
            algo._podracer.check_runners()
            time.sleep(0.25)
        restarts = algo._podracer.num_restarts
        death_events = sum(
            1 for e in state.list_lifecycle_events(limit=100000)
            if e.get("kind") == "actor" and e.get("state") in ("DEAD", "FAILED")
        )
        algo.stop()
        res = {
            "sync_env_steps_per_s": round(sync_rate, 1),
            "podracer_env_steps_per_s": round(pod_rate, 1),
            "podracer_speedup": round(pod_rate / sync_rate, 2),
            "runner_restarts": restarts,
            "lifecycle_runner_death_events": death_events,
            "num_runners": 4,
        }
        log(
            f"rl ppo: podracer {pod_rate:.0f} env-steps/s "
            f"({res['podracer_speedup']}x sync, {restarts} runner "
            f"restart(s) mid-run, {death_events} lifecycle death event(s))"
        )
        return res
    finally:
        ray_tpu.shutdown()


def _warmup(step, params, opt_state, batch, warmup, log, tag):
    import jax

    for i in range(warmup):
        t0 = time.perf_counter()
        params, opt_state, m = step(params, opt_state, batch)
        jax.block_until_ready(m["loss"])
        log(f"{tag} warmup[{i}] {time.perf_counter()-t0:.2f}s loss={float(m['loss']):.3f}")
    return params, opt_state


def _time_steps(step, params, opt_state, batch, steps, warmup, log, tag):
    import jax

    params, opt_state = _warmup(step, params, opt_state, batch, warmup, log, tag)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, m = step(params, opt_state, batch)
    jax.block_until_ready(m["loss"])
    dt = (time.perf_counter() - t0) / steps
    del params, opt_state
    return dt


def _time_interleaved(entries, batch, steps, warmup, log, tags, blocks: int = 4):
    """Median per-step time for each entry, measured in alternating blocks."""
    import statistics

    import jax

    states = []
    for (step, params, opt_state), tag in zip(entries, tags):
        params, opt_state = _warmup(step, params, opt_state, batch, warmup, log, tag)
        states.append((step, params, opt_state))
    samples = [[] for _ in entries]
    per_block = max(1, steps // blocks)
    for _ in range(blocks):
        for i, (step, params, opt_state) in enumerate(states):
            t0 = time.perf_counter()
            for _ in range(per_block):
                params, opt_state, m = step(params, opt_state, batch)
            jax.block_until_ready(m["loss"])
            samples[i].append((time.perf_counter() - t0) / per_block)
            states[i] = (step, params, opt_state)
    return [statistics.median(s) for s in samples]


if __name__ == "__main__":
    main()
