"""Podracer RL pipeline metrics.

Reference: the Podracer paper's Sebulba diagnostics (actor/learner queue
occupancy, policy staleness) mapped onto this repo's PR-1/PR-3 telemetry
pipeline: Counter/Gauge/Histogram instances recorded in ANY process
(sample-queue actor, env runners, the learner driver) flush to the
controller automatically and surface in Prometheus/Grafana (the "RL"
dashboard row) and ``state.summarize_rl()``.

``counts`` is a plain process-local mirror of the counters for tests and
bench.py: the metric registry drains *deltas* at flush time, so Metric
internals cannot be read back reliably from the recording process.
"""
from __future__ import annotations

import threading
from typing import Dict

_lock = threading.Lock()
_metrics = None

_MS_BOUNDARIES = (
    0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000, 15000,
)
# Policy lag is measured in weights VERSIONS (learner updates the runner's
# policy is behind); small integer-ish boundaries.
_LAG_BOUNDARIES = (0, 1, 2, 4, 8, 16, 32, 64)


class _RLMetrics:
    def __init__(self):
        from ray_tpu.util.metrics import Counter, Gauge, Histogram

        self.env_steps = Counter(
            "rl_env_steps_total",
            "Environment steps consumed by the learner (accepted fragments)",
        )
        self.fragments = Counter(
            "rl_fragments_total",
            "Trajectory fragments enqueued by env runners",
        )
        self.fragments_dropped = Counter(
            "rl_fragments_dropped_total",
            "Fragments dropped by the pipeline; reason is one of the "
            "bounded vocabulary {capacity, stale, lost}",
            ("reason",),
        )
        self.queue_depth = Gauge(
            "rl_queue_depth",
            "Fragments buffered in the sample queue between runners and "
            "the learner",
        )
        self.queue_wait_ms = Histogram(
            "rl_queue_wait_ms",
            "Time a fragment spent in the sample queue before the learner "
            "pulled it",
            _MS_BOUNDARIES,
        )
        self.policy_lag = Histogram(
            "rl_policy_lag",
            "Weights-version lag of fragments at learner pull time "
            "(current learner version minus the behaviour policy version)",
            _LAG_BOUNDARIES,
        )
        self.learner_step_ms = Histogram(
            "rl_learner_step_ms",
            "Wall time of one learner cycle: V-trace batch build + the "
            "jitted mesh update(s)",
            _MS_BOUNDARIES,
        )
        self.weights_published = Counter(
            "rl_weights_published_total",
            "Versioned weight broadcasts published by the learner",
        )
        self.runner_restarts = Counter(
            "rl_runner_restarts_total",
            "Env-runner actors restarted after a crash mid-stream",
        )
        # Process-local, non-draining counters (tests/bench read these).
        self.counts: Dict[str, float] = {}

    def bump(self, key: str, n: float = 1):
        with _lock:
            self.counts[key] = self.counts.get(key, 0) + n


def rl_metrics() -> _RLMetrics:
    global _metrics
    if _metrics is None:
        with _lock:
            if _metrics is None:
                _metrics = _RLMetrics()
    return _metrics
