"""Learner-side V-trace batch building with a batched, jitted recompute.

Replaces ``IMPALA._episodes_to_vtrace_batch``'s per-episode UNJITTED
module forwards on the driver: all episodes' (obs, actions) are
concatenated into one flat array, padded up to a bounded shape bucket
(powers of two — a handful of compiles total, never one per batch size),
and pushed through ONE jitted ``logp_entropy`` forward. The cheap
per-episode V-trace scans stay in numpy.

The produced batch carries fields for BOTH loss families so PPO and
IMPALA run on the same podracer pipeline:

- IMPALA loss:  ``pg_advantages``, ``vtrace_targets``
- PPO loss:     ``logp_old`` (behaviour), ``advantages`` (= pg_advantages,
                optionally normalized), ``returns`` (= vtrace targets),
                ``values_old`` (current-policy values)
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ray_tpu.rllib.episodes import SingleAgentEpisode

_MIN_BUCKET = 256


def _bucket_rows(n: int) -> int:
    """Next power-of-two bucket >= n (floored at _MIN_BUCKET): bounds the
    set of shapes the jitted forward ever sees."""
    b = _MIN_BUCKET
    while b < n:
        b <<= 1
    return b


class VtraceBatchBuilder:
    """One jitted forward per module, reused across every batch build."""

    def __init__(self, module):
        import jax

        self._module = module
        self._fwd = jax.jit(module.logp_entropy)

    def target_logps_values(self, params, obs: np.ndarray, actions: np.ndarray):
        """Batched target-policy recompute: logp(a|s) and V(s) under the
        CURRENT learner params for the whole concatenated batch."""
        import jax.numpy as jnp

        n = obs.shape[0]
        bucket = _bucket_rows(n)
        if bucket != n:
            pad = bucket - n
            obs = np.concatenate([obs, np.repeat(obs[-1:], pad, axis=0)])
            actions = np.concatenate([actions, np.repeat(actions[-1:], pad)])
        out = self._fwd(params, jnp.asarray(obs), jnp.asarray(actions))
        return (
            np.asarray(out["logp"], dtype=np.float32)[:n],
            np.asarray(out["vf"], dtype=np.float32)[:n],
        )

    def build(
        self,
        params,
        episodes: List[SingleAgentEpisode],
        gamma: float = 0.99,
        rho_bar: float = 1.0,
        c_bar: float = 1.0,
        normalize_advantages: bool = False,
    ) -> Optional[Dict[str, np.ndarray]]:
        """Episodes -> flat V-trace train batch (None when empty)."""
        from ray_tpu.rllib.impala import vtrace_returns

        episodes = [ep for ep in episodes if len(ep) > 0]
        if not episodes:
            return None
        lengths = [len(ep) for ep in episodes]
        obs = np.concatenate(
            [np.asarray(ep.observations[: len(ep)], dtype=np.float32) for ep in episodes]
        )
        actions = np.concatenate(
            [np.asarray(ep.actions, dtype=np.int32) for ep in episodes]
        )
        behaviour_logps = np.concatenate(
            [np.asarray(ep.logps, dtype=np.float32) for ep in episodes]
        )
        target_logps, values = self.target_logps_values(params, obs, actions)
        pg_l, vt_l = [], []
        lo = 0
        for ep, T in zip(episodes, lengths):
            hi = lo + T
            vs, pg_adv = vtrace_returns(
                behaviour_logps[lo:hi],
                target_logps[lo:hi],
                np.asarray(ep.rewards, dtype=np.float32),
                values[lo:hi],
                ep.final_value,
                ep.terminated,
                gamma=gamma,
                rho_bar=rho_bar,
                c_bar=c_bar,
            )
            pg_l.append(pg_adv)
            vt_l.append(vs)
            lo = hi
        pg_adv = np.concatenate(pg_l).astype(np.float32)
        vtrace_targets = np.concatenate(vt_l).astype(np.float32)
        advantages = pg_adv
        if normalize_advantages:
            advantages = (pg_adv - pg_adv.mean()) / (pg_adv.std() + 1e-8)
        return {
            "obs": obs,
            "actions": actions,
            # IMPALA fields
            "pg_advantages": pg_adv,
            "vtrace_targets": vtrace_targets,
            # PPO fields (APPO-style surrogate on V-trace targets)
            "logp_old": behaviour_logps,
            "advantages": advantages,
            "returns": vtrace_targets,
            "values_old": values,
        }
