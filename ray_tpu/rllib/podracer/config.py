"""PodracerConfig: knobs for the async actor–learner pipeline.

Built from an AlgorithmConfig (the fluent ``.podracer(...)`` section) by
``Algorithm``; both PPO and IMPALA construct their pipeline from this one
config object. ``num_async_runners=0`` means the podracer pipeline is off
and the algorithm uses the synchronous driver loop (the seed behaviour).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any


@dataclass
class PodracerConfig:
    env_spec: Any = None
    num_async_runners: int = 0
    num_envs_per_runner: int = 1
    rollout_fragment_length: int = 200
    seed: int = 0
    # Bounded sample queue between runners and the learner (fragments).
    sample_queue_size: int = 16
    # Staleness control: drop or V-trace-correct fragments whose behaviour
    # policy is > max_policy_lag weight versions behind the learner.
    max_policy_lag: int = 8
    policy_lag_mode: str = "correct"  # "correct" | "drop"
    # Publish a new weights version every N learner updates.
    weights_publish_interval: int = 1
    # Learner pull shape: up to max_pull fragments per queue poll, each
    # poll blocking at most poll_timeout_s.
    max_pull: int = 16
    poll_timeout_s: float = 2.0
    # Hard ceiling on one training_step's wait for env steps (runner
    # restarts happen within it; only a fully wedged fleet trips it).
    iteration_timeout_s: float = 300.0
    # Policy-lag cadence actuator (the driver-local health-plane leg,
    # see core/health.py): when observed lag exceeds max_policy_lag,
    # halve the effective publish interval (fresher weights reach the
    # runners); relax back toward the configured interval once lag
    # recovers. Each adaptation is an audited "action" lifecycle event.
    adaptive_cadence: bool = True
    cadence_cooldown_s: float = 10.0

    def validate(self) -> "PodracerConfig":
        if self.policy_lag_mode not in ("correct", "drop"):
            raise ValueError(
                "policy_lag_mode must be 'correct' or 'drop', got "
                f"{self.policy_lag_mode!r}"
            )
        if self.num_async_runners < 0:
            raise ValueError("num_async_runners must be >= 0")
        if self.sample_queue_size < 1:
            raise ValueError("sample_queue_size must be >= 1")
        return self

    @classmethod
    def from_algorithm_config(cls, c) -> "PodracerConfig":
        return cls(
            env_spec=c.env_spec,
            num_async_runners=c.num_async_runners,
            num_envs_per_runner=c.num_envs_per_runner,
            rollout_fragment_length=c.rollout_fragment_length,
            seed=c.seed,
            sample_queue_size=c.sample_queue_size,
            max_policy_lag=c.max_policy_lag,
            policy_lag_mode=c.policy_lag_mode,
            weights_publish_interval=c.weights_publish_interval,
            max_pull=c.podracer_max_pull,
            poll_timeout_s=c.podracer_poll_timeout_s,
            iteration_timeout_s=c.podracer_iteration_timeout_s,
            adaptive_cadence=getattr(c, "adaptive_cadence", True),
            cadence_cooldown_s=getattr(c, "cadence_cooldown_s", 10.0),
        ).validate()
