"""PodracerPipeline: the learner side of the Sebulba actor–learner split.

Owns the bounded SampleQueue, the versioned WeightBroadcast, and the
fault-tolerant fleet of PodracerEnvRunner actors running continuous
``run_loop`` tasks. The algorithm's training step drives it:

    episodes, steps = pipeline.pull_min(min_env_steps, deadline)
    ... build V-trace batch, update learner ...
    pipeline.publish(new_params)        # every publish_interval updates

Staleness control: fragments are tagged with the behaviour policy's
``weights_version``; at pull time ``max_policy_lag`` either DROPS
over-stale fragments (``policy_lag_mode="drop"``) or keeps them and lets
V-trace's rho/c truncation correct the off-policyness
(``policy_lag_mode="correct"``, the IMPALA default).

Crash tolerance: a runner dying mid-stream surfaces as its run_loop task
ref completing with an error; the health check restarts the actor (fresh
seed/worker_index, pulls current weights on its first poll) and relaunches
the loop — the queue keeps flowing, matching ``actor_manager`` semantics.
Restarts land in the control-plane lifecycle recorder (actor DEAD → new
actor ALIVE) and in ``rl_runner_restarts_total``.
"""
from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Tuple

import ray_tpu
from ray_tpu.rllib.actor_manager import FaultTolerantActorManager
from ray_tpu.rllib.episodes import SingleAgentEpisode
from ray_tpu.rllib.podracer.config import PodracerConfig
from ray_tpu.rllib.podracer.metrics import rl_metrics
from ray_tpu.rllib.podracer.runner import make_podracer_runner_cls
from ray_tpu.rllib.podracer.sample_queue import SampleQueue
from ray_tpu.rllib.podracer.weights import WeightBroadcast
from ray_tpu.util.actuators import Actuator, ActuatorRegistry, HealthSignal

logger = logging.getLogger("ray_tpu.rllib")


def partition_stale(
    records: List[Dict[str, Any]],
    current_version: int,
    max_policy_lag: int,
    mode: str = "correct",
) -> Tuple[List[Dict[str, Any]], List[Dict[str, Any]]]:
    """Split fragment records into (accepted, dropped_stale).

    ``mode="correct"`` accepts everything — V-trace's importance-sampling
    truncation corrects arbitrary off-policyness. ``mode="drop"`` rejects
    fragments whose behaviour policy is more than ``max_policy_lag``
    weight versions behind the learner. ``max_policy_lag < 0`` disables
    the cut in either mode.
    """
    if mode not in ("correct", "drop"):
        raise ValueError(f"policy_lag_mode must be 'correct' or 'drop', got {mode!r}")
    if mode == "correct" or max_policy_lag < 0:
        return list(records), []
    accepted, stale = [], []
    for rec in records:
        lag = current_version - int(rec.get("weights_version", 0))
        (stale if lag > max_policy_lag else accepted).append(rec)
    return accepted, stale


class _CadenceActuator(Actuator):
    """``policy_lag`` → adapt the weight-broadcast cadence.

    The driver-local leg of the health plane (core/health.py holds the
    controller-side four): when observed policy lag exceeds
    ``max_policy_lag``, halve the EFFECTIVE publish interval so runners
    see fresher weights sooner; once lag drops below half the budget,
    relax back toward the configured interval. Bounded between 1 and
    ``weights_publish_interval``, cooled by ``cadence_cooldown_s``, and
    audited — actions ship to the controller's lifecycle ring over
    ``task_events`` so ``summarize_health()`` shows them merged."""

    name = "podracer_cadence"
    triggers = ("policy_lag",)

    def __init__(self, pipeline: "PodracerPipeline", **kw):
        super().__init__(**kw)
        self._p = pipeline

    def fire(self, signal: HealthSignal):
        p = self._p
        lag = int(signal.detail.get("max_lag", 0))
        if lag > p.cfg.max_policy_lag:
            if p.publish_interval <= 1:
                return {"outcome": "skipped", "reason": "at_floor",
                        "max_lag": lag}
            p.publish_interval = max(1, p.publish_interval // 2)
            p.stats["cadence_adaptations"] += 1
            direction = "tighten"
        else:
            if p.publish_interval >= p.cfg.weights_publish_interval:
                return {"outcome": "skipped", "reason": "at_config",
                        "max_lag": lag}
            p.publish_interval = min(
                p.cfg.weights_publish_interval, p.publish_interval * 2
            )
            p.stats["cadence_adaptations"] += 1
            direction = "relax"
        logger.info(
            "podracer cadence %s: publish_interval -> %d (max lag %d, "
            "budget %d)", direction, p.publish_interval, lag,
            p.cfg.max_policy_lag,
        )
        return {"outcome": "acted", "direction": direction,
                "publish_interval": p.publish_interval, "max_lag": lag}


class PodracerPipeline:
    def __init__(self, config: "PodracerConfig", module_spec):
        self.cfg = config
        self._queue = SampleQueue(capacity=config.sample_queue_size)
        self._weights = WeightBroadcast()
        runner_cls = make_podracer_runner_cls()

        def make(i: int):
            return runner_cls.remote(
                config.env_spec,
                module_spec,
                num_envs=config.num_envs_per_runner,
                seed=config.seed,
                worker_index=i + 1,
            )

        self._manager = FaultTolerantActorManager(make, config.num_async_runners)
        self._loop_refs: Dict[int, Any] = {}
        self._returns: List[float] = []
        self.stats: Dict[str, float] = {
            "fragments_accepted": 0,
            "fragments_dropped_stale": 0,
            "fragments_lost": 0,
            "env_steps_accepted": 0,
            "env_steps_dropped": 0,
            "runner_restarts": 0,
            "queue_depth": 0,
            "max_policy_lag_seen": 0,
            "cadence_adaptations": 0,
        }
        self._started = False
        self._last_health_check = 0.0
        # Effective broadcast cadence — the cadence actuator's knob; the
        # algorithm consults pipeline.publish_interval, not the config.
        self.publish_interval = max(1, int(config.weights_publish_interval))
        self._cadence: "ActuatorRegistry | None" = None
        if config.adaptive_cadence:
            self._cadence = ActuatorRegistry(
                audit_ring=64, max_actions_per_min=12
            )
            self._cadence.register(
                _CadenceActuator(self, cooldown_s=config.cadence_cooldown_s)
            )

    # -- lifecycle --------------------------------------------------------
    def start(self, params):
        """Publish the initial weights (version 1) and launch every
        runner's continuous sample loop."""
        self.publish(params)
        for i in self._manager.actors:
            self._launch_loop(i)
        self._started = True

    def _launch_loop(self, idx: int):
        actor = self._manager.actors[idx]
        self._loop_refs[idx] = actor.run_loop.remote(
            self._queue.actor,
            self._weights.actor,
            self.cfg.rollout_fragment_length,
        )

    @property
    def version(self) -> int:
        return self._weights.version

    @property
    def num_restarts(self) -> int:
        return self._manager.num_restarts

    @property
    def manager(self) -> FaultTolerantActorManager:
        return self._manager

    def publish(self, params) -> int:
        return self._weights.publish(params)

    def check_runners(self):
        """A healthy runner's run_loop ref stays in flight; one that
        resolved means the loop exited — an error is a crash (restart +
        relaunch), a clean return means it was stopped."""
        refs = {ref: idx for idx, ref in self._loop_refs.items()}
        if not refs:
            return
        done, _ = ray_tpu.wait(
            list(refs), num_returns=len(refs), timeout=0
        )
        for ref in done:
            idx = refs[ref]
            try:
                ray_tpu.get(ref)
            except Exception as e:  # runner crashed mid-stream
                logger.warning(
                    "podracer runner %d crashed mid-stream (restarting): %s",
                    idx, e,
                )
                self._manager.restart_actor(idx)
                m = rl_metrics()
                m.runner_restarts.inc()
                m.bump("runner_restarts")
                self.stats["runner_restarts"] += 1
                self._launch_loop(idx)
            else:
                self._loop_refs.pop(idx, None)

    # -- the learner-side pull --------------------------------------------
    def pull_min(
        self, min_env_steps: int, deadline: float
    ) -> Tuple[List[SingleAgentEpisode], int]:
        """Accumulate fragments until ``min_env_steps`` accepted env steps
        (or ``deadline``); returns (episodes, accepted_env_steps)."""
        m = rl_metrics()
        cfg = self.cfg
        episodes: List[SingleAgentEpisode] = []
        steps = 0
        while steps < min_env_steps and time.monotonic() < deadline:
            timeout = min(cfg.poll_timeout_s,
                          max(0.05, deadline - time.monotonic()))
            records, info = self._queue.get_batch(
                max_records=cfg.max_pull, timeout=timeout
            )
            self.stats["queue_depth"] = info.get("depth", 0)
            # Health checks are an RPC: run one when the queue came up
            # empty (the strongest crash signal) or at most ~1/s.
            now = time.monotonic()
            if not records or now - self._last_health_check > 1.0:
                self._last_health_check = now
                self.check_runners()
            if not records:
                continue
            current = self.version
            lags = [max(0, current - int(r.get("weights_version", 0)))
                    for r in records]
            m.policy_lag.observe_many(lags)
            self.stats["max_policy_lag_seen"] = max(
                self.stats["max_policy_lag_seen"], max(lags)
            )
            self._observe_lag(max(lags))
            accepted, stale = partition_stale(
                records, current, cfg.max_policy_lag, cfg.policy_lag_mode
            )
            for rec in stale:
                m.fragments_dropped.inc(tags={"reason": "stale"})
                m.bump("fragments_dropped_stale")
                self.stats["fragments_dropped_stale"] += 1
                self.stats["env_steps_dropped"] += rec.get("env_steps", 0)
                # Episode returns are real even when the fragment is too
                # stale to train on — keep the reward signal dense.
                self._returns.extend(rec.get("returns", ()))
            # One batched fetch for the whole pull; fall back to
            # per-record fetches only to isolate a lost fragment.
            fetched = None
            if accepted:
                try:
                    fetched = ray_tpu.get(
                        [rec["ref"] for rec in accepted], timeout=60
                    )
                except Exception:  # noqa: BLE001 — isolate the loss below
                    fetched = None
            for j, rec in enumerate(accepted):
                if fetched is not None:
                    eps = fetched[j]
                else:
                    try:
                        eps = ray_tpu.get(rec["ref"], timeout=60)
                    except Exception as e:  # producer died before we pulled
                        logger.warning(
                            "podracer fragment from runner %s lost: %s",
                            rec.get("runner_index"), e,
                        )
                        m.fragments_dropped.inc(tags={"reason": "lost"})
                        m.bump("fragments_lost")
                        self.stats["fragments_lost"] += 1
                        # The episode returns are queue metadata that
                        # survived the producer — keep the reward signal
                        # (same rationale as the stale-drop path).
                        self._returns.extend(rec.get("returns", ()))
                        continue
                episodes.extend(eps)
                steps += rec.get("env_steps", 0)
                self.stats["fragments_accepted"] += 1
                self._returns.extend(rec.get("returns", ()))
        if steps:
            m.env_steps.inc(steps)
            m.bump("env_steps_accepted", steps)
            self.stats["env_steps_accepted"] += steps
        return episodes, steps

    def _observe_lag(self, max_lag: int):
        """Feed one pull's worst observed policy lag to the cadence
        actuator. Dispatch only at the decision boundaries (over budget,
        or recovered while tightened) — the registry's cooldown guards
        frequency, this guards pointless dispatches."""
        if self._cadence is None:
            return
        over = max_lag > self.cfg.max_policy_lag
        recovered = (
            max_lag <= max(0, self.cfg.max_policy_lag // 2)
            and self.publish_interval < self.cfg.weights_publish_interval
        )
        if not over and not recovered:
            return
        rows = self._cadence.dispatch(HealthSignal(
            "policy_lag", key="learner", target="learner",
            detail={"max_lag": int(max_lag),
                    "publish_interval": self.publish_interval},
        ))
        self._ship_actions(rows)

    def _ship_actions(self, rows: List[dict]):
        """Ship completed cadence actions to the controller's lifecycle
        ring (kind="action", remote=True) over the task_events channel so
        ``summarize_health()`` merges the driver-local audit."""
        evs = []
        for row in rows:
            if row.get("outcome") in ("cooldown", "throttled", "pending"):
                continue
            evs.append({
                "ts": row["ts"], "kind": "action", "id": row["id"],
                "state": "FAILED" if row["outcome"] == "failed" else "FINISHED",
                "actuator": row["actuator"], "trigger": row["trigger"],
                "target": row["target"], "outcome": row["outcome"],
                "dry_run": row["dry_run"] or None, "remote": True,
            })
        if not evs:
            return
        from ray_tpu.core import api

        core = api._global_worker
        if core is None:
            return
        try:
            core._submit("task_events", evs)
        except Exception as e:  # noqa: BLE001 — audit ship is best-effort
            logger.debug("cadence action ship failed: %s", e)

    def pop_returns(self) -> List[float]:
        out, self._returns = self._returns, []
        return out

    def shutdown(self):
        for idx, actor in self._manager.actors.items():
            try:
                actor.stop_loop.remote()
            except Exception as e:  # noqa: BLE001 — actor already dead
                logger.debug("stop_loop on runner %d failed: %s", idx, e)
        # Give loops one fragment boundary to exit cleanly, then kill.
        refs = list(self._loop_refs.values())
        if refs:
            try:
                ray_tpu.wait(refs, num_returns=len(refs), timeout=5)
            except Exception as e:  # noqa: BLE001 — cluster tearing down
                logger.debug("podracer loop drain failed: %s", e)
        for idx, actor in self._manager.actors.items():
            try:
                ray_tpu.kill(actor)
            except Exception as e:  # noqa: BLE001 — actor already dead
                logger.debug("kill runner %d failed: %s", idx, e)
        self._queue.shutdown()
        self._weights.shutdown()
