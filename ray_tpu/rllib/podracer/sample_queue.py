"""Bounded sample queue between env-runner actors and the learner.

Reference: the Sebulba actor–learner split of the Podracer paper
(arXiv:2104.06272) and IMPALA's learner queues
(rllib/algorithms/impala/impala.py:273 aggregation + queue plumbing).

Runners ``put`` fragment RECORDS — small dicts whose trajectory payload is
an object-store ref (``ray_tpu.put`` in the runner process), so the queue
actor never holds episode data, only metadata:

    {"ref": ObjectRef[List[SingleAgentEpisode]], "weights_version": int,
     "env_steps": int, "runner_index": int, "returns": [float, ...]}

Backpressure is drop-oldest: a full queue evicts the stalest fragment
(the one whose behaviour policy is furthest behind) instead of blocking
the producer — the Podracer shape where actors never stall on the
learner. Depth, wait-time, and drop metrics ride the telemetry pipeline.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Tuple

import ray_tpu
from ray_tpu.rllib.podracer.metrics import rl_metrics


class _SampleQueueActor:
    """Queue state lives in one actor; methods run on the actor's thread
    pool (max_concurrency > 1) so a learner blocked in ``get_batch`` can
    never starve runner ``put``s."""

    def __init__(self, capacity: int):
        self._capacity = max(1, int(capacity))
        self._dq: deque = deque()
        self._cv = threading.Condition(threading.Lock())
        self._put_total = 0
        self._dropped_capacity = 0

    def put(self, record: Dict[str, Any]) -> bool:
        """Enqueue one fragment record; full queue drops the OLDEST
        record. Returns False when this put caused a drop (backpressure
        signal for the runner's own accounting)."""
        m = rl_metrics()
        dropped = False
        record["ts_enqueue"] = time.time()
        with self._cv:
            if len(self._dq) >= self._capacity:
                self._dq.popleft()
                self._dropped_capacity += 1
                dropped = True
            self._dq.append(record)
            self._put_total += 1
            depth = len(self._dq)
            self._cv.notify()
        m.fragments.inc()
        m.bump("fragments_put")
        if dropped:
            m.fragments_dropped.inc(tags={"reason": "capacity"})
            m.bump("fragments_dropped_capacity")
        m.queue_depth.set(depth)
        return not dropped

    def get_batch(
        self, max_records: int, timeout: float
    ) -> Tuple[List[Dict[str, Any]], Dict[str, Any]]:
        """Dequeue up to ``max_records`` fragment records, blocking up to
        ``timeout`` seconds for the first one. Returns (records, info);
        each record gains ``queue_wait_ms``."""
        m = rl_metrics()
        deadline = time.monotonic() + max(0.0, timeout)
        out: List[Dict[str, Any]] = []
        with self._cv:
            while not self._dq:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cv.wait(remaining)
            now = time.time()
            while self._dq and len(out) < max_records:
                rec = self._dq.popleft()
                rec["queue_wait_ms"] = (now - rec.pop("ts_enqueue", now)) * 1e3
                out.append(rec)
            info = self._info_locked()
        if out:
            m.queue_wait_ms.observe_many([r["queue_wait_ms"] for r in out])
        m.queue_depth.set(info["depth"])
        return out, info

    def _info_locked(self) -> Dict[str, Any]:
        return {
            "depth": len(self._dq),
            "capacity": self._capacity,
            "put_total": self._put_total,
            "dropped_capacity": self._dropped_capacity,
        }

    def info(self) -> Dict[str, Any]:
        with self._cv:
            return self._info_locked()

    def ping(self) -> str:
        return "pong"


class SampleQueue:
    """Client wrapper; pass ``.actor`` into runner actors freely."""

    def __init__(self, capacity: int = 16):
        cls = ray_tpu.remote(num_cpus=0, max_concurrency=8)(_SampleQueueActor)
        self.actor = cls.remote(capacity)
        ray_tpu.wait_actor_ready(self.actor)

    def put(self, record: Dict[str, Any]) -> bool:
        return ray_tpu.get(self.actor.put.remote(record))

    def get_batch(
        self, max_records: int = 8, timeout: float = 5.0
    ) -> Tuple[List[Dict[str, Any]], Dict[str, Any]]:
        return ray_tpu.get(
            self.actor.get_batch.remote(max_records, timeout),
            timeout=timeout + 30.0,
        )

    def info(self) -> Dict[str, Any]:
        return ray_tpu.get(self.actor.info.remote())

    def shutdown(self):
        try:
            ray_tpu.kill(self.actor)
        except Exception as e:  # noqa: BLE001 — actor already dead at teardown
            import logging

            logging.getLogger("ray_tpu.rllib").debug(
                "sample queue kill failed: %s", e
            )
