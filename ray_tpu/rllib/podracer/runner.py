"""PodracerEnvRunner: a SingleAgentEnvRunner driving itself.

The Sebulba actor side (Podracer paper, arXiv:2104.06272): instead of the
driver calling ``sample.remote()`` in lockstep, each runner executes ONE
long-running ``run_loop`` task that continuously

    poll weights -> sample a fragment -> put the fragment ref on the queue

until told to stop. Weight pulls are asynchronous version polls against
the broadcast store (never a barrier with the learner), and fragment
payloads go to the object store — the queue actor only sees refs.

The actor is created with ``max_concurrency > 1`` so ``stop_loop``/
``ping`` calls land while ``run_loop`` occupies a thread.
"""
from __future__ import annotations

import time
from typing import Any

import ray_tpu
from ray_tpu.rllib.env_runner import SingleAgentEnvRunner


class PodracerEnvRunner(SingleAgentEnvRunner):
    def run_loop(
        self,
        queue_actor: Any,
        weight_actor: Any,
        fragment_len: int,
        max_fragments: int = 0,
    ) -> int:
        """Sample fragments forever (or ``max_fragments``); returns the
        fragment count when stopped. Raises through if the env or policy
        dies — the pipeline's health check restarts the actor."""
        self._stop_loop = False
        fragments = 0
        while not getattr(self, "_stop_loop", False):
            version, refbox = ray_tpu.get(
                weight_actor.poll.remote(self._weights_version)
            )
            if refbox is not None:
                self.set_state(ray_tpu.get(refbox[0]), version)
            episodes = self.sample(fragment_len)
            record = {
                "ref": ray_tpu.put(episodes),
                "weights_version": self._weights_version,
                "env_steps": sum(len(e) for e in episodes),
                "runner_index": self.worker_index,
                "returns": self.pop_metrics(),
                "ts_sampled": time.time(),
            }
            ray_tpu.get(queue_actor.put.remote(record))
            fragments += 1
            if max_fragments and fragments >= max_fragments:
                break
        return fragments

    def stop_loop(self) -> bool:
        """Cooperative stop flag, checked at each fragment boundary."""
        self._stop_loop = True
        return True


def make_podracer_runner_cls():
    """Remote actor class for podracer runners: CPU actor, no automatic
    restarts (the pipeline's FaultTolerantActorManager owns recovery so a
    restarted runner is re-seeded AND its run_loop relaunched), thread
    pool sized so control calls bypass the busy run_loop."""
    return ray_tpu.remote(num_cpus=1, max_restarts=0, max_concurrency=4)(
        PodracerEnvRunner
    )
