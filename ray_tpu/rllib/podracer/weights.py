"""Versioned weight broadcast: learner publishes, runners pull async.

Replaces the synchronous ``EnvRunnerGroup.sync_weights`` barrier for the
podracer pipeline: the learner ``publish``es each new weights version as
ONE object-store ref held by a tiny store actor; env runners ``poll`` at
fragment boundaries and pull the ref only when the version advanced — no
learner-side blocking, no per-runner push fan-out.

Cross-node, large weights are pre-staged onto every node over the
controller's pipelined broadcast chain (``object_broadcast``, reference:
push_manager.h) so N runners pulling the same version don't issue N
competing point-to-point pulls from the learner's node. Staging is
best-effort — a failure just means runners pull point-to-point.
"""
from __future__ import annotations

import logging
from typing import Any, Optional, Tuple

import ray_tpu
from ray_tpu.rllib.podracer.metrics import rl_metrics

logger = logging.getLogger("ray_tpu.rllib")


def stage_broadcast(ref) -> bool:
    """Best-effort pre-staging of ``ref`` onto every alive non-head node
    (no-op on single-node clusters / inline-small objects)."""
    try:
        core = ray_tpu.core.api._require_worker()
        nodes = {
            n["node_id"]
            for n in ray_tpu.nodes()
            if n["state"] == "ALIVE" and not n["is_head"]
        }
        if nodes:
            core._call("object_broadcast", ref.id, None, timeout=300)
        return True
    except Exception as e:  # noqa: BLE001 — staging is best-effort
        logger.warning(
            "weight broadcast staging failed (workers will pull "
            "point-to-point): %s", e,
        )
        return False


class _WeightStoreActor:
    """Holds the newest (version, weights-ref) pair.

    The ref travels BOXED in a 1-element list both ways: a top-level
    ObjectRef argument is auto-resolved to its value by the task layer
    (the ``set_state(ref)`` convenience), but the store must hold the ref
    itself — runners decide when to pull.
    """

    def __init__(self):
        self._version = 0
        self._refbox = None

    def publish(self, refbox, version: int):
        # Monotonic: a late/duplicate publish of an older version must
        # never roll runners back.
        if version > self._version:
            self._version = version
            self._refbox = refbox
        return self._version

    def poll(self, have_version: int) -> Tuple[int, Optional[Any]]:
        """(version, [ref]) when newer weights exist, else (version, None)."""
        if self._refbox is not None and self._version > have_version:
            return self._version, self._refbox
        return self._version, None

    def ping(self) -> str:
        return "pong"


class WeightBroadcast:
    """Learner-side publisher; pass ``.actor`` into runner actors."""

    def __init__(self):
        cls = ray_tpu.remote(num_cpus=0, max_concurrency=4)(_WeightStoreActor)
        self.actor = cls.remote()
        ray_tpu.wait_actor_ready(self.actor)
        self.version = 0

    def publish(self, params) -> int:
        """Put ``params`` once, stage it cross-node, and advance the
        published version. Returns the new version."""
        self.version += 1
        ref = ray_tpu.put(params)
        stage_broadcast(ref)
        ray_tpu.get(self.actor.publish.remote([ref], self.version))
        m = rl_metrics()
        m.weights_published.inc()
        m.bump("weights_published")
        return self.version

    def shutdown(self):
        try:
            ray_tpu.kill(self.actor)
        except Exception as e:  # noqa: BLE001 — actor already dead at teardown
            logger.debug("weight store kill failed: %s", e)
