"""ray_tpu.rllib.podracer — Sebulba-style async actor–learner RL.

Reference: "Podracer architectures for scalable Reinforcement Learning"
(arXiv:2104.06272). The Sebulba shape on this framework: CPU env-runner
actors continuously feed a bounded sample queue with trajectory-fragment
refs; the learner pulls, recomputes target logps with one batched jitted
forward, V-trace-corrects the off-policyness, runs the mesh-sharded
update, and publishes versioned weights that runners pull asynchronously.

Enable on any PPO/IMPALA config with::

    PPOConfig().environment("CartPole-v1").podracer(num_async_runners=4)

``num_async_runners=0`` (default) keeps the synchronous driver loop.
"""
from ray_tpu.rllib.podracer.config import PodracerConfig
from ray_tpu.rllib.podracer.metrics import rl_metrics
from ray_tpu.rllib.podracer.pipeline import PodracerPipeline, partition_stale
from ray_tpu.rllib.podracer.runner import PodracerEnvRunner
from ray_tpu.rllib.podracer.sample_queue import SampleQueue
from ray_tpu.rllib.podracer.vtrace_builder import VtraceBatchBuilder
from ray_tpu.rllib.podracer.weights import WeightBroadcast, stage_broadcast

__all__ = [
    "PodracerConfig",
    "PodracerPipeline",
    "PodracerEnvRunner",
    "SampleQueue",
    "WeightBroadcast",
    "VtraceBatchBuilder",
    "partition_stale",
    "stage_broadcast",
    "rl_metrics",
]
