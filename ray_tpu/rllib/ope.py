"""Off-policy evaluation estimators.

Reference: rllib/offline/estimators/ — importance_sampling.py (IS),
weighted_importance_sampling.py (WIS), direct_method.py (DM),
doubly_robust.py (DR). Estimate V^π of a *target* policy from episodes
sampled by a *behavior* policy, without running the target in the env.

All estimators take episodes whose ``logps`` are the behavior policy's
action log-probs (exactly what our EnvRunners record) and a (module,
params) pair for the target policy.
"""
from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ray_tpu.rllib.episodes import SingleAgentEpisode


def _target_logps(module, params, ep: SingleAgentEpisode) -> np.ndarray:
    import jax.numpy as jnp

    obs = np.asarray(ep.observations[: len(ep)], dtype=np.float32)
    acts = np.asarray(ep.actions, dtype=np.int32)
    out = module.logp_entropy(params, jnp.asarray(obs), jnp.asarray(acts))
    return np.asarray(out["logp"], dtype=np.float32)


def _step_weights(module, params, ep: SingleAgentEpisode, clip: float) -> np.ndarray:
    """Cumulative importance weights w_t = Π_{i<=t} π(a|s)/β(a|s)."""
    ratios = np.exp(
        np.clip(_target_logps(module, params, ep) - np.asarray(ep.logps, np.float32), -20, 20)
    )
    w = np.cumprod(ratios)
    return np.minimum(w, clip) if clip > 0 else w


class ImportanceSampling:
    """Per-episode trajectory-IS estimate of V^π (reference:
    importance_sampling.py): mean over episodes of Σ_t γ^t w_t r_t."""

    def __init__(self, module, params, gamma: float = 0.99, weight_clip: float = 100.0):
        self.module, self.params = module, params
        self.gamma = gamma
        self.clip = weight_clip

    def estimate(self, episodes: List[SingleAgentEpisode]) -> Dict[str, float]:
        vals = []
        for ep in episodes:
            if len(ep) == 0:
                continue
            w = _step_weights(self.module, self.params, ep, self.clip)
            r = np.asarray(ep.rewards, np.float32)
            disc = self.gamma ** np.arange(len(r))
            vals.append(float((disc * w * r).sum()))
        return {
            "v_target": float(np.mean(vals)) if vals else 0.0,
            "v_target_std": float(np.std(vals)) if vals else 0.0,
            "num_episodes": len(vals),
        }


class WeightedImportanceSampling:
    """WIS (reference: weighted_importance_sampling.py): per-timestep
    weights normalized by their across-episode mean — biased but far
    lower variance than plain IS."""

    def __init__(self, module, params, gamma: float = 0.99, weight_clip: float = 100.0):
        self.module, self.params = module, params
        self.gamma = gamma
        self.clip = weight_clip

    def estimate(self, episodes: List[SingleAgentEpisode]) -> Dict[str, float]:
        eps = [ep for ep in episodes if len(ep) > 0]
        if not eps:
            return {"v_target": 0.0, "v_target_std": 0.0, "num_episodes": 0}
        weights = [_step_weights(self.module, self.params, ep, self.clip) for ep in eps]
        T = max(len(w) for w in weights)
        # mean weight per timestep across episodes (missing steps → no term)
        sums = np.zeros(T)
        counts = np.zeros(T)
        for w in weights:
            sums[: len(w)] += w
            counts[: len(w)] += 1
        mean_w = np.where(counts > 0, sums / np.maximum(counts, 1), 1.0)
        vals = []
        for ep, w in zip(eps, weights):
            r = np.asarray(ep.rewards, np.float32)
            disc = self.gamma ** np.arange(len(r))
            norm = np.maximum(mean_w[: len(w)], 1e-8)
            vals.append(float((disc * (w / norm) * r).sum()))
        return {
            "v_target": float(np.mean(vals)),
            "v_target_std": float(np.std(vals)),
            "num_episodes": len(vals),
        }


class DirectMethod:
    """DM (reference: direct_method.py): V^π(s0) from the target policy's
    learned value head — no importance correction, pure model estimate."""

    def __init__(self, module, params, gamma: float = 0.99):
        self.module, self.params = module, params
        self.gamma = gamma

    def _v0(self, ep: SingleAgentEpisode) -> float:
        import jax.numpy as jnp

        obs0 = np.asarray(ep.observations[0], dtype=np.float32)[None]
        out = self.module.forward_train(self.params, jnp.asarray(obs0))
        return float(np.asarray(out["vf"])[0])

    def estimate(self, episodes: List[SingleAgentEpisode]) -> Dict[str, float]:
        vals = [self._v0(ep) for ep in episodes if len(ep) > 0]
        return {
            "v_target": float(np.mean(vals)) if vals else 0.0,
            "v_target_std": float(np.std(vals)) if vals else 0.0,
            "num_episodes": len(vals),
        }


class DoublyRobust:
    """DR (reference: doubly_robust.py): recursive combination of the
    model value and per-step importance-corrected TD residuals —
    unbiased if either the weights or the value model are right."""

    def __init__(self, module, params, gamma: float = 0.99, weight_clip: float = 100.0):
        self.module, self.params = module, params
        self.gamma = gamma
        self.clip = weight_clip

    def estimate(self, episodes: List[SingleAgentEpisode]) -> Dict[str, float]:
        import jax
        import jax.numpy as jnp

        vals = []
        for ep in episodes:
            T = len(ep)
            if T == 0:
                continue
            # One forward over all T+1 observations gives the target
            # policy's logits AND values (no second pass via logp_entropy).
            obs = np.asarray(ep.observations, dtype=np.float32)
            out = self.module.forward_train(self.params, jnp.asarray(obs))
            v = np.asarray(out["vf"], dtype=np.float32)
            logp_all = np.asarray(
                jax.nn.log_softmax(out["logits"], axis=-1), dtype=np.float32
            )
            acts = np.asarray(ep.actions, np.int32)
            target_logps = logp_all[np.arange(T), acts]
            ratios = np.exp(
                np.clip(target_logps - np.asarray(ep.logps, np.float32), -20, 20)
            )
            if self.clip > 0:
                ratios = np.minimum(ratios, self.clip)
            r = np.asarray(ep.rewards, np.float32)
            # backward recursion: V_DR(t) = v(s_t) + ρ_t (r_t + γ V_DR(t+1) − v(s_t));
            # truncated episodes bootstrap with the TARGET policy's value
            # of the final state, not the behavior policy's recorded one.
            acc = 0.0 if ep.terminated else float(v[T])
            for t in range(T - 1, -1, -1):
                acc = v[t] + ratios[t] * (r[t] + self.gamma * acc - v[t])
            vals.append(float(acc))
        return {
            "v_target": float(np.mean(vals)) if vals else 0.0,
            "v_target_std": float(np.std(vals)) if vals else 0.0,
            "num_episodes": len(vals),
        }
