"""Algorithm + AlgorithmConfig + EnvRunnerGroup.

Reference: rllib/algorithms/algorithm.py:596 (setup builds
EnvRunnerGroup + LearnerGroup; step :896 → training_step :1680) and
rllib/algorithms/algorithm_config.py (fluent config), env/env_runner_group.py:71.
"""
from __future__ import annotations

import json
import logging
import os
import pickle
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.actor_manager import FaultTolerantActorManager
from ray_tpu.rllib.env_runner import SingleAgentEnvRunner, _make_env
from ray_tpu.rllib.episodes import SingleAgentEpisode
from ray_tpu.rllib.learner import LearnerGroup
from ray_tpu.rllib.rl_module import RLModuleSpec


class AlgorithmConfig:
    """Fluent config (reference: algorithm_config.py — env_runners/
    training/learners/evaluation sections)."""

    def __init__(self):
        self.env_spec: Any = None
        self.num_env_runners: int = 0
        self.num_envs_per_runner: int = 1
        self.rollout_fragment_length: int = 200
        self.train_batch_size: int = 4000
        self.minibatch_size: int = 128
        self.num_epochs: int = 4
        self.lr: float = 3e-4
        self.gamma: float = 0.99
        self.lam: float = 0.95
        self.grad_clip: float = 0.5
        self.num_learners: int = 0
        self.num_cpus_per_learner: float = 1
        self.num_tpus_per_learner: float = 0
        self.hidden: tuple = (64, 64)
        self.seed: int = 0
        # -- podracer (Sebulba async actor–learner) section --------------
        # 0 = synchronous driver loop (seed behaviour); > 0 spawns that
        # many continuous env-runner actors feeding the bounded sample
        # queue (see ray_tpu.rllib.podracer).
        self.num_async_runners: int = 0
        self.sample_queue_size: int = 16
        self.max_policy_lag: int = 8
        self.policy_lag_mode: str = "correct"
        self.weights_publish_interval: int = 1
        self.podracer_max_pull: int = 16
        self.podracer_poll_timeout_s: float = 2.0
        self.podracer_iteration_timeout_s: float = 300.0
        # Policy-lag cadence actuator (the driver-local health-plane
        # leg): tighten the publish interval when observed lag exceeds
        # max_policy_lag, relax back once it recovers.
        self.adaptive_cadence: bool = True
        self.cadence_cooldown_s: float = 10.0
        self.extra: Dict[str, Any] = {}

    # fluent setters ------------------------------------------------------
    def environment(self, env: Any) -> "AlgorithmConfig":
        self.env_spec = env
        return self

    def env_runners(
        self, num_env_runners: int = 0, num_envs_per_env_runner: int = 1, rollout_fragment_length: int = 200
    ) -> "AlgorithmConfig":
        self.num_env_runners = num_env_runners
        self.num_envs_per_runner = num_envs_per_env_runner
        self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kw) -> "AlgorithmConfig":
        for k, v in kw.items():
            if hasattr(self, k):
                setattr(self, k, v)
            else:
                self.extra[k] = v
        return self

    def learners(
        self, num_learners: int = 0, num_cpus_per_learner: float = 1, num_tpus_per_learner: float = 0
    ) -> "AlgorithmConfig":
        self.num_learners = num_learners
        self.num_cpus_per_learner = num_cpus_per_learner
        self.num_tpus_per_learner = num_tpus_per_learner
        return self

    def debugging(self, seed: int = 0) -> "AlgorithmConfig":
        self.seed = seed
        return self

    def podracer(
        self,
        num_async_runners: int = 0,
        sample_queue_size: int = 16,
        max_policy_lag: int = 8,
        policy_lag_mode: str = "correct",
        weights_publish_interval: int = 1,
        max_pull: int = 16,
        poll_timeout_s: float = 2.0,
        iteration_timeout_s: float = 300.0,
        adaptive_cadence: bool = True,
        cadence_cooldown_s: float = 10.0,
    ) -> "AlgorithmConfig":
        """Sebulba async pipeline section (ray_tpu.rllib.podracer):
        continuous env-runner actors -> bounded sample queue -> learner,
        with versioned weight broadcast and ``max_policy_lag`` staleness
        control (``policy_lag_mode``: "drop" rejects over-stale fragments,
        "correct" keeps them for V-trace's rho/c truncation)."""
        self.num_async_runners = num_async_runners
        self.sample_queue_size = sample_queue_size
        self.max_policy_lag = max_policy_lag
        self.policy_lag_mode = policy_lag_mode
        self.weights_publish_interval = weights_publish_interval
        self.podracer_max_pull = max_pull
        self.podracer_poll_timeout_s = poll_timeout_s
        self.podracer_iteration_timeout_s = iteration_timeout_s
        self.adaptive_cadence = adaptive_cadence
        self.cadence_cooldown_s = cadence_cooldown_s
        return self

    def rl_module(self, hidden: tuple = (64, 64)) -> "AlgorithmConfig":
        self.hidden = hidden
        return self

    def module_spec(self) -> RLModuleSpec:
        env = _make_env(self.env_spec)
        obs_dim = int(np.prod(env.observation_space.shape))
        act_dim = int(env.action_space.n)
        env.close()
        return RLModuleSpec(observation_dim=obs_dim, action_dim=act_dim, hidden=tuple(self.hidden))

    def build(self) -> "Algorithm":
        raise NotImplementedError("use PPOConfig/IMPALAConfig")


class EnvRunnerGroup:
    """Local runner + N fault-tolerant remote runners (reference:
    env/env_runner_group.py:71, sync_weights :499)."""

    def __init__(self, config: AlgorithmConfig, module_spec: RLModuleSpec):
        self._cfg = config
        self._spec = module_spec
        self.local_runner = SingleAgentEnvRunner(
            config.env_spec, module_spec, num_envs=config.num_envs_per_runner, seed=config.seed
        )
        if config.num_env_runners > 0:
            runner_cls = ray_tpu.remote(num_cpus=1, max_restarts=0)(SingleAgentEnvRunner)

            def make(i: int):
                return runner_cls.remote(
                    config.env_spec,
                    module_spec,
                    num_envs=config.num_envs_per_runner,
                    seed=config.seed,
                    worker_index=i + 1,
                )

            self._manager = FaultTolerantActorManager(make, config.num_env_runners)
        else:
            self._manager = None
        self._weights_version = 0

    @property
    def num_remote_runners(self) -> int:
        return len(self._manager.actors) if self._manager else 0

    @property
    def num_restarts(self) -> int:
        return self._manager.num_restarts if self._manager else 0

    def sync_weights(self, params):
        """Ship learner weights to every runner via one object-store put
        (reference: sync_weights' broadcast-by-ref). When runners span
        multiple nodes, large weights are pre-staged onto every node over
        the pipelined broadcast chain (controller object_broadcast,
        reference: push_manager.h) so N runners don't issue N competing
        pulls from the one source node."""
        self._weights_version += 1
        self.local_runner.set_state(params, self._weights_version)
        if self._manager:
            from ray_tpu.rllib.podracer.weights import stage_broadcast

            ref = ray_tpu.put(params)
            stage_broadcast(ref)
            self._manager.foreach_actor(
                "set_state", ref, self._weights_version, timeout=60
            )

    def sample(self, total_env_steps: int) -> List[SingleAgentEpisode]:
        """Synchronous parallel sampling (reference:
        execution/rollout_ops.py synchronous_parallel_sample)."""
        if not self._manager:
            return self.local_runner.sample(total_env_steps)
        n = max(1, self._manager.num_healthy())
        per = max(1, total_env_steps // n)
        results = self._manager.foreach_actor("sample", per, timeout=300)
        episodes: List[SingleAgentEpisode] = []
        for _, eps in results:
            episodes.extend(eps)
        if not episodes:  # every remote failed this round — fall back local
            episodes = self.local_runner.sample(total_env_steps)
        return episodes

    def pop_metrics(self) -> List[float]:
        returns = self.local_runner.pop_metrics()
        if self._manager:
            for _, r in self._manager.foreach_actor("pop_metrics", timeout=60):
                returns.extend(r)
        return returns

    def evaluate(self, num_episodes: int = 5) -> float:
        return self.local_runner.evaluate(num_episodes)


class Algorithm:
    """Reference: rllib/algorithms/algorithm.py (Trainable-style:
    setup in __init__, train() per iteration, save/restore)."""

    loss_fn = None  # set by subclass
    # Podracer needs a V-trace-able on-policy module (PPO/IMPALA/APPO set
    # True); replay-buffer algorithms keep their own loops.
    supports_podracer = False

    def __init__(self, config: AlgorithmConfig):
        # Build-time overrides go on a COPY — build() must not edit the
        # caller's config object as a side effect.
        if config.num_async_runners > 0:
            import copy

            if not type(self).supports_podracer:
                logging.getLogger("ray_tpu.rllib").warning(
                    "%s does not run on the podracer pipeline — ignoring "
                    "num_async_runners=%d (synchronous loop used)",
                    type(self).__name__, config.num_async_runners,
                )
                config = copy.copy(config)
                config.num_async_runners = 0
            elif config.num_env_runners > 0:
                logging.getLogger("ray_tpu.rllib").warning(
                    "podracer mode (num_async_runners=%d) supersedes the "
                    "synchronous runner fleet — ignoring num_env_runners=%d",
                    config.num_async_runners, config.num_env_runners,
                )
                config = copy.copy(config)
                config.num_env_runners = 0
        self.config = config
        self.module_spec = config.module_spec()
        self.env_runner_group = EnvRunnerGroup(config, self.module_spec)
        self.learner_group = LearnerGroup(
            self.module_spec,
            type(self).loss_fn,
            loss_cfg=self._loss_cfg(),
            num_learners=config.num_learners,
            lr=config.lr,
            grad_clip=config.grad_clip,
            seed=config.seed,
            num_cpus_per_learner=config.num_cpus_per_learner,
            num_tpus_per_learner=config.num_tpus_per_learner,
        )
        self.iteration = 0
        self._total_env_steps = 0
        self._batch_builder_cache = None
        self.env_runner_group.sync_weights(self.learner_group.get_weights())
        self._podracer = None
        self._podracer_updates = 0
        if config.num_async_runners > 0:
            from ray_tpu.rllib.podracer import PodracerConfig, PodracerPipeline

            self._podracer = PodracerPipeline(
                PodracerConfig.from_algorithm_config(config), self.module_spec
            )
            self._podracer.start(self.learner_group.get_weights())

    def _loss_cfg(self) -> dict:
        return {}

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    # -- podracer (Sebulba async) path ------------------------------------
    def _batch_builder(self):
        """Shared batched+jitted V-trace batch builder over the target
        module (the learner's own module locally; a factory-built twin
        when learners are remote actors)."""
        if self._batch_builder_cache is None:
            from ray_tpu.rllib.podracer.vtrace_builder import VtraceBatchBuilder
            from ray_tpu.rllib.rl_module import make_module

            lg = self.learner_group
            module = (
                lg._local.module if lg._local is not None
                else make_module(self.module_spec)
            )
            self._batch_builder_cache = VtraceBatchBuilder(module)
        return self._batch_builder_cache

    def _podracer_builder_kwargs(self) -> dict:
        c = self.config
        return dict(
            gamma=c.gamma,
            rho_bar=getattr(c, "rho_bar", 1.0),
            c_bar=getattr(c, "c_bar", 1.0),
        )

    def _podracer_min_batch_env_steps(self) -> int:
        """Env steps accumulated per learner update (IMPALA-style: one
        fragment's worth, continuous updates; PPO overrides to its full
        train batch)."""
        return max(1, self.config.rollout_fragment_length)

    def _podracer_update_fn(self, batch) -> Dict[str, float]:
        """One learner cycle on a built batch; PPO overrides with its
        minibatch-epoch loop."""
        return self.learner_group.update_from_batch(batch)

    def _podracer_training_step(self) -> Dict[str, Any]:
        from ray_tpu.rllib.podracer.metrics import rl_metrics

        cfg = self.config
        pr = self._podracer
        m = rl_metrics()
        target = cfg.train_batch_size
        min_pull = self._podracer_min_batch_env_steps()
        deadline = time.monotonic() + pr.cfg.iteration_timeout_s
        consumed = 0
        metrics: Dict[str, float] = {}
        while consumed < target:
            if time.monotonic() >= deadline:
                if consumed:
                    # Updates already applied this iteration — return the
                    # partial result so step/return accounting stays
                    # truthful instead of raising it away.
                    logging.getLogger("ray_tpu.rllib").warning(
                        "podracer training step timed out at %d/%d env "
                        "steps (runner restarts: %d) — returning partial "
                        "iteration", consumed, target, pr.num_restarts,
                    )
                    break
                raise TimeoutError(
                    f"podracer training step starved: 0/{target} "
                    f"env steps within {pr.cfg.iteration_timeout_s}s "
                    f"(runner restarts: {pr.num_restarts})"
                )
            episodes, steps = pr.pull_min(
                min(min_pull, target - consumed), deadline
            )
            if not episodes:
                continue
            t0 = time.perf_counter()
            batch = self._batch_builder().build(
                self.learner_group.get_weights(),
                episodes,
                **self._podracer_builder_kwargs(),
            )
            if batch is None:
                continue
            metrics = self._podracer_update_fn(batch)
            self._podracer_updates += 1
            # pr.publish_interval is the cadence actuator's ADAPTED value
            # (== cfg.weights_publish_interval unless policy lag forced a
            # tighter broadcast cadence).
            if self._podracer_updates % pr.publish_interval == 0:
                pr.publish(self.learner_group.get_weights())
            m.learner_step_ms.observe((time.perf_counter() - t0) * 1e3)
            consumed += steps
        self._total_env_steps += consumed
        returns = pr.pop_returns()
        mean_ret = self._record_returns(returns)
        return {
            "env_steps_this_iter": consumed,
            "episode_return_mean": mean_ret,
            "num_episodes": len(returns),
            "podracer/weights_version": pr.version,
            "podracer/queue_depth": pr.stats["queue_depth"],
            "podracer/fragments_dropped_stale": pr.stats["fragments_dropped_stale"],
            "podracer/fragments_lost": pr.stats["fragments_lost"],
            "podracer/runner_restarts": pr.stats["runner_restarts"],
            "podracer/max_policy_lag_seen": pr.stats["max_policy_lag_seen"],
            "podracer/publish_interval": pr.publish_interval,
            "podracer/cadence_adaptations": pr.stats["cadence_adaptations"],
            **{f"learner/{k}": v for k, v in metrics.items()},
        }

    def _record_returns(self, returns: List[float]) -> float:
        """Fold completed-episode returns into the rolling-100 window;
        returns the current mean (0.0 before any episode finishes)."""
        if returns:
            self._recent_returns = (
                getattr(self, "_recent_returns", []) + returns
            )[-100:]
        recent = getattr(self, "_recent_returns", None)
        return float(np.mean(recent)) if recent else 0.0

    def train(self) -> Dict[str, Any]:
        t0 = time.time()
        result = self.training_step()
        self.iteration += 1
        result.setdefault("training_iteration", self.iteration)
        result["time_this_iter_s"] = time.time() - t0
        result["num_env_steps_sampled_lifetime"] = self._total_env_steps
        result["env_steps_per_sec"] = result.get("env_steps_this_iter", 0) / max(
            1e-9, result["time_this_iter_s"]
        )
        return result

    def evaluate(self, num_episodes: int = 5) -> float:
        if self._podracer is not None:
            # Podracer publishes weights to the broadcast store only; the
            # local eval runner never sees them — sync it lazily here so
            # evaluate() measures the TRAINED policy.
            self.env_runner_group.local_runner.set_state(
                self.learner_group.get_weights(), self._podracer.version
            )
        return self.env_runner_group.evaluate(num_episodes)

    # -- checkpointing (reference: Checkpointable mixin,
    # rllib/utils/checkpoints.py; Algorithm.from_checkpoint) -------------
    def save(self, path: str) -> str:
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "learner_state.pkl"), "wb") as f:
            pickle.dump(self.learner_group.get_state(), f)
        with open(os.path.join(path, "algo_state.json"), "w") as f:
            json.dump(
                {"iteration": self.iteration, "total_env_steps": self._total_env_steps},
                f,
            )
        return path

    def restore(self, path: str):
        with open(os.path.join(path, "learner_state.pkl"), "rb") as f:
            self.learner_group.set_state(pickle.load(f))
        with open(os.path.join(path, "algo_state.json")) as f:
            st = json.load(f)
        self.iteration = st["iteration"]
        self._total_env_steps = st["total_env_steps"]
        self.env_runner_group.sync_weights(self.learner_group.get_weights())
        if self._podracer is not None:
            self._podracer.publish(self.learner_group.get_weights())

    def stop(self):
        if self._podracer is not None:
            self._podracer.shutdown()
            self._podracer = None
        self.learner_group.shutdown()
