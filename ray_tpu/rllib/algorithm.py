"""Algorithm + AlgorithmConfig + EnvRunnerGroup.

Reference: rllib/algorithms/algorithm.py:596 (setup builds
EnvRunnerGroup + LearnerGroup; step :896 → training_step :1680) and
rllib/algorithms/algorithm_config.py (fluent config), env/env_runner_group.py:71.
"""
from __future__ import annotations

import json
import logging
import os
import pickle
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.actor_manager import FaultTolerantActorManager
from ray_tpu.rllib.env_runner import SingleAgentEnvRunner, _make_env
from ray_tpu.rllib.episodes import SingleAgentEpisode
from ray_tpu.rllib.learner import LearnerGroup
from ray_tpu.rllib.rl_module import RLModuleSpec


class AlgorithmConfig:
    """Fluent config (reference: algorithm_config.py — env_runners/
    training/learners/evaluation sections)."""

    def __init__(self):
        self.env_spec: Any = None
        self.num_env_runners: int = 0
        self.num_envs_per_runner: int = 1
        self.rollout_fragment_length: int = 200
        self.train_batch_size: int = 4000
        self.minibatch_size: int = 128
        self.num_epochs: int = 4
        self.lr: float = 3e-4
        self.gamma: float = 0.99
        self.lam: float = 0.95
        self.grad_clip: float = 0.5
        self.num_learners: int = 0
        self.num_cpus_per_learner: float = 1
        self.num_tpus_per_learner: float = 0
        self.hidden: tuple = (64, 64)
        self.seed: int = 0
        self.extra: Dict[str, Any] = {}

    # fluent setters ------------------------------------------------------
    def environment(self, env: Any) -> "AlgorithmConfig":
        self.env_spec = env
        return self

    def env_runners(
        self, num_env_runners: int = 0, num_envs_per_env_runner: int = 1, rollout_fragment_length: int = 200
    ) -> "AlgorithmConfig":
        self.num_env_runners = num_env_runners
        self.num_envs_per_runner = num_envs_per_env_runner
        self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kw) -> "AlgorithmConfig":
        for k, v in kw.items():
            if hasattr(self, k):
                setattr(self, k, v)
            else:
                self.extra[k] = v
        return self

    def learners(
        self, num_learners: int = 0, num_cpus_per_learner: float = 1, num_tpus_per_learner: float = 0
    ) -> "AlgorithmConfig":
        self.num_learners = num_learners
        self.num_cpus_per_learner = num_cpus_per_learner
        self.num_tpus_per_learner = num_tpus_per_learner
        return self

    def debugging(self, seed: int = 0) -> "AlgorithmConfig":
        self.seed = seed
        return self

    def rl_module(self, hidden: tuple = (64, 64)) -> "AlgorithmConfig":
        self.hidden = hidden
        return self

    def module_spec(self) -> RLModuleSpec:
        env = _make_env(self.env_spec)
        obs_dim = int(np.prod(env.observation_space.shape))
        act_dim = int(env.action_space.n)
        env.close()
        return RLModuleSpec(observation_dim=obs_dim, action_dim=act_dim, hidden=tuple(self.hidden))

    def build(self) -> "Algorithm":
        raise NotImplementedError("use PPOConfig/IMPALAConfig")


class EnvRunnerGroup:
    """Local runner + N fault-tolerant remote runners (reference:
    env/env_runner_group.py:71, sync_weights :499)."""

    def __init__(self, config: AlgorithmConfig, module_spec: RLModuleSpec):
        self._cfg = config
        self._spec = module_spec
        self.local_runner = SingleAgentEnvRunner(
            config.env_spec, module_spec, num_envs=config.num_envs_per_runner, seed=config.seed
        )
        if config.num_env_runners > 0:
            runner_cls = ray_tpu.remote(num_cpus=1, max_restarts=0)(SingleAgentEnvRunner)

            def make(i: int):
                return runner_cls.remote(
                    config.env_spec,
                    module_spec,
                    num_envs=config.num_envs_per_runner,
                    seed=config.seed,
                    worker_index=i + 1,
                )

            self._manager = FaultTolerantActorManager(make, config.num_env_runners)
        else:
            self._manager = None
        self._weights_version = 0

    @property
    def num_remote_runners(self) -> int:
        return len(self._manager.actors) if self._manager else 0

    @property
    def num_restarts(self) -> int:
        return self._manager.num_restarts if self._manager else 0

    def sync_weights(self, params):
        """Ship learner weights to every runner via one object-store put
        (reference: sync_weights' broadcast-by-ref). When runners span
        multiple nodes, large weights are pre-staged onto every node over
        the pipelined broadcast chain (controller object_broadcast,
        reference: push_manager.h) so N runners don't issue N competing
        pulls from the one source node."""
        self._weights_version += 1
        self.local_runner.set_state(params, self._weights_version)
        if self._manager:
            ref = ray_tpu.put(params)
            try:
                core = ray_tpu.core.api._require_worker()
                nodes = {
                    n["node_id"] for n in ray_tpu.nodes()
                    if n["state"] == "ALIVE" and not n["is_head"]
                }
                if nodes:
                    # False for inline-small weights (nothing to stage)
                    core._call("object_broadcast", ref.id, None, timeout=300)
            except Exception as e:  # noqa: BLE001 — staging is best-effort
                logging.getLogger("ray_tpu.rllib").warning(
                    "weight broadcast staging failed (workers will pull "
                    "point-to-point): %s", e,
                )
            self._manager.foreach_actor(
                "set_state", ref, self._weights_version, timeout=60
            )

    def sample(self, total_env_steps: int) -> List[SingleAgentEpisode]:
        """Synchronous parallel sampling (reference:
        execution/rollout_ops.py synchronous_parallel_sample)."""
        if not self._manager:
            return self.local_runner.sample(total_env_steps)
        n = max(1, self._manager.num_healthy())
        per = max(1, total_env_steps // n)
        results = self._manager.foreach_actor("sample", per, timeout=300)
        episodes: List[SingleAgentEpisode] = []
        for _, eps in results:
            episodes.extend(eps)
        if not episodes:  # every remote failed this round — fall back local
            episodes = self.local_runner.sample(total_env_steps)
        return episodes

    def pop_metrics(self) -> List[float]:
        returns = self.local_runner.pop_metrics()
        if self._manager:
            for _, r in self._manager.foreach_actor("pop_metrics", timeout=60):
                returns.extend(r)
        return returns

    def evaluate(self, num_episodes: int = 5) -> float:
        return self.local_runner.evaluate(num_episodes)


class Algorithm:
    """Reference: rllib/algorithms/algorithm.py (Trainable-style:
    setup in __init__, train() per iteration, save/restore)."""

    loss_fn = None  # set by subclass

    def __init__(self, config: AlgorithmConfig):
        self.config = config
        self.module_spec = config.module_spec()
        self.env_runner_group = EnvRunnerGroup(config, self.module_spec)
        self.learner_group = LearnerGroup(
            self.module_spec,
            type(self).loss_fn,
            loss_cfg=self._loss_cfg(),
            num_learners=config.num_learners,
            lr=config.lr,
            grad_clip=config.grad_clip,
            seed=config.seed,
            num_cpus_per_learner=config.num_cpus_per_learner,
            num_tpus_per_learner=config.num_tpus_per_learner,
        )
        self.iteration = 0
        self._total_env_steps = 0
        self.env_runner_group.sync_weights(self.learner_group.get_weights())

    def _loss_cfg(self) -> dict:
        return {}

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def train(self) -> Dict[str, Any]:
        t0 = time.time()
        result = self.training_step()
        self.iteration += 1
        result.setdefault("training_iteration", self.iteration)
        result["time_this_iter_s"] = time.time() - t0
        result["num_env_steps_sampled_lifetime"] = self._total_env_steps
        result["env_steps_per_sec"] = result.get("env_steps_this_iter", 0) / max(
            1e-9, result["time_this_iter_s"]
        )
        return result

    def evaluate(self, num_episodes: int = 5) -> float:
        return self.env_runner_group.evaluate(num_episodes)

    # -- checkpointing (reference: Checkpointable mixin,
    # rllib/utils/checkpoints.py; Algorithm.from_checkpoint) -------------
    def save(self, path: str) -> str:
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "learner_state.pkl"), "wb") as f:
            pickle.dump(self.learner_group.get_state(), f)
        with open(os.path.join(path, "algo_state.json"), "w") as f:
            json.dump(
                {"iteration": self.iteration, "total_env_steps": self._total_env_steps},
                f,
            )
        return path

    def restore(self, path: str):
        with open(os.path.join(path, "learner_state.pkl"), "rb") as f:
            self.learner_group.set_state(pickle.load(f))
        with open(os.path.join(path, "algo_state.json")) as f:
            st = json.load(f)
        self.iteration = st["iteration"]
        self._total_env_steps = st["total_env_steps"]
        self.env_runner_group.sync_weights(self.learner_group.get_weights())

    def stop(self):
        self.learner_group.shutdown()
