"""APPO — asynchronous PPO: IMPALA-style async sampling with the PPO
clipped-surrogate objective on V-trace-corrected advantages.

Reference: rllib/algorithms/appo/appo.py (APPOConfig: use_kl_loss,
kl_coeff/kl_target, clip_param, target-network update cadence) and
rllib/algorithms/appo/torch/appo_torch_learner.py (surrogate clip on the
behavior/target ratio, V-trace advantages, optional KL penalty toward
the behavior policy). The async control loop is inherited from our
IMPALA (saturated in-flight sample() calls, harvest-whichever-finished).
"""
from __future__ import annotations

from ray_tpu.rllib.impala import IMPALA, IMPALAConfig


def appo_loss(
    module,
    params,
    batch,
    clip_param: float = 0.2,
    vf_loss_coeff: float = 0.5,
    entropy_coeff: float = 0.005,
    use_kl_loss: bool = True,
    kl_coeff: float = 0.2,
):
    import jax.numpy as jnp

    out = module.logp_entropy(params, batch["obs"], batch["actions"])
    ratio = jnp.exp(out["logp"] - batch["logp_old"])
    adv = batch["pg_advantages"]
    surrogate = jnp.minimum(
        ratio * adv, jnp.clip(ratio, 1 - clip_param, 1 + clip_param) * adv
    )
    policy_loss = -jnp.mean(surrogate)
    vf_loss = 0.5 * jnp.mean((out["vf"] - batch["vtrace_targets"]) ** 2)
    entropy = jnp.mean(out["entropy"])
    # KL(behavior ‖ target) estimated from sampled actions (reference:
    # appo_torch_learner mean-KL penalty; keeps the target policy near
    # the behavior policy that generated the stale trajectories).
    approx_kl = jnp.mean(batch["logp_old"] - out["logp"])
    total = policy_loss + vf_loss_coeff * vf_loss - entropy_coeff * entropy
    if use_kl_loss:
        total = total + kl_coeff * approx_kl
    return total, {
        "policy_loss": policy_loss,
        "vf_loss": vf_loss,
        "entropy": entropy,
        "approx_kl": approx_kl,
    }


class APPOConfig(IMPALAConfig):
    def __init__(self):
        super().__init__()
        self.clip_param = 0.2
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.005
        self.use_kl_loss = True
        self.kl_coeff = 0.2
        self.num_epochs = 1  # async: one pass over each harvested batch

    def build(self) -> "APPO":
        return APPO(self)


class APPO(IMPALA):
    loss_fn = staticmethod(appo_loss)

    def _loss_cfg(self) -> dict:
        c = self.config
        return dict(
            clip_param=c.clip_param,
            vf_loss_coeff=c.vf_loss_coeff,
            entropy_coeff=c.entropy_coeff,
            use_kl_loss=c.use_kl_loss,
            kl_coeff=c.kl_coeff,
        )

    # The shared VtraceBatchBuilder already carries the behavior logps
    # (``logp_old``) the surrogate ratio needs — no batch override.
