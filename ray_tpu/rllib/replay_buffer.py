"""Replay buffers for off-policy algorithms.

Reference: rllib/utils/replay_buffers/ (EpisodeReplayBuffer,
PrioritizedEpisodeReplayBuffer). Re-designed around flat numpy transition
arrays instead of episode lists: the learner consumes fixed-shape
minibatches, which keeps the jitted TPU update static-shaped, and numpy
ring buffers make sampling O(batch) with no per-episode bookkeeping.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.rllib.episodes import SingleAgentEpisode


def episodes_to_transitions(
    episodes: List[SingleAgentEpisode],
) -> Dict[str, np.ndarray]:
    """Flatten episodes into (obs, action, reward, next_obs, done) arrays.

    ``done`` is 1 only on a *terminated* final transition — truncation
    (fragment cut or time limit) still bootstraps through next_obs.
    """
    obs, acts, rews, nobs, dones = [], [], [], [], []
    for ep in episodes:
        T = len(ep)
        if T == 0:
            continue
        o = np.asarray(ep.observations, dtype=np.float32)  # [T+1, d]
        obs.append(o[:T])
        nobs.append(o[1 : T + 1])
        acts.append(np.asarray(ep.actions, dtype=np.int32))
        rews.append(np.asarray(ep.rewards, dtype=np.float32))
        d = np.zeros(T, dtype=np.float32)
        if ep.terminated:
            d[-1] = 1.0
        dones.append(d)
    return {
        "obs": np.concatenate(obs),
        "actions": np.concatenate(acts),
        "rewards": np.concatenate(rews),
        "next_obs": np.concatenate(nobs),
        "dones": np.concatenate(dones),
    }


class ReplayBuffer:
    """Uniform ring buffer over flat transitions."""

    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = capacity
        self._store: Optional[Dict[str, np.ndarray]] = None
        self._size = 0
        self._next = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def add_episodes(self, episodes: List[SingleAgentEpisode]):
        batch = episodes_to_transitions(episodes)
        n = len(batch["obs"])
        if n == 0:
            return
        if self._store is None:
            self._store = {
                k: np.zeros((self.capacity,) + v.shape[1:], v.dtype)
                for k, v in batch.items()
            }
        for ofs in range(0, n, self.capacity):
            chunk = {k: v[ofs : ofs + self.capacity] for k, v in batch.items()}
            m = len(chunk["obs"])
            idx = (self._next + np.arange(m)) % self.capacity
            for k, v in chunk.items():
                self._store[k][idx] = v
            self._next = int((self._next + m) % self.capacity)
            self._size = min(self.capacity, self._size + m)

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self._size, batch_size)
        out = {k: v[idx] for k, v in self._store.items()}
        out["weights"] = np.ones(batch_size, np.float32)
        out["idx"] = idx.astype(np.int64)
        return out

    def update_priorities(self, idx: np.ndarray, priorities: np.ndarray):
        pass  # uniform buffer: no-op


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized replay (reference:
    PrioritizedEpisodeReplayBuffer; Schaul et al. PER). Priorities are
    kept as a flat array and sampling normalizes on the fly — at the
    transition counts an RL learner on one host sees, the O(n) normalize
    is cheaper than maintaining a sum-tree in Python."""

    def __init__(self, capacity: int, alpha: float = 0.6, beta: float = 0.4, seed: int = 0):
        super().__init__(capacity, seed)
        self.alpha = alpha
        self.beta = beta
        self._prios = np.zeros(capacity, np.float32)
        self._max_prio = 1.0

    def add_episodes(self, episodes: List[SingleAgentEpisode]):
        before_next = self._next
        n = min(sum(len(ep) for ep in episodes), self.capacity)
        super().add_episodes(episodes)
        # New transitions enter at max priority so they are seen at least once.
        idx = (before_next + np.arange(n)) % self.capacity
        self._prios[idx] = self._max_prio

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        p = self._prios[: self._size] ** self.alpha
        p = p / p.sum()
        idx = self._rng.choice(self._size, batch_size, p=p)
        out = {k: v[idx] for k, v in self._store.items()}
        # Importance weights, normalized by the max for stability.
        w = (self._size * p[idx]) ** (-self.beta)
        out["weights"] = (w / w.max()).astype(np.float32)
        out["idx"] = idx.astype(np.int64)
        return out

    def update_priorities(self, idx: np.ndarray, priorities: np.ndarray):
        prios = np.abs(priorities) + 1e-6
        self._prios[idx] = prios
        self._max_prio = max(self._max_prio, float(prios.max()))
