"""CQL — conservative Q-learning for offline RL (discrete).

Reference: rllib/algorithms/cql/cql.py + cql_torch_learner.py: SAC's
twin-Q soft-Bellman machinery plus the conservative regularizer
``E_s[logsumexp_a Q(s,a)] - E_{(s,a)~D}[Q(s,a)]`` that pushes down
out-of-distribution action values; trained purely from a fixed dataset
(offline_data.py path), evaluated by rolling out the learned policy.

TPU shape: one fused jitted update (critics + actor + temperature +
conservative term in a single loss) rather than the reference's separate
optimizer passes — the whole update is one XLA program.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.off_policy import OffPolicyAlgorithm, OffPolicyConfig
from ray_tpu.rllib.rl_module import RLModuleSpec
from ray_tpu.rllib.sac import sac_loss
from ray_tpu.rllib.episodes import SingleAgentEpisode


def cql_loss(
    module,
    params,
    batch,
    gamma: float = 0.99,
    target_entropy: float = -1.0,
    cql_alpha: float = 1.0,
):
    """SAC loss + conservative penalty on both critics."""
    import jax.numpy as jnp

    base, metrics = sac_loss(
        module, params, batch, gamma=gamma, target_entropy=target_entropy
    )
    out = module.forward_train(params, batch["obs"])
    q1, q2 = out["q1"], out["q2"]
    ar = jnp.arange(batch["obs"].shape[0])
    data_q1 = q1[ar, batch["actions"]]
    data_q2 = q2[ar, batch["actions"]]
    # logsumexp over the action set = soft-maximum of OOD action values
    # (discrete CQL(H); reference: cql_torch_learner's cql_loss term).
    gap1 = jnp.mean(_logsumexp(q1) - data_q1)
    gap2 = jnp.mean(_logsumexp(q2) - data_q2)
    penalty = cql_alpha * (gap1 + gap2)
    loss = base + penalty
    metrics = dict(metrics)
    metrics["cql_penalty"] = penalty
    return loss, metrics


def _logsumexp(q):
    from jax import nn

    return nn.logsumexp(q, axis=-1)


class CQLConfig(OffPolicyConfig):
    def __init__(self):
        super().__init__()
        self.lr = 3e-4
        self.cql_alpha = 1.0
        self.target_entropy = -1.0
        self.target_update_freq = 100
        self.num_updates_per_iter = 64
        self.train_batch_size = 128
        self._offline_episodes: Optional[List[SingleAgentEpisode]] = None

    def offline_data(self, episodes: List[SingleAgentEpisode]) -> "CQLConfig":
        self._offline_episodes = episodes
        return self

    def module_spec(self) -> RLModuleSpec:
        spec = super().module_spec()
        spec.kind = "sac"
        return spec

    def build(self) -> "CQL":
        return CQL(self)


class CQL(OffPolicyAlgorithm):
    """Offline variant of the off-policy loop: the replay buffer is seeded
    once from the dataset and training_step never samples the env
    (_sync_target and the target machinery are inherited)."""

    loss_fn = staticmethod(cql_loss)
    target_pairs = (("q1", "q1_target"), ("q2", "q2_target"))

    def __init__(self, config: CQLConfig):
        if config._offline_episodes is None:
            raise ValueError("CQL requires .offline_data(episodes)")
        # Size the buffer to hold the full dataset before the base class
        # builds it.
        config.buffer_size = max(
            config.buffer_size, sum(len(e) for e in config._offline_episodes)
        )
        super().__init__(config)
        self.buffer.add_episodes(config._offline_episodes)

    def _loss_cfg(self) -> dict:
        c = self.config
        return dict(
            gamma=c.gamma, target_entropy=c.target_entropy, cql_alpha=c.cql_alpha
        )

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        metrics: Dict[str, float] = {}
        for _ in range(cfg.num_updates_per_iter):
            mb = self.buffer.sample(cfg.train_batch_size)
            mb.pop("idx", None)
            metrics = self.learner_group.update_from_batch(mb)
            metrics.pop("td_errors", None)
            self._num_updates += 1
            if self._num_updates % cfg.target_update_freq == 0:
                self._sync_target()
        self.env_runner_group.sync_weights(self.learner_group.get_weights())
        return {
            "env_steps_this_iter": 0,
            "num_learner_updates": self._num_updates,
            **{f"learner/{k}": v for k, v in metrics.items() if np.ndim(v) == 0},
        }
