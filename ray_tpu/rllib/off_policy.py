"""Shared off-policy training loop (DQN/SAC).

Reference: rllib/algorithms/dqn/dqn.py training_step — sample →
replay-buffer add → N replay updates → periodic target-net sync →
weight sync to runners. The loop is algorithm-agnostic; the loss and the
module family differ.
"""
from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.replay_buffer import PrioritizedReplayBuffer, ReplayBuffer


class OffPolicyConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.buffer_size = 50_000
        self.prioritized_replay = False
        self.per_alpha = 0.6
        self.per_beta = 0.4
        self.learning_starts = 1000
        self.target_update_freq = 200  # in learner updates
        self.num_updates_per_iter = 32
        self.train_batch_size = 64
        self.rollout_fragment_length = 4
        self.lr = 1e-3
        self.gamma = 0.99


class OffPolicyAlgorithm(Algorithm):
    # Names of param subtrees to copy online → target on sync.
    target_pairs = ()  # e.g. (("q", "target"),)

    def __init__(self, config: OffPolicyConfig):
        super().__init__(config)
        if config.prioritized_replay:
            self.buffer = PrioritizedReplayBuffer(
                config.buffer_size, config.per_alpha, config.per_beta, seed=config.seed
            )
        else:
            self.buffer = ReplayBuffer(config.buffer_size, seed=config.seed)
        self._num_updates = 0

    # -- target networks -------------------------------------------------
    def _sync_target(self):
        """Hard-copy online → target subtrees (reference: DQN
        target_network_update_freq)."""
        import jax

        state = self.learner_group.get_state()
        params = state["params"]
        for online, target in type(self).target_pairs:
            params[target] = jax.tree.map(lambda x: x, params[online])
        self.learner_group.set_state(state)

    def _explore_hook(self, weights: Dict[str, Any]) -> Dict[str, Any]:
        """Subclass hook: mutate the weights shipped to runners (e.g. set
        the ε-greedy schedule value)."""
        return weights

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        n_sources = max(1, self.env_runner_group.num_remote_runners)
        episodes = self.env_runner_group.sample(
            cfg.rollout_fragment_length * n_sources * cfg.num_envs_per_runner
        )
        env_steps = sum(len(e) for e in episodes)
        self._total_env_steps += env_steps
        self.buffer.add_episodes(episodes)

        metrics: Dict[str, float] = {}
        if len(self.buffer) >= cfg.learning_starts:
            for _ in range(cfg.num_updates_per_iter):
                mb = self.buffer.sample(cfg.train_batch_size)
                idx = mb.pop("idx")
                metrics = self.learner_group.update_from_batch(mb)
                td = metrics.pop("td_errors", None)
                if td is not None:
                    # The learner may pad the batch to its device-mesh size;
                    # padded rows carry no buffer slot.
                    self.buffer.update_priorities(idx, np.asarray(td)[: len(idx)])
                self._num_updates += 1
                if self._num_updates % cfg.target_update_freq == 0:
                    self._sync_target()

        weights = dict(self.learner_group.get_weights())
        self.env_runner_group.sync_weights(self._explore_hook(weights))

        returns = self.env_runner_group.pop_metrics()
        if returns:
            self._recent_returns = (getattr(self, "_recent_returns", []) + returns)[-100:]
        mean_ret = (
            float(np.mean(self._recent_returns))
            if getattr(self, "_recent_returns", None)
            else 0.0
        )
        return {
            "env_steps_this_iter": env_steps,
            "episode_return_mean": mean_ret,
            "num_episodes": len(returns),
            "buffer_size": len(self.buffer),
            "num_learner_updates": self._num_updates,
            **{f"learner/{k}": v for k, v in metrics.items() if np.ndim(v) == 0},
        }
