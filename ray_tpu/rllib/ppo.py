"""PPO — clipped surrogate objective on GAE advantages.

Reference: rllib/algorithms/ppo/ppo.py:378 (training_step :413 — sample →
learner update → sync weights) and ppo_learner's loss
(rllib/algorithms/ppo/torch/ppo_torch_learner.py): ratio clip, value-loss
clip, entropy bonus, KL early-stop.
"""
from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.episodes import episodes_to_batch


def ppo_loss(
    module,
    params,
    batch,
    clip_param: float = 0.2,
    vf_clip_param: float = 10.0,
    vf_loss_coeff: float = 0.5,
    entropy_coeff: float = 0.0,
):
    import jax.numpy as jnp

    out = module.logp_entropy(params, batch["obs"], batch["actions"])
    ratio = jnp.exp(out["logp"] - batch["logp_old"])
    adv = batch["advantages"]
    surrogate = jnp.minimum(
        ratio * adv, jnp.clip(ratio, 1 - clip_param, 1 + clip_param) * adv
    )
    policy_loss = -jnp.mean(surrogate)
    # clipped value loss (reference: ppo_torch_learner vf_clip)
    vf_err = (out["vf"] - batch["returns"]) ** 2
    vf_clipped = batch["values_old"] + jnp.clip(
        out["vf"] - batch["values_old"], -vf_clip_param, vf_clip_param
    )
    vf_err_clipped = (vf_clipped - batch["returns"]) ** 2
    vf_loss = 0.5 * jnp.mean(jnp.maximum(vf_err, vf_err_clipped))
    entropy = jnp.mean(out["entropy"])
    total = policy_loss + vf_loss_coeff * vf_loss - entropy_coeff * entropy
    approx_kl = jnp.mean(batch["logp_old"] - out["logp"])
    return total, {
        "policy_loss": policy_loss,
        "vf_loss": vf_loss,
        "entropy": entropy,
        "approx_kl": approx_kl,
    }


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.clip_param = 0.2
        self.vf_clip_param = 10.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.0
        self.kl_target = 0.02

    def build(self) -> "PPO":
        return PPO(self)


class PPO(Algorithm):
    loss_fn = staticmethod(ppo_loss)
    supports_podracer = True

    def _loss_cfg(self) -> dict:
        c = self.config
        return dict(
            clip_param=c.clip_param,
            vf_clip_param=c.vf_clip_param,
            vf_loss_coeff=c.vf_loss_coeff,
            entropy_coeff=c.entropy_coeff,
        )

    # -- podracer (Sebulba async) overrides -------------------------------
    def _podracer_builder_kwargs(self) -> dict:
        kw = super()._podracer_builder_kwargs()
        kw["normalize_advantages"] = True
        return kw

    def _podracer_min_batch_env_steps(self) -> int:
        # PPO keeps its epoch semantics: one full train batch per cycle.
        return self.config.train_batch_size

    def _minibatch_epochs(self, batch) -> Dict[str, float]:
        """The PPO learner cycle (reference: learner minibatch cycle):
        ``num_epochs`` seeded-permutation passes of ``minibatch_size``
        updates with the KL early-stop. Shared by the synchronous loop
        (GAE batches) and the podracer path (V-trace batches, IMPACT-style
        surrogate against the BEHAVIOUR logp)."""
        cfg = self.config
        rows = len(batch["obs"])
        rng = np.random.default_rng(cfg.seed + self.iteration)
        metrics: Dict[str, float] = {}
        for _ in range(cfg.num_epochs):
            order = rng.permutation(rows)
            for lo in range(0, rows, cfg.minibatch_size):
                idx = order[lo : lo + cfg.minibatch_size]
                mb = {k: v[idx] for k, v in batch.items()}
                metrics = self.learner_group.update_from_batch(mb)
            if metrics.get("approx_kl", 0.0) > 1.5 * cfg.kl_target:
                break  # KL early-stop (reference: ppo kl coeff logic)
        return metrics

    _podracer_update_fn = _minibatch_epochs

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        if self._podracer is not None:
            return self._podracer_training_step()
        # 1. sample (reference: ppo.py:418 synchronous_parallel_sample)
        episodes = self.env_runner_group.sample(cfg.train_batch_size)
        env_steps = sum(len(e) for e in episodes)
        self._total_env_steps += env_steps
        batch = episodes_to_batch(episodes, gamma=cfg.gamma, lam=cfg.lam)
        # 2. minibatch-epoch updates
        metrics = self._minibatch_epochs(batch)
        # 3. sync weights to runners (reference: ppo.py:500)
        self.env_runner_group.sync_weights(self.learner_group.get_weights())
        returns = self.env_runner_group.pop_metrics()
        mean_ret = self._record_returns(returns)
        return {
            "env_steps_this_iter": env_steps,
            "episode_return_mean": mean_ret,
            "num_episodes": len(returns),
            **{f"learner/{k}": v for k, v in metrics.items()},
        }
