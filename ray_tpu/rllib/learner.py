"""Learner + LearnerGroup: the gradient-update side of the RL stack.

Reference: rllib/core/learner/learner.py:117 (Learner — owns optimizer +
loss, ``update_from_batch`` :954) and learner_group.py:79 (local or N
remote learner actors). The reference syncs gradients with torch DDP
across learner actors (torch_rl_module.py:160); here the TPU-native
replacements are:

- single learner, N local devices: the update step is one jit over the
  device mesh — batch sharded on the 'dp' axis, params replicated, and
  XLA inserts the psum for the gradient mean (in-graph, rides ICI).
- N learner actors (multi-host): each actor runs the jitted update on its
  shard and gradients are allreduced through ray_tpu.collective's host
  group (the torch-DDP-across-actors analogue).
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.rllib.rl_module import Params, RLModule, RLModuleSpec, make_module

LossFn = Callable[..., Any]  # (module, params, batch, **cfg) -> (loss, metrics)


class Learner:
    """Owns params + optax optimizer + a jitted, mesh-aware update."""

    def __init__(
        self,
        module_spec: RLModuleSpec,
        loss_fn: LossFn,
        loss_cfg: Optional[dict] = None,
        lr: float = 3e-4,
        grad_clip: float = 0.5,
        seed: int = 0,
        use_device_mesh: bool = True,
        collective_group: Optional[str] = None,
        world_size: int = 1,
        rank: int = 0,
    ):
        import jax
        import optax

        self.module = make_module(module_spec)
        self.params = self.module.init_params(jax.random.PRNGKey(seed))
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(grad_clip), optax.adam(lr)
        )
        self.opt_state = self.optimizer.init(self.params)
        self._loss_fn = loss_fn
        self._loss_cfg = loss_cfg or {}
        self._collective_group = collective_group
        self._world_size = world_size
        self._rank = rank
        self._build_update(use_device_mesh)

    # -- the TPU-native "DDP": in-graph psum over the device mesh --------
    def _build_update(self, use_device_mesh: bool):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        module, loss_fn, cfg = self.module, self._loss_fn, self._loss_cfg

        def update(params, opt_state, batch):
            def scalar_loss(p):
                loss, metrics = loss_fn(module, p, batch, **cfg)
                return loss, metrics

            (loss, metrics), grads = jax.value_and_grad(scalar_loss, has_aux=True)(
                params
            )
            updates, new_opt = self.optimizer.update(grads, opt_state, params)
            import optax

            new_params = optax.apply_updates(params, updates)
            metrics = dict(metrics)
            metrics["loss"] = loss
            metrics["grad_norm"] = optax.global_norm(grads)
            return new_params, new_opt, metrics, grads

        devs = jax.local_devices()
        if use_device_mesh and len(devs) > 1:
            # Batch rows sharded over 'dp'; params replicated. XLA emits the
            # gradient-mean psum inside the compiled program (ICI path).
            self.mesh = Mesh(np.array(devs), ("dp",))
            batch_sharding = NamedSharding(self.mesh, P("dp"))
            repl = NamedSharding(self.mesh, P())
            self._update = jax.jit(
                update,
                in_shardings=(repl, repl, batch_sharding),
                out_shardings=(repl, repl, repl, repl),
            )
        else:
            self.mesh = None
            self._update = jax.jit(update)

    # -- API -------------------------------------------------------------
    def update_from_batch(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        import jax
        import jax.numpy as jnp

        rows = len(next(iter(batch.values()))) if batch else 0
        if rows == 0:
            # Empty shard (the driver split fewer rows than learners):
            # skip the jitted update — a zero-row batch crashes it — but
            # when this learner replica syncs gradients over a collective
            # group it MUST still join the allreduce with zero grads and
            # apply the averaged update, or the peer ranks hang and the
            # replicas drift apart.
            if self._collective_group is not None and self._world_size > 1:
                self._sync_and_apply(
                    jax.tree.map(jnp.zeros_like, self.params), contributed=False
                )
            return {}
        if self.mesh is not None:
            # pad batch rows to a multiple of the mesh size
            n = len(jax.local_devices())
            rows = len(next(iter(batch.values())))
            pad = (-rows) % n
            if pad:
                batch = {
                    k: np.concatenate([v, np.repeat(v[-1:], pad, axis=0)])
                    for k, v in batch.items()
                }
        batch = {k: jnp.asarray(v) for k, v in batch.items()}
        new_params, new_opt, metrics, grads = self._update(
            self.params, self.opt_state, batch
        )
        if self._collective_group is not None and self._world_size > 1:
            self._sync_and_apply(grads)
        else:
            self.params, self.opt_state = new_params, new_opt
        out = {}
        for k, v in metrics.items():
            arr = np.asarray(v)
            # Scalars become floats; per-sample arrays (e.g. td_errors for
            # prioritized replay) pass through.
            out[k] = float(arr) if arr.ndim == 0 else arr
        return out

    def _sync_and_apply(self, grads, contributed: bool = True):
        """Cross-actor gradient sync (the torch-DDP analogue): average
        grads over the host collective, then re-apply locally so all
        learner replicas stay bit-identical. Every rank must call this
        once per update — including empty-shard ranks, with zero grads
        and ``contributed=False``. The mean divides by the number of
        CONTRIBUTING ranks (allreduced alongside the grads), so empty
        shards don't silently dilute the averaged gradient."""
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu import collective
        from ray_tpu.collective.types import ReduceOp

        k = collective.allreduce(
            np.asarray([1.0 if contributed else 0.0], dtype=np.float32),
            group_name=self._collective_group,
            op=ReduceOp.SUM,
        )
        denom = max(1.0, float(k[0]))
        flat, treedef = jax.tree.flatten(grads)
        avg = []
        for g in flat:
            arr = np.asarray(g, dtype=np.float32) / denom
            arr = collective.allreduce(
                arr, group_name=self._collective_group, op=ReduceOp.SUM
            )
            avg.append(jnp.asarray(arr))
        grads = jax.tree.unflatten(treedef, avg)
        updates, self.opt_state = self.optimizer.update(
            grads, self.opt_state, self.params
        )
        self.params = optax.apply_updates(self.params, updates)

    def get_weights(self) -> Params:
        return self.params

    def set_weights(self, params: Params):
        self.params = params

    def get_state(self) -> dict:
        return {"params": self.params, "opt_state": self.opt_state}

    def set_state(self, state: dict):
        self.params = state["params"]
        self.opt_state = state["opt_state"]

    def ping(self) -> str:
        return "pong"


class _RemoteLearner(Learner):
    """Actor wrapper that joins the gradient-sync collective group."""

    def __init__(self, group_name: str, world_size: int, rank: int, **kw):
        from ray_tpu import collective

        collective.init_collective_group(
            world_size=world_size, rank=rank, group_name=group_name
        )
        super().__init__(
            collective_group=group_name, world_size=world_size, rank=rank, **kw
        )


class LearnerGroup:
    """Reference: rllib/core/learner/learner_group.py:79 — local mode (one
    in-process learner, mesh-parallel over local devices) or remote mode
    (N learner actors with collective grad sync)."""

    def __init__(
        self,
        module_spec: RLModuleSpec,
        loss_fn: LossFn,
        loss_cfg: Optional[dict] = None,
        num_learners: int = 0,
        lr: float = 3e-4,
        grad_clip: float = 0.5,
        seed: int = 0,
        num_cpus_per_learner: float = 1,
        num_tpus_per_learner: float = 0,
    ):
        self._num = num_learners
        if num_learners <= 0:
            self._local = Learner(
                module_spec, loss_fn, loss_cfg, lr=lr, grad_clip=grad_clip, seed=seed
            )
            self._actors = []
        else:
            import ray_tpu
            import time

            self._local = None
            group_name = f"learners_{int(time.time()*1e6)}"
            cls = ray_tpu.remote(
                num_cpus=num_cpus_per_learner, num_tpus=num_tpus_per_learner
            )(_RemoteLearner)
            self._actors = [
                cls.remote(
                    group_name,
                    num_learners,
                    rank,
                    module_spec=module_spec,
                    loss_fn=loss_fn,
                    loss_cfg=loss_cfg,
                    lr=lr,
                    grad_clip=grad_clip,
                    seed=seed,
                    use_device_mesh=False,
                )
                for rank in range(num_learners)
            ]
            for a in self._actors:
                ray_tpu.wait_actor_ready(a)

    def update_from_batch(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        if self._local is not None:
            return self._local.update_from_batch(batch)
        import ray_tpu

        n = len(self._actors)
        rows = len(next(iter(batch.values())))
        shard = max(1, rows // n)
        refs = []
        for i, a in enumerate(self._actors):
            lo = min(i * shard, rows)
            hi = rows if i == n - 1 else min((i + 1) * shard, rows)
            # rows < n leaves trailing actors with EMPTY slices; they are
            # still called (every rank must join the gradient allreduce)
            # but the Learner skips the jitted update for them.
            refs.append(
                a.update_from_batch.remote({k: v[lo:hi] for k, v in batch.items()})
            )
        all_metrics = [m for m in ray_tpu.get(refs) if m]
        out = {}
        for k in all_metrics[0] if all_metrics else ():
            vals = [m[k] for m in all_metrics]
            if np.ndim(vals[0]) == 0:
                out[k] = float(np.mean(vals))
            else:
                # Per-sample arrays: shards were contiguous row ranges in
                # order, so concatenation restores batch order.
                out[k] = np.concatenate(vals)
        return out

    def get_weights(self) -> Params:
        if self._local is not None:
            return self._local.get_weights()
        import ray_tpu

        return ray_tpu.get(self._actors[0].get_weights.remote())

    def get_state(self) -> dict:
        if self._local is not None:
            return self._local.get_state()
        import ray_tpu

        return ray_tpu.get(self._actors[0].get_state.remote())

    def set_state(self, state: dict):
        if self._local is not None:
            self._local.set_state(state)
        else:
            import ray_tpu

            ray_tpu.get([a.set_state.remote(state) for a in self._actors])

    def shutdown(self):
        import ray_tpu

        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
