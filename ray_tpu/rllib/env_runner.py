"""SingleAgentEnvRunner: vectorized gymnasium sampling actor.

Reference: rllib/env/single_agent_env_runner.py:61 (``sample`` :131 —
vector env stepping with an inference-only module + connectors). Runs as
a CPU actor; the policy forward is jitted once (CPU backend) and stepped
over the vector env.
"""
from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.episodes import SingleAgentEpisode
from ray_tpu.rllib.rl_module import RLModuleSpec, make_module


def _make_env(env_spec):
    import gymnasium as gym

    if callable(env_spec):
        return env_spec()
    return gym.make(env_spec)


class SingleAgentEnvRunner:
    """Samples episodes with the current policy weights.

    Runs standalone (local mode) or as a remote actor in an
    EnvRunnerGroup.
    """

    def __init__(
        self,
        env_spec: Any,
        module_spec: RLModuleSpec,
        num_envs: int = 1,
        seed: int = 0,
        worker_index: int = 0,
    ):
        import jax

        self._envs = [_make_env(env_spec) for _ in range(num_envs)]
        self._num_envs = num_envs
        self._seed = seed
        self.worker_index = worker_index
        self.module = make_module(module_spec)
        self.params = self.module.init_params(jax.random.PRNGKey(seed))
        self._key = jax.random.PRNGKey(seed * 100003 + worker_index)
        self._explore = jax.jit(self.module.forward_exploration)
        self._obs = [env.reset(seed=self._env_seed(i))[0] for i, env in enumerate(self._envs)]
        self._episodes = [SingleAgentEpisode(observations=[o]) for o in self._obs]
        self._weights_version = 0
        # true per-episode returns across fragment cuts (metrics only)
        self._return_acc = [0.0] * num_envs
        self._completed_returns: List[float] = []

    def _env_seed(self, i: int) -> int:
        """Per-env reset seed: the construction-time scheme, also used
        when evaluate() re-seeds the clobbered vector env."""
        return self._seed + self.worker_index * 1000 + i

    # -- weight sync (reference: env_runner_group.sync_weights) ----------
    def set_state(self, params, weights_version: int = 0):
        import jax

        self.params = jax.tree.map(lambda x: x, params)
        self._weights_version = weights_version

    def get_state(self):
        return {"params": self.params, "weights_version": self._weights_version}

    def ping(self) -> str:
        return "pong"

    def sample(self, num_env_steps: int, explore: bool = True) -> List[SingleAgentEpisode]:
        """Step all envs until ``num_env_steps`` total steps are collected;
        returns completed episodes plus truncated in-progress chunks (each
        with a bootstrap value)."""
        import jax
        import jax.numpy as jnp

        done_eps: List[SingleAgentEpisode] = []
        steps = 0
        while steps < num_env_steps:
            obs_batch = np.stack(self._obs).astype(np.float32)
            self._key, sub = jax.random.split(self._key)
            out = self._explore(self.params, jnp.asarray(obs_batch), sub)
            actions = np.asarray(out["action"])
            logps = np.asarray(out["logp"])
            values = np.asarray(out["vf"])
            for i, env in enumerate(self._envs):
                act = int(actions[i])
                nobs, rew, term, trunc, _ = env.step(act)
                ep = self._episodes[i]
                ep.actions.append(act)
                ep.rewards.append(float(rew))
                ep.logps.append(float(logps[i]))
                ep.values.append(float(values[i]))
                ep.observations.append(nobs)
                steps += 1
                self._return_acc[i] += float(rew)
                if term or trunc:
                    self._completed_returns.append(self._return_acc[i])
                    self._return_acc[i] = 0.0
                if term or trunc:
                    ep.terminated = bool(term)
                    ep.truncated = bool(trunc)
                    if trunc:
                        ep.final_value = self._bootstrap_value(nobs)
                    done_eps.append(ep)
                    nobs = env.reset()[0]
                    self._episodes[i] = SingleAgentEpisode(observations=[nobs])
                self._obs[i] = nobs
        # cut in-progress episodes, bootstrapping their final value
        for i in range(self._num_envs):
            ep = self._episodes[i]
            if len(ep) > 0:
                ep.truncated = True
                ep.final_value = self._bootstrap_value(self._obs[i])
                done_eps.append(ep)
                self._episodes[i] = SingleAgentEpisode(observations=[self._obs[i]])
        return done_eps

    def _bootstrap_value(self, obs) -> float:
        """V(s) for truncation bootstrap; value-less module families
        (DQN/SAC) return 0 — their losses bootstrap through next_obs in
        the replay buffer instead."""
        import jax.numpy as jnp

        out = self.module.forward_train(
            self.params, jnp.asarray(obs[None].astype(np.float32))
        )
        if "vf" not in out:
            return 0.0
        return float(np.asarray(out["vf"])[0])

    def pop_metrics(self) -> List[float]:
        """Completed-episode returns since the last call (true returns,
        unaffected by fragment cuts)."""
        out = self._completed_returns
        self._completed_returns = []
        return out

    def evaluate(self, num_episodes: int = 5) -> float:
        """Mean greedy-policy return (deterministic eval)."""
        import jax
        import jax.numpy as jnp

        infer = jax.jit(self.module.forward_inference)
        total = 0.0
        env = self._envs[0]
        for e in range(num_episodes):
            obs, _ = env.reset(seed=10_000 + e)
            done = False
            while not done:
                act = int(np.asarray(infer(self.params, jnp.asarray(obs[None].astype(np.float32))))[0])
                obs, rew, term, trunc, _ = env.step(act)
                total += float(rew)
                done = term or trunc
        # Runner state was clobbered; reset in-progress episodes with the
        # SAME construction-time seed scheme — ``seed=i`` here silently
        # collapsed every runner onto identical episode streams post-eval,
        # perturbing cross-runner determinism.
        self._obs = [env.reset(seed=self._env_seed(i))[0] for i, env in enumerate(self._envs)]
        self._episodes = [SingleAgentEpisode(observations=[o]) for o in self._obs]
        return total / num_episodes
