"""Multi-agent RL: env API, episodes, env runner, MultiRLModule, MA-PPO.

Reference: rllib/env/multi_agent_env.py (dict-keyed step/reset protocol
with the ``__all__`` termination key), rllib/env/multi_agent_env_runner.py
(per-agent episode accounting while agents join/leave), and
rllib/core/rl_module/multi_rl_module.py (module_id -> RLModule with a
policy_mapping_fn routing agents onto shared or private policies).

TPU shape: each policy's update is an independent jitted step; agents
mapped to the same module batch together, so shared policies see one
large MXU-friendly batch instead of per-agent fragments.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.actor_manager import FaultTolerantActorManager
from ray_tpu.rllib.dqn import DQNConfig
from ray_tpu.rllib.episodes import SingleAgentEpisode, episodes_to_batch
from ray_tpu.rllib.learner import LearnerGroup
from ray_tpu.rllib.ppo import PPOConfig, ppo_loss
from ray_tpu.rllib.rl_module import RLModuleSpec, make_module


class MultiAgentEnv:
    """Reference: rllib/env/multi_agent_env.py. Subclasses define
    ``possible_agents``, ``observation_spaces``/``action_spaces`` (dicts)
    and the dict-keyed reset/step protocol; terminateds/truncateds carry
    the ``__all__`` aggregate key."""

    possible_agents: List[str] = []

    def reset(self, *, seed: Optional[int] = None):
        raise NotImplementedError

    def step(self, action_dict: Dict[str, int]):
        raise NotImplementedError

    def close(self):
        pass


class MultiAgentEpisode:
    """Per-agent SingleAgentEpisodes sharing one env rollout (reference:
    rllib/env/multi_agent_episode.py)."""

    def __init__(self):
        self.agent_episodes: Dict[str, SingleAgentEpisode] = {}

    def add_reset(self, agent_id: str, obs):
        self.agent_episodes[agent_id] = SingleAgentEpisode(observations=[obs])

    def total_reward(self) -> float:
        return sum(ep.total_reward for ep in self.agent_episodes.values())


class MultiAgentEnvRunner:
    """Samples a multi-agent env with one inference module per policy.

    Reference: rllib/env/multi_agent_env_runner.py:  sample() steps the
    env with the joint action dict; episodes are cut per agent; the
    policy_mapping_fn routes each agent onto its module's params."""

    def __init__(
        self,
        env_spec: Any,
        module_specs: Dict[str, RLModuleSpec],
        policy_mapping_fn: Callable[[str], str],
        seed: int = 0,
        worker_index: int = 0,
    ):
        import zlib

        import jax

        self._env = env_spec() if callable(env_spec) else env_spec
        self.modules = {mid: make_module(spec) for mid, spec in module_specs.items()}
        # crc32, not hash(): str hash is randomized per process, which would
        # make param init nondeterministic despite an explicit seed.
        self.params = {
            mid: m.init_params(
                jax.random.PRNGKey(seed + zlib.crc32(mid.encode()) % 10000)
            )
            for mid, m in self.modules.items()
        }
        self._mapping = policy_mapping_fn
        self._explore = {
            mid: jax.jit(m.forward_exploration) for mid, m in self.modules.items()
        }
        self._key = jax.random.PRNGKey(seed * 100003 + worker_index + 17)
        self._seed = seed + worker_index * 1000
        self._reset_env()
        self.worker_index = worker_index
        self._weights_version = 0
        self._completed_returns: List[float] = []
        self._return_acc = 0.0

    def _reset_env(self):
        obs, _ = self._env.reset(seed=self._seed)
        self._seed += 1
        self._obs: Dict[str, Any] = dict(obs)
        self._ma_episode = MultiAgentEpisode()
        # Rewards paid to an agent before its first action of the episode
        # (reference: multi_agent_episode hanging rewards).
        self._hanging_rewards: Dict[str, float] = {}
        for aid, o in obs.items():
            self._ma_episode.add_reset(aid, o)

    def set_state(self, params: Dict[str, Any], weights_version: int = 0):
        import jax

        self.params = jax.tree.map(lambda x: x, params)
        self._weights_version = weights_version

    def ping(self) -> str:
        return "pong"

    def _act(self, obs_by_agent: Dict[str, Any]):
        """Joint action via per-module batched inference: agents sharing a
        module are stacked into one forward pass."""
        import jax
        import jax.numpy as jnp

        by_module: Dict[str, List[str]] = {}
        for aid in obs_by_agent:
            by_module.setdefault(self._mapping(aid), []).append(aid)
        actions, logps, values = {}, {}, {}
        for mid, aids in by_module.items():
            batch = np.stack([np.asarray(obs_by_agent[a], dtype=np.float32) for a in aids])
            self._key, sub = jax.random.split(self._key)
            out = self._explore[mid](self.params[mid], jnp.asarray(batch), sub)
            for j, a in enumerate(aids):
                actions[a] = int(np.asarray(out["action"])[j])
                logps[a] = float(np.asarray(out["logp"])[j])
                values[a] = float(np.asarray(out["vf"])[j])
        return actions, logps, values

    def _bootstrap(self, mid: str, obs) -> float:
        import jax.numpy as jnp

        module = self.modules[mid]
        out = module.forward_train(self.params[mid], jnp.asarray(np.asarray(obs, dtype=np.float32))[None])
        if "vf" in out:
            return float(np.asarray(out["vf"])[0])
        if "q" in out:  # value-based modules: V(s) ≈ max_a Q(s, a)
            return float(np.asarray(out["q"]).max())
        return 0.0

    def sample(self, num_env_steps: int) -> List[tuple]:
        """Returns [(module_id, SingleAgentEpisode), ...] fragments — the
        learner groups them by module."""
        done: List[tuple] = []
        steps = 0
        while steps < num_env_steps:
            acting = dict(self._obs)
            actions, logps, values = self._act(acting)
            obs, rewards, terms, truncs, _ = self._env.step(actions)
            for aid in acting:
                ep = self._ma_episode.agent_episodes[aid]
                ep.actions.append(actions[aid])
                ep.rewards.append(
                    float(rewards.get(aid, 0.0)) + self._hanging_rewards.pop(aid, 0.0)
                )
                ep.logps.append(logps[aid])
                ep.values.append(values[aid])
            # Rewards paid to agents that did NOT act this step (turn-based
            # zero-sum envs commonly reward every agent on the terminal
            # move) are credited to the agent's LAST action — or held as
            # hanging rewards until its first (reference: multi-agent
            # hanging-reward accumulation).
            for aid, r in rewards.items():
                if aid in acting or not r:
                    continue
                ep = self._ma_episode.agent_episodes.get(aid)
                if ep is not None and len(ep) > 0:
                    ep.rewards[-1] += float(r)
                else:
                    self._hanging_rewards[aid] = (
                        self._hanging_rewards.get(aid, 0.0) + float(r)
                    )
            self._return_acc += sum(float(r) for r in rewards.values())
            steps += 1
            all_term = terms.get("__all__", False)
            all_done = all_term or truncs.get("__all__", False)
            for aid in acting:
                ep = self._ma_episode.agent_episodes[aid]
                # An env may end the whole episode with only __all__ set:
                # every live agent is then *terminated* (no value bootstrap),
                # not truncated (reference: multi_agent_env_runner treats
                # __all__-termination as terminal for all agents).
                a_term = terms.get(aid, False) or all_term
                a_trunc = truncs.get(aid, False)
                if aid in obs:
                    ep.observations.append(obs[aid])
                else:
                    ep.observations.append(ep.observations[-1])
                if a_term or a_trunc or all_done:
                    ep.terminated = bool(a_term)
                    ep.truncated = not a_term
                    mid = self._mapping(aid)
                    if not a_term:
                        ep.final_value = self._bootstrap(mid, ep.observations[-1])
                    done.append((mid, ep))
                    # An individually-finished agent leaves the episode; the
                    # tail cut below must not re-emit (and re-bootstrap) it.
                    # If the env hands it obs again, the late-join path
                    # starts a fresh episode.
                    del self._ma_episode.agent_episodes[aid]
            if all_done:
                # Finalize agents that did NOT act on the final step but
                # still have in-progress episodes (turn-based envs observe
                # one agent per step): their transitions must not be
                # discarded by _reset_env.
                for aid, ep in list(self._ma_episode.agent_episodes.items()):
                    if len(ep) > 0:
                        if aid in obs:
                            # env supplied a real final observation —
                            # replace the stale duplicate so a truncation
                            # bootstrap uses it
                            ep.observations[-1] = obs[aid]
                        mid = self._mapping(aid)
                        ep.terminated = bool(all_term)
                        ep.truncated = not all_term
                        if not all_term:
                            ep.final_value = self._bootstrap(mid, ep.observations[-1])
                        done.append((mid, ep))
                self._completed_returns.append(self._return_acc)
                self._return_acc = 0.0
                self._reset_env()
            else:
                self._obs = {aid: obs[aid] for aid in obs}
                for aid in obs:
                    if aid not in self._ma_episode.agent_episodes:
                        # late-joining agent (reference: agents may enter
                        # mid-episode)
                        self._ma_episode.add_reset(aid, obs[aid])
                    elif aid not in acting:
                        # Re-observed without having acted this step (turn-
                        # based envs): its last stored observation is the
                        # stale duplicate appended when it last acted —
                        # replace it so the obs the agent will act on is
                        # the one stored at index len(actions).
                        self._ma_episode.agent_episodes[aid].observations[-1] = obs[aid]
        # cut in-progress per-agent episodes with bootstrap values
        for aid, ep in list(self._ma_episode.agent_episodes.items()):
            if len(ep) > 0:
                mid = self._mapping(aid)
                ep.truncated = True
                ep.final_value = self._bootstrap(mid, ep.observations[-1])
                done.append((mid, ep))
                last_obs = ep.observations[-1]
                self._ma_episode.agent_episodes[aid] = SingleAgentEpisode(
                    observations=[last_obs]
                )
        return done

    def pop_metrics(self) -> List[float]:
        out = self._completed_returns
        self._completed_returns = []
        return out

    def evaluate(self, num_episodes: int = 5) -> float:
        """Greedy joint-policy rollouts; returns mean summed return."""
        import jax.numpy as jnp

        totals = []
        for e in range(num_episodes):
            obs, _ = self._env.reset(seed=10_000 + e)
            total, done_all = 0.0, False
            while not done_all:
                actions = {}
                for aid, o in obs.items():
                    mid = self._mapping(aid)
                    a = self.modules[mid].forward_inference(
                        self.params[mid], jnp.asarray(np.asarray(o, dtype=np.float32))[None]
                    )
                    actions[aid] = int(np.asarray(a)[0])
                obs, rewards, terms, truncs, _ = self._env.step(actions)
                total += sum(float(r) for r in rewards.values())
                done_all = terms.get("__all__", False) or truncs.get("__all__", False)
            totals.append(total)
        return float(np.mean(totals))


class _MultiAgentConfigMixin:
    """``multi_agent()`` fluent surface shared by MA algorithms
    (reference: AlgorithmConfig.multi_agent)."""

    def _init_multi_agent(self):
        self._module_specs: Dict[str, RLModuleSpec] = {}
        self._policy_mapping_fn: Callable[[str], str] = lambda aid: "default"
        self._policies_to_train: Optional[List[str]] = None

    def multi_agent(
        self,
        module_specs: Dict[str, RLModuleSpec],
        policy_mapping_fn: Callable[[str], str],
        policies_to_train: Optional[List[str]] = None,
    ):
        self._module_specs = module_specs
        self._policy_mapping_fn = policy_mapping_fn
        self._policies_to_train = policies_to_train
        return self


class _MultiAgentAlgorithmBase:
    """Runner/manager plumbing shared by the MA algorithms: per-policy
    learner groups over one joint rollout, weight fan-out, fault-tolerant
    remote runners (reference: the Algorithm + EnvRunnerGroup split)."""

    def __init__(self, config, module_specs: Dict[str, RLModuleSpec]):
        if not module_specs:
            raise ValueError("use .multi_agent(module_specs=..., policy_mapping_fn=...)")
        self.config = config
        self._specs = module_specs
        self._trainable = config._policies_to_train or list(module_specs)
        self.local_runner = MultiAgentEnvRunner(
            config.env_spec,
            module_specs,
            config._policy_mapping_fn,
            seed=config.seed,
        )
        if config.num_env_runners > 0:
            runner_cls = ray_tpu.remote(num_cpus=1, max_restarts=0)(MultiAgentEnvRunner)

            def make(i: int):
                return runner_cls.remote(
                    config.env_spec,
                    module_specs,
                    config._policy_mapping_fn,
                    seed=config.seed,
                    worker_index=i + 1,
                )

            self._manager = FaultTolerantActorManager(make, config.num_env_runners)
        else:
            self._manager = None
        self.learner_groups: Dict[str, LearnerGroup] = {}
        self.iteration = 0
        self._total_env_steps = 0
        self._recent_returns: List[float] = []

    def _weights(self) -> Dict[str, Any]:
        w = dict(self.local_runner.params)
        for mid, lg in self.learner_groups.items():
            w[mid] = lg.get_weights()
        return w

    def _sync_weights(self):
        params = self._weights()
        self.local_runner.set_state(params)
        if self._manager:
            ref = ray_tpu.put(params)
            self._manager.foreach_actor("set_state", ref, timeout=60)

    def _sample(self, want: int) -> List[tuple]:
        if not self._manager:
            return self.local_runner.sample(want)
        n = max(1, self._manager.num_healthy())
        per = max(1, want // n)
        out: List[tuple] = []
        for _, frags in self._manager.foreach_actor("sample", per, timeout=300):
            out.extend(frags)
        return out or self.local_runner.sample(want)

    def _collect_returns(self) -> List[float]:
        returns = self.local_runner.pop_metrics()
        if self._manager:
            for _, r in self._manager.foreach_actor("pop_metrics", timeout=60):
                returns.extend(r)
        if returns:
            self._recent_returns = (self._recent_returns + returns)[-100:]
        return returns

    def evaluate(self, num_episodes: int = 5) -> float:
        return self.local_runner.evaluate(num_episodes)

    def stop(self):
        for lg in self.learner_groups.values():
            lg.shutdown()
        if self._manager:
            for actor in self._manager.actors.values():
                try:
                    ray_tpu.kill(actor)
                except Exception:  # noqa: BLE001 — already dead
                    pass


class MultiAgentPPOConfig(PPOConfig, _MultiAgentConfigMixin):
    """PPO over a MultiRLModule (reference: PPO + MultiRLModule new-stack
    path; ``multi_agent()`` mirrors AlgorithmConfig.multi_agent)."""

    def __init__(self):
        super().__init__()
        self._init_multi_agent()

    def build(self) -> "MultiAgentPPO":
        return MultiAgentPPO(self)


class MultiAgentPPO(_MultiAgentAlgorithmBase):
    """One LearnerGroup per trainable policy; agents sharing a policy are
    batched together (reference: MultiRLModule learner update where each
    module's loss runs over its own agents' sub-batch)."""

    def __init__(self, config: MultiAgentPPOConfig):
        _MultiAgentAlgorithmBase.__init__(self, config, config._module_specs)
        self.learner_groups = {
            mid: LearnerGroup(
                spec,
                ppo_loss,
                loss_cfg=dict(
                    clip_param=config.clip_param,
                    vf_clip_param=config.vf_clip_param,
                    vf_loss_coeff=config.vf_loss_coeff,
                    entropy_coeff=config.entropy_coeff,
                ),
                num_learners=0,
                lr=config.lr,
                grad_clip=config.grad_clip,
                seed=config.seed,
            )
            for mid, spec in self._specs.items()
            if mid in self._trainable
        }
        self._sync_weights()

    def train(self) -> Dict[str, Any]:
        import time

        t0 = time.time()
        cfg = self.config
        frags = self._sample(cfg.train_batch_size)
        env_steps = sum(len(ep) for _, ep in frags)
        self._total_env_steps += env_steps
        by_module: Dict[str, List[SingleAgentEpisode]] = {}
        for mid, ep in frags:
            if len(ep) > 0:
                by_module.setdefault(mid, []).append(ep)
        metrics: Dict[str, Any] = {}
        rng = np.random.default_rng(cfg.seed + self.iteration)
        for mid, lg in self.learner_groups.items():
            eps = by_module.get(mid)
            if not eps:
                continue
            batch = episodes_to_batch(eps, gamma=cfg.gamma, lam=cfg.lam)
            rows = len(batch["obs"])
            for _ in range(cfg.num_epochs):
                order = rng.permutation(rows)
                for lo in range(0, rows, cfg.minibatch_size):
                    idx = order[lo : lo + cfg.minibatch_size]
                    mb = {k: v[idx] for k, v in batch.items()}
                    m = lg.update_from_batch(mb)
                metrics.update({f"learner/{mid}/{k}": v for k, v in m.items()})
        self._sync_weights()
        self._collect_returns()
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "env_steps_this_iter": env_steps,
            "num_env_steps_sampled_lifetime": self._total_env_steps,
            "episode_return_mean": float(np.mean(self._recent_returns))
            if self._recent_returns
            else 0.0,
            "time_this_iter_s": time.time() - t0,
            **metrics,
        }


class MultiAgentDQNConfig(DQNConfig, _MultiAgentConfigMixin):
    """DQN over a MultiRLModule (reference: the multi-agent variants of
    the off-policy algorithms on the new API stack: per-policy Q modules,
    replay buffers, and target networks; agents sharing a policy share
    all three)."""

    def __init__(self):
        super().__init__()
        self._init_multi_agent()

    def build(self) -> "MultiAgentDQN":
        return MultiAgentDQN(self)


class MultiAgentDQN(_MultiAgentAlgorithmBase):
    """One Q-learner + replay buffer + target net per trainable policy;
    the joint env rollout feeds each policy's buffer with its agents'
    transitions. Exploration is a shared ε-greedy schedule injected into
    every module's shipped weights."""

    def __init__(self, config: MultiAgentDQNConfig):
        import dataclasses

        from ray_tpu.rllib.dqn import dqn_loss
        from ray_tpu.rllib.replay_buffer import (
            PrioritizedReplayBuffer,
            ReplayBuffer,
        )

        # COPY specs to q-kind — the caller's spec objects must not be
        # mutated (reusing them for an MA-PPO would silently swap modules)
        specs = {
            mid: dataclasses.replace(spec, kind="q")
            for mid, spec in config._module_specs.items()
        }
        _MultiAgentAlgorithmBase.__init__(self, config, specs)
        self.learner_groups = {
            mid: LearnerGroup(
                spec,
                dqn_loss,
                loss_cfg=dict(gamma=config.gamma, use_huber=config.use_huber),
                num_learners=0,
                lr=config.lr,
                grad_clip=config.grad_clip,
                seed=config.seed,
            )
            for mid, spec in self._specs.items()
            if mid in self._trainable
        }
        self.buffers = {
            mid: (
                PrioritizedReplayBuffer(
                    config.buffer_size, config.per_alpha, config.per_beta,
                    seed=config.seed,
                )
                if config.prioritized_replay
                else ReplayBuffer(config.buffer_size, seed=config.seed)
            )
            for mid in self.learner_groups
        }
        self._num_updates: Dict[str, int] = {mid: 0 for mid in self.learner_groups}
        self._sync_weights()

    # -- ε schedule (shared across policies; reference: DQN epsilon) -----
    def current_epsilon(self) -> float:
        c = self.config
        frac = min(1.0, self._total_env_steps / max(1, c.epsilon_decay_steps))
        return float(c.epsilon_initial + frac * (c.epsilon_final - c.epsilon_initial))

    def _weights(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        eps = jnp.asarray(self.current_epsilon(), jnp.float32)
        w = {}
        for mid, params in self.local_runner.params.items():
            lg = self.learner_groups.get(mid)
            p = dict(lg.get_weights()) if lg is not None else dict(params)
            p["epsilon"] = eps
            w[mid] = p
        return w

    def _sync_target(self, mid: str):
        import jax

        lg = self.learner_groups[mid]
        state = lg.get_state()
        params = state["params"]
        params["target"] = jax.tree.map(lambda x: x, params["q"])
        lg.set_state(state)

    def train(self) -> Dict[str, Any]:
        import time

        t0 = time.time()
        cfg = self.config
        frags = self._sample(
            cfg.rollout_fragment_length * max(1, cfg.num_env_runners or 1)
        )
        env_steps = sum(len(ep) for _, ep in frags)
        self._total_env_steps += env_steps
        for mid, ep in frags:
            buf = self.buffers.get(mid)
            if buf is not None and len(ep) > 0:
                buf.add_episodes([ep])

        metrics: Dict[str, Any] = {}
        for mid, lg in self.learner_groups.items():
            buf = self.buffers[mid]
            if len(buf) < cfg.learning_starts:
                continue
            m: Dict[str, Any] = {}
            for _ in range(cfg.num_updates_per_iter):
                mb = buf.sample(cfg.train_batch_size)
                idx = mb.pop("idx")
                m = lg.update_from_batch(mb)
                td = m.pop("td_errors", None)
                if td is not None:
                    buf.update_priorities(idx, np.asarray(td)[: len(idx)])
                self._num_updates[mid] += 1
                if self._num_updates[mid] % cfg.target_update_freq == 0:
                    self._sync_target(mid)
            metrics.update(
                {f"learner/{mid}/{k}": v for k, v in m.items() if np.ndim(v) == 0}
            )
        self._sync_weights()
        self._collect_returns()
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "env_steps_this_iter": env_steps,
            "num_env_steps_sampled_lifetime": self._total_env_steps,
            "episode_return_mean": float(np.mean(self._recent_returns))
            if self._recent_returns
            else 0.0,
            "epsilon": self.current_epsilon(),
            "time_this_iter_s": time.time() - t0,
            **metrics,
        }
