"""Offline RL: BC and MARWIL.

Reference: rllib/algorithms/bc (plain imitation; the reference implements
BC as MARWIL with beta=0) and rllib/algorithms/marwil
(advantage-weighted imitation, offline_data.py / offline_prelearner.py
for the data path). Data here is a list of episodes or a flat batch —
the streaming ingest path (ray_tpu.data.Dataset.iter_batches) plugs in
by producing the same dict layout.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.episodes import SingleAgentEpisode


def episodes_to_offline_batch(
    episodes: List[SingleAgentEpisode], gamma: float = 0.99
) -> Dict[str, np.ndarray]:
    """Episodes → {obs, actions, returns} with discounted returns-to-go
    (bootstrapped through truncation)."""
    obs, acts, rets = [], [], []
    for ep in episodes:
        T = len(ep)
        if T == 0:
            continue
        r = np.asarray(ep.rewards, dtype=np.float32)
        R = np.zeros(T, dtype=np.float32)
        acc = 0.0 if ep.terminated else float(ep.final_value)
        for t in range(T - 1, -1, -1):
            acc = r[t] + gamma * acc
            R[t] = acc
        obs.append(np.asarray(ep.observations[:T], dtype=np.float32))
        acts.append(np.asarray(ep.actions, dtype=np.int32))
        rets.append(R)
    return {
        "obs": np.concatenate(obs),
        "actions": np.concatenate(acts),
        "returns": np.concatenate(rets),
    }


def marwil_loss(
    module,
    params,
    batch,
    beta: float = 1.0,
    vf_coeff: float = 1.0,
    entropy_coeff: float = 0.0,
):
    """MARWIL objective: exp(β·Â)-weighted log-likelihood + value
    regression; β=0 reduces to plain BC (reference: marwil_learner)."""
    import jax
    import jax.numpy as jnp

    out = module.logp_entropy(params, batch["obs"], batch["actions"])
    logp, vf = out["logp"], out["vf"]
    if beta > 0:
        adv = batch["returns"] - vf
        # Per-batch moving-free normalization (reference keeps a running
        # MA of the squared advantage; a batch estimate is the same
        # quantity without cross-step state).
        norm = jnp.sqrt(jnp.mean(jax.lax.stop_gradient(adv) ** 2) + 1e-8)
        weights = jnp.exp(jnp.clip(beta * jax.lax.stop_gradient(adv) / norm, -5.0, 5.0))
        vf_loss = jnp.mean(adv**2)
    else:
        weights = jnp.ones_like(logp)
        vf_loss = jnp.asarray(0.0)
    policy_loss = -jnp.mean(weights * logp)
    entropy = jnp.mean(out["entropy"])
    loss = policy_loss + vf_coeff * vf_loss - entropy_coeff * entropy
    return loss, {
        "policy_loss": policy_loss,
        "vf_loss": vf_loss,
        "entropy": entropy,
    }


class MARWILConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.beta = 1.0
        self.vf_coeff = 1.0
        self.entropy_coeff = 0.0
        self.train_batch_size = 256
        self.num_updates_per_iter = 16
        self._offline_episodes: Optional[List[SingleAgentEpisode]] = None
        self._offline_batch: Optional[Dict[str, np.ndarray]] = None

    def offline_data(
        self,
        episodes: Optional[List[SingleAgentEpisode]] = None,
        batch: Optional[Dict[str, np.ndarray]] = None,
    ) -> "MARWILConfig":
        self._offline_episodes = episodes
        self._offline_batch = batch
        return self

    def build(self) -> "MARWIL":
        return MARWIL(self)


class BCConfig(MARWILConfig):
    """BC = MARWIL with beta=0 (exactly the reference's relationship)."""

    def __init__(self):
        super().__init__()
        self.beta = 0.0

    def build(self) -> "BC":
        return BC(self)


class MARWIL(Algorithm):
    loss_fn = staticmethod(marwil_loss)

    def __init__(self, config: MARWILConfig):
        super().__init__(config)
        if config._offline_batch is not None:
            self._data = dict(config._offline_batch)
        elif config._offline_episodes is not None:
            self._data = episodes_to_offline_batch(
                config._offline_episodes, gamma=config.gamma
            )
        else:
            raise ValueError("MARWIL/BC requires .offline_data(...)")
        if "returns" not in self._data:
            self._data["returns"] = np.zeros(len(self._data["obs"]), np.float32)
        self._rng = np.random.default_rng(config.seed)

    def _loss_cfg(self) -> dict:
        c = self.config
        return dict(beta=c.beta, vf_coeff=c.vf_coeff, entropy_coeff=c.entropy_coeff)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        rows = len(self._data["obs"])
        metrics: Dict[str, float] = {}
        for _ in range(cfg.num_updates_per_iter):
            idx = self._rng.integers(0, rows, cfg.train_batch_size)
            mb = {k: v[idx] for k, v in self._data.items()}
            metrics = self.learner_group.update_from_batch(mb)
        self.env_runner_group.sync_weights(self.learner_group.get_weights())
        return {
            "env_steps_this_iter": 0,
            "offline_samples_trained": cfg.num_updates_per_iter * cfg.train_batch_size,
            **{f"learner/{k}": v for k, v in metrics.items()},
        }


class BC(MARWIL):
    pass
