"""SAC (discrete) — soft actor-critic with twin Q heads and learned
temperature.

Reference: rllib/algorithms/sac (DefaultSACRLModule, sac_learner twin-Q
TD loss, temperature auto-tuning). Discrete adaptation: policy is
categorical, so the soft value and the actor/temperature objectives are
exact expectations over the action set (no reparameterized sampling) —
one fused jitted update instead of three separate optimizer passes.
"""
from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.off_policy import OffPolicyAlgorithm, OffPolicyConfig
from ray_tpu.rllib.rl_module import RLModuleSpec


def sac_loss(
    module,
    params,
    batch,
    gamma: float = 0.99,
    target_entropy: float = -1.0,  # <0 → auto: 0.98 * log(|A|)
):
    import jax
    import jax.numpy as jnp

    obs, actions = batch["obs"], batch["actions"]
    n = obs.shape[0]
    ar = jnp.arange(n)
    num_actions = module.spec.action_dim
    if target_entropy < 0:
        target_entropy = 0.98 * float(np.log(num_actions))

    out = module.forward_train(params, obs)
    logits, q1, q2 = out["logits"], out["q1"], out["q2"]
    logpi = jax.nn.log_softmax(logits)
    pi = jnp.exp(logpi)
    alpha = jnp.exp(params["log_alpha"])

    # --- critic: soft Bellman target through the target twin-Q minimum ---
    logits_next = module._mlp(params["pi"], batch["next_obs"])
    logpi_next = jax.nn.log_softmax(logits_next)
    pi_next = jnp.exp(logpi_next)
    sg = jax.lax.stop_gradient
    q1t = module._mlp(jax.tree.map(sg, params["q1_target"]), batch["next_obs"])
    q2t = module._mlp(jax.tree.map(sg, params["q2_target"]), batch["next_obs"])
    v_next = jnp.sum(pi_next * (jnp.minimum(q1t, q2t) - alpha * logpi_next), axis=-1)
    target = sg(batch["rewards"] + gamma * (1.0 - batch["dones"]) * v_next)
    td1 = q1[ar, actions] - target
    td2 = q2[ar, actions] - target
    critic_loss = 0.5 * jnp.mean(batch["weights"] * (td1**2 + td2**2))

    # --- actor: maximize soft value under the current twin-Q minimum -----
    q_min = sg(jnp.minimum(q1, q2))
    actor_loss = jnp.mean(jnp.sum(pi * (sg(alpha) * logpi - q_min), axis=-1))

    # --- temperature: drive policy entropy toward the target -------------
    entropy = -jnp.sum(pi * logpi, axis=-1)
    alpha_loss = jnp.mean(params["log_alpha"] * sg(entropy - target_entropy))

    loss = critic_loss + actor_loss + alpha_loss
    return loss, {
        "critic_loss": critic_loss,
        "actor_loss": actor_loss,
        "alpha_loss": alpha_loss,
        "alpha": alpha,
        "entropy": jnp.mean(entropy),
        "mean_q": jnp.mean(q_min[ar, actions]),
        "td_errors": td1,
    }


class SACConfig(OffPolicyConfig):
    def __init__(self):
        super().__init__()
        self.lr = 3e-4
        self.target_entropy = -1.0  # auto
        self.target_update_freq = 100

    def module_spec(self) -> RLModuleSpec:
        spec = super().module_spec()
        spec.kind = "sac"
        return spec

    def build(self) -> "SAC":
        return SAC(self)


class SAC(OffPolicyAlgorithm):
    loss_fn = staticmethod(sac_loss)
    target_pairs = (("q1", "q1_target"), ("q2", "q2_target"))

    def _loss_cfg(self) -> dict:
        return dict(
            gamma=self.config.gamma, target_entropy=self.config.target_entropy
        )
