"""ray_tpu.rllib: reinforcement learning — JAX modules, TPU learners.

Reference: rllib/ (new API stack: Algorithm/EnvRunner/RLModule/Learner).
"""
from ray_tpu.rllib.actor_manager import FaultTolerantActorManager
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig, EnvRunnerGroup
from ray_tpu.rllib.appo import APPO, APPOConfig
from ray_tpu.rllib.cql import CQL, CQLConfig
from ray_tpu.rllib.dqn import DQN, DQNConfig
from ray_tpu.rllib.env_runner import SingleAgentEnvRunner
from ray_tpu.rllib.episodes import SingleAgentEpisode, compute_gae, episodes_to_batch
from ray_tpu.rllib.impala import IMPALA, IMPALAConfig, vtrace_returns
from ray_tpu.rllib.learner import Learner, LearnerGroup
from ray_tpu.rllib.multi_agent import (
    MultiAgentEnv,
    MultiAgentEnvRunner,
    MultiAgentEpisode,
    MultiAgentPPO,
    MultiAgentDQN,
    MultiAgentDQNConfig,
    MultiAgentPPOConfig,
)
from ray_tpu.rllib.offline import BC, BCConfig, MARWIL, MARWILConfig
from ray_tpu.rllib.podracer import (
    PodracerConfig,
    PodracerEnvRunner,
    PodracerPipeline,
    SampleQueue,
    VtraceBatchBuilder,
    WeightBroadcast,
)
from ray_tpu.rllib.ope import (
    DirectMethod,
    DoublyRobust,
    ImportanceSampling,
    WeightedImportanceSampling,
)
from ray_tpu.rllib.ppo import PPO, PPOConfig
from ray_tpu.rllib.replay_buffer import PrioritizedReplayBuffer, ReplayBuffer
from ray_tpu.rllib.rl_module import QRLModule, RLModule, RLModuleSpec, SACRLModule, make_module
from ray_tpu.rllib.sac import SAC, SACConfig

__all__ = [
    "Algorithm",
    "AlgorithmConfig",
    "EnvRunnerGroup",
    "FaultTolerantActorManager",
    "SingleAgentEnvRunner",
    "SingleAgentEpisode",
    "compute_gae",
    "episodes_to_batch",
    "RLModule",
    "QRLModule",
    "SACRLModule",
    "make_module",
    "RLModuleSpec",
    "Learner",
    "LearnerGroup",
    "PPO",
    "PPOConfig",
    "IMPALA",
    "IMPALAConfig",
    "vtrace_returns",
    "PodracerConfig",
    "PodracerEnvRunner",
    "PodracerPipeline",
    "SampleQueue",
    "VtraceBatchBuilder",
    "WeightBroadcast",
    "APPO",
    "APPOConfig",
    "MultiAgentEnv",
    "MultiAgentEnvRunner",
    "MultiAgentEpisode",
    "MultiAgentPPO",
    "MultiAgentDQN",
    "MultiAgentDQNConfig",
    "MultiAgentPPOConfig",
    "CQL",
    "CQLConfig",
    "DQN",
    "DQNConfig",
    "SAC",
    "SACConfig",
    "BC",
    "BCConfig",
    "ImportanceSampling",
    "WeightedImportanceSampling",
    "DirectMethod",
    "DoublyRobust",
    "MARWIL",
    "MARWILConfig",
    "ReplayBuffer",
    "PrioritizedReplayBuffer",
]
