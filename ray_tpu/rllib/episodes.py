"""Episode containers + advantage estimation connectors.

Reference: rllib/env/single_agent_episode.py (SingleAgentEpisode) and the
learner connector pipeline (rllib/connectors/learner/
general_advantage_estimation.py). GAE/V-trace are pure numpy/jax
functions here — they run inside the learner's jit on TPU or on the CPU
path in env runners.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np


@dataclass
class SingleAgentEpisode:
    observations: List[np.ndarray] = field(default_factory=list)  # T+1
    actions: List[int] = field(default_factory=list)  # T
    rewards: List[float] = field(default_factory=list)  # T
    logps: List[float] = field(default_factory=list)  # T
    values: List[float] = field(default_factory=list)  # T
    terminated: bool = False
    truncated: bool = False
    final_value: float = 0.0  # bootstrap V(s_T) when truncated

    def __len__(self) -> int:
        return len(self.actions)

    @property
    def total_reward(self) -> float:
        return float(sum(self.rewards))


def compute_gae(
    rewards: np.ndarray,
    values: np.ndarray,
    final_value: float,
    terminated: bool,
    gamma: float = 0.99,
    lam: float = 0.95,
):
    """Generalized Advantage Estimation over one episode (reference:
    rllib/connectors/learner/general_advantage_estimation.py +
    rllib/evaluation/postprocessing.py compute_advantages)."""
    T = len(rewards)
    adv = np.zeros(T, dtype=np.float32)
    next_v = 0.0 if terminated else float(final_value)
    gae = 0.0
    for t in range(T - 1, -1, -1):
        delta = rewards[t] + gamma * next_v - values[t]
        gae = delta + gamma * lam * gae
        adv[t] = gae
        next_v = values[t]
    returns = adv + values
    return adv, returns


def episodes_to_batch(
    episodes: List[SingleAgentEpisode],
    gamma: float = 0.99,
    lam: float = 0.95,
    normalize_advantages: bool = True,
) -> Dict[str, np.ndarray]:
    """Learner-connector: episodes → flat train batch with GAE targets."""
    obs, acts, logps, advs, rets, vals = [], [], [], [], [], []
    for ep in episodes:
        if len(ep) == 0:
            continue
        r = np.asarray(ep.rewards, dtype=np.float32)
        v = np.asarray(ep.values, dtype=np.float32)
        a, ret = compute_gae(r, v, ep.final_value, ep.terminated, gamma, lam)
        obs.append(np.asarray(ep.observations[: len(ep)], dtype=np.float32))
        acts.append(np.asarray(ep.actions, dtype=np.int32))
        logps.append(np.asarray(ep.logps, dtype=np.float32))
        advs.append(a)
        rets.append(ret)
        vals.append(v)
    batch = {
        "obs": np.concatenate(obs),
        "actions": np.concatenate(acts),
        "logp_old": np.concatenate(logps),
        "advantages": np.concatenate(advs),
        "returns": np.concatenate(rets),
        "values_old": np.concatenate(vals),
    }
    if normalize_advantages:
        a = batch["advantages"]
        batch["advantages"] = (a - a.mean()) / (a.std() + 1e-8)
    return batch
