"""FaultTolerantActorManager: restart-and-resync failed actors.

Reference: rllib/utils/actor_manager.py (FaultTolerantActorManager —
foreach with error collection, health probing, restart) as used by
EnvRunnerGroup (rllib/env/env_runner_group.py:833 foreach_worker,
restart-and-resync at :357).
"""
from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional, Tuple

import ray_tpu

logger = logging.getLogger("ray_tpu.rllib")


class FaultTolerantActorManager:
    def __init__(self, make_actor: Callable[[int], Any], num_actors: int):
        """``make_actor(index)`` returns a fresh remote actor handle."""
        self._make_actor = make_actor
        self._actors: Dict[int, Any] = {i: make_actor(i) for i in range(num_actors)}
        self._healthy: Dict[int, bool] = {i: True for i in self._actors}
        self.num_restarts = 0

    @property
    def actors(self) -> Dict[int, Any]:
        return dict(self._actors)

    def num_healthy(self) -> int:
        return sum(self._healthy.values())

    def foreach_actor(
        self,
        fn_name: str,
        *args,
        timeout: Optional[float] = None,
        restart_failed: bool = True,
        **kwargs,
    ) -> List[Tuple[int, Any]]:
        """Call ``fn_name(*args)`` on every healthy actor; failed actors are
        marked unhealthy (and optionally restarted). Returns
        [(index, result)] for the successes."""
        refs = {}
        for i, actor in self._actors.items():
            if not self._healthy[i]:
                continue
            refs[i] = getattr(actor, fn_name).remote(*args, **kwargs)
        results: List[Tuple[int, Any]] = []
        for i, ref in refs.items():
            try:
                results.append((i, ray_tpu.get(ref, timeout=timeout)))
            except Exception as e:  # actor died / task failed
                logger.warning("env-runner %d failed %s: %s", i, fn_name, e)
                self._healthy[i] = False
                if restart_failed:
                    self.restart_actor(i)
        return results

    def restart_actor(self, i: int):
        """Reference: env_runner_group.py restart-and-resync."""
        try:
            ray_tpu.kill(self._actors[i])
        except Exception as e:  # noqa: BLE001 — restarting a dead actor
            logger.debug("kill before restart failed (actor %d): %s", i, e)
        self._actors[i] = self._make_actor(i)
        self._healthy[i] = True
        self.num_restarts += 1

    def probe_health(self) -> List[int]:
        """Ping everyone; returns indices that failed (now restarted)."""
        failed = []
        for i, actor in list(self._actors.items()):
            try:
                ray_tpu.get(actor.ping.remote(), timeout=10)
            except Exception:
                failed.append(i)
                self._healthy[i] = False
                self.restart_actor(i)
        return failed
