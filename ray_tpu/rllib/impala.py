"""IMPALA — asynchronous sampling + V-trace off-policy correction.

Reference: rllib/algorithms/impala/impala.py:81 (async sample.remote()
streams, aggregation :273, learner queues) and the V-trace math from
rllib/algorithms/impala/torch/vtrace_torch_v2.py (Espeholt et al. 2018).

Async shape: env-runner sample() calls stay in flight continuously; the
driver harvests whichever finished (ray_tpu.wait), updates the learner
with slightly-stale trajectories, and V-trace's importance-sampling
truncation (rho-bar/c-bar) corrects the off-policyness.
"""
from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.episodes import SingleAgentEpisode


def vtrace_returns(
    behavior_logps: np.ndarray,
    target_logps: np.ndarray,
    rewards: np.ndarray,
    values: np.ndarray,
    final_value: float,
    terminated: bool,
    gamma: float = 0.99,
    rho_bar: float = 1.0,
    c_bar: float = 1.0,
):
    """Per-episode V-trace targets (numpy reference implementation; the
    learner's jit recomputes target logps but targets are computed here at
    batch-build time, matching the reference's connector placement)."""
    T = len(rewards)
    rhos = np.minimum(rho_bar, np.exp(target_logps - behavior_logps))
    cs = np.minimum(c_bar, np.exp(target_logps - behavior_logps))
    next_values = np.append(values[1:], 0.0 if terminated else final_value)
    deltas = rhos * (rewards + gamma * next_values - values)
    vs_minus_v = np.zeros(T + 1, dtype=np.float32)
    for t in range(T - 1, -1, -1):
        vs_minus_v[t] = deltas[t] + gamma * cs[t] * vs_minus_v[t + 1]
    vs = vs_minus_v[:T] + values
    vs_next = np.append(vs[1:], 0.0 if terminated else final_value)
    pg_adv = rhos * (rewards + gamma * vs_next - values)
    return vs, pg_adv


def impala_loss(
    module,
    params,
    batch,
    vf_loss_coeff: float = 0.5,
    entropy_coeff: float = 0.005,
):
    import jax.numpy as jnp

    out = module.logp_entropy(params, batch["obs"], batch["actions"])
    policy_loss = -jnp.mean(out["logp"] * batch["pg_advantages"])
    vf_loss = 0.5 * jnp.mean((out["vf"] - batch["vtrace_targets"]) ** 2)
    entropy = jnp.mean(out["entropy"])
    total = policy_loss + vf_loss_coeff * vf_loss - entropy_coeff * entropy
    return total, {"policy_loss": policy_loss, "vf_loss": vf_loss, "entropy": entropy}


class IMPALAConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.005
        self.rho_bar = 1.0
        self.c_bar = 1.0
        self.max_requests_in_flight = 2

    def build(self) -> "IMPALA":
        return IMPALA(self)


class IMPALA(Algorithm):
    loss_fn = staticmethod(impala_loss)
    supports_podracer = True

    def _loss_cfg(self) -> dict:
        c = self.config
        return dict(vf_loss_coeff=c.vf_loss_coeff, entropy_coeff=c.entropy_coeff)

    def __init__(self, config: IMPALAConfig):
        super().__init__(config)
        self._inflight: Dict[Any, int] = {}  # sample ref -> runner index

    def _episodes_to_vtrace_batch(self, episodes: List[SingleAgentEpisode]):
        """Behavior logps come from the (stale) runner policy; target logps
        from the current learner params — the V-trace correction. The
        recompute is ONE batched, jitted forward over the concatenated
        episodes (podracer's VtraceBatchBuilder, bounded shape buckets),
        replacing the old per-episode unjitted driver forwards; the module
        comes from the ``make_module`` factory like every other call site."""
        cfg = self.config
        return self._batch_builder().build(
            self.learner_group.get_weights(),
            episodes,
            gamma=cfg.gamma,
            rho_bar=cfg.rho_bar,
            c_bar=cfg.c_bar,
        )

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        if self._podracer is not None:
            return self._podracer_training_step()
        group = self.env_runner_group
        metrics: Dict[str, float] = {}
        if group._manager is None:
            # local synchronous fallback
            episodes = group.sample(cfg.rollout_fragment_length)
        else:
            # keep every runner saturated with in-flight sample() calls
            actors = group._manager.actors
            for i, actor in actors.items():
                live = sum(1 for v in self._inflight.values() if v == i)
                while live < cfg.max_requests_in_flight:
                    self._inflight[actor.sample.remote(cfg.rollout_fragment_length)] = i
                    live += 1
            ready, _ = ray_tpu.wait(
                list(self._inflight), num_returns=1, timeout=120
            )
            episodes = []
            for ref in ready:
                idx = self._inflight.pop(ref)
                try:
                    episodes.extend(ray_tpu.get(ref))
                except Exception:
                    group._manager.restart_actor(idx)
                    # drop other in-flight refs of the dead runner
                    self._inflight = {
                        r: j for r, j in self._inflight.items() if j != idx
                    }
            if not episodes:
                episodes = group.local_runner.sample(cfg.rollout_fragment_length)
        env_steps = sum(len(e) for e in episodes)
        self._total_env_steps += env_steps
        batch = self._episodes_to_vtrace_batch(episodes)
        if batch is not None:
            metrics = self.learner_group.update_from_batch(batch)
        group.sync_weights(self.learner_group.get_weights())
        returns = group.pop_metrics()
        mean_ret = self._record_returns(returns)
        return {
            "env_steps_this_iter": env_steps,
            "episode_return_mean": mean_ret,
            "num_episodes": len(returns),
            **{f"learner/{k}": v for k, v in metrics.items()},
        }

    def stop(self):
        self._inflight.clear()
        super().stop()
