"""RLModule: the framework-neutral model abstraction, JAX-native.

Reference: rllib/core/rl_module/rl_module.py (RLModule,
forward_inference/forward_exploration/forward_train, inference-only
state) — re-designed for TPU: params are pytrees, forwards are pure
functions jitted by the caller, so the same module runs vmapped in env
runners (CPU) and pjit-sharded in learners (TPU mesh).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Params = Dict[str, Any]


@dataclass
class RLModuleSpec:
    """Reference: rllib/core/rl_module/rl_module.py RLModuleSpec."""

    observation_dim: int
    action_dim: int
    hidden: Tuple[int, ...] = (64, 64)
    free_log_std: bool = False  # continuous-action stddev as free params
    discrete: bool = True
    # Module family: "pg" (policy+value, PPO/IMPALA/BC), "q" (value-based,
    # DQN), "sac" (policy + twin Q + temperature).
    kind: str = "pg"


class RLModule:
    """Policy + value function over flat observations.

    Subclasses override ``init_params`` / ``forward_train``; the base class
    implements an MLP torso with separate policy and value heads (the
    reference's default FC net, rllib/models/catalog defaults).
    """

    def __init__(self, spec: RLModuleSpec):
        self.spec = spec

    # -- params ----------------------------------------------------------
    def _head(self, key: jax.Array, out_dim: Optional[int] = None) -> Params:
        """One MLP head: He-scaled hidden layers, 0.01-scaled output."""
        out_dim = self.spec.action_dim if out_dim is None else out_dim
        sizes = (self.spec.observation_dim,) + tuple(self.spec.hidden)
        keys = jax.random.split(key, len(sizes))
        layers: Params = {}
        for i in range(len(sizes) - 1):
            layers[f"w{i}"] = (
                jax.random.normal(keys[i], (sizes[i], sizes[i + 1]))
                * np.sqrt(2.0 / sizes[i])
            ).astype(jnp.float32)
            layers[f"b{i}"] = jnp.zeros(sizes[i + 1])
        layers["w_out"] = (
            jax.random.normal(keys[-1], (sizes[-1], out_dim)) * 0.01
        ).astype(jnp.float32)
        layers["b_out"] = jnp.zeros(out_dim)
        return layers

    def init_params(self, key: jax.Array) -> Params:
        k_pi, k_vf = jax.random.split(key)
        params: Params = {
            "pi": self._head(k_pi, self.spec.action_dim),
            "vf": self._head(k_vf, 1),
        }
        if not self.spec.discrete and self.spec.free_log_std:
            params["log_std"] = jnp.zeros(self.spec.action_dim)
        return params

    def _mlp(self, layers: Params, x: jax.Array) -> jax.Array:
        n = len(self.spec.hidden)
        for i in range(n):
            x = jnp.tanh(x @ layers[f"w{i}"] + layers[f"b{i}"])
        return x @ layers["w_out"] + layers["b_out"]

    # -- forwards (pure; caller jits) ------------------------------------
    def forward_train(self, params: Params, obs: jax.Array) -> Dict[str, jax.Array]:
        """Both heads: action logits + value estimates."""
        logits = self._mlp(params["pi"], obs)
        values = self._mlp(params["vf"], obs)[..., 0]
        return {"logits": logits, "vf": values}

    def forward_inference(self, params: Params, obs: jax.Array) -> jax.Array:
        """Greedy action (deterministic serving path)."""
        return jnp.argmax(self._mlp(params["pi"], obs), axis=-1)

    def forward_exploration(
        self, params: Params, obs: jax.Array, key: jax.Array
    ) -> Dict[str, jax.Array]:
        """Sampled action + logp + value (rollout path)."""
        out = self.forward_train(params, obs)
        logits = out["logits"]
        action = jax.random.categorical(key, logits)
        logp = jax.nn.log_softmax(logits)[
            jnp.arange(logits.shape[0]), action
        ]
        return {"action": action, "logp": logp, "vf": out["vf"]}

    def logp_entropy(
        self, params: Params, obs: jax.Array, actions: jax.Array
    ) -> Dict[str, jax.Array]:
        out = self.forward_train(params, obs)
        logits = out["logits"]
        logsm = jax.nn.log_softmax(logits)
        logp = logsm[jnp.arange(logits.shape[0]), actions]
        entropy = -jnp.sum(jnp.exp(logsm) * logsm, axis=-1)
        return {"logp": logp, "entropy": entropy, "vf": out["vf"], "logits": logits}


class QRLModule(RLModule):
    """Value-based module: one MLP mapping obs → Q(s, ·), plus a target
    copy (reference: rllib/algorithms/dqn — DefaultDQNRLModule with
    target network). Exploration is ε-greedy; ε rides in the params tree
    so weight sync (learner → runner) carries the schedule with it."""

    def init_params(self, key: jax.Array) -> Params:
        q = self._head(key)
        return {
            "q": q,
            "target": jax.tree.map(jnp.copy, q),
            "epsilon": jnp.asarray(1.0, jnp.float32),
        }

    def q_values(self, head: Params, obs: jax.Array) -> jax.Array:
        return self._mlp(head, obs)

    def forward_train(self, params: Params, obs: jax.Array) -> Dict[str, jax.Array]:
        return {"q": self.q_values(params["q"], obs)}

    def forward_inference(self, params: Params, obs: jax.Array) -> jax.Array:
        return jnp.argmax(self.q_values(params["q"], obs), axis=-1)

    def forward_exploration(
        self, params: Params, obs: jax.Array, key: jax.Array
    ) -> Dict[str, jax.Array]:
        q = self.q_values(params["q"], obs)
        greedy = jnp.argmax(q, axis=-1)
        k_u, k_a = jax.random.split(key)
        n = obs.shape[0]
        random_a = jax.random.randint(k_a, (n,), 0, self.spec.action_dim)
        explore = jax.random.uniform(k_u, (n,)) < params["epsilon"]
        action = jnp.where(explore, random_a, greedy)
        zeros = jnp.zeros((n,), jnp.float32)
        # logp/vf filled for the runner's episode bookkeeping; unused by DQN.
        return {"action": action, "logp": zeros, "vf": zeros}


class SACRLModule(RLModule):
    """Discrete soft actor-critic module: categorical policy, twin Q heads
    with target copies, and a learnable temperature (reference:
    rllib/algorithms/sac — DefaultSACRLModule; discrete variant computes
    exact expectations over the action set instead of reparameterized
    samples)."""

    def init_params(self, key: jax.Array) -> Params:
        k_pi, k_q1, k_q2 = jax.random.split(key, 3)
        pi = self._head(k_pi)
        q1 = self._head(k_q1)
        q2 = self._head(k_q2)
        return {
            "pi": pi,
            "q1": q1,
            "q2": q2,
            "q1_target": jax.tree.map(jnp.copy, q1),
            "q2_target": jax.tree.map(jnp.copy, q2),
            "log_alpha": jnp.asarray(0.0, jnp.float32),
        }

    def forward_train(self, params: Params, obs: jax.Array) -> Dict[str, jax.Array]:
        return {
            "logits": self._mlp(params["pi"], obs),
            "q1": self._mlp(params["q1"], obs),
            "q2": self._mlp(params["q2"], obs),
        }

    def forward_inference(self, params: Params, obs: jax.Array) -> jax.Array:
        return jnp.argmax(self._mlp(params["pi"], obs), axis=-1)

    def forward_exploration(
        self, params: Params, obs: jax.Array, key: jax.Array
    ) -> Dict[str, jax.Array]:
        logits = self._mlp(params["pi"], obs)
        action = jax.random.categorical(key, logits)
        logp = jax.nn.log_softmax(logits)[jnp.arange(logits.shape[0]), action]
        zeros = jnp.zeros((obs.shape[0],), jnp.float32)
        return {"action": action, "logp": logp, "vf": zeros}


def make_module(spec: RLModuleSpec) -> RLModule:
    """Module factory keyed on ``spec.kind`` (reference analogue:
    RLModuleSpec.build resolving the module class)."""
    cls = {"pg": RLModule, "q": QRLModule, "sac": SACRLModule}[spec.kind]
    return cls(spec)
