"""RLModule: the framework-neutral model abstraction, JAX-native.

Reference: rllib/core/rl_module/rl_module.py (RLModule,
forward_inference/forward_exploration/forward_train, inference-only
state) — re-designed for TPU: params are pytrees, forwards are pure
functions jitted by the caller, so the same module runs vmapped in env
runners (CPU) and pjit-sharded in learners (TPU mesh).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Params = Dict[str, Any]


@dataclass
class RLModuleSpec:
    """Reference: rllib/core/rl_module/rl_module.py RLModuleSpec."""

    observation_dim: int
    action_dim: int
    hidden: Tuple[int, ...] = (64, 64)
    free_log_std: bool = False  # continuous-action stddev as free params
    discrete: bool = True


class RLModule:
    """Policy + value function over flat observations.

    Subclasses override ``init_params`` / ``forward_train``; the base class
    implements an MLP torso with separate policy and value heads (the
    reference's default FC net, rllib/models/catalog defaults).
    """

    def __init__(self, spec: RLModuleSpec):
        self.spec = spec

    # -- params ----------------------------------------------------------
    def init_params(self, key: jax.Array) -> Params:
        sizes = (self.spec.observation_dim,) + tuple(self.spec.hidden)
        params: Params = {"pi": {}, "vf": {}}
        keys = jax.random.split(key, 2 * len(sizes) + 2)
        ki = 0
        for head, out_dim in (("pi", self.spec.action_dim), ("vf", 1)):
            layers = {}
            for i in range(len(sizes) - 1):
                layers[f"w{i}"] = (
                    jax.random.normal(keys[ki], (sizes[i], sizes[i + 1]))
                    * np.sqrt(2.0 / sizes[i])
                ).astype(jnp.float32)
                layers[f"b{i}"] = jnp.zeros(sizes[i + 1])
                ki += 1
            layers["w_out"] = (
                jax.random.normal(keys[ki], (sizes[-1], out_dim)) * 0.01
            ).astype(jnp.float32)
            layers["b_out"] = jnp.zeros(out_dim)
            ki += 1
            params[head] = layers
        if not self.spec.discrete and self.spec.free_log_std:
            params["log_std"] = jnp.zeros(self.spec.action_dim)
        return params

    def _mlp(self, layers: Params, x: jax.Array) -> jax.Array:
        n = len(self.spec.hidden)
        for i in range(n):
            x = jnp.tanh(x @ layers[f"w{i}"] + layers[f"b{i}"])
        return x @ layers["w_out"] + layers["b_out"]

    # -- forwards (pure; caller jits) ------------------------------------
    def forward_train(self, params: Params, obs: jax.Array) -> Dict[str, jax.Array]:
        """Both heads: action logits + value estimates."""
        logits = self._mlp(params["pi"], obs)
        values = self._mlp(params["vf"], obs)[..., 0]
        return {"logits": logits, "vf": values}

    def forward_inference(self, params: Params, obs: jax.Array) -> jax.Array:
        """Greedy action (deterministic serving path)."""
        return jnp.argmax(self._mlp(params["pi"], obs), axis=-1)

    def forward_exploration(
        self, params: Params, obs: jax.Array, key: jax.Array
    ) -> Dict[str, jax.Array]:
        """Sampled action + logp + value (rollout path)."""
        out = self.forward_train(params, obs)
        logits = out["logits"]
        action = jax.random.categorical(key, logits)
        logp = jax.nn.log_softmax(logits)[
            jnp.arange(logits.shape[0]), action
        ]
        return {"action": action, "logp": logp, "vf": out["vf"]}

    def logp_entropy(
        self, params: Params, obs: jax.Array, actions: jax.Array
    ) -> Dict[str, jax.Array]:
        out = self.forward_train(params, obs)
        logits = out["logits"]
        logsm = jax.nn.log_softmax(logits)
        logp = logsm[jnp.arange(logits.shape[0]), actions]
        entropy = -jnp.sum(jnp.exp(logsm) * logsm, axis=-1)
        return {"logp": logp, "entropy": entropy, "vf": out["vf"], "logits": logits}
