"""DQN — double Q-learning with target network and (optionally
prioritized) replay.

Reference: rllib/algorithms/dqn/dqn.py (training_step: sample → store →
replay updates → target sync) and dqn_rainbow_learner's TD loss. The
TPU-native differences: the update is one jitted step (double-DQN target
computed in-graph), and ε-greedy exploration ships inside the params
tree so runner sync is a single object-store put.
"""
from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.off_policy import OffPolicyAlgorithm, OffPolicyConfig
from ray_tpu.rllib.rl_module import RLModuleSpec


def dqn_loss(module, params, batch, gamma: float = 0.99, use_huber: bool = True):
    import jax
    import jax.numpy as jnp

    obs, actions = batch["obs"], batch["actions"]
    n = obs.shape[0]
    ar = jnp.arange(n)
    q_all = module.q_values(params["q"], obs)
    q_sel = q_all[ar, actions]

    # Double DQN: online net picks a*, target net evaluates it.
    q_next_online = module.q_values(params["q"], batch["next_obs"])
    a_star = jnp.argmax(q_next_online, axis=-1)
    target_head = jax.tree.map(jax.lax.stop_gradient, params["target"])
    q_next_target = module.q_values(target_head, batch["next_obs"])
    target = batch["rewards"] + gamma * (1.0 - batch["dones"]) * q_next_target[ar, a_star]

    td = q_sel - jax.lax.stop_gradient(target)
    if use_huber:
        err = jnp.where(jnp.abs(td) <= 1.0, 0.5 * td * td, jnp.abs(td) - 0.5)
    else:
        err = 0.5 * td * td
    loss = jnp.mean(batch["weights"] * err)
    return loss, {
        "mean_q": jnp.mean(q_sel),
        "td_error_mean": jnp.mean(jnp.abs(td)),
        "td_errors": td,  # per-sample, consumed by prioritized replay
    }


class DQNConfig(OffPolicyConfig):
    def __init__(self):
        super().__init__()
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.05
        self.epsilon_decay_steps = 10_000
        self.use_huber = True

    def module_spec(self) -> RLModuleSpec:
        spec = super().module_spec()
        spec.kind = "q"
        return spec

    def build(self) -> "DQN":
        return DQN(self)


class DQN(OffPolicyAlgorithm):
    loss_fn = staticmethod(dqn_loss)
    target_pairs = (("q", "target"),)

    def _loss_cfg(self) -> dict:
        return dict(gamma=self.config.gamma, use_huber=self.config.use_huber)

    def current_epsilon(self) -> float:
        c = self.config
        frac = min(1.0, self._total_env_steps / max(1, c.epsilon_decay_steps))
        return float(c.epsilon_initial + frac * (c.epsilon_final - c.epsilon_initial))

    def _explore_hook(self, weights: Dict[str, Any]) -> Dict[str, Any]:
        import jax.numpy as jnp

        weights["epsilon"] = jnp.asarray(self.current_epsilon(), jnp.float32)
        return weights

    def training_step(self) -> Dict[str, Any]:
        out = super().training_step()
        out["epsilon"] = self.current_epsilon()
        return out
