"""Exception taxonomy.

Mirrors the reference's failure taxonomy (reference:
python/ray/exceptions.py:27-858) so users can handle the same classes of
failures: task errors wrapping user exceptions, actor death/unavailability,
object loss (with causes), OOM, and cancellation.
"""
from __future__ import annotations


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A task raised an exception during execution.

    Wraps the user exception with the remote traceback string so the driver
    sees where the failure happened (reference: python/ray/exceptions.py
    ``RayTaskError``).
    """

    def __init__(self, function_name: str, traceback_str: str, cause: Exception | None = None):
        self.function_name = function_name
        self.traceback_str = traceback_str
        self.cause = cause
        super().__init__(
            f"Task {function_name} failed.\nRemote traceback:\n{traceback_str}"
        )


    def __reduce__(self):
        return (TaskError, (self.function_name, self.traceback_str, self.cause))

class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died unexpectedly."""


class ActorError(RayTpuError):
    pass


class ActorDiedError(ActorError):
    """The actor is dead and will not be restarted (reference:
    python/ray/exceptions.py:326)."""

    def __init__(self, actor_id: str = "", reason: str = ""):
        self.actor_id = actor_id
        self.reason = reason
        super().__init__(f"Actor {actor_id} is dead: {reason}")


    def __reduce__(self):
        return (ActorDiedError, (self.actor_id, self.reason))

class ActorUnavailableError(ActorError):
    """The actor is temporarily unreachable (restarting or network partition)
    (reference: python/ray/exceptions.py:402)."""


class ObjectLostError(RayTpuError):
    """An object is unrecoverable (reference: python/ray/exceptions.py:511)."""

    def __init__(self, object_id: str = "", reason: str = ""):
        self.object_id = object_id
        self.reason = reason
        super().__init__(f"Object {object_id} lost: {reason}")


    def __reduce__(self):
        return (type(self), (self.object_id, self.reason))

class ObjectFetchTimedOutError(ObjectLostError):
    pass


class OwnerDiedError(ObjectLostError):
    pass


class ObjectReconstructionFailedError(ObjectLostError):
    pass


class OutOfMemoryError(RayTpuError):
    """Node memory is exhausted; the task/actor was killed by the memory
    monitor (reference: python/ray/exceptions.py:483)."""


class TaskCancelledError(RayTpuError):
    def __init__(self, task_id: str = ""):
        self.task_id = task_id
        super().__init__(f"Task {task_id} was cancelled")


    def __reduce__(self):
        return (TaskCancelledError, (self.task_id,))

class GetTimeoutError(RayTpuError, TimeoutError):
    """ray_tpu.get() timed out."""


class RuntimeEnvSetupError(RayTpuError):
    pass


class ChannelError(RayTpuError):
    """Compiled-graph channel error (reference: python/ray/exceptions.py:842)."""


class PlacementGroupError(RayTpuError):
    pass
