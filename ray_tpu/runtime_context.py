"""Runtime context: who am I, where am I running.

Reference: python/ray/runtime_context.py (``ray.get_runtime_context()`` —
``get_node_id``, ``get_actor_id``, ``get_task_id``, ``get_worker_id``).
Process-level fields are set once by the worker entrypoint; task-scoped
fields are thread-local because user code runs on executor threads.
"""
from __future__ import annotations

import threading
from typing import Optional

_process = {"node_id": None, "worker_id": None, "job_id": "default"}
_task_local = threading.local()


def _set_process(node_id: Optional[str], worker_id: Optional[str]):
    _process["node_id"] = node_id
    _process["worker_id"] = worker_id


def _set_task(task_id: Optional[str], actor_id: Optional[str]):
    _task_local.task_id = task_id
    _task_local.actor_id = actor_id


class RuntimeContext:
    """Snapshot view; create via :func:`get_runtime_context`."""

    def get_node_id(self) -> Optional[str]:
        return _process["node_id"]

    def get_worker_id(self) -> Optional[str]:
        return _process["worker_id"]

    def get_job_id(self) -> str:
        return _process["job_id"]

    def get_task_id(self) -> Optional[str]:
        return getattr(_task_local, "task_id", None)

    def get_actor_id(self) -> Optional[str]:
        return getattr(_task_local, "actor_id", None)

    @property
    def was_current_actor_reconstructed(self) -> bool:
        return False

    def get(self) -> dict:
        return {
            "node_id": self.get_node_id(),
            "worker_id": self.get_worker_id(),
            "task_id": self.get_task_id(),
            "actor_id": self.get_actor_id(),
            "job_id": self.get_job_id(),
        }


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext()
