"""Asyncio message-passing RPC.

The reference's control plane is gRPC (reference: src/ray/rpc/grpc_server.h,
rpc/client_call.h). We use a symmetric length-prefixed pickle protocol over
TCP: either end of a connection can issue requests and receive responses on
the same socket (the reference needs bidirectional streams for the same
reason — ray_syncer.proto). This keeps the control plane dependency-free and
fast enough for the microbenchmark targets (tens of thousands of small
messages/sec).

Frame: ``[u64 length][pickle (kind, msg_id, method_or_result, payload)]``
kinds: 0=request, 1=response, 2=error-response, 3=one-way notification.
"""
from __future__ import annotations

import asyncio
import itertools
import logging
import pickle
import struct
import threading
from typing import Any, Callable

logger = logging.getLogger(__name__)

_REQ, _RESP, _ERR, _NOTIFY = 0, 1, 2, 3
_HDR = struct.Struct("<Q")

# ---------------------------------------------------------------------------
# Deterministic fault injection (ray_tpu.util.chaos.FaultSchedule): when a
# schedule is installed, every method-addressed frame (request/notify) is
# offered to it before send ("out") and before dispatch ("in") — matched
# rules delay, drop, or fail the frame. None (the default) costs one
# attribute check per frame. The chaos module owns plan parsing and pushes
# the schedule here to keep this module dependency-free.
_fault_schedule = None
# Control frames that manage injection itself are exempt — a drop-all
# partition must still be clearable at runtime. Both legs: the driver→
# controller fan-out request AND the controller→agent install.
_FAULT_EXEMPT = frozenset({"chaos_install", "install_fault_plan"})


def set_fault_schedule(schedule) -> None:
    global _fault_schedule
    _fault_schedule = schedule


def get_fault_schedule():
    return _fault_schedule


def _intercept(method: str, direction: str, label: str):
    if _fault_schedule is None or method in _FAULT_EXEMPT:
        return None
    try:
        return _fault_schedule.intercept(method, direction, label)
    except Exception:  # noqa: BLE001 — a broken plan must not break RPC
        logger.exception("fault schedule intercept failed")
        return None
# Out-of-band frame marker: frames normally start with pickle's 0x80
# protocol opcode; a 0x01 first byte instead means
# [0x01][u32 head_len][head pickle (kind, msg_id)][raw payload bytes] —
# the payload crosses WITHOUT being pickled (no serialize copy on the
# sender, zero-copy memoryview on the receiver). Used for bulk data
# (object-transfer chunks; reference analogue: gRPC byte-buffer frames).
_OOB_MARK = 0x01
_OOB_HEAD = struct.Struct("<I")


class ConnectionLost(ConnectionError):
    pass


class Raw:
    """Wrap a handler's return value to send it as an out-of-band raw
    frame; the caller receives a zero-copy memoryview."""

    __slots__ = ("data",)

    def __init__(self, data):
        self.data = data


class Peer:
    """One side of an established RPC connection.

    Writes are BUFFERED: frames append to an output list and one flush
    task drains it with a single ``writer.write`` per wakeup — pipelined
    small calls (the actor microbench pattern) cost one syscall per
    batch, not per frame (the reference gets this from gRPC's HTTP/2
    framing + ClientCallManager batching, rpc/client_call.h)."""

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter, handler: Any):
        self.reader = reader
        self.writer = writer
        self.handler = handler
        self._ids = itertools.count(1)
        self._pending: dict[int, asyncio.Future] = {}
        self._closed = False
        self._recv_task: asyncio.Task | None = None
        self._outbuf: list[bytes] = []
        self._outbuf_bytes = 0
        self._flushing = False
        self._drained = asyncio.Event()
        self._drained.set()
        # notify() applies backpressure above this backlog (call() gets
        # natural flow control from awaiting replies).
        self.backlog_limit = 8 * 1024 * 1024
        # Arbitrary metadata the handler may attach (worker id, node id, ...).
        self.meta: dict[str, Any] = {}
        # Human label for fault-injection peer matching ("controller",
        # "worker:<hex8>", ...); set by whoever knows the identity.
        self.label: str = ""

    def start(self):
        self._recv_task = asyncio.get_running_loop().create_task(self._recv_loop())
        return self

    # -- buffered write path -------------------------------------------
    def _enqueue_frame(self, frame: tuple):
        data = pickle.dumps(frame, protocol=5)
        self._outbuf.append(_HDR.pack(len(data)))
        self._outbuf.append(data)
        self._outbuf_bytes += _HDR.size + len(data)
        if self._outbuf_bytes > self.backlog_limit:
            self._drained.clear()
        if not self._flushing:
            self._flushing = True
            asyncio.get_running_loop().create_task(self._flush())

    def _enqueue_raw_response(self, msg_id: int, payload):
        head = pickle.dumps((_RESP, msg_id), protocol=5)
        payload = memoryview(payload)
        total = 1 + _OOB_HEAD.size + len(head) + payload.nbytes
        self._outbuf.append(_HDR.pack(total))
        self._outbuf.append(bytes([_OOB_MARK]) + _OOB_HEAD.pack(len(head)) + head)
        self._outbuf.append(payload)  # written without a join copy
        self._outbuf_bytes += _HDR.size + total
        if self._outbuf_bytes > self.backlog_limit:
            self._drained.clear()
        if not self._flushing:
            self._flushing = True
            asyncio.get_running_loop().create_task(self._flush())

    async def _flush(self):
        try:
            while self._outbuf:
                chunk, self._outbuf = self._outbuf, []
                self._outbuf_bytes = 0
                # Large items (raw payloads) are written individually so
                # the b"".join never copies bulk data.
                small: list[bytes] = []
                for item in chunk:
                    if len(item) > 256 * 1024:
                        if small:
                            self.writer.write(b"".join(small))
                            small = []
                        self.writer.write(item)
                    else:
                        small.append(bytes(item))
                if small:
                    self.writer.write(b"".join(small))
                await self.writer.drain()
                if self._outbuf_bytes <= self.backlog_limit:
                    self._drained.set()
        except (ConnectionError, OSError):
            if not self._closed:
                await self._on_disconnect()
        finally:
            self._flushing = False
            self._drained.set()  # never leave a notifier waiting forever

    def call_nowait(self, method: str, *args, **kwargs) -> asyncio.Future:
        """Issue a request and return its reply future without awaiting
        (hot path: the direct actor transport pipelines thousands of
        these). Must run on the connection's loop."""
        fut = asyncio.get_running_loop().create_future()
        if self._closed:
            fut.set_exception(ConnectionLost(f"connection closed (call to {method})"))
            return fut
        msg_id = next(self._ids)
        self._pending[msg_id] = fut
        act = _intercept(method, "out", self.label)
        if act is not None:
            kind = act["action"]
            if kind == "error":
                self._pending.pop(msg_id, None)
                fut.set_exception(act["error"])
                return fut
            if kind == "drop":
                # The frame vanishes like a lost packet: the future stays
                # pending (caller's timeout governs) and resolves with
                # ConnectionLost if the connection later closes.
                return fut
            asyncio.get_running_loop().create_task(
                self._enqueue_delayed((_REQ, msg_id, method, (args, kwargs)),
                                      act["delay_s"])
            )
            return fut
        self._enqueue_frame((_REQ, msg_id, method, (args, kwargs)))
        return fut

    async def _enqueue_delayed(self, frame: tuple, delay_s: float):
        await asyncio.sleep(delay_s)
        if not self._closed:
            self._enqueue_frame(frame)

    async def call(self, method: str, *args, **kwargs) -> Any:
        return await self.call_nowait(method, *args, **kwargs)

    async def notify(self, method: str, *args, **kwargs):
        if self._closed:
            return
        act = _intercept(method, "out", self.label)
        if act is not None:
            if act["action"] in ("drop", "error"):
                return  # fire-and-forget: an injected failure is a drop
            await asyncio.sleep(act["delay_s"])
            if self._closed:
                return
        self._enqueue_frame((_NOTIFY, 0, method, (args, kwargs)))
        if not self._drained.is_set():
            # Backpressure: a fast notifier must not grow the buffer
            # unboundedly against a slow receiver (the pre-batching path
            # awaited writer.drain on every send).
            await self._drained.wait()

    async def _recv_loop(self):
        try:
            while True:
                hdr = await self.reader.readexactly(_HDR.size)
                (length,) = _HDR.unpack(hdr)
                data = await self.reader.readexactly(length)
                if data[0] == _OOB_MARK:
                    (head_len,) = _OOB_HEAD.unpack(data[1 : 1 + _OOB_HEAD.size])
                    off = 1 + _OOB_HEAD.size
                    kind, msg_id = pickle.loads(data[off : off + head_len])
                    a = memoryview(data)[off + head_len :]  # zero-copy payload
                    b = None
                else:
                    kind, msg_id, a, b = pickle.loads(data)
                if kind == _RESP:
                    fut = self._pending.pop(msg_id, None)
                    if fut is not None and not fut.done():
                        fut.set_result(a)
                elif kind == _ERR:
                    fut = self._pending.pop(msg_id, None)
                    if fut is not None and not fut.done():
                        fut.set_exception(a)
                elif kind == _REQ:
                    self._dispatch(msg_id, a, b)
                else:  # _NOTIFY
                    self._dispatch(None, a, b)
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        except asyncio.CancelledError:
            return
        except Exception:
            logger.exception("rpc recv loop error")
        finally:
            await self._on_disconnect()

    def _dispatch(self, msg_id, method, payload):
        """Run the handler INLINE when it is synchronous (or returns a
        Future) — per-request task creation only for true coroutines."""
        if _fault_schedule is not None:
            act = _intercept(method, "in", self.label)
            if act is not None:
                kind = act["action"]
                if kind == "drop":
                    return  # request vanishes: no response, caller times out
                if kind == "error":
                    self._respond_err(msg_id, method, act["error"])
                    return
                asyncio.get_running_loop().create_task(
                    self._dispatch_delayed(msg_id, method, payload,
                                           act["delay_s"])
                )
                return
        self._dispatch_now(msg_id, method, payload)

    async def _dispatch_delayed(self, msg_id, method, payload, delay_s: float):
        await asyncio.sleep(delay_s)
        if not self._closed:
            self._dispatch_now(msg_id, method, payload)

    def _dispatch_now(self, msg_id, method, payload):
        args, kwargs = payload
        try:
            fn = getattr(self.handler, "rpc_" + method, None)
            if fn is None:
                raise AttributeError(f"no rpc method {method!r} on {type(self.handler).__name__}")
            res = fn(self, *args, **kwargs)
        except Exception as e:  # noqa: BLE001 — errors cross the wire
            self._respond_err(msg_id, method, e)
            return
        if asyncio.iscoroutine(res):
            asyncio.get_running_loop().create_task(self._finish_async(msg_id, method, res))
        elif isinstance(res, asyncio.Future):
            if msg_id is not None:
                res.add_done_callback(
                    lambda f, m=msg_id, name=method: self._respond_from_future(m, name, f)
                )
        elif msg_id is not None:
            self._respond(msg_id, method, res)

    def _respond(self, msg_id, method, res):
        if self._closed:
            return
        try:
            if isinstance(res, Raw):
                self._enqueue_raw_response(msg_id, res.data)
            else:
                self._enqueue_frame((_RESP, msg_id, res, None))
        except Exception as e:  # noqa: BLE001 — unpicklable result
            self._respond_err(msg_id, method, e)

    async def _finish_async(self, msg_id, method, coro):
        try:
            res = await coro
        except Exception as e:  # noqa: BLE001
            self._respond_err(msg_id, method, e)
            return
        if isinstance(res, asyncio.Future):
            if msg_id is not None:
                res.add_done_callback(
                    lambda f, m=msg_id, name=method: self._respond_from_future(m, name, f)
                )
            return
        if msg_id is not None:
            self._respond(msg_id, method, res)

    def _respond_from_future(self, msg_id, method, fut: asyncio.Future):
        if self._closed:
            return
        if fut.cancelled():
            self._respond_err(msg_id, method, ConnectionLost("handler cancelled"))
            return
        exc = fut.exception()
        if exc is not None:
            self._respond_err(msg_id, method, exc)
        else:
            # already-done future (done-callback): no wait  # ray-tpu: lint-ignore[RTL008]
            self._respond(msg_id, method, fut.result())

    def _respond_err(self, msg_id, method, e: Exception):
        if msg_id is not None:
            if not self._closed:
                try:
                    self._enqueue_frame((_ERR, msg_id, e, None))
                except Exception:
                    logger.exception("failed to send error response for %s", method)
        else:
            logger.error("error in notification handler %s: %r", method, e)

    async def _on_disconnect(self):
        if self._closed:
            return
        self._closed = True
        for fut in self._pending.values():
            if not fut.done():
                try:
                    fut.set_exception(ConnectionLost("peer disconnected"))
                except RuntimeError:
                    # teardown race: the loop closed under us — nobody is
                    # left to read the future either
                    pass
        self._pending.clear()
        cb = getattr(self.handler, "on_disconnect", None)
        if cb is not None:
            try:
                res = cb(self)
                if asyncio.iscoroutine(res):
                    await res
            except Exception:
                logger.exception("on_disconnect handler error")
        try:
            self.writer.close()
        except Exception:
            pass

    async def close(self):
        # Flush buffered frames first — fire-and-forget notifies enqueued
        # just before a clean shutdown (submit_task, ref_update) must
        # reach the wire (pre-batching, notify() drained synchronously).
        try:
            if self._outbuf and not self._closed:
                chunk, self._outbuf = self._outbuf, []
                self.writer.write(b"".join(chunk))
                await self.writer.drain()
        except Exception:  # noqa: BLE001 — already disconnecting
            pass
        if self._recv_task is not None:
            self._recv_task.cancel()
        try:
            self.writer.close()
            await self.writer.wait_closed()
        except Exception:
            pass
        await self._on_disconnect()

    @property
    def closed(self) -> bool:
        return self._closed


async def serve(handler_factory: Callable[[], Any] | Any, host: str = "127.0.0.1", port: int = 0):
    """Start a server; each connection gets a Peer bound to the handler.

    Returns (server, port). ``handler_factory`` may be a shared handler
    object (typical: the Controller) — its ``on_connect(peer)`` is called for
    every new connection.
    """
    handler = handler_factory() if callable(handler_factory) and not hasattr(handler_factory, "on_connect") else handler_factory

    async def on_conn(reader, writer):
        peer = Peer(reader, writer, handler).start()
        cb = getattr(handler, "on_connect", None)
        if cb is not None:
            res = cb(peer)
            if asyncio.iscoroutine(res):
                await res

    server = await asyncio.start_server(on_conn, host, port)
    actual_port = server.sockets[0].getsockname()[1]
    return server, actual_port


async def connect(host: str, port: int, handler: Any, retries: int = 60,
                  delay: float = 0.1, max_delay: float = 2.0,
                  total_timeout: float = 10.0) -> Peer:
    """Dial with bounded retry and jittered exponential backoff.

    A fixed retry cadence synchronizes every reconnecting client into
    thundering-herd waves against a restarting controller; exponential
    backoff with jitter spreads them out while keeping the first retries
    fast. Both ``retries`` AND ``total_timeout`` bound the dial — with
    backed-off waits, the attempt count alone would stretch a dead
    address from seconds to minutes."""
    import random as _random

    last = None
    wait = delay
    deadline = asyncio.get_running_loop().time() + total_timeout
    for _ in range(retries):
        try:
            reader, writer = await asyncio.open_connection(host, port)
            return Peer(reader, writer, handler).start()
        except (ConnectionError, OSError) as e:
            last = e
            remaining = deadline - asyncio.get_running_loop().time()
            if remaining <= 0:
                break
            await asyncio.sleep(min(wait * (0.5 + _random.random()), remaining))
            wait = min(wait * 1.5, max_delay)
    raise ConnectionLost(f"could not connect to {host}:{port}: {last}")


class BatchedHandoff:
    """Thread→loop handoff amortizing call_soon_threadsafe wakeups: N
    pushes between drains cost ONE self-pipe write. The wake-flag race
    is benign — a double wakeup drains an empty deque."""

    __slots__ = ("_loop", "_fn", "_q", "_wake")

    def __init__(self, loop, fn):
        import collections

        self._loop = loop
        self._fn = fn  # called on the loop thread, once per item
        self._q = collections.deque()
        self._wake = False

    def push(self, item):
        self._q.append(item)
        if not self._wake:
            self._wake = True
            self._loop.call_soon_threadsafe(self._drain)

    def _drain(self):
        self._wake = False
        q = self._q
        fn = self._fn
        while True:
            try:
                item = q.popleft()
            except IndexError:
                return
            fn(item)


class EventLoopThread:
    """A dedicated asyncio loop running in a daemon thread.

    The driver and each worker embed one (the reference embeds a C++ io
    service per CoreWorker — core_worker/core_worker_process.cc); blocking
    public APIs bridge into it with run_coroutine_threadsafe.
    """

    def __init__(self, name: str = "ray-tpu-io"):
        self.loop = asyncio.new_event_loop()
        self.thread = threading.Thread(target=self._run, name=name, daemon=True)
        self.thread.start()

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    def run(self, coro, timeout: float | None = None):
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def submit(self, coro):
        return asyncio.run_coroutine_threadsafe(coro, self.loop)

    def stop(self):
        async def _drain_cancel():
            tasks = [
                t for t in asyncio.all_tasks(self.loop)
                if t is not asyncio.current_task()
            ]
            for t in tasks:
                t.cancel()
            # Give cancelled tasks a cycle to unwind WHILE the loop is
            # still alive: recv loops run their disconnect cleanup here,
            # so no "Task was destroyed but it is pending" at GC and no
            # set_exception against a closed loop.
            await asyncio.gather(*tasks, return_exceptions=True)

        try:
            fut = asyncio.run_coroutine_threadsafe(_drain_cancel(), self.loop)
            try:
                fut.result(timeout=3)
            except Exception:  # noqa: BLE001 — wedged task; stop anyway
                pass
            self.loop.call_soon_threadsafe(self.loop.stop)
            self.thread.join(timeout=5)
            if not self.loop.is_running():
                self.loop.close()
        except Exception:
            pass
