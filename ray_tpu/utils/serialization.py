"""Serialization with zero-copy buffer support.

The reference uses a cloudpickle fork with pickle-protocol-5 out-of-band
buffers for zero-copy numpy/arrow (reference:
python/ray/_private/serialization.py:122 ``SerializationContext``). We use
stock ``cloudpickle`` (vendored with JAX's ecosystem) + protocol 5: large
contiguous buffers are split out so they can land in / be mapped from the
shared-memory object store without copies.

Wire format of a serialized object:
    [u32 meta_len][meta pickle][buffer 0][buffer 1]...
meta = (payload_pickle_bytes, [buffer lengths], [buffer alignments])
"""
from __future__ import annotations

import io
import pickle
import struct
from typing import Any

try:
    import cloudpickle
except ImportError:  # pragma: no cover
    cloudpickle = None

_PROTOCOL = 5
_OOB_THRESHOLD = 4096  # buffers smaller than this are inlined into the pickle


def _dumps(obj: Any, buffer_callback=None) -> bytes:
    if cloudpickle is not None:
        return cloudpickle.dumps(obj, protocol=_PROTOCOL, buffer_callback=buffer_callback)
    return pickle.dumps(obj, protocol=_PROTOCOL, buffer_callback=buffer_callback)


def serialize_parts(obj: Any) -> "tuple[bytes, list, int]":
    """(meta, raw out-of-band buffers, total wire size) WITHOUT assembling
    a contiguous blob — large puts write the parts straight into the
    shared-memory mapping (one copy instead of two; the reference's plasma
    put serializes directly into the store buffer the same way)."""
    buffers: list[pickle.PickleBuffer] = []

    def cb(buf: pickle.PickleBuffer):
        if buf.raw().nbytes >= _OOB_THRESHOLD:
            buffers.append(buf)
            return False  # take out of band
        return True  # keep in-band

    # Fast path: stdlib pickle (C implementation, ~10x cheaper than
    # cloudpickle's Python pickler) — safe unless the payload references
    # __main__ definitions, which stdlib pickles BY NAME (broken across
    # processes) and cloudpickle by value. The b"__main__" scan is a
    # conservative detector: module names appear as plain text in pickle
    # streams; a false positive merely re-serializes via cloudpickle.
    payload = None
    if cloudpickle is not None:
        try:
            fast = pickle.dumps(obj, protocol=_PROTOCOL, buffer_callback=cb)
            if b"__main__" not in fast:
                payload = fast
            else:
                buffers.clear()
        except Exception:  # noqa: BLE001 — lambdas/closures/local classes
            buffers.clear()
        if payload is None:
            payload = cloudpickle.dumps(obj, protocol=_PROTOCOL, buffer_callback=cb)
    else:
        payload = pickle.dumps(obj, protocol=_PROTOCOL, buffer_callback=cb)
    raws = [b.raw() for b in buffers]
    meta = pickle.dumps((payload, [r.nbytes for r in raws]), protocol=_PROTOCOL)
    total = 4 + len(meta) + sum(r.nbytes for r in raws)
    return meta, raws, total


def write_parts(view: memoryview, meta: bytes, raws: list) -> None:
    """Lay out the wire format into a writable buffer (same layout
    ``deserialize`` reads)."""
    view[:4] = struct.pack("<I", len(meta))
    off = 4
    view[off : off + len(meta)] = meta
    off += len(meta)
    for r in raws:  # PickleBuffer.raw() views are always flat bytes
        n = r.nbytes
        view[off : off + n] = r
        off += n


def assemble_parts(meta: bytes, raws: list) -> bytes:
    out = io.BytesIO()
    out.write(struct.pack("<I", len(meta)))
    out.write(meta)
    for r in raws:
        out.write(r)
    return out.getvalue()


def serialize(obj: Any) -> bytes:
    """Serialize to a single contiguous byte string (with OOB buffers packed)."""
    meta, raws, _ = serialize_parts(obj)
    return assemble_parts(meta, raws)


def deserialize(data: bytes | memoryview) -> Any:
    """Deserialize; buffers are zero-copy views into ``data`` when possible."""
    mv = memoryview(data)
    (meta_len,) = struct.unpack("<I", mv[:4])
    payload, lengths = pickle.loads(mv[4 : 4 + meta_len])
    buffers = []
    off = 4 + meta_len
    for n in lengths:
        buffers.append(mv[off : off + n])
        off += n
    return pickle.loads(payload, buffers=buffers)


def serialize_function(fn) -> bytes:
    """Pickle code objects / closures (needs cloudpickle for lambdas)."""
    if cloudpickle is not None:
        return cloudpickle.dumps(fn, protocol=_PROTOCOL)
    return pickle.dumps(fn, protocol=_PROTOCOL)


def deserialize_function(data: bytes):
    return pickle.loads(data)
