"""Binary identifiers.

The reference uses 28-byte binary IDs with embedded owner/actor information
(reference: src/ray/common/id.h). We keep compact random binary IDs with a
type tag and hex rendering; task→object derivation embeds the parent task id
plus a return index so object ids are deterministic given the task
(needed for lineage reconstruction).
"""
from __future__ import annotations

import hashlib
import os
import struct

_ID_BYTES = 16


class BaseID:
    __slots__ = ("_bytes", "_h")
    _prefix = "id"

    def __init__(self, raw: bytes):
        assert isinstance(raw, bytes) and len(raw) == _ID_BYTES, raw
        self._bytes = raw
        self._h = None  # hash cache — ids key hot dicts on every call

    @classmethod
    def from_random(cls):
        return cls(os.urandom(_ID_BYTES))

    @classmethod
    def from_hex(cls, h: str):
        return cls(bytes.fromhex(h))

    @classmethod
    def nil(cls):
        return cls(b"\x00" * _ID_BYTES)

    def is_nil(self) -> bool:
        return self._bytes == b"\x00" * _ID_BYTES

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self):
        h = self._h
        if h is None:
            # hash of raw bytes — cross-type collisions are resolved by
            # __eq__ (which checks the concrete type) and are vanishingly
            # rare for random 16-byte ids anyway
            h = self._h = hash(self._bytes)
        return h

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    _prefix = "job"


class NodeID(BaseID):
    _prefix = "node"


class WorkerID(BaseID):
    _prefix = "worker"


class ActorID(BaseID):
    _prefix = "actor"


class PlacementGroupID(BaseID):
    _prefix = "pg"


class TaskID(BaseID):
    _prefix = "task"

    @classmethod
    def for_actor_creation(cls, actor_id: ActorID) -> "TaskID":
        return cls(_digest(b"actor_creation", actor_id.binary()))

    @classmethod
    def for_index(cls, worker_id: "WorkerID", index: int) -> "TaskID":
        """Counter-derived id — ~7x cheaper than os.urandom on the hot
        submission path, still unique per process (worker ids are random)."""
        return cls(_digest(b"task", worker_id.binary(), struct.pack("<Q", index)))


class ObjectID(BaseID):
    _prefix = "object"

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        """Deterministic: re-executing a task recreates the same object ids."""
        return cls(_digest(b"return", task_id.binary(), struct.pack("<I", index)))

    @classmethod
    def for_put(cls, worker_id: WorkerID, put_index: int) -> "ObjectID":
        return cls(_digest(b"put", worker_id.binary(), struct.pack("<Q", put_index)))


def _digest(*parts: bytes) -> bytes:
    h = hashlib.blake2b(digest_size=_ID_BYTES)
    for p in parts:
        h.update(p)
    return h.digest()
