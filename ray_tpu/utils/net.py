"""Host address resolution for cross-host listeners.

Listeners that other HOSTS must reach (worker direct-transport
listeners, agent object-transfer listeners) bind all interfaces and
advertise a routable address: RAY_TPU_NODE_IP when the operator set
one, else the hostname's resolved address, else loopback (single-host
simulations)."""
from __future__ import annotations

import os
import socket


def host_ip() -> str:
    ip = os.environ.get("RAY_TPU_NODE_IP")
    if ip:
        return ip
    try:
        return socket.gethostbyname(socket.gethostname())
    except OSError:
        return "127.0.0.1"
