"""Host address resolution for cross-host listeners.

Multi-host mode is an EXPLICIT opt-in via RAY_TPU_NODE_IP (set per host
on real pods): listeners that other hosts must reach (worker
direct-transport listeners, agent object-transfer listeners, the
controller) then bind all interfaces and advertise that address.
Without it, everything binds loopback — single-host runs never expose
unauthenticated task-execution or object endpoints on the network, and
no unroutable guessed address (the Debian 127.0.1.1 hostname wart) is
ever advertised to a remote host.
"""
from __future__ import annotations

import os


def multihost_enabled() -> bool:
    return bool(os.environ.get("RAY_TPU_NODE_IP"))


def bind_host() -> str:
    return "0.0.0.0" if multihost_enabled() else "127.0.0.1"


def host_ip() -> str:
    return os.environ.get("RAY_TPU_NODE_IP") or "127.0.0.1"
