"""Compatibility shims across jax versions.

The parallelism code targets the jax >= 0.5 surface (``jax.shard_map``
with ``axis_names=``/``check_vma=``, ``jax.sharding.get_abstract_mesh``);
older runtimes (0.4.x, as baked into some TPU host images) expose the
same functionality as ``jax.experimental.shard_map.shard_map`` with
``auto=``/``check_rep=`` and no ambient abstract-mesh accessor. These
wrappers translate so the call sites stay written against the modern
API.
"""
from __future__ import annotations

from typing import Optional


def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None,
              check_vma: Optional[bool] = None):
    """``jax.shard_map`` when available; else the 0.4.x experimental one.

    ``axis_names`` (modern: the axes to manualize) maps to the legacy
    ``auto`` frozenset (its complement over the mesh axes);
    ``check_vma`` maps to legacy ``check_rep``.
    """
    import jax

    native = getattr(jax, "shard_map", None)
    if native is not None:
        kwargs = {}
        if axis_names is not None:
            kwargs["axis_names"] = axis_names
        if check_vma is not None:
            kwargs["check_vma"] = check_vma
        return native(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **kwargs)
    from jax.experimental.shard_map import shard_map as legacy

    kwargs = {}
    if axis_names is not None:
        mesh_axes = getattr(mesh, "axis_names", ())
        kwargs["auto"] = frozenset(mesh_axes) - set(axis_names)
    if check_vma is not None:
        kwargs["check_rep"] = bool(check_vma)
    return legacy(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  **kwargs)


def axis_size(axis_name):
    """Static size of a named mesh axis inside a shard_map/pmap body.
    ``jax.lax.axis_size`` when available; ``psum(1, axis)`` (a trace-time
    constant) on older jax."""
    import jax

    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return jax.lax.psum(1, axis_name)


def get_abstract_mesh():
    """The ambient abstract mesh (set inside a modern shard_map trace),
    or None when this jax has no such accessor / none is active. Callers
    fall back to their construction-time concrete mesh on None."""
    import jax

    getter = getattr(jax.sharding, "get_abstract_mesh", None)
    if getter is None:
        return None
    mesh = getter()
    # modern jax returns an empty AbstractMesh outside any context;
    # treat anything without a usable shape as "no ambient mesh"
    return mesh if getattr(mesh, "shape", None) else None
