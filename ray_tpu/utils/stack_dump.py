"""Live thread stack dumps for cluster processes.

Reference: the dashboard reporter's py-spy integration + the ``ray
stack`` CLI (python/ray/dashboard/modules/reporter/) — on-demand stack
traces of every worker for debugging hangs. py-spy attaches externally;
here every process can dump itself over its existing RPC channel
(sys._current_frames covers all threads, including executors stuck in
user code).
"""
from __future__ import annotations

import sys
import threading
import traceback


def dump_all_threads() -> str:
    """Formatted stacks of every thread in THIS process."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for ident, frame in sorted(sys._current_frames().items()):
        out.append(f"--- Thread {names.get(ident, '?')} (id {ident}) ---")
        out.append("".join(traceback.format_stack(frame)))
    return "\n".join(out)
