"""URI-aware filesystem layer: one abstraction for every component that
persists to a path (train/tune storage_path, orbax checkpoints, workflow
storage, object spilling).

Reference: python/ray/train/_internal/storage.py:352 (StorageContext
resolves storage_path through pyarrow.fs so `s3://`/`gs://` work
everywhere a local path does) and
python/ray/_private/external_storage.py:452 (object spilling through
smart_open). Here: fsspec (bundled, with gcsfs for `gs://`) behind a
local fast path — local paths never touch fsspec, so the hot spill path
stays plain os I/O.

`memory://` (fsspec's in-process filesystem) stands in for a cloud
bucket in tests — same code path as `gs://`, no network.
"""
from __future__ import annotations

import os
import re
import shutil
from typing import List, Optional, Tuple

_URI_RE = re.compile(r"^[a-zA-Z][a-zA-Z0-9+.-]*://")

# file:// is a URI but resolves to plain local I/O.
_LOCAL_SCHEMES = ("file://", "local://")


def is_uri(path: str) -> bool:
    """True for non-local URIs (gs://, s3://, memory://, …)."""
    if not _URI_RE.match(path or ""):
        return False
    return not path.startswith(_LOCAL_SCHEMES)


def normalize(path: str) -> str:
    """abspath for local paths; URIs pass through UNTOUCHED (abspath on
    `gs://bucket/x` yields `/…/gs:/bucket/x` — the round-2 checkpoint
    bug this module exists to prevent)."""
    if is_uri(path):
        return path
    for scheme in _LOCAL_SCHEMES:
        if path.startswith(scheme):
            path = path[len(scheme):]
            break
    return os.path.abspath(path)


def _fs(path: str):
    import fsspec

    fs, fs_path = fsspec.core.url_to_fs(path)
    return fs, fs_path


def _throttle(path: str, nbytes: int) -> None:
    """Bench/test seam: pace writes landing under
    ``RAY_TPU_CLOUDFS_THROTTLE_PATH`` to ``RAY_TPU_CLOUDFS_THROTTLE_MBPS``
    megabytes/s — models a bandwidth-bound persistent store (the thing a
    real ``gs://`` storage_path is) next to fast host disk, so the
    non-blocking-checkpoint A/B measures a real gap on one box. Inactive
    unless both variables are set; never throttles paths outside the
    prefix (staging snapshots stay at disk speed)."""
    prefix = os.environ.get("RAY_TPU_CLOUDFS_THROTTLE_PATH", "")
    if not prefix or not normalize(path).startswith(normalize(prefix)):
        return
    try:
        mbps = float(os.environ.get("RAY_TPU_CLOUDFS_THROTTLE_MBPS", "") or 0)
    except ValueError:
        return
    if mbps > 0:
        import time

        time.sleep(nbytes / (mbps * 1024 * 1024))


def join(base: str, *parts: str) -> str:
    if is_uri(base):
        return "/".join([base.rstrip("/")] + [p.strip("/") for p in parts])
    return os.path.join(base, *parts)


def makedirs(path: str) -> None:
    if is_uri(path):
        fs, p = _fs(path)
        fs.makedirs(p, exist_ok=True)
    else:
        os.makedirs(normalize(path), exist_ok=True)


def exists(path: str) -> bool:
    if is_uri(path):
        fs, p = _fs(path)
        return fs.exists(p)
    return os.path.exists(normalize(path))


def isdir(path: str) -> bool:
    if is_uri(path):
        fs, p = _fs(path)
        return fs.isdir(p)
    return os.path.isdir(normalize(path))


def listdir(path: str) -> List[str]:
    """Base names of entries directly under ``path``."""
    if is_uri(path):
        fs, p = _fs(path)
        return [e.rstrip("/").rsplit("/", 1)[-1] for e in fs.ls(p, detail=False)]
    return os.listdir(normalize(path))


def write_bytes(path: str, data: bytes) -> None:
    if is_uri(path):
        fs, p = _fs(path)
        parent = p.rsplit("/", 1)[0]
        if parent:
            fs.makedirs(parent, exist_ok=True)
        with fs.open(p, "wb") as f:
            f.write(data)
    else:
        path = normalize(path)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "wb") as f:
            f.write(data)
    _throttle(path, len(data))


def read_bytes(path: str) -> bytes:
    if is_uri(path):
        fs, p = _fs(path)
        with fs.open(p, "rb") as f:
            return f.read()
    with open(normalize(path), "rb") as f:
        return f.read()


def write_text(path: str, text: str) -> None:
    write_bytes(path, text.encode())


def read_text(path: str) -> str:
    return read_bytes(path).decode()


def touch(path: str) -> None:
    write_bytes(path, b"")


def delete(path: str, recursive: bool = True) -> None:
    if is_uri(path):
        fs, p = _fs(path)
        try:
            fs.rm(p, recursive=recursive)
        except FileNotFoundError:
            pass
    else:
        path = normalize(path)
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        else:
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass


def copy_dir(src: str, dest: str) -> None:
    """Recursive directory copy across any (local|URI) × (local|URI)
    combination (reference: StorageContext.persist_current_checkpoint
    uploads rank-local dirs to cloud storage)."""
    if not is_uri(src) and not is_uri(dest):
        dest_n = normalize(dest)

        def _copy(s, d, *, follow_symlinks=True):
            out = shutil.copy2(s, d, follow_symlinks=follow_symlinks)
            _throttle(dest_n, os.path.getsize(s))
            return out

        shutil.copytree(normalize(src), dest_n, dirs_exist_ok=True,
                        copy_function=_copy)
        return
    if not is_uri(src) and is_uri(dest):
        fs, p = _fs(dest)
        fs.makedirs(p, exist_ok=True)
        src = normalize(src)
        for root, _dirs, files in os.walk(src):
            rel = os.path.relpath(root, src)
            for fname in files:
                sub = fname if rel == "." else f"{rel}/{fname}"
                with open(os.path.join(root, fname), "rb") as f:
                    data = f.read()
                target = f"{p.rstrip('/')}/{sub}"
                parent = target.rsplit("/", 1)[0]
                fs.makedirs(parent, exist_ok=True)
                with fs.open(target, "wb") as f:
                    f.write(data)
        return
    if is_uri(src) and not is_uri(dest):
        fs, p = _fs(src)
        dest = normalize(dest)
        os.makedirs(dest, exist_ok=True)
        base = p.rstrip("/")
        for entry in fs.find(base):
            rel = entry[len(base):].lstrip("/")
            local = os.path.join(dest, rel)
            os.makedirs(os.path.dirname(local), exist_ok=True)
            with fs.open(entry, "rb") as f:
                data = f.read()
            with open(local, "wb") as f:
                f.write(data)
        return
    # URI → URI
    sfs, sp = _fs(src)
    dfs, dp = _fs(dest)
    base = sp.rstrip("/")
    for entry in sfs.find(base):
        rel = entry[len(base):].lstrip("/")
        with sfs.open(entry, "rb") as f:
            data = f.read()
        target = f"{dp.rstrip('/')}/{rel}"
        parent = target.rsplit("/", 1)[0]
        dfs.makedirs(parent, exist_ok=True)
        with dfs.open(target, "wb") as f:
            f.write(data)


def download_file(src: str, dest: str, chunk: int = 8 * 1024 * 1024) -> None:
    """Stream a (possibly multi-GB) URI file to a local path in chunks —
    O(chunk) memory, unlike read_bytes/write_bytes."""
    fs, p = _fs(src)
    os.makedirs(os.path.dirname(normalize(dest)) or ".", exist_ok=True)
    with fs.open(p, "rb") as fin, open(normalize(dest), "wb") as fout:
        while True:
            buf = fin.read(chunk)
            if not buf:
                return
            fout.write(buf)


def as_local_dir(path: str) -> Tuple[str, bool]:
    """(local_dir, is_temp): a local view of ``path`` — downloads URI
    contents to a temp dir (caller cleans up when is_temp)."""
    if not is_uri(path):
        return normalize(path), False
    import tempfile

    tmp = tempfile.mkdtemp(prefix="rt_fs_")
    copy_dir(path, tmp)
    return tmp, True
