"""Physical operators for the streaming executor.

Reference: python/ray/data/_internal/execution/operators/ —
TaskPoolMapOperator, ActorPoolMapOperator, InputDataBuffer,
AllToAllOperator (exchange-based shuffle under
_internal/planner/exchange/), LimitOperator, OutputSplitter.

Blocks move between operators as ``RefBundle``s (an ObjectRef plus
driver-side BlockMetadata); transforms run as remote tasks returning
``(block, metadata)`` so the driver only ever fetches the tiny metadata.
"""
from __future__ import annotations

import collections
import logging
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata

logger = logging.getLogger("ray_tpu.data")
from ray_tpu.data.logical import FusedMap, MapLike


@dataclass
class RefBundle:
    ref: Any  # ObjectRef[Block]
    meta: BlockMetadata


# ---------------------------------------------------------------------------
# Remote transform kernels (plain functions wrapped lazily with ray_tpu.remote
# so importing this module never requires an initialized cluster).
# ---------------------------------------------------------------------------


def _apply_stage(block: Block, st: MapLike, udf: Optional[Callable] = None) -> Block:
    fn = udf if udf is not None else st.fn
    acc = BlockAccessor.for_block(block)
    if st.kind == "map_batches":
        batch = acc.to_batch()
        n = acc.num_rows()
        bs = st.batch_size
        if bs is None or n <= bs:
            out = fn(batch, *st.fn_args, **st.fn_kwargs)
            return out if isinstance(out, (dict, list)) else list(out)
        parts = []
        for s in range(0, n, bs):
            sub = {k: v[s : s + bs] for k, v in batch.items()}
            parts.append(fn(sub, *st.fn_args, **st.fn_kwargs))
        return BlockAccessor.concat(parts)
    if st.kind == "map":
        return [fn(r, *st.fn_args, **st.fn_kwargs) for r in acc.iter_rows()]
    if st.kind == "flat_map":
        out: List[Any] = []
        for r in acc.iter_rows():
            out.extend(fn(r, *st.fn_args, **st.fn_kwargs))
        return out
    if st.kind == "filter":
        rows = [r for r in acc.iter_rows() if fn(r, *st.fn_args, **st.fn_kwargs)]
        if isinstance(block, dict) and rows:
            return {k: np.asarray([r[k] for r in rows]) for k in rows[0]}
        return rows
    raise ValueError(f"unknown map kind {st.kind}")


def _run_stages(block: Block, stages: List[MapLike]) -> Tuple[Block, BlockMetadata]:
    for st in stages:
        block = _apply_stage(block, st)
    return block, BlockAccessor.for_block(block).metadata()


def _run_read(read_fn: Callable, stages: List[MapLike]) -> Tuple[Block, BlockMetadata]:
    blocks = list(read_fn())
    block = blocks[0] if len(blocks) == 1 else BlockAccessor.concat(blocks)
    return _run_stages(block, stages)


def _slice_block(block: Block, start: int, end: int) -> Tuple[Block, BlockMetadata]:
    out = BlockAccessor.for_block(block).slice(start, end)
    return out, BlockAccessor.for_block(out).metadata()


def _partition_block(
    block: Block, n: int, key: Optional[str], mode: str, seed, boundaries
) -> Tuple:
    """Map side of the exchange: split one block into n sub-blocks.

    mode: 'rr' (repartition round-robin), 'random' (shuffle), 'hash'
    (groupby), 'range' (sort).  Returns n blocks + 1 metadata list.
    """
    acc = BlockAccessor.for_block(block)
    rows = acc.num_rows()
    if mode == "rr":
        idx = np.arange(rows) % n
    elif mode == "random":
        rng = np.random.default_rng(seed)
        idx = rng.integers(0, n, size=rows)
    elif mode == "hash":
        # NOT python hash(): that is salted per process, and map tasks for
        # different blocks run in different workers — keys must route to
        # the same partition regardless of which worker partitioned them.
        def stable_hash(x) -> int:
            import zlib

            return zlib.crc32(repr(x).encode())

        if isinstance(block, dict):
            col = block[key]
            idx = np.asarray([stable_hash(x) % n for x in col])
        else:
            idx = np.asarray([stable_hash(r[key]) % n for r in acc.iter_rows()])
    elif mode == "range":
        if isinstance(block, dict):
            col = np.asarray(block[key])
        else:
            col = np.asarray([r[key] for r in acc.iter_rows()])
        idx = np.searchsorted(np.asarray(boundaries), col, side="right")
    else:
        raise ValueError(mode)
    outs = [acc.take_indices(np.nonzero(idx == i)[0]) for i in range(n)]
    metas = [BlockAccessor.for_block(o).metadata() for o in outs]
    return tuple(outs) + (metas,)


def _merge_blocks(*parts_and_opts) -> Tuple[Block, BlockMetadata]:
    """Reduce side: concat sub-blocks; optional sort within partition."""
    *parts, key, descending, shuffle_seed = parts_and_opts
    block = BlockAccessor.concat(list(parts))
    acc = BlockAccessor.for_block(block)
    if key is not None:
        batch_or_rows = block
        if isinstance(batch_or_rows, dict):
            order = np.argsort(np.asarray(batch_or_rows[key]), kind="stable")
            if descending:
                order = order[::-1]
            block = acc.take_indices(order)
        else:
            block = sorted(acc.to_rows(), key=lambda r: r[key], reverse=descending)
    elif shuffle_seed is not None:
        rng = np.random.default_rng(shuffle_seed)
        order = rng.permutation(acc.num_rows())
        block = acc.take_indices(order)
    return block, BlockAccessor.for_block(block).metadata()


def _sample_boundaries(block: Block, key: Optional[str]) -> List[Any]:
    return BlockAccessor.for_block(block).sample_keys(key)


_REMOTE_CACHE: Dict[Tuple[str, float, float], Any] = {}


def _remote(fn, num_returns=2, num_cpus=1, num_tpus=0):
    k = (fn.__name__, num_returns, num_cpus, num_tpus)
    if k not in _REMOTE_CACHE:
        _REMOTE_CACHE[k] = ray_tpu.remote(
            num_returns=num_returns, num_cpus=num_cpus, num_tpus=num_tpus
        )(fn)
    return _REMOTE_CACHE[k]


# ---------------------------------------------------------------------------
# Physical operator interface
# ---------------------------------------------------------------------------


class PhysicalOperator:
    def __init__(self, name: str):
        self.name = name
        self._in_queue: collections.deque = collections.deque()
        self._out_queue: collections.deque = collections.deque()
        self._inputs_done = False
        self._finished = False
        # stats
        self.rows_out = 0
        self.blocks_out = 0
        self.tasks_submitted = 0
        self.peak_in_bytes = 0
        # Scheduler ticks that refused to poll this operator because its
        # downstream buffer was saturated (backpressure observability —
        # also data_backpressure_stalls_total{op}).
        self.backpressure_stalls = 0

    # -- executor-facing ---------------------------------------------------
    def add_input(self, bundle: RefBundle):
        self._in_queue.append(bundle)
        self.peak_in_bytes = max(self.peak_in_bytes, self.input_bytes())

    def input_bytes(self) -> int:
        """Bytes buffered at this operator's input (block sizes from the
        bundles' metadata) — drives byte-budgeted backpressure."""
        return sum(b.meta.size_bytes for b in self._in_queue)

    def output_bytes(self) -> int:
        return sum(b.meta.size_bytes for b in self._out_queue)

    def all_inputs_done(self):
        self._inputs_done = True

    def has_next(self) -> bool:
        return bool(self._out_queue)

    def get_next(self) -> RefBundle:
        b = self._out_queue.popleft()
        self.rows_out += b.meta.num_rows
        self.blocks_out += 1
        return b

    def outputs_buffered(self) -> int:
        return len(self._out_queue)

    def num_active_tasks(self) -> int:
        return 0

    def poll(self):
        """Advance: submit work, harvest finished tasks. Non-blocking."""

    def completed(self) -> bool:
        return (
            self._inputs_done
            and not self._in_queue
            and not self._out_queue
            and self.num_active_tasks() == 0
            and self._finished_extra()
        )

    def _finished_extra(self) -> bool:
        return True

    def _harvest_ordered(self):
        """Emit the ready *prefix* of ``self._live`` in submission order so
        downstream row order is deterministic (reference: ExecutionOptions
        preserve_order)."""
        while self._live:
            block_ref, meta_ref = self._live[0]
            ready, _ = ray_tpu.wait([meta_ref], timeout=0)
            if not ready:
                break
            self._live.pop(0)
            self._out_queue.append(RefBundle(block_ref, ray_tpu.get(meta_ref)))

    def shutdown(self):
        pass


class InputDataBuffer(PhysicalOperator):
    """Holds pre-planned input bundles (reference:
    execution/operators/input_data_buffer.py)."""

    def __init__(self, bundles: List[RefBundle]):
        super().__init__("Input")
        self._out_queue.extend(bundles)
        self._inputs_done = True


class ReadOperator(PhysicalOperator):
    """Executes ReadTasks remotely, with any fused map stages applied
    in the same task (read fusion — reference: operator fusion rule)."""

    def __init__(self, read_tasks, stages: List[MapLike], concurrency: int = 8):
        super().__init__("Read" + ("->" + "->".join(s.name for s in stages) if stages else ""))
        self._pending = list(read_tasks)
        self._stages = stages
        self._concurrency = concurrency
        self._live: List[Tuple[Any, Any]] = []  # (block_ref, meta_ref)
        self._inputs_done = True

    def num_active_tasks(self) -> int:
        return len(self._live)

    def poll(self):
        fn = _remote(_run_read)
        while self._pending and len(self._live) < self._concurrency:
            rt = self._pending.pop(0)
            block_ref, meta_ref = fn.remote(rt.read_fn, self._stages)
            self.tasks_submitted += 1
            self._live.append((block_ref, meta_ref))
        self._harvest_ordered()

    def _finished_extra(self) -> bool:
        return not self._pending and not self._live


class TaskPoolMapOperator(PhysicalOperator):
    def __init__(self, fused: FusedMap, concurrency: int = 8):
        super().__init__(fused.name)
        self._stages = fused.stages
        self._concurrency = concurrency
        st = fused.stages[0]
        self._num_cpus = st.num_cpus
        self._num_tpus = st.num_tpus
        self._live: List[Tuple[Any, Any]] = []

    def num_active_tasks(self) -> int:
        return len(self._live)

    def poll(self):
        fn = _remote(_run_stages, num_cpus=self._num_cpus, num_tpus=self._num_tpus)
        while self._in_queue and len(self._live) < self._concurrency:
            bundle = self._in_queue.popleft()
            block_ref, meta_ref = fn.remote(bundle.ref, self._stages)
            self.tasks_submitted += 1
            self._live.append((block_ref, meta_ref))
        self._harvest_ordered()

    def _finished_extra(self) -> bool:
        return not self._live


class _UDFActor:
    """Actor wrapper instantiating a stateful UDF class once (reference:
    execution/operators/actor_pool_map_operator.py _MapWorker)."""

    def __init__(self, cls_or_fn, ctor_args, stages):
        self._stages = stages
        self._udf = cls_or_fn(*ctor_args) if isinstance(cls_or_fn, type) else cls_or_fn

    def apply(self, block):
        st = self._stages[0]
        block = _apply_stage(block, st, udf=self._udf)
        for extra in self._stages[1:]:
            block = _apply_stage(block, extra)
        return block, BlockAccessor.for_block(block).metadata()


class ActorPoolMapOperator(PhysicalOperator):
    """Stateful-UDF map over an actor pool that AUTOSCALES between a min
    and max size on queue depth (reference:
    execution/autoscaler/default_autoscaler.py + actor_pool_map_operator's
    scale_up/scale_down): ``concurrency=N`` pins the pool at N;
    ``concurrency=(lo, hi)`` starts at ``lo``, grows while queued input
    exceeds in-flight capacity, and reaps actors idle past the context's
    idle timeout back down to ``lo``."""

    def __init__(self, op: MapLike, tasks_per_actor: int = 2):
        ca = op.compute_actors
        self._min, self._max = (ca, ca) if isinstance(ca, int) else (ca[0], ca[1])
        super().__init__(f"{op.name}(actors={self._min}..{self._max})")
        self._op = op
        self._tasks_per_actor = tasks_per_actor
        self._actors: Dict[int, Any] = {}
        self._load: Dict[int, int] = {}
        self._idle_since: Dict[int, float] = {}
        self._next_idx = 0
        self._live: List[Tuple[int, Any, Any]] = []
        self.actors_peak = 0

    @property
    def pool_size(self) -> int:
        return len(self._actors)

    def _add_actor(self):
        cls = ray_tpu.remote(num_cpus=self._op.num_cpus, num_tpus=self._op.num_tpus)(
            _UDFActor
        )
        i = self._next_idx
        self._next_idx += 1
        self._actors[i] = cls.remote(
            self._op.fn, self._op.fn_constructor_args, [self._op]
        )
        self._load[i] = 0
        self.actors_peak = max(self.actors_peak, len(self._actors))

    def _scale(self):
        import time as _time

        from ray_tpu.data.context import DataContext

        while len(self._actors) < self._min:
            self._add_actor()
        free_slots = sum(
            max(0, self._tasks_per_actor - n) for n in self._load.values()
        )
        # scale UP: queued work beyond what the pool can take in flight
        while (
            len(self._actors) < self._max
            and len(self._in_queue) > free_slots
        ):
            self._add_actor()
            free_slots += self._tasks_per_actor
        # scale DOWN: reap actors idle past the timeout, min floor holds.
        # Never while input is queued — poll() is about to dispatch it and
        # a kill-then-respawn would re-pay UDF constructor cost per burst.
        if len(self._actors) > self._min and not self._in_queue:
            now = _time.monotonic()
            timeout = DataContext.get_current().actor_idle_timeout_s
            for i in list(self._actors):
                if len(self._actors) <= self._min:
                    break
                if self._load[i] > 0:
                    self._idle_since.pop(i, None)
                    continue
                since = self._idle_since.setdefault(i, now)
                if now - since >= timeout:
                    try:
                        ray_tpu.kill(self._actors[i])
                    except Exception as e:  # noqa: BLE001 — already dead
                        logger.debug("idle map-actor kill failed: %s", e)
                    del self._actors[i]
                    del self._load[i]
                    self._idle_since.pop(i, None)

    def num_active_tasks(self) -> int:
        return len(self._live)

    def poll(self):
        self._scale()
        cap = len(self._actors) * self._tasks_per_actor
        while self._in_queue and len(self._live) < cap:
            bundle = self._in_queue.popleft()
            i = min(self._load, key=self._load.get)
            block_ref, meta_ref = (
                self._actors[i].apply.options(num_returns=2).remote(bundle.ref)
            )
            self.tasks_submitted += 1
            self._load[i] += 1
            self._live.append((i, block_ref, meta_ref))
        while self._live:
            i, block_ref, meta_ref = self._live[0]
            ready, _ = ray_tpu.wait([meta_ref], timeout=0)
            if not ready:
                break
            self._live.pop(0)
            if i in self._load:
                self._load[i] -= 1
            self._out_queue.append(RefBundle(block_ref, ray_tpu.get(meta_ref)))

    def _finished_extra(self) -> bool:
        return not self._live

    def shutdown(self):
        for a in self._actors.values():
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        self._actors = {}
        self._load = {}


class AllToAllOperator(PhysicalOperator):
    """Two-stage push-based exchange (reference:
    _internal/planner/exchange/ shuffle_task_scheduler + sort/hash
    partition specs). Barriers on all inputs, then map-partitions each
    block into N sub-blocks and merges partition-wise."""

    def __init__(self, kind: str, num_outputs, key=None, descending=False, seed=None):
        super().__init__(kind)
        self.kind = kind
        self._num_outputs = num_outputs
        self._key = key
        self._descending = descending
        if kind == "shuffle" and seed is None:
            # Unseeded shuffle must differ across calls; draw fresh entropy
            # once so the execution itself is still internally consistent.
            import secrets

            seed = secrets.randbits(32)
        self._seed = seed
        self._collected: List[RefBundle] = []
        self._phase = "collect"
        self._map_live: List[Any] = []
        self._reduce_live: List[Tuple[Any, Any]] = []
        self._boundary_refs: List[Any] = []

    def num_active_tasks(self) -> int:
        return len(self._map_live) + len(self._reduce_live)

    def poll(self):
        while self._in_queue:
            self._collected.append(self._in_queue.popleft())
        if self._phase == "collect" and self._inputs_done:
            self._start_exchange()
        elif self._phase == "boundaries":
            self._poll_boundaries()
        elif self._phase == "map":
            self._poll_map()
        elif self._phase == "reduce":
            self._poll_reduce()

    def _start_exchange(self):
        if not self._collected:
            self._phase = "done"
            return
        n = self._num_outputs or len(self._collected)
        self._n = max(1, n)
        if self.kind == "sort":
            sample = _remote(_sample_boundaries, num_returns=1)
            self._boundary_refs = [
                sample.remote(b.ref, self._key) for b in self._collected
            ]
            self._phase = "boundaries"
        else:
            self._launch_map(None)

    def _poll_boundaries(self):
        ready, _ = ray_tpu.wait(
            self._boundary_refs, num_returns=len(self._boundary_refs), timeout=0
        )
        if len(ready) < len(self._boundary_refs):
            return
        samples = sorted(
            s for ref in self._boundary_refs for s in ray_tpu.get(ref)
        )
        if samples:
            idx = np.linspace(0, len(samples) - 1, num=self._n + 1).astype(int)[1:-1]
            boundaries = [samples[i] for i in idx]
        else:
            boundaries = []
        self._launch_map(boundaries)

    def _launch_map(self, boundaries):
        mode = {"repartition": "rr", "shuffle": "random", "sort": "range", "hash": "hash"}[
            self.kind
        ]
        part = _remote(_partition_block, num_returns=self._n + 1)
        self._partials: List[List[Any]] = [[] for _ in range(self._n)]
        for j, b in enumerate(self._collected):
            seed = None if self._seed is None else self._seed + j
            out = part.remote(b.ref, self._n, self._key, mode, seed, boundaries)
            for i in range(self._n):
                self._partials[i].append(out[i])
            self._map_live.append(out[self._n])  # metas ref as completion marker
        self._phase = "map"

    def _poll_map(self):
        ready, _ = ray_tpu.wait(self._map_live, num_returns=len(self._map_live), timeout=0)
        if len(ready) < len(self._map_live):
            return
        merge = _remote(_merge_blocks)
        sort_key = self._key if self.kind == "sort" else None
        partials = self._partials
        if self.kind == "sort" and self._descending:
            # Range partitions are ascending; a descending sort emits them
            # in reverse partition order.
            partials = list(reversed(partials))
        for i, parts in enumerate(partials):
            shuffle_seed = (
                None if self.kind != "shuffle" else (self._seed or 0) * 13 + i
            )
            block_ref, meta_ref = merge.remote(
                *parts, sort_key, self._descending, shuffle_seed
            )
            self._reduce_live.append((block_ref, meta_ref))
        self._map_live = []
        self._phase = "reduce"

    def _poll_reduce(self):
        # Ordered harvest: partition order IS the output order (a sorted
        # dataset's global order depends on emitting partition i before i+1).
        while self._reduce_live:
            block_ref, meta_ref = self._reduce_live[0]
            ready, _ = ray_tpu.wait([meta_ref], timeout=0)
            if not ready:
                return
            self._reduce_live.pop(0)
            self._out_queue.append(RefBundle(block_ref, ray_tpu.get(meta_ref)))
        self._phase = "done"

    def _finished_extra(self) -> bool:
        return self._phase == "done" and not self.num_active_tasks()


class LimitOperator(PhysicalOperator):
    def __init__(self, limit: int):
        super().__init__(f"Limit[{limit}]")
        self._remaining = limit
        self._slice_live: List[Tuple[Any, Any]] = []

    def num_active_tasks(self) -> int:
        return len(self._slice_live)

    def poll(self):
        while self._in_queue:
            bundle = self._in_queue.popleft()
            if self._remaining <= 0:
                continue
            if bundle.meta.num_rows <= self._remaining:
                self._remaining -= bundle.meta.num_rows
                self._out_queue.append(bundle)
            else:
                fn = _remote(_slice_block)
                block_ref, meta_ref = fn.remote(bundle.ref, 0, self._remaining)
                self._remaining = 0
                self._slice_live.append((block_ref, meta_ref))
        if self._slice_live:
            ready, _ = ray_tpu.wait(
                [m for _, m in self._slice_live],
                num_returns=len(self._slice_live),
                timeout=0,
            )
            ready_set = set(ready)
            still = []
            for block_ref, meta_ref in self._slice_live:
                if meta_ref in ready_set:
                    self._out_queue.append(RefBundle(block_ref, ray_tpu.get(meta_ref)))
                else:
                    still.append((block_ref, meta_ref))
            self._slice_live = still

    def reached_limit(self) -> bool:
        return self._remaining <= 0 and not self._slice_live

    def _finished_extra(self) -> bool:
        return not self._slice_live
