"""Streaming executor: drives the physical operator chain.

Reference: python/ray/data/_internal/execution/streaming_executor.py
(StreamingExecutor thread, ``_scheduling_loop_step`` :272) plus the
backpressure policies under execution/backpressure_policy/. The loop here
is pull-based: each tick moves bundles downstream, polls operators (which
submit/harvest remote tasks), and applies backpressure by refusing to poll
an operator whose downstream buffer is already full.
"""
from __future__ import annotations

import threading
import time
from typing import Iterator, List, Optional

from ray_tpu.data.logical import (
    AllToAll,
    FusedMap,
    InputData,
    Limit,
    LogicalPlan,
    MapLike,
    Read,
    Union as LUnion,
    Zip as LZip,
)
from ray_tpu.data.metrics import data_metrics
from ray_tpu.data.operators import (
    ActorPoolMapOperator,
    AllToAllOperator,
    InputDataBuffer,
    LimitOperator,
    PhysicalOperator,
    ReadOperator,
    RefBundle,
    TaskPoolMapOperator,
)

# Max bundles buffered between two operators before upstream is paused
# (reference: backpressure_policy/streaming_output_backpressure_policy.py).
# The byte budget (DataContext.max_buffered_bytes) is the primary limit —
# block sizes come from bundle metadata — with this count cap for tiny
# blocks. MAX_BUFFERED remains the count default.
MAX_BUFFERED = 16


def _input_saturated(op) -> bool:
    from ray_tpu.data.context import DataContext

    ctx = DataContext.get_current()
    q = op._in_queue
    return len(q) >= ctx.max_buffered_blocks or op.input_bytes() >= ctx.max_buffered_bytes


def _output_saturated(op) -> bool:
    from ray_tpu.data.context import DataContext

    ctx = DataContext.get_current()
    return (
        op.outputs_buffered() >= ctx.max_buffered_blocks
        or op.output_bytes() >= ctx.max_buffered_bytes
    )


def plan_to_operators(plan: LogicalPlan, concurrency: int = 8) -> List[PhysicalOperator]:
    """Lower the optimized logical DAG to a physical chain (reference:
    _internal/planner/planner.py)."""
    ops: List[PhysicalOperator] = []
    for lop in plan.dag.chain():
        if isinstance(lop, Read):
            par = lop.parallelism if lop.parallelism > 0 else concurrency * 2
            ops.append(ReadOperator(lop.datasource.get_read_tasks(par), [], concurrency))
        elif isinstance(lop, InputData):
            ops.append(InputDataBuffer([RefBundle(r, m) for r, m in lop.bundles]))
        elif isinstance(lop, FusedMap):
            # Read->Map fusion: fold map stages into the upstream read tasks
            # (only for default-resource stages — reads run with 1 CPU, so a
            # stage requesting TPUs/extra CPUs must stay its own task).
            default_res = all(
                (s.num_cpus, s.num_tpus) == (1, 0) for s in lop.stages
            )
            if ops and default_res and isinstance(ops[-1], ReadOperator) and not ops[-1]._stages and not ops[-1].tasks_submitted:
                rd = ops[-1]
                rd._stages = lop.stages
                rd.name = "Read->" + "->".join(s.name for s in lop.stages)
            else:
                ops.append(TaskPoolMapOperator(lop, concurrency))
        elif isinstance(lop, MapLike):  # unfused: actor-pool compute
            ops.append(ActorPoolMapOperator(lop))
        elif isinstance(lop, AllToAll):
            kind = {"repartition": "repartition", "shuffle": "shuffle", "sort": "sort", "aggregate": "hash"}[lop.kind]
            ops.append(
                AllToAllOperator(
                    kind, lop.num_outputs, key=lop.key, descending=lop.descending, seed=lop.seed
                )
            )
        elif isinstance(lop, Limit):
            ops.append(LimitOperator(lop.limit))
        elif isinstance(lop, LUnion):
            # The chain walked so far is branch 0; the other branches lower
            # recursively. All collapse into one UnionOperator node.
            chains = [ops] + [
                plan_to_operators(LogicalPlan(o), concurrency) for o in lop.others
            ]
            ops = [UnionOperator(chains)]
        elif isinstance(lop, LZip):
            chains = [ops] + [
                plan_to_operators(LogicalPlan(o), concurrency) for o in lop.others
            ]
            ops = [ZipOperator(chains)]
        else:
            raise NotImplementedError(f"cannot lower {lop}")
    return ops


class StreamingExecutor:
    """Executes the chain, yielding output RefBundles as they materialize."""

    def __init__(self, ops: List[PhysicalOperator]):
        self._ops = ops
        self._stopped = False

    def stats(self) -> List[dict]:
        out = []
        for o in self._ops:
            row = dict(
                op=o.name,
                rows_out=o.rows_out,
                blocks_out=o.blocks_out,
                tasks=o.tasks_submitted,
                queued_blocks=len(o._in_queue),
                queued_bytes=o.input_bytes(),
                peak_in_bytes=o.peak_in_bytes,
                active_tasks=o.num_active_tasks(),
                backpressure_stalls=o.backpressure_stalls,
            )
            if hasattr(o, "pool_size"):
                row["actors"] = o.pool_size
                row["actors_peak"] = o.actors_peak
            out.append(row)
        return out

    def _step(self) -> bool:
        """One scheduling tick; returns True if the pipeline is finished."""
        return _step_chain(self._ops)

    def iter_bundles(self) -> Iterator[RefBundle]:
        last = self._ops[-1]
        try:
            while True:
                done = self._step()
                emitted = False
                while last.has_next():
                    emitted = True
                    yield last.get_next()
                if done and not last.has_next():
                    break
                if not emitted:
                    time.sleep(0.002)
        finally:
            self.shutdown()

    def shutdown(self):
        if self._stopped:
            return
        self._stopped = True
        record_last_stats(self.stats())
        for op in self._ops:
            op.shutdown()


# Last execution's per-op stats, surfaced by the state API
# (ray_tpu.util.state.summarize_data — reference: the dashboard's data
# module exposing per-operator metrics from _internal/stats.py).
_last_stats: List[dict] = []


def record_last_stats(stats: List[dict]):
    global _last_stats
    _last_stats = stats


def last_execution_stats() -> List[dict]:
    return list(_last_stats)


def _step_chain(ops: List[PhysicalOperator]) -> bool:
    # Move bundles downstream (last op's outputs are consumed by caller).
    for i, op in enumerate(ops[:-1]):
        nxt = ops[i + 1]
        while op.has_next() and not _input_saturated(nxt):
            nxt.add_input(op.get_next())
        if op.completed() and not nxt._inputs_done:
            nxt.all_inputs_done()
    # Early-exit: a satisfied Limit upstream-cancels the producers
    # (reference: streaming executor limit propagation).
    for i, op in enumerate(ops):
        if isinstance(op, LimitOperator) and op.reached_limit():
            for up in ops[:i]:
                if not up._inputs_done:
                    up.all_inputs_done()
                up._in_queue.clear()
                if hasattr(up, "_pending"):
                    up._pending = []
            if not op._inputs_done:
                op.all_inputs_done()
    # Poll operators unless their downstream buffer is saturated (by
    # block count OR byte budget — a fat producer stalls instead of
    # OOMing the store; reference: resource-aware backpressure).
    for i, op in enumerate(ops):
        downstream_full = i + 1 < len(ops) and _input_saturated(ops[i + 1])
        if downstream_full or _output_saturated(op):
            if not op.completed():
                op.backpressure_stalls += 1
                data_metrics().backpressure_stalls.inc(1, {"op": op.name})
            continue
        op.poll()
    return all(o.completed() for o in ops)


class UnionOperator(PhysicalOperator):
    """Lazy union: owns the branch operator chains and steps them in place.
    Branches execute concurrently (each chain's own backpressure applies)
    but outputs stream in branch order for determinism."""

    def __init__(self, chains: List[List[PhysicalOperator]]):
        super().__init__(f"Union[{len(chains)}]")
        self._chains = chains
        self._emit_branch = 0
        self._inputs_done = True

    def num_active_tasks(self) -> int:
        return sum(op.num_active_tasks() for ch in self._chains for op in ch)

    def poll(self):
        for ch in self._chains:
            _step_chain(ch)
        while self._emit_branch < len(self._chains):
            ch = self._chains[self._emit_branch]
            last = ch[-1]
            emitted = False
            while last.has_next() and len(self._out_queue) < MAX_BUFFERED:
                self._out_queue.append(last.get_next())
                emitted = True
            if all(op.completed() for op in ch) and not last.has_next():
                self._emit_branch += 1
                continue
            if not emitted or len(self._out_queue) >= MAX_BUFFERED:
                break

    def _finished_extra(self) -> bool:
        return self._emit_branch >= len(self._chains)

    def shutdown(self):
        for ch in self._chains:
            for op in ch:
                op.shutdown()


class ZipOperator(PhysicalOperator):
    """Row-aligned zip of N branch chains (reference: Ray Data's
    ZipOperator). Streams: per-branch column buffers fill as branch
    blocks materialize; whenever every branch has rows pending, a merged
    block of ``min(pending)`` rows is emitted — no full materialization,
    and uneven block boundaries across branches are re-aligned here."""

    def __init__(self, chains: List[PhysicalOperator]):
        super().__init__(f"Zip[{len(chains)}]")
        self._chains = chains
        self._inputs_done = True
        # per-branch: list of (batch dict, row offset)
        self._buffers: List[list] = [[] for _ in chains]
        self._drained = [False] * len(chains)

    def num_active_tasks(self) -> int:
        return sum(op.num_active_tasks() for ch in self._chains for op in ch)

    def _pull_branches(self):
        import ray_tpu
        from ray_tpu.data.block import BlockAccessor

        for i, ch in enumerate(self._chains):
            # Backpressure: stop stepping/pulling a branch that is already
            # MAX_BUFFERED blocks ahead — otherwise a fast branch zipped
            # with a slow one materializes entirely into driver memory.
            if len(self._buffers[i]) >= MAX_BUFFERED:
                continue
            _step_chain(ch)
            last = ch[-1]
            while last.has_next() and len(self._buffers[i]) < MAX_BUFFERED:
                bundle = last.get_next()
                batch = BlockAccessor.for_block(ray_tpu.get(bundle.ref)).to_batch()
                n = len(next(iter(batch.values()))) if batch else 0
                if n:
                    self._buffers[i].append([batch, 0])
            if all(op.completed() for op in ch) and not last.has_next():
                self._drained[i] = True

    def _rows_buffered(self, i: int) -> int:
        return sum(
            len(next(iter(b.values()))) - off for b, off in self._buffers[i]
        )

    def _take_rows(self, i: int, n: int) -> dict:
        """Consume n rows from branch i's buffer as one column batch."""
        import numpy as np

        parts: List[dict] = []
        need = n
        while need > 0:
            batch, off = self._buffers[i][0]
            avail = len(next(iter(batch.values()))) - off
            take = min(avail, need)
            parts.append({k: np.asarray(v)[off : off + take] for k, v in batch.items()})
            if take == avail:
                self._buffers[i].pop(0)
            else:
                self._buffers[i][0][1] = off + take
            need -= take
        if len(parts) == 1:
            return parts[0]
        return {k: np.concatenate([p[k] for p in parts]) for k in parts[0]}

    def poll(self):
        import ray_tpu
        from ray_tpu.data.block import BlockMetadata

        self._pull_branches()
        while len(self._out_queue) < MAX_BUFFERED:
            counts = [self._rows_buffered(i) for i in range(len(self._chains))]
            n = min(counts)
            if n == 0:
                # A fully-drained empty branch while another still holds
                # rows means the datasets have unequal row counts — an
                # error, exactly as the reference's zip treats it.
                if any(
                    self._drained[i] and counts[i] == 0 and max(counts) > 0
                    for i in range(len(counts))
                ):
                    raise ValueError(
                        "Dataset.zip requires equal row counts across all "
                        f"datasets; got a drained branch with {counts} rows "
                        "still buffered elsewhere"
                    )
                break
            merged: dict = {}
            for i in range(len(self._chains)):
                part = self._take_rows(i, n)
                for k, v in part.items():
                    key = k
                    while key in merged:
                        key = key + "_1"  # collision suffix (reference: zip renames dupes)
                    merged[key] = v
            size = sum(v.nbytes if hasattr(v, "nbytes") else 64 for v in merged.values())
            self._out_queue.append(
                RefBundle(ray_tpu.put(merged), BlockMetadata(num_rows=n, size_bytes=size))
            )

    def _finished_extra(self) -> bool:
        if not all(self._drained):
            return False
        # Done once no further aligned rows can be produced.
        return min(self._rows_buffered(i) for i in range(len(self._chains))) == 0

    def shutdown(self):
        for ch in self._chains:
            for op in ch:
                op.shutdown()


class SplitCoordinator:
    """Driver-side fan-out for ``streaming_split`` (reference:
    execution/operators/output_splitter.py + StreamSplitDataIterator).

    Runs the executor on a background thread; ``n`` consumers each pull
    from a dedicated queue fed round-robin (equal-ish block counts).
    """

    def __init__(self, ops: List[PhysicalOperator], n: int, equal: bool):
        import queue

        self._executor = StreamingExecutor(ops)
        self._queues = [queue.Queue(maxsize=MAX_BUFFERED) for _ in range(n)]
        self._dead = [False] * n
        self._n = n
        self._equal = equal
        # A pump-thread crash must NOT look like a clean end of stream —
        # consumers re-raise this instead of stopping at the sentinel
        # (otherwise every rank trains on partial data and fit() reports
        # success).
        self._error: Optional[BaseException] = None
        self._thread = threading.Thread(target=self._pump, daemon=True, name="split-pump")
        self._thread.start()

    def _check_error(self):
        if self._error is not None:
            raise RuntimeError(
                "streaming_split execution failed"
            ) from self._error

    def _pump(self):
        import queue as _q

        i = 0
        try:
            for bundle in self._executor.iter_bundles():
                # Round-robin keeps block counts equal across splits. A
                # consumer that abandoned its iterator is skipped so one
                # dead split can't stall the others.
                placed = False
                while not placed:
                    if all(self._dead):
                        return
                    target = i % self._n
                    i += 1
                    if self._dead[target]:
                        continue
                    try:
                        self._queues[target].put(bundle, timeout=1.0)
                        placed = True
                    except _q.Full:
                        if not self._equal:
                            continue  # try the next split
                        # equal=True: must keep round-robin; retry same slot
                        # by rewinding unless it died meanwhile.
                        i -= 1
        except BaseException as e:  # noqa: BLE001 — re-raised consumer-side
            self._error = e
        finally:
            for idx, q in enumerate(self._queues):
                while not self._dead[idx]:
                    try:
                        q.put(None, timeout=0.5)
                        break
                    except _q.Full:
                        continue

    def iter_split(self, idx: int) -> Iterator[RefBundle]:
        q = self._queues[idx]
        try:
            while True:
                # pump guarantees a sentinel even on error (finally)  # ray-tpu: lint-ignore[RTL008]
                item = q.get()
                if item is None:
                    self._check_error()
                    return
                yield item
        finally:
            self._dead[idx] = True

    def release(self, idx: int):
        """Mark split ``idx`` abandoned: the pump skips it from now on and
        its queued bundles are discarded (the same invariant iter_split's
        ``finally`` enforces — without it, one consumer stopping early
        leaves the pump stalled on that split's full queue and starves
        every other split)."""
        import queue as _q

        self._dead[idx] = True
        while True:
            try:
                self._queues[idx].get_nowait()
            except _q.Empty:
                return

    def next_batch(self, idx: int, max_n: int = 8) -> Optional[List[RefBundle]]:
        """Up to ``max_n`` bundles for split ``idx`` — blocks for the
        first (None = end of stream), then drains whatever is immediately
        ready without blocking. The amortized pull interface the
        cross-process shard coordinator actor exposes to train workers."""
        import queue as _q

        q = self._queues[idx]
        if self._dead[idx]:
            self._check_error()
            return None
        # pump guarantees a sentinel even on error (finally)  # ray-tpu: lint-ignore[RTL008]
        item = q.get()
        if item is None:
            self._dead[idx] = True
            self._check_error()
            return None
        out = [item]
        while len(out) < max_n:
            try:
                nxt = q.get_nowait()
            except _q.Empty:
                break
            if nxt is None:
                # Don't raise mid-drain — the collected bundles still
                # belong to the consumer; the next call sees _dead and
                # surfaces any pump error.
                self._dead[idx] = True
                break
            out.append(nxt)
        return out
