"""Block model for ray_tpu.data.

A *block* is the unit of distributed data: either a columnar batch
(``dict[str, np.ndarray]`` — the preferred form; it maps 1:1 onto device
arrays for TPU ingest) or a plain Python list of rows (fallback for
arbitrary objects). ``BlockAccessor`` abstracts over both.

Reference: python/ray/data/_internal/arrow_block.py / pandas_block.py and
python/ray/data/block.py (BlockAccessor, BlockMetadata). The reference's
Arrow-first design is replaced by numpy-columnar-first: TPU input pipelines
feed ``jax.device_put`` from host numpy, so the native in-memory format is
the one the accelerator consumes.
"""
from __future__ import annotations

import sys
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Union

import numpy as np

Block = Union[Dict[str, np.ndarray], List[Any]]


@dataclass
class BlockMetadata:
    """Side-channel info shipped with every block ref (reference:
    python/ray/data/block.py BlockMetadata)."""

    num_rows: int
    size_bytes: int
    schema: Optional[Dict[str, str]] = None
    input_files: List[str] = field(default_factory=list)
    exec_stats: Optional[Dict[str, float]] = None
    # True iff the block is a dict of ndarray columns — the zero-copy
    # decode precondition; None = unknown (the consumer probes). Lets the
    # iterator skip the pinned-view attempt (and its decode-twice
    # fallback) for blocks known not to qualify.
    columnar: Optional[bool] = None


def _rows_of(block: Block) -> int:
    if isinstance(block, dict):
        if not block:
            return 0
        return len(next(iter(block.values())))
    return len(block)


def _size_of(block: Block) -> int:
    if isinstance(block, dict):
        return int(sum(v.nbytes if hasattr(v, "nbytes") else sys.getsizeof(v) for v in block.values()))
    return int(sum(sys.getsizeof(r) for r in block[:100]) * (len(block) / max(1, min(len(block), 100))))


def _schema_of(block: Block) -> Optional[Dict[str, str]]:
    if isinstance(block, dict):
        return {k: str(v.dtype) if hasattr(v, "dtype") else type(v).__name__ for k, v in block.items()}
    if block and isinstance(block[0], dict):
        return {k: type(v).__name__ for k, v in block[0].items()}
    return None


class BlockAccessor:
    """Uniform view over columnar-batch and row-list blocks."""

    def __init__(self, block: Block):
        self._block = block

    @staticmethod
    def for_block(block: Block) -> "BlockAccessor":
        return BlockAccessor(block)

    @property
    def block(self) -> Block:
        return self._block

    def num_rows(self) -> int:
        return _rows_of(self._block)

    def size_bytes(self) -> int:
        return _size_of(self._block)

    def metadata(self, input_files: Optional[List[str]] = None) -> BlockMetadata:
        b = self._block
        return BlockMetadata(
            num_rows=self.num_rows(),
            size_bytes=self.size_bytes(),
            schema=_schema_of(b),
            input_files=input_files or [],
            columnar=isinstance(b, dict)
            and bool(b)
            and all(isinstance(v, np.ndarray) for v in b.values()),
        )

    # -- row iteration ----------------------------------------------------
    def iter_rows(self) -> Iterator[Any]:
        if isinstance(self._block, dict):
            cols = self._block
            n = self.num_rows()
            keys = list(cols)
            for i in range(n):
                yield {k: cols[k][i] for k in keys}
        else:
            yield from self._block

    # -- batch conversion -------------------------------------------------
    def to_batch(self) -> Dict[str, np.ndarray]:
        """Columnar view; row-lists of dicts are transposed, scalars become
        an ``item`` column (mirrors the reference's strict-mode row model)."""
        if isinstance(self._block, dict):
            return self._block
        rows = self._block
        if not rows:
            return {}
        if isinstance(rows[0], dict):
            keys = rows[0].keys()
            return {k: np.asarray([r[k] for r in rows]) for k in keys}
        return {"item": np.asarray(rows)}

    def to_rows(self) -> List[Any]:
        if isinstance(self._block, list):
            return self._block
        return list(self.iter_rows())

    def to_pandas(self):
        import pandas as pd

        return pd.DataFrame(self.to_batch())

    # -- slicing / combining ----------------------------------------------
    def slice(self, start: int, end: int) -> Block:
        if isinstance(self._block, dict):
            return {k: v[start:end] for k, v in self._block.items()}
        return self._block[start:end]

    def take_indices(self, idx) -> Block:
        if isinstance(self._block, dict):
            return {k: v[idx] for k, v in self._block.items()}
        return [self._block[i] for i in idx]

    @staticmethod
    def concat(blocks: List[Block]) -> Block:
        blocks = [b for b in blocks if _rows_of(b) > 0]
        if not blocks:
            return []
        if all(isinstance(b, dict) for b in blocks):
            keys = list(blocks[0])
            return {k: np.concatenate([np.asarray(b[k]) for b in blocks]) for k in keys}
        out: List[Any] = []
        for b in blocks:
            out.extend(BlockAccessor(b).to_rows())
        return out

    def sample_keys(self, key: Optional[str], n: int = 20) -> List[Any]:
        """Boundary sampling for sort (reference:
        python/ray/data/_internal/planner/exchange/sort_task_spec.py)."""
        total = self.num_rows()
        if total == 0:
            return []
        idx = np.linspace(0, total - 1, num=min(n, total)).astype(int)
        if isinstance(self._block, dict):
            col = self._block[key] if key else next(iter(self._block.values()))
            return [col[i] for i in idx]
        rows = self._block
        if key is None:
            return [rows[i] for i in idx]
        return [rows[i][key] for i in idx]
