"""Dataset: the lazy, streaming-executed distributed data API.

Reference: python/ray/data/dataset.py:139 (Dataset, 5,255 L). Transforms
append logical operators; execution happens when an action
(take/count/iter_batches/materialize/...) pulls on the stream, via the
streaming executor over remote tasks.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data.aggregate import AggregateFn, Count as _CountAgg, aggregate_block
from ray_tpu.data.block import BlockAccessor
from ray_tpu.data.executor import SplitCoordinator, StreamingExecutor, plan_to_operators
from ray_tpu.data.iterator import DataIterator
from ray_tpu.data.logical import (
    AllToAll,
    InputData,
    Limit,
    LogicalOp,
    LogicalPlan,
    MapLike,
    Read,
)


class Dataset:
    def __init__(self, dag: LogicalOp):
        self._dag = dag

    # ------------------------------------------------------------------
    # Transforms (lazy)
    # ------------------------------------------------------------------
    def _append(self, op: LogicalOp) -> "Dataset":
        op.input = self._dag
        return Dataset(op)

    def map(self, fn: Callable, **opts) -> "Dataset":
        return self._append(_map_op("map", fn, opts))

    def map_batches(
        self,
        fn: Callable,
        *,
        batch_size: Optional[int] = None,
        concurrency: Optional[int] = None,
        fn_args: tuple = (),
        fn_kwargs: Optional[dict] = None,
        fn_constructor_args: tuple = (),
        num_cpus: float = 1,
        num_tpus: float = 0,
        **_,
    ) -> "Dataset":
        if isinstance(fn, type) and not concurrency:
            raise ValueError(
                "class-based map_batches UDFs are stateful and run in an actor "
                "pool; pass concurrency=N or concurrency=(min, max) for an "
                "autoscaling pool (reference: Dataset.map_batches compute "
                "semantics / ActorPoolStrategy)"
            )
        if isinstance(concurrency, (tuple, list)):
            if not isinstance(fn, type):
                raise ValueError(
                    "concurrency=(min, max) requires a class-based UDF "
                    "(autoscaling actor pool)"
                )
            lo, hi = concurrency
            if not (0 < lo <= hi):
                raise ValueError(f"invalid concurrency range {concurrency}")
            concurrency = (int(lo), int(hi))
        op = MapLike(
            name=f"MapBatches({getattr(fn, '__name__', type(fn).__name__)})",
            kind="map_batches",
            fn=fn,
            fn_args=fn_args,
            fn_kwargs=fn_kwargs or {},
            batch_size=batch_size,
            compute_actors=concurrency if isinstance(fn, type) else 0,
            fn_constructor_args=fn_constructor_args,
            num_cpus=num_cpus,
            num_tpus=num_tpus,
        )
        return self._append(op)

    def flat_map(self, fn: Callable, **opts) -> "Dataset":
        return self._append(_map_op("flat_map", fn, opts))

    def filter(self, fn: Callable, **opts) -> "Dataset":
        return self._append(_map_op("filter", fn, opts))

    def add_column(self, name: str, fn: Callable) -> "Dataset":
        def add(batch, name=name, fn=fn):
            batch = dict(batch)
            batch[name] = np.asarray(fn(batch))
            return batch

        return self.map_batches(add)

    def drop_columns(self, cols: List[str]) -> "Dataset":
        return self.map_batches(
            lambda b, cols=tuple(cols): {k: v for k, v in b.items() if k not in cols}
        )

    def select_columns(self, cols: List[str]) -> "Dataset":
        return self.map_batches(
            lambda b, cols=tuple(cols): {k: b[k] for k in cols}
        )

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._append(AllToAll(name="Repartition", kind="repartition", num_outputs=num_blocks))

    def random_shuffle(self, *, seed: Optional[int] = None, num_blocks: Optional[int] = None) -> "Dataset":
        return self._append(
            AllToAll(name="RandomShuffle", kind="shuffle", num_outputs=num_blocks, seed=seed)
        )

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        return self._append(
            AllToAll(name=f"Sort({key})", kind="sort", key=key, descending=descending)
        )

    def limit(self, n: int) -> "Dataset":
        return self._append(Limit(name=f"Limit[{n}]", limit=n))

    def union(self, *others: "Dataset") -> "Dataset":
        """Lazy union: branches execute only when this dataset is pulled on,
        streamed one branch after another."""
        from ray_tpu.data.logical import Union as LUnion

        return Dataset(
            LUnion(name="Union", input=self._dag, others=[o._dag for o in others])
        )

    def zip(self, *others: "Dataset") -> "Dataset":
        """Row-aligned column concatenation (reference: Dataset.zip);
        duplicate column names from the right side get a ``_1`` suffix."""
        from ray_tpu.data.logical import Zip as LZip

        return Dataset(
            LZip(name="Zip", input=self._dag, others=[o._dag for o in others])
        )

    def groupby(self, key: Optional[str]) -> "GroupedData":
        return GroupedData(self, key)

    def random_sample(self, fraction: float, *, seed: Optional[int] = None) -> "Dataset":
        def sample(batch, fraction=fraction, seed=seed):
            n = len(next(iter(batch.values()))) if batch else 0
            rng = np.random.default_rng(seed)
            mask = rng.random(n) < fraction
            return {k: np.asarray(v)[mask] for k, v in batch.items()}

        return self.map_batches(sample)

    # ------------------------------------------------------------------
    # Execution
    # ------------------------------------------------------------------
    def _plan(self) -> LogicalPlan:
        return LogicalPlan(self._dag).optimized()

    def _execute_bundles(self):
        ops = plan_to_operators(self._plan())
        return StreamingExecutor(ops).iter_bundles()

    def iterator(self) -> DataIterator:
        return DataIterator(self._execute_bundles)

    def iter_rows(self):
        return self.iterator().iter_rows()

    def iter_batches(self, **kw):
        return self.iterator().iter_batches(**kw)

    def iter_jax_batches(self, **kw):
        return self.iterator().iter_jax_batches(**kw)

    def iter_torch_batches(self, **kw):
        return self.iterator().iter_torch_batches(**kw)

    def streaming_split(self, n: int, *, equal: bool = True) -> List[DataIterator]:
        """N concurrent iterators over one shared execution (reference:
        dataset.py streaming_split → StreamSplitDataIterator); the canonical
        per-training-worker ingest path."""
        coord = SplitCoordinator(plan_to_operators(self._plan()), n, equal)
        return [
            DataIterator(functools.partial(coord.iter_split, i)) for i in range(n)
        ]

    def take(self, n: int = 20) -> List[Any]:
        out: List[Any] = []
        for row in self.limit(n).iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[Any]:
        return list(self.iter_rows())

    def count(self) -> int:
        return sum(b.meta.num_rows for b in self._execute_bundles())

    def schema(self) -> Optional[Dict[str, str]]:
        for b in self._execute_bundles():
            if b.meta.schema:
                return b.meta.schema
        return None

    def num_blocks(self) -> int:
        return sum(1 for _ in self._execute_bundles())

    def size_bytes(self) -> int:
        return sum(b.meta.size_bytes for b in self._execute_bundles())

    def materialize(self) -> "Dataset":
        """Execute now, pin blocks in the object store, return a dataset
        over the materialized bundles (reference: Dataset.materialize)."""
        bundles = [(b.ref, b.meta) for b in self._execute_bundles()]
        return Dataset(InputData(name="Materialized", bundles=bundles))

    def stats(self) -> List[dict]:
        ops = plan_to_operators(self._plan())
        ex = StreamingExecutor(ops)
        for _ in ex.iter_bundles():
            pass
        return ex.stats()

    def to_pandas(self):
        import pandas as pd

        blocks = [
            BlockAccessor.for_block(ray_tpu.get(b.ref)).to_pandas()
            for b in self._execute_bundles()
        ]
        if not blocks:
            return pd.DataFrame()
        return pd.concat(blocks, ignore_index=True)

    def to_numpy(self) -> Dict[str, np.ndarray]:
        batches = [
            BlockAccessor.for_block(ray_tpu.get(b.ref)).to_batch()
            for b in self._execute_bundles()
        ]
        batches = [b for b in batches if b]
        if not batches:
            return {}
        return {k: np.concatenate([np.asarray(b[k]) for b in batches]) for k in batches[0]}

    # Writes ------------------------------------------------------------
    def write_datasink(self, sink) -> List[Any]:
        """Distributed write: one remote task per output block, running
        where the block lives (reference: Dataset.write_datasink /
        datasource/datasink.py). Returns the per-block write results."""
        import ray_tpu
        from ray_tpu.data.datasink import _write_block_task

        refs = [
            _write_block_task.remote(b.ref, sink, i)
            for i, b in enumerate(self._execute_bundles())
        ]
        results = ray_tpu.get(refs)
        sink.on_write_complete(results)
        return results

    def write_parquet(self, path: str) -> List[str]:
        from ray_tpu.data.datasink import ParquetDatasink

        return self.write_datasink(ParquetDatasink(path))

    def write_csv(self, path: str) -> List[str]:
        from ray_tpu.data.datasink import CSVDatasink

        return self.write_datasink(CSVDatasink(path))

    def write_json(self, path: str) -> List[str]:
        from ray_tpu.data.datasink import JSONDatasink

        return self.write_datasink(JSONDatasink(path))

    def write_numpy(self, path: str, *, column: Optional[str] = None) -> List[str]:
        from ray_tpu.data.datasink import NumpyDatasink

        return self.write_datasink(NumpyDatasink(path, column))

    def write_tfrecords(self, path: str) -> List[str]:
        from ray_tpu.data.tfrecord import TFRecordDatasink

        return self.write_datasink(TFRecordDatasink(path))

    def write_webdataset(self, path: str) -> List[str]:
        from ray_tpu.data.extra_datasources import WebDatasetDatasink

        return self.write_datasink(WebDatasetDatasink(path))

    # Global aggregates -------------------------------------------------
    def aggregate(self, *aggs: AggregateFn) -> Dict[str, Any]:
        rows = self.groupby(None)._aggregate_rows(*aggs)
        return rows[0] if rows else {}

    def sum(self, on: str):
        from ray_tpu.data.aggregate import Sum

        return self.aggregate(Sum(on)).get(f"sum({on})")

    def min(self, on: str):
        from ray_tpu.data.aggregate import Min

        return self.aggregate(Min(on)).get(f"min({on})")

    def max(self, on: str):
        from ray_tpu.data.aggregate import Max

        return self.aggregate(Max(on)).get(f"max({on})")

    def mean(self, on: str):
        from ray_tpu.data.aggregate import Mean

        return self.aggregate(Mean(on)).get(f"mean({on})")

    def std(self, on: str):
        from ray_tpu.data.aggregate import Std

        return self.aggregate(Std(on)).get(f"std({on})")

    def __repr__(self):
        names = [op.name for op in self._dag.chain()]
        return f"Dataset({' -> '.join(names)})"


class GroupedData:
    """Reference: python/ray/data/grouped_data.py."""

    def __init__(self, ds: Dataset, key: Optional[str]):
        self._ds = ds
        self._key = key

    def _aggregate_rows(self, *aggs: AggregateFn) -> List[dict]:
        key = self._key
        agg_list = list(aggs)
        if key is None:
            # Global aggregate: tree-merge unfinalized accumulator states.
            return [_merge_global(self._ds, agg_list)]
        # Hash-partition by key so each partition holds whole groups, then
        # aggregate partition-side in remote tasks.
        ds = self._ds._append(
            AllToAll(name=f"GroupBy({key})", kind="aggregate", key=key)
        )
        fn = ray_tpu.remote(num_returns=1)(aggregate_block)
        row_refs = [
            fn.remote(bundle.ref, key, agg_list) for bundle in ds._execute_bundles()
        ]
        partials: List[dict] = []
        for rows in ray_tpu.get(row_refs):
            partials.extend(rows)
        return sorted(partials, key=lambda r: (r[key] is None, r[key]))

    def aggregate(self, *aggs: AggregateFn) -> Dataset:
        rows = self._aggregate_rows(*aggs)
        from ray_tpu.data import from_items

        return from_items(rows)

    def count(self) -> Dataset:
        return self.aggregate(_CountAgg())

    def sum(self, on: str) -> Dataset:
        from ray_tpu.data.aggregate import Sum

        return self.aggregate(Sum(on))

    def min(self, on: str) -> Dataset:
        from ray_tpu.data.aggregate import Min

        return self.aggregate(Min(on))

    def max(self, on: str) -> Dataset:
        from ray_tpu.data.aggregate import Max

        return self.aggregate(Max(on))

    def mean(self, on: str) -> Dataset:
        from ray_tpu.data.aggregate import Mean

        return self.aggregate(Mean(on))

    def std(self, on: str) -> Dataset:
        from ray_tpu.data.aggregate import Std

        return self.aggregate(Std(on))

    def map_groups(self, fn: Callable) -> Dataset:
        key = self._key
        ds = self._ds._append(
            AllToAll(name=f"GroupBy({key})", kind="aggregate", key=key)
        )

        def apply_groups(batch, key=key, fn=fn):
            acc = BlockAccessor.for_block(batch)
            groups: dict = {}
            for row in acc.iter_rows():
                groups.setdefault(row[key], []).append(row)
            out = []
            for k in sorted(groups, key=lambda x: (x is None, x)):
                res = fn(groups[k])
                out.extend(res if isinstance(res, list) else [res])
            return BlockAccessor.for_block(out).to_batch()

        # Applies per whole partition (batch_size=None → no sub-batching).
        return Dataset(
            MapLike(
                name=f"MapGroups({key})",
                kind="map_batches",
                fn=apply_groups,
                input=ds._dag,
            )
        )


def _merge_global(ds: Dataset, aggs: List[AggregateFn]) -> dict:
    """Tree-merge unfinalized accumulator states for a global aggregate."""

    def partial_states(block, aggs=aggs):
        states = [a.init() for a in aggs]
        for row in BlockAccessor.for_block(block).iter_rows():
            for i, a in enumerate(aggs):
                states[i] = a.accumulate_row(states[i], row)
        return states

    state_refs = []
    fn = ray_tpu.remote(num_returns=1)(partial_states)
    for bundle in ds._execute_bundles():
        state_refs.append(fn.remote(bundle.ref))
    merged = [a.init() for a in aggs]
    for states in ray_tpu.get(state_refs):
        merged = [a.merge(m, s) for a, m, s in zip(aggs, merged, states)]
    return {a.name: a.finalize(m) for a, m in zip(aggs, merged)}


def _map_op(kind: str, fn: Callable, opts: dict) -> MapLike:
    return MapLike(
        name=f"{kind.title().replace('_','')}({getattr(fn, '__name__', 'fn')})",
        kind=kind,
        fn=fn,
        fn_args=opts.get("fn_args", ()),
        fn_kwargs=opts.get("fn_kwargs", {}) or {},
        num_cpus=opts.get("num_cpus", 1),
        num_tpus=opts.get("num_tpus", 0),
    )
