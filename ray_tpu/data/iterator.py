"""DataIterator: batch iteration over a stream of block refs.

Reference: python/ray/data/iterator.py (``iter_batches``/
``iter_torch_batches``) — TPU-first addition: ``iter_jax_batches`` yields
device-resident (optionally sharded) jax arrays, the terminal stage of a
TPU ingest pipeline.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, Optional

import numpy as np

import ray_tpu
from ray_tpu.data.block import BlockAccessor


class DataIterator:
    def __init__(self, bundle_iter_factory: Callable[[], Iterator]):
        self._factory = bundle_iter_factory

    def _iter_blocks(self):
        for bundle in self._factory():
            yield ray_tpu.get(bundle.ref)

    def iter_rows(self) -> Iterator[Any]:
        for block in self._iter_blocks():
            yield from BlockAccessor.for_block(block).iter_rows()

    def iter_batches(
        self,
        *,
        batch_size: Optional[int] = 256,
        drop_last: bool = False,
        local_shuffle_buffer_size: Optional[int] = None,
        local_shuffle_seed: Optional[int] = None,
    ) -> Iterator[Dict[str, np.ndarray]]:
        """Re-batches the block stream into fixed-size columnar batches."""
        carry: Optional[Dict[str, np.ndarray]] = None
        rng = (
            np.random.default_rng(local_shuffle_seed)
            if local_shuffle_buffer_size
            else None
        )

        def blocks_with_shuffle_buffer():
            """Accumulate ≥buffer_size rows, emit random permutations — rows
            mix ACROSS blocks up to the buffer size (reference:
            iterator local_shuffle_buffer_size semantics)."""
            buf: Optional[Dict[str, np.ndarray]] = None
            for block in self._iter_blocks():
                b = BlockAccessor.for_block(block).to_batch()
                if not b:
                    continue
                buf = (
                    b
                    if buf is None
                    else {k: np.concatenate([buf[k], np.asarray(b[k])]) for k in b}
                )
                n = len(next(iter(buf.values())))
                if n >= local_shuffle_buffer_size:
                    order = rng.permutation(n)
                    yield {k: np.asarray(v)[order] for k, v in buf.items()}
                    buf = None
            if buf is not None:
                n = len(next(iter(buf.values())))
                order = rng.permutation(n)
                yield {k: np.asarray(v)[order] for k, v in buf.items()}

        if rng is not None:
            source = blocks_with_shuffle_buffer()
        else:
            source = (
                BlockAccessor.for_block(b).to_batch() for b in self._iter_blocks()
            )
        for batch in source:
            if not batch:
                continue
            if carry is not None:
                batch = {
                    k: np.concatenate([carry[k], np.asarray(batch[k])]) for k in batch
                }
            carry = None
            if batch_size is None:
                yield batch
                continue
            n = len(next(iter(batch.values())))
            start = 0
            while n - start >= batch_size:
                yield {k: v[start : start + batch_size] for k, v in batch.items()}
                start += batch_size
            if start < n:
                carry = {k: v[start:] for k, v in batch.items()}
        if carry is not None and not drop_last:
            yield carry

    def iter_jax_batches(
        self,
        *,
        batch_size: Optional[int] = 256,
        drop_last: bool = False,
        dtypes: Optional[Dict[str, Any]] = None,
        sharding: Optional[Any] = None,
        **kw,
    ):
        """Device-put each batch; with a ``jax.sharding.Sharding`` the batch
        lands already sharded across the mesh (global-batch ingest)."""
        import jax

        for batch in self.iter_batches(batch_size=batch_size, drop_last=drop_last, **kw):
            if dtypes:
                batch = {
                    k: np.asarray(v, dtype=dtypes.get(k, getattr(v, "dtype", None)))
                    for k, v in batch.items()
                }
            if sharding is not None:
                yield {k: jax.device_put(v, sharding) for k, v in batch.items()}
            else:
                yield {k: jax.device_put(v) for k, v in batch.items()}

    def iter_torch_batches(self, *, batch_size: Optional[int] = 256, **kw):
        import torch

        for batch in self.iter_batches(batch_size=batch_size, **kw):
            yield {k: torch.as_tensor(np.asarray(v)) for k, v in batch.items()}
