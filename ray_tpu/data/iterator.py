"""DataIterator: batch iteration over a stream of block refs.

Reference: python/ray/data/iterator.py (``iter_batches``/
``iter_torch_batches``, with ``prefetch_batches`` pipelining) — TPU-first
addition: ``iter_jax_batches`` yields device-resident (optionally sharded)
jax arrays, the terminal stage of a TPU ingest pipeline.

The consumption end is pipelined so the device never waits on the host and
the host never waits on the device (Podracer-style ingest overlap):

  block-ref prefetch  →  zero-copy decode  →  background rebatch  →  device prefetch
  (bounded lookahead     (numpy views over    (concat/shuffle/slice   (jax.device_put
   resolving bundle       the plasma shm       on a pipeline thread    dispatched for
   refs concurrently,     mapping, pinned      feeding a bounded       batch N+1 while
   order-preserving)      until the arrays     queue)                  the caller steps
                          die)                                         on batch N)

Every stage is off by default-knob only: ``prefetch_blocks=0`` +
``prefetch_to_device=0`` reproduces the fully synchronous legacy path with
a byte-identical batch stream. Defaults live in
:class:`ray_tpu.data.context.DataContext`.
"""
from __future__ import annotations

import collections
import queue
import threading
import time
import weakref
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, Iterator, Optional

import numpy as np

import ray_tpu
from ray_tpu.data.block import BlockAccessor
from ray_tpu.data.context import DataContext
from ray_tpu.data.metrics import data_metrics


# ---------------------------------------------------------------------------
# Zero-copy block decode
# ---------------------------------------------------------------------------


class _BlockLifetime:
    """Holds an object's arena pin (and its ObjectRef, which keeps the
    distributed refcount positive so the store cannot delete the object)
    until every column array decoded from it has been garbage-collected."""

    def __init__(self, ref, release: Callable[[], None], n_arrays: int):
        self._ref = ref
        self._release = release
        self._remaining = n_arrays
        self._lock = threading.Lock()

    def attach(self, arr: np.ndarray):
        weakref.finalize(arr, self._dec)

    def _dec(self):
        with self._lock:
            self._remaining -= 1
            done = self._remaining == 0
        if done:
            self._release()
            self._ref = None


def _fetch_block(bundle):
    """Materialize a RefBundle's block.

    Zero-copy path: pin + map the sealed shm object and decode columns as
    numpy views over the mapping (protocol-5 out-of-band buffers → no
    copy); the pin is released when the last decoded array dies, so
    eviction pressure can never tear a batch mid-use. Fallback (inline-tier
    objects, row blocks, unviewable/spilled objects): a copying get,
    counted in ``data_zero_copy_misses_total``.
    """
    from ray_tpu.core.api import _require_worker
    from ray_tpu.utils.serialization import deserialize

    m = data_metrics()
    m.bump("blocks_fetched")
    try:
        pv = _require_worker().get_pinned_view(bundle.ref.id)
    except Exception:  # noqa: BLE001 — the copying fallback below settles it
        pv = None
    if pv is None:
        # Inline-tier object (payload bytes own their memory) or the
        # pinned-view resolve failed — plain get. Plain get is NOT a
        # guaranteed copy: it deserializes large columns as UNPINNED
        # views over the arena mapping, which eviction can recycle under
        # a live batch — copy any non-owning column out.
        m.zero_copy_misses.inc(1)
        m.bump("zero_copy_misses")
        block = ray_tpu.get(bundle.ref)
        if isinstance(block, dict):
            block = {
                k: np.array(v)
                if isinstance(v, np.ndarray) and not v.flags["OWNDATA"]
                else v
                for k, v in block.items()
            }
        return block
    view, release = pv
    if getattr(bundle.meta, "columnar", None) is False:
        # Known non-columnar: the view-decode attempt would find the
        # block unviewable and decode AGAIN from a copy — single decode
        # from copied bytes (safe against eviction), then drop the pin.
        try:
            block = deserialize(bytes(view))
        finally:
            release()
        m.zero_copy_misses.inc(1)
        m.bump("zero_copy_misses")
        return block
    try:
        block = deserialize(view)
    except BaseException:
        release()
        raise
    if (
        isinstance(block, dict)
        and block
        and all(isinstance(v, np.ndarray) for v in block.values())
    ):
        # Columns under serialization._OOB_THRESHOLD are inlined in
        # the pickle and deserialize as private copies; only arrays
        # whose data pointer lands inside the mapping actually view
        # it and need the pin kept alive.
        lo = np.frombuffer(view, dtype=np.uint8).__array_interface__["data"][0]
        hi = lo + view.nbytes
        if any(
            lo <= v.__array_interface__["data"][0] < hi
            for v in block.values()
        ):
            life = _BlockLifetime(bundle.ref, release, len(block))
            for v in block.values():
                life.attach(v)
            m.zero_copy_hits.inc(1)
            m.bump("zero_copy_hits")
            return block
        # Every column is a private copy — nothing views the slot.
        release()
        m.zero_copy_misses.inc(1)
        m.bump("zero_copy_misses")
        return block
    # Row/object blocks may still embed arrays viewing the mapping —
    # re-decode from a private copy, then drop the pin.
    try:
        block = deserialize(bytes(view))
    finally:
        release()
    m.zero_copy_misses.inc(1)
    m.bump("zero_copy_misses")
    return block


# ---------------------------------------------------------------------------
# Pipeline-thread plumbing
# ---------------------------------------------------------------------------

_END = object()


def _through_thread(make_gen: Callable[[], Iterator], depth: int, stage: str):
    """Run ``make_gen()`` on a pipeline thread feeding a bounded queue of
    ``depth`` items; yields in order. Errors propagate; abandoning the
    consumer stops the producer."""
    q: "queue.Queue" = queue.Queue(maxsize=max(1, int(depth)))
    stop = threading.Event()

    def _put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _pump():
        try:
            for item in make_gen():
                if not _put((None, item)):
                    return
        except BaseException as e:  # noqa: BLE001 — re-raised consumer-side
            _put((e, None))
            return
        _put((None, _END))

    t = threading.Thread(target=_pump, daemon=True, name=f"data-{stage}")
    t.start()
    m = data_metrics()
    try:
        while True:
            m.prefetch_depth.set(float(q.qsize()), {"stage": stage})
            # pump thread guarantees a sentinel/error item (finally)  # ray-tpu: lint-ignore[RTL008]
            err, item = q.get()
            if err is not None:
                raise err
            if item is _END:
                return
            yield item
    finally:
        stop.set()
        while True:  # unblock a producer stuck on a full queue
            try:
                q.get_nowait()
            except queue.Empty:
                break


def _timed(source: Iterator):
    """Record consumer-side wait per item (``data_iter_wait_ms``) — with
    the pipeline on this is queue wait and collapses toward zero; off, it
    is the whole inline fetch+rebatch cost."""
    m = data_metrics()
    it = iter(source)
    while True:
        t0 = time.monotonic()
        try:
            item = next(it)
        except StopIteration:
            return
        m.iter_wait_ms.observe((time.monotonic() - t0) * 1000.0)
        yield item


def _maybe_cast(v, dtype):
    """Cast only when needed: a matching-dtype ndarray passes through
    untouched so zero-copy decode survives to ``jax.device_put``."""
    if dtype is None:
        return v if isinstance(v, np.ndarray) else np.asarray(v)
    if isinstance(v, np.ndarray) and v.dtype == np.dtype(dtype):
        return v
    return np.asarray(v, dtype=dtype)


class DataIterator:
    def __init__(self, bundle_iter_factory: Callable[[], Iterator]):
        self._factory = bundle_iter_factory

    def _iter_blocks(self, prefetch_blocks: Optional[int] = None):
        """Blocks in bundle order. ``prefetch_blocks > 0``: up to that many
        bundle refs resolve concurrently ahead of the consumer (remote
        fetch / plasma map overlaps consumption; order preserved)."""
        if prefetch_blocks is None:
            prefetch_blocks = DataContext.get_current().prefetch_blocks
        bundles = self._factory()
        if not prefetch_blocks or prefetch_blocks <= 0:
            for bundle in bundles:
                yield _fetch_block(bundle)
            return
        depth = int(prefetch_blocks)
        pool = ThreadPoolExecutor(
            max_workers=min(depth, 8), thread_name_prefix="data-prefetch"
        )
        pending: collections.deque = collections.deque()
        try:
            exhausted = False
            while True:
                while not exhausted and len(pending) < depth:
                    try:
                        b = next(bundles)
                    except StopIteration:
                        exhausted = True
                        break
                    pending.append(pool.submit(_fetch_block, b))
                if not pending:
                    return
                # data-plane prefetch: workload-duration wait by design  # ray-tpu: lint-ignore[RTL008]
                yield pending.popleft().result()
        finally:
            close = getattr(bundles, "close", None)
            if close is not None:
                close()
            pool.shutdown(wait=False, cancel_futures=True)

    def iter_rows(self) -> Iterator[Any]:
        for block in self._iter_blocks():
            yield from BlockAccessor.for_block(block).iter_rows()

    def iter_batches(
        self,
        *,
        batch_size: Optional[int] = 256,
        drop_last: bool = False,
        local_shuffle_buffer_size: Optional[int] = None,
        local_shuffle_seed: Optional[int] = None,
        prefetch_blocks: Optional[int] = None,
        rebatch_queue_depth: Optional[int] = None,
    ) -> Iterator[Dict[str, np.ndarray]]:
        """Re-batches the block stream into fixed-size columnar batches.

        With ``prefetch_blocks > 0`` (the default, via DataContext) the
        concat/shuffle/rebatch work runs on a pipeline thread feeding a
        bounded queue of ``rebatch_queue_depth`` batches, so host CPU work
        overlaps the consumer's (device) step. ``prefetch_blocks=0`` is the
        synchronous legacy path with an identical batch stream.
        """
        source = self._host_batches(
            batch_size=batch_size,
            drop_last=drop_last,
            local_shuffle_buffer_size=local_shuffle_buffer_size,
            local_shuffle_seed=local_shuffle_seed,
            prefetch_blocks=prefetch_blocks,
            rebatch_queue_depth=rebatch_queue_depth,
        )
        return _timed(source)

    def _host_batches(
        self,
        *,
        batch_size: Optional[int] = 256,
        drop_last: bool = False,
        local_shuffle_buffer_size: Optional[int] = None,
        local_shuffle_seed: Optional[int] = None,
        prefetch_blocks: Optional[int] = None,
        rebatch_queue_depth: Optional[int] = None,
    ) -> Iterator[Dict[str, np.ndarray]]:
        """iter_batches without the consumer-wait metric — the shared host
        stage; iter_jax_batches times at ITS boundary instead (the wait the
        stepping caller actually sees)."""
        ctx = DataContext.get_current()
        if prefetch_blocks is None:
            prefetch_blocks = ctx.prefetch_blocks
        if rebatch_queue_depth is None:
            rebatch_queue_depth = ctx.rebatch_queue_depth

        def make():
            return self._rebatch(
                batch_size=batch_size,
                drop_last=drop_last,
                local_shuffle_buffer_size=local_shuffle_buffer_size,
                local_shuffle_seed=local_shuffle_seed,
                prefetch_blocks=prefetch_blocks,
            )

        if prefetch_blocks and prefetch_blocks > 0 and rebatch_queue_depth > 0:
            return _through_thread(make, rebatch_queue_depth, "rebatch")
        return make()

    def _rebatch(
        self,
        *,
        batch_size: Optional[int],
        drop_last: bool,
        local_shuffle_buffer_size: Optional[int],
        local_shuffle_seed: Optional[int],
        prefetch_blocks: Optional[int],
    ) -> Iterator[Dict[str, np.ndarray]]:
        carry: Optional[Dict[str, np.ndarray]] = None
        rng = (
            np.random.default_rng(local_shuffle_seed)
            if local_shuffle_buffer_size
            else None
        )

        def blocks_with_shuffle_buffer():
            """Accumulate ≥buffer_size rows, emit random permutations — rows
            mix ACROSS blocks up to the buffer size (reference:
            iterator local_shuffle_buffer_size semantics). Incoming batches
            are held as a list and concatenated ONCE per emit — repeated
            per-block np.concatenate made the buffer O(n²) in its size."""
            parts: list = []
            n = 0
            for block in self._iter_blocks(prefetch_blocks):
                b = BlockAccessor.for_block(block).to_batch()
                if not b:
                    continue
                parts.append(b)
                n += len(next(iter(b.values())))
                if n >= local_shuffle_buffer_size:
                    yield _concat_permuted(parts, rng, n)
                    parts, n = [], 0
            if parts:
                yield _concat_permuted(parts, rng, n)

        if rng is not None:
            source = blocks_with_shuffle_buffer()
        else:
            source = (
                BlockAccessor.for_block(b).to_batch()
                for b in self._iter_blocks(prefetch_blocks)
            )
        for batch in source:
            if not batch:
                continue
            if carry is not None:
                batch = {
                    k: np.concatenate([carry[k], np.asarray(batch[k])]) for k in batch
                }
            carry = None
            if batch_size is None:
                yield batch
                continue
            n = len(next(iter(batch.values())))
            start = 0
            while n - start >= batch_size:
                yield {k: v[start : start + batch_size] for k, v in batch.items()}
                start += batch_size
            if start < n:
                carry = {k: v[start:] for k, v in batch.items()}
        if carry is not None and not drop_last:
            yield carry

    def iter_jax_batches(
        self,
        *,
        batch_size: Optional[int] = 256,
        drop_last: bool = False,
        dtypes: Optional[Dict[str, Any]] = None,
        sharding: Optional[Any] = None,
        prefetch_to_device: Optional[int] = None,
        **kw,
    ):
        """Device-put each batch; with a ``jax.sharding.Sharding`` the batch
        lands already sharded across the mesh (global-batch ingest).

        ``prefetch_to_device > 0`` (default, via DataContext) dispatches
        ``jax.device_put`` for upcoming batches on a pipeline thread while
        the caller is still stepping on the current one — double-buffered,
        so at most ``prefetch_to_device`` batches of HBM are held ahead of
        the consumer. ``prefetch_to_device=0`` transfers synchronously.
        """
        import jax

        if prefetch_to_device is None:
            prefetch_to_device = DataContext.get_current().prefetch_to_device
        m = data_metrics()

        def to_device(batch):
            if dtypes:
                batch = {k: _maybe_cast(v, dtypes.get(k)) for k, v in batch.items()}
            t0 = time.monotonic()
            if sharding is not None:
                out = {k: jax.device_put(v, sharding) for k, v in batch.items()}
            else:
                out = {k: jax.device_put(v) for k, v in batch.items()}
            m.h2d_ms.observe((time.monotonic() - t0) * 1000.0)
            return out

        # data_iter_wait_ms is recorded HERE, at the boundary the stepping
        # caller blocks on — not inside the host stage (which, pipelined,
        # runs on the h2d thread and would report its own queue wait).
        if prefetch_to_device and prefetch_to_device > 0:
            # HBM budget: at most prefetch_to_device transferred batches
            # ahead of the consumer. The producer takes a slot BEFORE
            # device_put and the consumer returns it at dequeue, so queue
            # occupancy plus the in-flight transfer never exceed the
            # documented bound (a bare bounded queue overshoots by one:
            # depth queued + one transferred-in-hand blocked on put).
            depth = int(prefetch_to_device)
            slots = threading.Semaphore(depth)

            def device_gen():
                for batch in self._host_batches(
                    batch_size=batch_size, drop_last=drop_last, **kw
                ):
                    slots.acquire()
                    yield to_device(batch)

            def dequeued():
                gen = _through_thread(device_gen, depth, "h2d")
                try:
                    for item in gen:
                        slots.release()
                        yield item
                finally:
                    # Unblock a producer parked in acquire() so the
                    # pipeline thread can observe stop and exit.
                    for _ in range(depth):
                        slots.release()
                    gen.close()

            return _timed(dequeued())

        def device_gen_sync():
            for batch in self._host_batches(
                batch_size=batch_size, drop_last=drop_last, **kw
            ):
                yield to_device(batch)

        return _timed(device_gen_sync())

    def iter_torch_batches(self, *, batch_size: Optional[int] = 256, **kw):
        import torch

        for batch in self.iter_batches(batch_size=batch_size, **kw):
            yield {k: torch.as_tensor(np.asarray(v)) for k, v in batch.items()}


def _concat_permuted(parts: list, rng, n: int) -> Dict[str, np.ndarray]:
    if len(parts) == 1:
        buf = {k: np.asarray(v) for k, v in parts[0].items()}
    else:
        buf = {
            k: np.concatenate([np.asarray(p[k]) for p in parts]) for k in parts[0]
        }
    order = rng.permutation(n)
    return {k: v[order] for k, v in buf.items()}
