"""Execution context / tuning knobs for the data layer.

Reference: python/ray/data/context.py (DataContext) — a process-wide
singleton the executor consults, overridable per test/workload.
"""
from __future__ import annotations

from dataclasses import dataclass


@dataclass
class DataContext:
    # Back-pressure between operators is BYTE-budgeted (block sizes come
    # from RefBundle metadata), with a block-count cap for tiny blocks
    # (reference: backpressure_policy/ streaming output backpressure).
    max_buffered_blocks: int = 16
    max_buffered_bytes: int = 128 * 1024 * 1024
    # Autoscaling actor pools: kill an idle actor above min_size after
    # this long (reference: execution/autoscaler actor-pool scaling).
    actor_idle_timeout_s: float = 2.0
    # Consumption-end pipeline (data/iterator.py). ``prefetch_blocks``:
    # bundle refs resolved ahead of the consumer (0 disables both block
    # prefetch and the background rebatch thread — the fully synchronous
    # legacy path). ``rebatch_queue_depth``: host batches buffered between
    # the rebatch thread and the consumer. ``prefetch_to_device``: batches
    # device_put ahead of the caller in iter_jax_batches (bounds pinned
    # HBM; 0 = synchronous transfer).
    prefetch_blocks: int = 2
    rebatch_queue_depth: int = 2
    prefetch_to_device: int = 2

    _current = None

    @classmethod
    def get_current(cls) -> "DataContext":
        if cls._current is None:
            cls._current = cls()
        return cls._current

    def to_dict(self) -> dict:
        """Snapshot for shipping to another process (the context is
        process-local; actors/train workers get the driver's values via
        this + apply_overrides)."""
        import dataclasses

        return dataclasses.asdict(self)

    @classmethod
    def apply_overrides(cls, overrides: "dict | None") -> "DataContext":
        ctx = cls.get_current()
        for k, v in (overrides or {}).items():
            if hasattr(ctx, k):
                setattr(ctx, k, v)
        return ctx
