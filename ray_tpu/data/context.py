"""Execution context / tuning knobs for the data layer.

Reference: python/ray/data/context.py (DataContext) — a process-wide
singleton the executor consults, overridable per test/workload.
"""
from __future__ import annotations

from dataclasses import dataclass


@dataclass
class DataContext:
    # Back-pressure between operators is BYTE-budgeted (block sizes come
    # from RefBundle metadata), with a block-count cap for tiny blocks
    # (reference: backpressure_policy/ streaming output backpressure).
    max_buffered_blocks: int = 16
    max_buffered_bytes: int = 128 * 1024 * 1024
    # Autoscaling actor pools: kill an idle actor above min_size after
    # this long (reference: execution/autoscaler actor-pool scaling).
    actor_idle_timeout_s: float = 2.0

    _current = None

    @classmethod
    def get_current(cls) -> "DataContext":
        if cls._current is None:
            cls._current = cls()
        return cls._current
