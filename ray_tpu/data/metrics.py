"""Consumption-end data-pipeline metrics.

Reference: the reference's per-iterator stats (python/ray/data/_internal/
stats.py ``iter_wait_s``/``iter_total_blocked_s``) exported as metrics.
These ride the PR-1/PR-3 telemetry pipeline: Counter/Gauge/Histogram
instances flush to the controller and surface in Prometheus/Grafana (the
"Data" dashboard row) automatically.

``counts`` is a plain process-local mirror of the counters for tests and
bench.py: the metric registry drains *deltas* at flush time, so Metric
internals cannot be read back reliably from the recording process.
"""
from __future__ import annotations

import threading
from typing import Dict

_lock = threading.Lock()
_metrics = None

# Wait/transfer times are sub-millisecond when the pipeline keeps up —
# boundaries start well below the step times train_step_wall_ms uses.
_MS_BOUNDARIES = (
    0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000, 5000,
)


class _DataMetrics:
    def __init__(self):
        from ray_tpu.util.metrics import Counter, Gauge, Histogram

        self.iter_wait_ms = Histogram(
            "data_iter_wait_ms",
            "Consumer-side wait for the next batch from a DataIterator "
            "(pipeline on: queue wait; pipeline off: inline fetch+rebatch)",
            _MS_BOUNDARIES,
        )
        self.prefetch_depth = Gauge(
            "data_prefetch_depth",
            "Batches buffered ahead of the consumer in a pipeline stage",
            ("stage",),
        )
        self.zero_copy_hits = Counter(
            "data_zero_copy_hits_total",
            "Blocks decoded as numpy views over the shared-memory store "
            "(no deserialize copy)",
        )
        self.zero_copy_misses = Counter(
            "data_zero_copy_misses_total",
            "Blocks materialized through the copying get path (inline-tier, "
            "row blocks, or unviewable objects)",
        )
        self.h2d_ms = Histogram(
            "data_h2d_ms",
            "Host-to-device dispatch time per batch (jax.device_put)",
            _MS_BOUNDARIES,
        )
        self.backpressure_stalls = Counter(
            "data_backpressure_stalls_total",
            "Scheduler ticks that refused to poll an operator because its "
            "downstream buffer was saturated",
            ("op",),
        )
        # Process-local, non-draining counters (tests/bench read these).
        self.counts: Dict[str, int] = {}

    def bump(self, key: str, n: int = 1):
        with _lock:
            self.counts[key] = self.counts.get(key, 0) + n


def data_metrics() -> _DataMetrics:
    global _metrics
    if _metrics is None:
        with _lock:
            if _metrics is None:
                _metrics = _DataMetrics()
    return _metrics
