"""TFRecord datasource/datasink — dependency-free implementation.

Reference: python/ray/data/_internal/datasource/tfrecords_datasource.py
(reads tf.train.Example records into columnar batches) and
python/ray/data/dataset.py write_tfrecords. The reference leans on
tensorflow/protobuf; here both layers are implemented directly:

- TFRecord framing: ``[len:uint64le][masked-crc32c(len):uint32le]
  [data][masked-crc32c(data):uint32le]`` per record.
- ``tf.train.Example`` protobuf wire format (features { feature { map
  entry -> bytes_list/float_list/int64_list } }) encoded/decoded with a
  minimal varint codec — no protobuf runtime needed.
"""
from __future__ import annotations

import struct
from typing import Any, Dict, Iterable, List, Optional

import numpy as np

from ray_tpu.data.block import Block, BlockAccessor
from ray_tpu.data.datasink import _FileDatasink
from ray_tpu.data.datasource import FileBasedDatasource

# ---------------------------------------------------------------------------
# CRC32C (Castagnoli), table-driven; TFRecord masks it as
# ((crc >> 15 | crc << 17) + 0xa282ead8) & 0xffffffff.
# ---------------------------------------------------------------------------

_CRC_TABLE = []


def _crc_table():
    global _CRC_TABLE
    if _CRC_TABLE:
        return _CRC_TABLE
    poly = 0x82F63B78  # reflected Castagnoli polynomial
    table = []
    for n in range(256):
        c = n
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        table.append(c)
    _CRC_TABLE = table
    return table


def crc32c(data: bytes) -> int:
    table = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# Protobuf wire helpers (varint + length-delimited fields).
# ---------------------------------------------------------------------------


def _write_varint(out: bytearray, v: int):
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _read_varint(buf: bytes, pos: int):
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _field(out: bytearray, number: int, wire_type: int, payload: bytes):
    _write_varint(out, (number << 3) | wire_type)
    if wire_type == 2:
        _write_varint(out, len(payload))
    out.extend(payload)


def _encode_feature(value) -> bytes:
    """One ``tf.train.Feature``: field 1 bytes_list, 2 float_list,
    3 int64_list."""
    inner = bytearray()
    if isinstance(value, bytes):
        vals = [value]
        kind = 1
    elif isinstance(value, str):
        vals = [value.encode()]
        kind = 1
    elif isinstance(value, (list, tuple, np.ndarray)):
        vals = list(value)
        if not vals:
            kind = 3
        elif isinstance(vals[0], (bytes, str)):
            vals = [v.encode() if isinstance(v, str) else v for v in vals]
            kind = 1
        elif isinstance(vals[0], (float, np.floating)):
            kind = 2
        else:
            kind = 3
    elif isinstance(value, (float, np.floating)):
        vals, kind = [value], 2
    else:
        vals, kind = [int(value)], 3

    if kind == 1:
        for v in vals:
            _field(inner, 1, 2, bytes(v))
    elif kind == 2:
        packed = struct.pack(f"<{len(vals)}f", *[float(v) for v in vals])
        _field(inner, 1, 2, packed)
    else:
        packed = bytearray()
        for v in vals:
            _write_varint(packed, int(v) & 0xFFFFFFFFFFFFFFFF)
        _field(inner, 1, 2, bytes(packed))

    feat = bytearray()
    _field(feat, kind, 2, bytes(inner))
    return bytes(feat)


def encode_example(row: Dict[str, Any]) -> bytes:
    """Dict row → serialized ``tf.train.Example``."""
    features = bytearray()
    for key, value in row.items():
        entry = bytearray()
        _field(entry, 1, 2, key.encode())
        _field(entry, 2, 2, _encode_feature(value))
        _field(features, 1, 2, bytes(entry))  # map<string,Feature> entry
    example = bytearray()
    _field(example, 1, 2, bytes(features))
    return bytes(example)


def _decode_feature(buf: bytes):
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        number, wt = tag >> 3, tag & 7
        ln, pos = _read_varint(buf, pos)
        inner = buf[pos : pos + ln]
        pos += ln
        # inner is a BytesList/FloatList/Int64List message: field 1 repeated
        values: List[Any] = []
        ipos = 0
        while ipos < len(inner):
            itag, ipos = _read_varint(inner, ipos)
            iwt = itag & 7
            if iwt == 2:
                iln, ipos = _read_varint(inner, ipos)
                payload = inner[ipos : ipos + iln]
                ipos += iln
                if number == 1:  # bytes_list
                    values.append(payload)
                elif number == 2:  # packed floats
                    values.extend(struct.unpack(f"<{len(payload)//4}f", payload))
                else:  # packed varints
                    vpos = 0
                    while vpos < len(payload):
                        v, vpos = _read_varint(payload, vpos)
                        if v >= 1 << 63:
                            v -= 1 << 64
                        values.append(v)
            elif iwt == 5:  # unpacked float
                values.append(struct.unpack("<f", inner[ipos : ipos + 4])[0])
                ipos += 4
            else:  # unpacked varint
                v, ipos = _read_varint(inner, ipos)
                if number == 3 and v >= 1 << 63:
                    v -= 1 << 64
                values.append(v)
        return values
    return []


def decode_example(data: bytes) -> Dict[str, Any]:
    row: Dict[str, Any] = {}
    pos = 0
    while pos < len(data):
        tag, pos = _read_varint(data, pos)
        ln, pos = _read_varint(data, pos)
        features = data[pos : pos + ln]
        pos += ln
        fpos = 0
        while fpos < len(features):
            ftag, fpos = _read_varint(features, fpos)
            fln, fpos = _read_varint(features, fpos)
            entry = features[fpos : fpos + fln]
            fpos += fln
            # map entry: 1=key, 2=Feature
            epos = 0
            key, feat = "", b""
            while epos < len(entry):
                etag, epos = _read_varint(entry, epos)
                eln, epos = _read_varint(entry, epos)
                payload = entry[epos : epos + eln]
                epos += eln
                if etag >> 3 == 1:
                    key = payload.decode()
                else:
                    feat = payload
            values = _decode_feature(feat)
            row[key] = values[0] if len(values) == 1 else values
    return row


# ---------------------------------------------------------------------------
# Record-level IO.
# ---------------------------------------------------------------------------


def write_tfrecords_file(path: str, rows: Iterable[Dict[str, Any]]):
    with open(path, "wb") as f:
        for row in rows:
            data = encode_example(row)
            header = struct.pack("<Q", len(data))
            f.write(header)
            f.write(struct.pack("<I", _masked_crc(header)))
            f.write(data)
            f.write(struct.pack("<I", _masked_crc(data)))


def read_tfrecords_file(path: str) -> List[Dict[str, Any]]:
    rows = []
    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if len(header) < 8:
                break
            (length,) = struct.unpack("<Q", header)
            (hcrc,) = struct.unpack("<I", f.read(4))
            if hcrc != _masked_crc(header):
                raise ValueError(f"corrupt TFRecord length CRC in {path}")
            data = f.read(length)
            (dcrc,) = struct.unpack("<I", f.read(4))
            if dcrc != _masked_crc(data):
                raise ValueError(f"corrupt TFRecord data CRC in {path}")
            rows.append(decode_example(data))
    return rows


class TFRecordDatasource(FileBasedDatasource):
    def _read_file(self, path: str) -> Iterable[Block]:
        yield read_tfrecords_file(path)


class TFRecordDatasink(_FileDatasink):
    def __init__(self, path: str):
        super().__init__(path, "tfrecords")

    def _write_block(self, block: Block, out: str):
        write_tfrecords_file(out, BlockAccessor.for_block(block).iter_rows())
