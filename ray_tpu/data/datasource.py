"""Datasources: lazily-planned read tasks.

Reference: python/ray/data/datasource/ (Datasource/ReadTask/Reader) and
python/ray/data/_internal/datasource/ (per-format impls). Each datasource
plans ``ReadTask``s — serializable zero-arg callables that yield blocks —
so reads execute remotely, in parallel, and only when the streaming
executor pulls on them.
"""
from __future__ import annotations

import glob as _glob
import json as _json
import os
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterable, List, Optional

import numpy as np

from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata


@dataclass
class ReadTask:
    read_fn: Callable[[], Iterable[Block]]
    metadata: BlockMetadata


class Datasource:
    def estimate_inmemory_data_size(self) -> Optional[int]:
        return None

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        raise NotImplementedError

    @property
    def name(self) -> str:
        return type(self).__name__.replace("Datasource", "")


class RangeDatasource(Datasource):
    """``range(n)`` / ``range_tensor`` (reference:
    python/ray/data/_internal/datasource/range_datasource.py)."""

    def __init__(self, n: int, tensor_shape: Optional[tuple] = None):
        self._n = n
        self._shape = tensor_shape

    def estimate_inmemory_data_size(self) -> Optional[int]:
        per = 8 * (int(np.prod(self._shape)) if self._shape else 1)
        return self._n * per

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        if self._n == 0:
            shape = self._shape
            empty = (
                {"data": np.empty((0,) + shape, np.int64)}
                if shape
                else {"id": np.empty(0, np.int64)}
            )
            return [ReadTask(lambda e=empty: iter([e]), BlockMetadata(0, 0))]
        tasks = []
        parallelism = max(1, min(parallelism, self._n))
        chunk = -(-self._n // parallelism)
        for start in range(0, self._n, chunk):
            end = min(start + chunk, self._n)
            shape = self._shape

            def read(start=start, end=end, shape=shape) -> Iterable[Block]:
                ids = np.arange(start, end, dtype=np.int64)
                if shape:
                    data = np.broadcast_to(
                        ids.reshape((-1,) + (1,) * len(shape)), (end - start,) + shape
                    ).copy()
                    yield {"data": data}
                else:
                    yield {"id": ids}

            meta = BlockMetadata(num_rows=end - start, size_bytes=(end - start) * 8)
            tasks.append(ReadTask(read, meta))
        return tasks


class ItemsDatasource(Datasource):
    def __init__(self, items: List[Any]):
        self._items = items

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        items = self._items
        n = len(items)
        parallelism = max(1, min(parallelism, n or 1))
        chunk = -(-n // parallelism) if n else 1
        tasks = []
        for start in range(0, n, chunk):
            part = items[start : start + chunk]

            def read(part=part) -> Iterable[Block]:
                yield part

            tasks.append(ReadTask(read, BlockAccessor(part).metadata()))
        return tasks or [ReadTask(lambda: iter([[]]), BlockMetadata(0, 0))]


class NumpyDatasource(Datasource):
    def __init__(self, arrays: Dict[str, np.ndarray]):
        n = {len(v) for v in arrays.values()}
        if len(n) > 1:
            raise ValueError(f"ragged columns: {n}")
        self._arrays = arrays
        self._n = n.pop() if n else 0

    def estimate_inmemory_data_size(self) -> Optional[int]:
        return sum(v.nbytes for v in self._arrays.values())

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        parallelism = max(1, min(parallelism, self._n or 1))
        chunk = -(-self._n // parallelism) if self._n else 1
        tasks = []
        for start in range(0, self._n, chunk):
            end = min(start + chunk, self._n)
            part = {k: v[start:end] for k, v in self._arrays.items()}

            def read(part=part) -> Iterable[Block]:
                yield part

            tasks.append(ReadTask(read, BlockAccessor(part).metadata()))
        return tasks or [ReadTask(lambda: iter([{}]), BlockMetadata(0, 0))]


def _expand_paths(paths) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(_glob.glob(os.path.join(p, "**", "*"), recursive=True)))
        elif any(c in p for c in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    return [p for p in out if os.path.isfile(p)]


class FileBasedDatasource(Datasource):
    """One read task per file group (reference:
    python/ray/data/datasource/file_based_datasource.py)."""

    def __init__(self, paths):
        self._files = _expand_paths(paths)
        if not self._files:
            raise ValueError(f"no input files found for {paths!r}")

    def _read_file(self, path: str) -> Iterable[Block]:
        raise NotImplementedError

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        groups: List[List[str]] = [[] for _ in range(max(1, min(parallelism, len(self._files))))]
        for i, f in enumerate(self._files):
            groups[i % len(groups)].append(f)
        read_file = self._read_file
        tasks = []
        for grp in groups:
            if not grp:
                continue

            def read(grp=grp) -> Iterable[Block]:
                for path in grp:
                    yield from read_file(path)

            size = sum(os.path.getsize(f) for f in grp)
            tasks.append(
                ReadTask(read, BlockMetadata(num_rows=0, size_bytes=size, input_files=grp))
            )
        return tasks


class CSVDatasource(FileBasedDatasource):
    def _read_file(self, path: str) -> Iterable[Block]:
        import csv

        with open(path, newline="") as f:
            rows = list(csv.DictReader(f))
        for r in rows:
            for k, v in r.items():
                try:
                    r[k] = int(v)
                except (TypeError, ValueError):
                    try:
                        r[k] = float(v)
                    except (TypeError, ValueError):
                        pass
        yield rows


class JSONDatasource(FileBasedDatasource):
    """JSONL or a top-level JSON array per file."""

    def _read_file(self, path: str) -> Iterable[Block]:
        with open(path) as f:
            head = f.read(1)
            f.seek(0)
            if head == "[":
                yield _json.load(f)
            else:
                yield [_json.loads(line) for line in f if line.strip()]


class TextDatasource(FileBasedDatasource):
    def _read_file(self, path: str) -> Iterable[Block]:
        with open(path) as f:
            yield [{"text": line.rstrip("\n")} for line in f]


class BinaryDatasource(FileBasedDatasource):
    def _read_file(self, path: str) -> Iterable[Block]:
        with open(path, "rb") as f:
            yield [{"path": path, "bytes": f.read()}]


class NumpyFileDatasource(FileBasedDatasource):
    def _read_file(self, path: str) -> Iterable[Block]:
        arr = np.load(path)
        yield {"data": arr}


class ParquetDatasource(FileBasedDatasource):
    def _read_file(self, path: str) -> Iterable[Block]:
        import pyarrow.parquet as pq

        table = pq.read_table(path)
        yield {c: table.column(c).to_numpy(zero_copy_only=False) for c in table.column_names}
