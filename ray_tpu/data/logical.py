"""Logical plan: lazy operator DAG + optimizer.

Reference: python/ray/data/_internal/logical/ (LogicalPlan, operators,
``_internal/logical/optimizers.py:43-59`` rule-based optimizer with the
operator-fusion rule in ``_internal/logical/rules/operator_fusion.py``).

The optimizer here implements the one rule that matters for throughput:
fusing chains of one-to-one (map-like) operators into a single task per
block, which removes intermediate object-store round trips.
"""
from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.data.datasource import Datasource


@dataclass
class LogicalOp:
    name: str
    input: Optional["LogicalOp"] = None

    def chain(self) -> List["LogicalOp"]:
        ops: List[LogicalOp] = []
        op: Optional[LogicalOp] = self
        while op is not None:
            ops.append(op)
            op = op.input
        return list(reversed(ops))


@dataclass
class Read(LogicalOp):
    datasource: Datasource = None
    parallelism: int = -1


@dataclass
class InputData(LogicalOp):
    """Pre-materialized (block_ref, metadata) pairs — from_blocks / unions."""

    bundles: List[Any] = field(default_factory=list)


@dataclass
class MapLike(LogicalOp):
    """One-to-one row/batch transform; fusable.

    kind: map | map_batches | flat_map | filter
    """

    kind: str = "map"
    fn: Callable = None
    fn_args: tuple = ()
    fn_kwargs: Dict[str, Any] = field(default_factory=dict)
    batch_size: Optional[int] = None
    # Actor-pool compute for stateful/expensive UDFs (class constructors).
    compute_actors: int = 0
    fn_constructor_args: tuple = ()
    num_cpus: float = 1
    num_tpus: float = 0


@dataclass
class AllToAll(LogicalOp):
    """Barrier ops: repartition / random_shuffle / sort / groupby-aggregate.

    kind: repartition | shuffle | sort | aggregate
    """

    kind: str = "repartition"
    num_outputs: Optional[int] = None
    key: Optional[str] = None
    descending: bool = False
    seed: Optional[int] = None
    aggs: List[Any] = field(default_factory=list)


@dataclass
class Limit(LogicalOp):
    limit: int = 0


@dataclass
class Union(LogicalOp):
    others: List[LogicalOp] = field(default_factory=list)


@dataclass
class Zip(LogicalOp):
    """Row-aligned column concatenation of N datasets (reference:
    python/ray/data/dataset.py Dataset.zip / _internal ZipOperator)."""

    others: List[LogicalOp] = field(default_factory=list)


@dataclass
class LogicalPlan:
    dag: LogicalOp

    def optimized(self) -> "LogicalPlan":
        return LogicalPlan(_fuse(self.dag))


def _fuse(op: LogicalOp) -> LogicalOp:
    """Collapse MapLike→MapLike edges into FusedMap nodes."""
    if op is None:
        return None
    inp = _fuse(op.input)
    if isinstance(op, (Union, Zip)):
        op = replace(op, others=[_fuse(o) for o in op.others])
    op = replace(op, input=inp)
    if (
        isinstance(op, MapLike)
        and isinstance(inp, FusedMap)
        and op.compute_actors == 0
        and all(s.compute_actors == 0 for s in inp.stages)
        # Fusing stages with different resource requests would silently run
        # one stage under the other's reservation — keep them separate tasks.
        and all(
            (s.num_cpus, s.num_tpus) == (op.num_cpus, op.num_tpus)
            for s in inp.stages
        )
    ):
        return FusedMap(
            name=f"{inp.name}->{op.name}", input=inp.input, stages=inp.stages + [op]
        )
    if isinstance(op, MapLike) and op.compute_actors == 0:
        return FusedMap(name=op.name, input=inp, stages=[op])
    return op


@dataclass
class FusedMap(LogicalOp):
    stages: List[MapLike] = field(default_factory=list)
