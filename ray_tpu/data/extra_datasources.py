"""Additional datasources: WebDataset (tar shards), SQL (DB-API), images.

Reference: python/ray/data/_internal/datasource/webdataset_datasource.py
(tar shards with samples grouped by key prefix),
sql_datasource.py (connection-factory + query sharding),
image_datasource.py (PIL decode to HWC arrays).
"""
from __future__ import annotations

import io
import json
import os
import tarfile
from typing import Any, Callable, Dict, Iterable, List, Optional

import numpy as np

from ray_tpu.data.block import Block, BlockAccessor
from ray_tpu.data.datasink import _FileDatasink
from ray_tpu.data.datasource import Datasource, FileBasedDatasource, ReadTask
from ray_tpu.data.block import BlockMetadata


def _decode_component(ext: str, data: bytes):
    """WebDataset convention: decode by extension; unknown stays bytes."""
    ext = ext.split(".")[-1]  # "cls.json" decodes by its final suffix
    if ext in ("txt", "text"):
        return data.decode()
    if ext in ("json",):
        return json.loads(data)
    if ext in ("cls", "index", "id"):
        try:
            return int(data.decode().strip())
        except ValueError:
            return data.decode()
    if ext in ("npy",):
        return np.load(io.BytesIO(data), allow_pickle=False)
    return data


class WebDatasetDatasource(FileBasedDatasource):
    """Tar shards where ``key.ext`` members with a shared key form one
    sample (the WebDataset layout)."""

    def _read_file(self, path: str) -> Iterable[Block]:
        samples: Dict[str, Dict[str, Any]] = {}
        order: List[str] = []
        with tarfile.open(path) as tar:
            for member in tar:
                if not member.isfile():
                    continue
                name = member.name
                key, _, ext = name.partition(".")
                data = tar.extractfile(member).read()
                if key not in samples:
                    samples[key] = {"__key__": key}
                    order.append(key)
                # Schema stability across write→read: the sink appends a
                # codec suffix ("cls" → "cls.json"); strip it from the
                # column name when the extension is multi-part so the
                # original column comes back. Plain single-part extensions
                # (standard WebDataset: "json", "txt", "cls") are kept.
                col = ext
                if "." in ext and ext.rsplit(".", 1)[-1].lower() in ("json", "npy"):
                    col = ext.rsplit(".", 1)[0]
                samples[key][col] = _decode_component(ext.lower(), data)
        yield [samples[k] for k in order]


class WebDatasetDatasink(_FileDatasink):
    """One ``.tar`` shard per block; row dict values become members named
    ``{key}.{column}``."""

    def __init__(self, path: str):
        super().__init__(path, "tar")

    def _write_block(self, block: Block, out: str):
        with tarfile.open(out, "w") as tar:
            for i, row in enumerate(BlockAccessor.for_block(block).iter_rows()):
                if not isinstance(row, dict):
                    row = {"data": row}
                key = str(row.get("__key__", f"{i:08d}"))
                for col, value in row.items():
                    if col == "__key__":
                        continue
                    if isinstance(value, bytes):
                        payload = value
                    elif isinstance(value, str):
                        payload = value.encode()
                    elif isinstance(value, np.ndarray):
                        buf = io.BytesIO()
                        np.save(buf, value)
                        payload = buf.getvalue()
                        col = col + ".npy" if not col.endswith(".npy") else col
                    else:
                        # numpy scalars (columnar blocks yield np.int64 etc.)
                        # are not JSON-serializable; .item() unwraps them
                        payload = json.dumps(
                            value,
                            default=lambda o: o.item() if hasattr(o, "item") else str(o),
                        ).encode()
                        col = col + ".json" if "." not in col else col
                    info = tarfile.TarInfo(f"{key}.{col}")
                    info.size = len(payload)
                    tar.addfile(info, io.BytesIO(payload))


class SQLDatasource(Datasource):
    """DB-API 2.0 reads: ``connection_factory`` must be a serializable
    zero-arg callable (it runs inside read tasks on workers)."""

    def __init__(self, sql: str, connection_factory: Callable[[], Any], parallelism_column: Optional[str] = None):
        self._sql = sql
        self._factory = connection_factory
        self._shard_col = parallelism_column

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        sql, factory = self._sql, self._factory

        if not self._shard_col or parallelism <= 1:
            def read() -> Iterable[Block]:
                conn = factory()
                try:
                    cur = conn.cursor()
                    cur.execute(sql)
                    cols = [d[0] for d in cur.description]
                    yield [dict(zip(cols, row)) for row in cur.fetchall()]
                finally:
                    conn.close()

            return [ReadTask(read, BlockMetadata(0, 0))]

        shard_col = self._shard_col
        tasks = []
        for i in range(parallelism):
            def read(i=i) -> Iterable[Block]:
                conn = factory()
                try:
                    cur = conn.cursor()
                    # Subquery alias: required by Postgres/MySQL (SQLite
                    # accepts it too). Double modulo keeps negative shard
                    # columns in [0, p); COALESCE routes NULLs to shard 0
                    # instead of silently dropping them.
                    cur.execute(
                        f"SELECT * FROM ({sql}) AS _rt_shard WHERE "
                        f"COALESCE((({shard_col}) % {parallelism} + {parallelism})"
                        f" % {parallelism}, 0) = {i}"
                    )
                    cols = [d[0] for d in cur.description]
                    yield [dict(zip(cols, row)) for row in cur.fetchall()]
                finally:
                    conn.close()

            tasks.append(ReadTask(read, BlockMetadata(0, 0)))
        return tasks


class BigQueryDatasource(Datasource):
    """Reference: python/ray/data/_internal/datasource/bigquery_datasource.py
    (the reference shards a BigQuery read across Storage-API streams).
    Requires ``google-cloud-bigquery`` (gated import — read tasks fail
    with a clear error if it is absent). With ``parallelism > 1`` the
    read fans out into N tasks, each running a deterministic hash-shard
    of the query (``FARM_FINGERPRINT(TO_JSON_STRING(row)) MOD N``) so
    shards are disjoint and exhaustive server-side.

    Sharding is OPT-IN (``read_bigquery(..., parallelism=N)`` with an
    explicit N>1): each shard re-executes the query with an output
    filter, so an N-way read costs N query scans and requires a
    deterministic query (no RAND()/unordered LIMIT). The default read
    stays a single query execution.

    ``client_factory`` (serialized into the read tasks, runs on workers)
    exists for dependency injection in tests and for custom auth."""

    def __init__(self, project_id: str, query: str,
                 client_factory: Optional[Callable[[], Any]] = None,
                 shard: bool = False):
        self._project = project_id
        self._query = query
        self._factory = client_factory
        self._shard = shard

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        project, query, factory = self._project, self._query, self._factory

        def make_client():
            if factory is not None:
                return factory()
            try:
                from google.cloud import bigquery  # type: ignore
            except ImportError as e:
                raise ImportError(
                    "read_bigquery requires google-cloud-bigquery"
                ) from e
            return bigquery.Client(project=project)

        p = max(1, parallelism) if self._shard else 1

        def read(i: int = 0, p: int = p) -> Iterable[Block]:
            client = make_client()
            q = query if p == 1 else (
                f"SELECT * FROM ({query}) AS _rt WHERE "
                f"MOD(ABS(FARM_FINGERPRINT(TO_JSON_STRING(_rt))), {p}) = {i}"
            )
            # BigQuery job: workload-duration wait by design  # ray-tpu: lint-ignore[RTL008]
            rows = client.query(q).result()
            yield [dict(r) for r in rows]

        return [
            ReadTask((lambda i=i: read(i)), BlockMetadata(0, 0))
            for i in range(p)
        ]


class MongoDatasource(Datasource):
    """Reference: mongo_datasource.py (the reference partitions the
    collection across read tasks). Requires ``pymongo`` (gated). With
    ``parallelism > 1`` each task reads the documents whose hashed
    ``_id`` falls in its shard (``$toHashedIndexKey`` — disjoint and
    exhaustive; requires MongoDB server >= 7.0). Against older servers
    the sharded read degrades to a single full read on task 0 (with a
    warning) rather than failing every task at runtime."""

    def __init__(self, uri: str, database: str, collection: str,
                 pipeline: Optional[list] = None,
                 client_factory: Optional[Callable[[], Any]] = None,
                 shard: bool = False):
        self._uri = uri
        self._db = database
        self._coll = collection
        self._pipeline = pipeline or []
        self._factory = client_factory
        self._shard = shard

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        uri, db, coll, pipeline = self._uri, self._db, self._coll, self._pipeline
        factory = self._factory

        def make_client():
            if factory is not None:
                return factory()
            try:
                import pymongo  # type: ignore
            except ImportError as e:
                raise ImportError("read_mongo requires pymongo") from e
            return pymongo.MongoClient(uri)

        # A user pipeline's $group/$sort/$limit stages are GLOBAL
        # aggregations; running them per-shard would concatenate partial
        # results — never shard around a pipeline.
        p = max(1, parallelism) if (self._shard and not pipeline) else 1

        def read(i: int = 0, p: int = p) -> Iterable[Block]:
            client = make_client()
            try:
                c = client[db][coll]
                if p == 1:
                    cursor = c.aggregate(pipeline) if pipeline else c.find()
                else:
                    shard = {
                        "$match": {
                            "$expr": {
                                "$eq": [
                                    {"$mod": [
                                        {"$abs": {"$toHashedIndexKey": "$_id"}},
                                        p,
                                    ]},
                                    i,
                                ]
                            }
                        }
                    }
                    try:
                        cursor = c.aggregate([shard])
                    except Exception as e:  # noqa: BLE001 — server capability probe
                        # Degrade ONLY for the missing-operator error; any
                        # other OperationFailure (stepdown, killed cursor,
                        # auth) must propagate — swallowing it would return
                        # an empty shard and silently drop 1/p of the rows.
                        if (
                            type(e).__name__ != "OperationFailure"
                            or "toHashedIndexKey" not in str(e)
                        ):
                            raise
                        # Pre-7.0 server: no $toHashedIndexKey. Degrade to
                        # one full read (task 0) so results stay correct.
                        import warnings

                        warnings.warn(
                            "MongoDB server lacks $toHashedIndexKey (needs "
                            ">= 7.0); sharded read degrades to a single "
                            "task reading the full collection",
                            stacklevel=2,
                        )
                        cursor = c.find() if i == 0 else iter(())
                yield [
                    {k: v for k, v in doc.items() if k != "_id"}
                    for doc in cursor
                ]
            finally:
                client.close()

        return [
            ReadTask((lambda i=i: read(i)), BlockMetadata(0, 0))
            for i in range(p)
        ]


class LanceDatasource(Datasource):
    """Reference: lance_datasource.py (the reference fans out over Lance
    FRAGMENTS). Requires ``lance`` (gated). Each read task opens the
    dataset and reads the fragment stripe ``fragments[i::N]`` — no
    plan-time metadata call, so the driver does not need the client."""

    def __init__(self, uri: str,
                 dataset_factory: Optional[Callable[[], Any]] = None):
        self._uri = uri
        self._factory = dataset_factory

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        uri, factory = self._uri, self._factory

        def open_dataset():
            if factory is not None:
                return factory()
            try:
                import lance  # type: ignore
            except ImportError as e:
                raise ImportError("read_lance requires pylance") from e
            return lance.dataset(uri)

        p = max(1, parallelism)

        def read(i: int = 0, p: int = p) -> Iterable[Block]:
            ds = open_dataset()
            frags = list(ds.get_fragments())[i::p] if p > 1 else [None]
            for frag in frags:
                source = frag if frag is not None else ds
                for batch in source.to_batches():
                    yield {
                        c: batch.column(c).to_numpy(zero_copy_only=False)
                        for c in batch.schema.names
                    }

        return [
            ReadTask((lambda i=i: read(i)), BlockMetadata(0, 0))
            for i in range(p)
        ]


class IcebergDatasource(Datasource):
    """Reference: iceberg_datasource.py (the reference fans out over the
    scan's ``plan_files``). Requires ``pyiceberg`` (gated). Each read
    task loads the table, plans the scan, and reads the file stripe
    ``plan_files()[i::N]`` through pyiceberg's arrow projection (falling
    back to a raw parquet read of ``task.file.file_path``; tasks with
    delete files reject the raw path rather than return wrong rows)."""

    def __init__(self, table_identifier: str, catalog_kwargs: Optional[dict] = None,
                 row_filter: Optional[str] = None,
                 scan_factory: Optional[Callable[[], Any]] = None):
        self._table = table_identifier
        self._catalog_kwargs = catalog_kwargs or {}
        self._filter = row_filter
        self._factory = scan_factory

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        table_id, ckw, flt = self._table, self._catalog_kwargs, self._filter
        factory = self._factory

        def make_scan():
            if factory is not None:
                return factory()
            try:
                from pyiceberg.catalog import load_catalog  # type: ignore
            except ImportError as e:
                raise ImportError("read_iceberg requires pyiceberg") from e
            catalog = load_catalog(**ckw)
            table = catalog.load_table(table_id)
            return table.scan(row_filter=flt) if flt else table.scan()

        # plan_files stripes cannot re-apply a row_filter (file stats only
        # prune whole files); a filtered scan stays single-task so results
        # never depend on parallelism.
        p = max(1, parallelism) if flt is None else 1

        def _arrow_to_block(arrow) -> Block:
            return {
                c: arrow.column(c).to_numpy(zero_copy_only=False)
                for c in arrow.column_names
            }

        def read(i: int = 0, p: int = p) -> Iterable[Block]:
            scan = make_scan()
            if p == 1:
                yield _arrow_to_block(scan.to_arrow())
                return
            tasks = list(scan.plan_files())[i::p]
            # Preferred stripe reader: pyiceberg's own arrow projection
            # (field-id-based) — identical schema semantics to to_arrow()
            # on schema-evolved tables (renamed/dropped/added columns) and
            # correct merge-on-read delete handling. The raw parquet read
            # below is only for mocks/missing-API fallback (reference:
            # _internal/datasource/iceberg_datasource.py:160 uses
            # project_table per FileScanTask for exactly this reason).
            try:
                from pyiceberg.io.pyarrow import project_table  # type: ignore

                meta = scan.table_metadata
                io = scan.io
                proj = scan.projection()
                rf = scan.row_filter
            except (ImportError, AttributeError):
                project_table = None
            for t in tasks:
                if project_table is not None:
                    yield _arrow_to_block(project_table([t], meta, io, rf, proj))
                    continue
                reader = getattr(t, "to_arrow", None)
                if callable(reader):  # test/mock or future pyiceberg API
                    yield _arrow_to_block(reader())
                    continue
                if getattr(t, "delete_files", None):
                    raise NotImplementedError(
                        "sharded iceberg read cannot apply merge-on-read "
                        "delete files; use parallelism=1 or compact the table"
                    )
                import pyarrow.parquet as pq

                yield _arrow_to_block(pq.read_table(t.file.file_path))

        return [
            ReadTask((lambda i=i: read(i)), BlockMetadata(0, 0))
            for i in range(p)
        ]


class ImageDatasource(FileBasedDatasource):
    """Decode images to HWC uint8 arrays (requires PIL; gated import).

    ``mode`` normalizes every file to one PIL mode (default RGB) so mixed
    grayscale/RGBA/palette inputs produce a uniform (H, W, 3) column
    (reference: image_datasource.py's mode conversion)."""

    def __init__(self, paths, size: Optional[tuple] = None, mode: Optional[str] = "RGB"):
        super().__init__(paths)
        self._size = size
        self._mode = mode

    def _read_file(self, path: str) -> Iterable[Block]:
        try:
            from PIL import Image
        except ImportError as e:  # pragma: no cover - PIL is present in CI
            raise ImportError("read_images requires pillow") from e
        img = Image.open(path)
        if self._mode is not None:
            img = img.convert(self._mode)
        if self._size is not None:
            img = img.resize(self._size)
        yield [{"image": np.asarray(img), "path": path}]
