"""Additional datasources: WebDataset (tar shards), SQL (DB-API), images.

Reference: python/ray/data/_internal/datasource/webdataset_datasource.py
(tar shards with samples grouped by key prefix),
sql_datasource.py (connection-factory + query sharding),
image_datasource.py (PIL decode to HWC arrays).
"""
from __future__ import annotations

import io
import json
import os
import tarfile
from typing import Any, Callable, Dict, Iterable, List, Optional

import numpy as np

from ray_tpu.data.block import Block, BlockAccessor
from ray_tpu.data.datasink import _FileDatasink
from ray_tpu.data.datasource import Datasource, FileBasedDatasource, ReadTask
from ray_tpu.data.block import BlockMetadata


def _decode_component(ext: str, data: bytes):
    """WebDataset convention: decode by extension; unknown stays bytes."""
    ext = ext.split(".")[-1]  # "cls.json" decodes by its final suffix
    if ext in ("txt", "text"):
        return data.decode()
    if ext in ("json",):
        return json.loads(data)
    if ext in ("cls", "index", "id"):
        try:
            return int(data.decode().strip())
        except ValueError:
            return data.decode()
    if ext in ("npy",):
        return np.load(io.BytesIO(data), allow_pickle=False)
    return data


class WebDatasetDatasource(FileBasedDatasource):
    """Tar shards where ``key.ext`` members with a shared key form one
    sample (the WebDataset layout)."""

    def _read_file(self, path: str) -> Iterable[Block]:
        samples: Dict[str, Dict[str, Any]] = {}
        order: List[str] = []
        with tarfile.open(path) as tar:
            for member in tar:
                if not member.isfile():
                    continue
                name = member.name
                key, _, ext = name.partition(".")
                data = tar.extractfile(member).read()
                if key not in samples:
                    samples[key] = {"__key__": key}
                    order.append(key)
                # Schema stability across write→read: the sink appends a
                # codec suffix ("cls" → "cls.json"); strip it from the
                # column name when the extension is multi-part so the
                # original column comes back. Plain single-part extensions
                # (standard WebDataset: "json", "txt", "cls") are kept.
                col = ext
                if "." in ext and ext.rsplit(".", 1)[-1].lower() in ("json", "npy"):
                    col = ext.rsplit(".", 1)[0]
                samples[key][col] = _decode_component(ext.lower(), data)
        yield [samples[k] for k in order]


class WebDatasetDatasink(_FileDatasink):
    """One ``.tar`` shard per block; row dict values become members named
    ``{key}.{column}``."""

    def __init__(self, path: str):
        super().__init__(path, "tar")

    def _write_block(self, block: Block, out: str):
        with tarfile.open(out, "w") as tar:
            for i, row in enumerate(BlockAccessor.for_block(block).iter_rows()):
                if not isinstance(row, dict):
                    row = {"data": row}
                key = str(row.get("__key__", f"{i:08d}"))
                for col, value in row.items():
                    if col == "__key__":
                        continue
                    if isinstance(value, bytes):
                        payload = value
                    elif isinstance(value, str):
                        payload = value.encode()
                    elif isinstance(value, np.ndarray):
                        buf = io.BytesIO()
                        np.save(buf, value)
                        payload = buf.getvalue()
                        col = col + ".npy" if not col.endswith(".npy") else col
                    else:
                        # numpy scalars (columnar blocks yield np.int64 etc.)
                        # are not JSON-serializable; .item() unwraps them
                        payload = json.dumps(
                            value,
                            default=lambda o: o.item() if hasattr(o, "item") else str(o),
                        ).encode()
                        col = col + ".json" if "." not in col else col
                    info = tarfile.TarInfo(f"{key}.{col}")
                    info.size = len(payload)
                    tar.addfile(info, io.BytesIO(payload))


class SQLDatasource(Datasource):
    """DB-API 2.0 reads: ``connection_factory`` must be a serializable
    zero-arg callable (it runs inside read tasks on workers)."""

    def __init__(self, sql: str, connection_factory: Callable[[], Any], parallelism_column: Optional[str] = None):
        self._sql = sql
        self._factory = connection_factory
        self._shard_col = parallelism_column

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        sql, factory = self._sql, self._factory

        if not self._shard_col or parallelism <= 1:
            def read() -> Iterable[Block]:
                conn = factory()
                try:
                    cur = conn.cursor()
                    cur.execute(sql)
                    cols = [d[0] for d in cur.description]
                    yield [dict(zip(cols, row)) for row in cur.fetchall()]
                finally:
                    conn.close()

            return [ReadTask(read, BlockMetadata(0, 0))]

        shard_col = self._shard_col
        tasks = []
        for i in range(parallelism):
            def read(i=i) -> Iterable[Block]:
                conn = factory()
                try:
                    cur = conn.cursor()
                    # Subquery alias: required by Postgres/MySQL (SQLite
                    # accepts it too). Double modulo keeps negative shard
                    # columns in [0, p); COALESCE routes NULLs to shard 0
                    # instead of silently dropping them.
                    cur.execute(
                        f"SELECT * FROM ({sql}) AS _rt_shard WHERE "
                        f"COALESCE((({shard_col}) % {parallelism} + {parallelism})"
                        f" % {parallelism}, 0) = {i}"
                    )
                    cols = [d[0] for d in cur.description]
                    yield [dict(zip(cols, row)) for row in cur.fetchall()]
                finally:
                    conn.close()

            tasks.append(ReadTask(read, BlockMetadata(0, 0)))
        return tasks


class BigQueryDatasource(Datasource):
    """Reference: python/ray/data/_internal/datasource/bigquery_datasource.py.
    Requires ``google-cloud-bigquery`` (gated import — read tasks fail
    with a clear error if it is absent). Single-task read: the query
    result lands in one block (``parallelism`` is ignored); shard large
    tables by issuing range-partitioned queries via ``read_sql``-style
    WHERE clauses."""

    def __init__(self, project_id: str, query: str):
        self._project = project_id
        self._query = query

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        project, query = self._project, self._query

        def read() -> Iterable[Block]:
            try:
                from google.cloud import bigquery  # type: ignore
            except ImportError as e:
                raise ImportError(
                    "read_bigquery requires google-cloud-bigquery"
                ) from e
            client = bigquery.Client(project=project)
            rows = client.query(query).result()
            yield [dict(r) for r in rows]

        return [ReadTask(read, BlockMetadata(0, 0))]


class MongoDatasource(Datasource):
    """Reference: mongo_datasource.py. Requires ``pymongo`` (gated).
    Single-task read (``parallelism`` ignored); shard by passing a
    ``pipeline`` with ``$match`` partitions per call."""

    def __init__(self, uri: str, database: str, collection: str, pipeline: Optional[list] = None):
        self._uri = uri
        self._db = database
        self._coll = collection
        self._pipeline = pipeline or []

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        uri, db, coll, pipeline = self._uri, self._db, self._coll, self._pipeline

        def read() -> Iterable[Block]:
            try:
                import pymongo  # type: ignore
            except ImportError as e:
                raise ImportError("read_mongo requires pymongo") from e
            client = pymongo.MongoClient(uri)
            try:
                cursor = client[db][coll].aggregate(pipeline) if pipeline else client[db][coll].find()
                yield [{k: v for k, v in doc.items() if k != "_id"} for doc in cursor]
            finally:
                client.close()

        return [ReadTask(read, BlockMetadata(0, 0))]


class LanceDatasource(Datasource):
    """Reference: lance_datasource.py. Requires ``lance`` (gated). Lance
    datasets are directories, not file globs, so this is a plain
    single-task Datasource like IcebergDatasource."""

    def __init__(self, uri: str):
        self._uri = uri

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        uri = self._uri

        def read() -> Iterable[Block]:
            try:
                import lance  # type: ignore
            except ImportError as e:
                raise ImportError("read_lance requires pylance") from e
            ds = lance.dataset(uri)
            for batch in ds.to_batches():
                yield {
                    c: batch.column(c).to_numpy(zero_copy_only=False)
                    for c in batch.schema.names
                }

        return [ReadTask(read, BlockMetadata(0, 0))]


class IcebergDatasource(Datasource):
    """Reference: iceberg_datasource.py. Requires ``pyiceberg`` (gated).
    Single-task read (``parallelism`` ignored); use ``row_filter`` to
    shard by partition predicates."""

    def __init__(self, table_identifier: str, catalog_kwargs: Optional[dict] = None, row_filter: Optional[str] = None):
        self._table = table_identifier
        self._catalog_kwargs = catalog_kwargs or {}
        self._filter = row_filter

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        table_id, ckw, flt = self._table, self._catalog_kwargs, self._filter

        def read() -> Iterable[Block]:
            try:
                from pyiceberg.catalog import load_catalog  # type: ignore
            except ImportError as e:
                raise ImportError("read_iceberg requires pyiceberg") from e
            catalog = load_catalog(**ckw)
            table = catalog.load_table(table_id)
            scan = table.scan(row_filter=flt) if flt else table.scan()
            arrow = scan.to_arrow()
            yield {
                c: arrow.column(c).to_numpy(zero_copy_only=False)
                for c in arrow.column_names
            }

        return [ReadTask(read, BlockMetadata(0, 0))]


class ImageDatasource(FileBasedDatasource):
    """Decode images to HWC uint8 arrays (requires PIL; gated import).

    ``mode`` normalizes every file to one PIL mode (default RGB) so mixed
    grayscale/RGBA/palette inputs produce a uniform (H, W, 3) column
    (reference: image_datasource.py's mode conversion)."""

    def __init__(self, paths, size: Optional[tuple] = None, mode: Optional[str] = "RGB"):
        super().__init__(paths)
        self._size = size
        self._mode = mode

    def _read_file(self, path: str) -> Iterable[Block]:
        try:
            from PIL import Image
        except ImportError as e:  # pragma: no cover - PIL is present in CI
            raise ImportError("read_images requires pillow") from e
        img = Image.open(path)
        if self._mode is not None:
            img = img.convert(self._mode)
        if self._size is not None:
            img = img.resize(self._size)
        yield [{"image": np.asarray(img), "path": path}]
