"""Aggregation functions for groupby / global aggregates.

Reference: python/ray/data/aggregate.py (AggregateFn, Count/Sum/Min/Max/
Mean/Std) — Std uses Welford-style merge of (count, mean, M2).
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import numpy as np

from ray_tpu.data.block import Block, BlockAccessor


class AggregateFn:
    def __init__(
        self,
        init: Callable[[], Any],
        accumulate_row: Callable[[Any, Any], Any],
        merge: Callable[[Any, Any], Any],
        finalize: Callable[[Any], Any] = lambda a: a,
        name: str = "agg",
        on: Optional[str] = None,
    ):
        self.init = init
        self.accumulate_row = accumulate_row
        self.merge = merge
        self.finalize = finalize
        self.name = name
        self.on = on

    def _value(self, row):
        if self.on is None:
            return row
        return row[self.on]


class Count(AggregateFn):
    def __init__(self):
        super().__init__(
            init=lambda: 0,
            accumulate_row=lambda a, r: a + 1,
            merge=lambda a, b: a + b,
            name="count()",
        )


class Sum(AggregateFn):
    def __init__(self, on: Optional[str] = None):
        super().__init__(
            init=lambda: 0,
            accumulate_row=lambda a, r: a + self._value(r),
            merge=lambda a, b: a + b,
            name=f"sum({on or ''})",
            on=on,
        )


class Min(AggregateFn):
    def __init__(self, on: Optional[str] = None):
        super().__init__(
            init=lambda: None,
            accumulate_row=lambda a, r: self._value(r) if a is None else min(a, self._value(r)),
            merge=lambda a, b: b if a is None else (a if b is None else min(a, b)),
            name=f"min({on or ''})",
            on=on,
        )


class Max(AggregateFn):
    def __init__(self, on: Optional[str] = None):
        super().__init__(
            init=lambda: None,
            accumulate_row=lambda a, r: self._value(r) if a is None else max(a, self._value(r)),
            merge=lambda a, b: b if a is None else (a if b is None else max(a, b)),
            name=f"max({on or ''})",
            on=on,
        )


class Mean(AggregateFn):
    def __init__(self, on: Optional[str] = None):
        super().__init__(
            init=lambda: (0, 0.0),
            accumulate_row=lambda a, r: (a[0] + 1, a[1] + self._value(r)),
            merge=lambda a, b: (a[0] + b[0], a[1] + b[1]),
            finalize=lambda a: a[1] / a[0] if a[0] else None,
            name=f"mean({on or ''})",
            on=on,
        )


class Std(AggregateFn):
    def __init__(self, on: Optional[str] = None, ddof: int = 1):
        def acc(a, r):
            n, mean, m2 = a
            x = self._value(r)
            n += 1
            d = x - mean
            mean += d / n
            m2 += d * (x - mean)
            return (n, mean, m2)

        def merge(a, b):
            na, ma, m2a = a
            nb, mb, m2b = b
            if na == 0:
                return b
            if nb == 0:
                return a
            n = na + nb
            d = mb - ma
            return (n, ma + d * nb / n, m2a + m2b + d * d * na * nb / n)

        super().__init__(
            init=lambda: (0, 0.0, 0.0),
            accumulate_row=acc,
            merge=merge,
            finalize=lambda a: float(np.sqrt(a[2] / (a[0] - ddof))) if a[0] > ddof else None,
            name=f"std({on or ''})",
            on=on,
        )


def aggregate_block(block: Block, key: Optional[str], aggs) -> Block:
    """Per-partition grouped aggregation; runs inside a remote task."""
    acc = BlockAccessor.for_block(block)
    groups: dict = {}
    for row in acc.iter_rows():
        k = row[key] if key is not None else None
        if k not in groups:
            groups[k] = [a.init() for a in aggs]
        st = groups[k]
        for i, a in enumerate(aggs):
            st[i] = a.accumulate_row(st[i], row)
    rows = []
    for k in sorted(groups, key=lambda x: (x is None, x)):
        row = {} if key is None else {key: k}
        for a, s in zip(aggs, groups[k]):
            row[a.name] = a.finalize(s)
        rows.append(row)
    return rows
