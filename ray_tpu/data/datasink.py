"""Datasinks: distributed block writes.

Reference: python/ray/data/datasource/datasink.py (Datasink.write per
block, on_write_complete) and the per-format file datasinks
(_internal/datasource/parquet_datasink.py etc.). Each output block is
written by a remote task where the block lives — the driver only
collects the written paths.
"""
from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.data.block import Block, BlockAccessor


import ray_tpu


@ray_tpu.remote
def _write_block_task(block, sink, idx):
    """Module-level so its serialized form is digest-cached once per
    process instead of re-shipped on every write call."""
    return sink.write(block, {"task_index": idx})


class Datasink:
    """Write interface: ``write`` runs remotely once per block."""

    def write(self, block: Block, ctx: Dict[str, Any]) -> Any:
        raise NotImplementedError

    def on_write_complete(self, results: List[Any]) -> None:
        pass


class _FileDatasink(Datasink):
    def __init__(self, path: str, file_format: str):
        import uuid

        self.path = path
        self.file_format = file_format
        # Per-write token in every filename so re-writing a directory never
        # silently mixes in stale parts from a previous, larger write
        # (reference datasinks embed a write UUID for the same reason).
        self.write_token = uuid.uuid4().hex[:8]

    def _filename(self, ctx: Dict[str, Any]) -> str:
        return os.path.join(
            self.path,
            f"part-{self.write_token}-{ctx['task_index']:06d}.{self.file_format}",
        )

    def write(self, block: Block, ctx: Dict[str, Any]) -> str:
        os.makedirs(self.path, exist_ok=True)
        out = self._filename(ctx)
        self._write_block(block, out)
        return out

    def _write_block(self, block: Block, out: str):
        raise NotImplementedError


class ParquetDatasink(_FileDatasink):
    def __init__(self, path: str):
        super().__init__(path, "parquet")

    def _write_block(self, block: Block, out: str):
        BlockAccessor.for_block(block).to_pandas().to_parquet(out, index=False)


class CSVDatasink(_FileDatasink):
    def __init__(self, path: str):
        super().__init__(path, "csv")

    def _write_block(self, block: Block, out: str):
        BlockAccessor.for_block(block).to_pandas().to_csv(out, index=False)


class JSONDatasink(_FileDatasink):
    def __init__(self, path: str):
        super().__init__(path, "json")

    def _write_block(self, block: Block, out: str):
        BlockAccessor.for_block(block).to_pandas().to_json(
            out, orient="records", lines=True
        )


class NumpyDatasink(_FileDatasink):
    def __init__(self, path: str, column: Optional[str] = None):
        super().__init__(path, "npy")
        self.column = column

    def _write_block(self, block: Block, out: str):
        batch = BlockAccessor.for_block(block).to_batch()
        if not batch:  # empty block (e.g. everything filtered out)
            np.save(out, np.empty(0))
            return
        col = self.column or next(iter(batch))
        np.save(out, np.asarray(batch[col]))
