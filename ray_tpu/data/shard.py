"""Dataset shards for distributed trainers.

Reference: python/ray/data/_internal/iterator/stream_split_iterator.py —
the reference hosts a ``SplitCoordinator`` actor that runs ONE shared
streaming execution and fans output bundles out to n consumers.
``Dataset.streaming_split`` covers same-process consumers via
executor.SplitCoordinator; this module lifts the same coordinator behind
an actor so TRAIN WORKERS in other processes can pull bundle *refs* (never
block payloads — those resolve worker-side through the pipelined
DataIterator, zero-copy where the tiers allow) from one shared execution.
"""
from __future__ import annotations

import logging
from typing import List, Optional, Tuple

import ray_tpu
from ray_tpu.data.operators import RefBundle

logger = logging.getLogger(__name__)


class ShardCoordinator:
    """Actor: runs one streaming execution, fans bundles out to n splits.

    Must be created with ``max_concurrency > n`` — each split's blocking
    pull occupies an actor thread, and one starved split must not block
    the others (see :func:`create_shard_coordinator`).
    """

    def __init__(self, dag, n: int, equal: bool = True,
                 data_context: Optional[dict] = None):
        from ray_tpu.data.context import DataContext
        from ray_tpu.data.executor import SplitCoordinator, plan_to_operators
        from ray_tpu.data.logical import LogicalPlan

        # The driver's DataContext does not propagate to actor processes —
        # apply its snapshot so executor knobs (byte budgets, ...) behave
        # as tuned on the driver.
        DataContext.apply_overrides(data_context)
        plan = LogicalPlan(dag).optimized()
        self._coord = SplitCoordinator(plan_to_operators(plan), n, equal)

    def next_bundles(
        self, split: int, max_n: int = 8
    ) -> Optional[List[Tuple]]:
        """Up to ``max_n`` ``(ref, meta)`` pairs for ``split``; blocks for
        the first; None at end of stream."""
        bundles = self._coord.next_batch(split, max_n)
        if bundles is None:
            return None
        return [(b.ref, b.meta) for b in bundles]

    def release_split(self, split: int):
        """A consumer stopped iterating early — unblock the pump so the
        remaining splits keep streaming."""
        self._coord.release(split)
        return True


def create_shard_coordinator(ds, n: int, *, equal: bool = True):
    """Spawn the coordinator actor for ``ds`` split ``n`` ways."""
    from ray_tpu.data.context import DataContext

    actor_cls = ray_tpu.remote(ShardCoordinator)
    return actor_cls.options(max_concurrency=n + 2).remote(
        ds._dag, n, equal, DataContext.get_current().to_dict()
    )


def shard_iterator(actor, split: int):
    """Worker-side :class:`DataIterator` over one split of a coordinator
    actor's execution (what ``train.get_dataset_shard`` hands the loop)."""
    from ray_tpu.data.iterator import DataIterator

    def factory():
        done = False
        try:
            while True:
                out = ray_tpu.get(actor.next_bundles.remote(split))
                if not out:
                    done = True
                    return
                for ref, meta in out:
                    yield RefBundle(ref, meta)
        finally:
            if not done:
                # Abandoned mid-stream (break / error): tell the
                # coordinator, or the round-robin pump stalls on this
                # split's full queue and starves the other ranks.
                try:
                    actor.release_split.remote(split)
                except Exception:
                    logger.debug(
                        "release_split(%d) failed (coordinator gone?)",
                        split,
                        exc_info=True,
                    )

    return DataIterator(factory)
