"""ray_tpu.data: streaming distributed data processing for TPU ingest.

Reference: python/ray/data/__init__.py (read_* / from_* factory surface).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.data.aggregate import AggregateFn, Count, Max, Mean, Min, Std, Sum
from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata
from ray_tpu.data.context import DataContext
from ray_tpu.data.dataset import Dataset, GroupedData
from ray_tpu.data.iterator import DataIterator
from ray_tpu.data.datasource import (
    BinaryDatasource,
    CSVDatasource,
    Datasource,
    ItemsDatasource,
    JSONDatasource,
    NumpyDatasource,
    NumpyFileDatasource,
    ParquetDatasource,
    RangeDatasource,
    TextDatasource,
)
from ray_tpu.data.datasink import (
    CSVDatasink,
    Datasink,
    JSONDatasink,
    NumpyDatasink,
    ParquetDatasink,
)
from ray_tpu.data.logical import Read


def _read(ds: Datasource, parallelism: int = -1) -> Dataset:
    return Dataset(Read(name=f"Read{ds.name}", datasource=ds, parallelism=parallelism))


def range(n: int, *, parallelism: int = -1) -> Dataset:  # noqa: A001
    return _read(RangeDatasource(n), parallelism)


def range_tensor(n: int, *, shape: tuple = (1,), parallelism: int = -1) -> Dataset:
    return _read(RangeDatasource(n, tensor_shape=tuple(shape)), parallelism)


def from_items(items: List[Any], *, parallelism: int = -1) -> Dataset:
    return _read(ItemsDatasource(list(items)), parallelism)


def from_numpy(arrays, *, parallelism: int = -1) -> Dataset:
    if isinstance(arrays, np.ndarray):
        arrays = {"data": arrays}
    return _read(NumpyDatasource({k: np.asarray(v) for k, v in arrays.items()}), parallelism)


def from_pandas(df, *, parallelism: int = -1) -> Dataset:
    return _read(
        NumpyDatasource({c: df[c].to_numpy() for c in df.columns}), parallelism
    )


def read_csv(paths, *, parallelism: int = -1) -> Dataset:
    return _read(CSVDatasource(paths), parallelism)


def read_json(paths, *, parallelism: int = -1) -> Dataset:
    return _read(JSONDatasource(paths), parallelism)


def read_text(paths, *, parallelism: int = -1) -> Dataset:
    return _read(TextDatasource(paths), parallelism)


def read_binary_files(paths, *, parallelism: int = -1) -> Dataset:
    return _read(BinaryDatasource(paths), parallelism)


def read_numpy(paths, *, parallelism: int = -1) -> Dataset:
    return _read(NumpyFileDatasource(paths), parallelism)


def read_parquet(paths, *, parallelism: int = -1) -> Dataset:
    return _read(ParquetDatasource(paths), parallelism)


def read_bigquery(project_id: str, query: str, *, parallelism: int = -1,
                  _client_factory=None) -> Dataset:
    from ray_tpu.data.extra_datasources import BigQueryDatasource

    # sharding only on an EXPLICIT parallelism (each shard re-runs the
    # query; the -1 default must stay one query execution)
    return _read(
        BigQueryDatasource(project_id, query, _client_factory, shard=parallelism > 1),
        parallelism,
    )


def read_mongo(uri: str, database: str, collection: str, *, pipeline=None,
               parallelism: int = -1, _client_factory=None) -> Dataset:
    from ray_tpu.data.extra_datasources import MongoDatasource

    return _read(
        MongoDatasource(uri, database, collection, pipeline, _client_factory,
                        shard=parallelism > 1),
        parallelism,
    )


def read_lance(uri: str, *, parallelism: int = -1, _dataset_factory=None) -> Dataset:
    from ray_tpu.data.extra_datasources import LanceDatasource

    return _read(LanceDatasource(uri, _dataset_factory), parallelism)


def read_iceberg(table_identifier: str, *, catalog_kwargs=None, row_filter=None,
                 parallelism: int = -1, _scan_factory=None) -> Dataset:
    from ray_tpu.data.extra_datasources import IcebergDatasource

    return _read(
        IcebergDatasource(table_identifier, catalog_kwargs, row_filter, _scan_factory),
        parallelism,
    )


def read_datasource(ds: Datasource, *, parallelism: int = -1) -> Dataset:
    return _read(ds, parallelism)


def read_tfrecords(paths, *, parallelism: int = -1) -> Dataset:
    from ray_tpu.data.tfrecord import TFRecordDatasource

    return _read(TFRecordDatasource(paths), parallelism)


def read_webdataset(paths, *, parallelism: int = -1) -> Dataset:
    from ray_tpu.data.extra_datasources import WebDatasetDatasource

    return _read(WebDatasetDatasource(paths), parallelism)


def read_sql(sql: str, connection_factory, *, parallelism: int = -1, parallelism_column: Optional[str] = None) -> Dataset:
    from ray_tpu.data.extra_datasources import SQLDatasource

    return _read(SQLDatasource(sql, connection_factory, parallelism_column), parallelism)


def read_images(paths, *, size: Optional[tuple] = None, mode: Optional[str] = "RGB", parallelism: int = -1) -> Dataset:
    from ray_tpu.data.extra_datasources import ImageDatasource

    return _read(ImageDatasource(paths, size=size, mode=mode), parallelism)


__all__ = [
    "Dataset",
    "DataIterator",
    "GroupedData",
    "Datasource",
    "Block",
    "BlockAccessor",
    "BlockMetadata",
    "AggregateFn",
    "Count",
    "Sum",
    "Min",
    "Max",
    "Mean",
    "Std",
    "range",
    "range_tensor",
    "from_items",
    "from_numpy",
    "from_pandas",
    "read_csv",
    "read_json",
    "read_text",
    "read_binary_files",
    "read_numpy",
    "read_parquet",
    "read_tfrecords",
    "read_webdataset",
    "read_sql",
    "read_images",
    "read_bigquery",
    "read_mongo",
    "read_lance",
    "read_iceberg",
    "read_datasource",
    "Datasink",
    "ParquetDatasink",
    "CSVDatasink",
    "JSONDatasink",
    "NumpyDatasink",
    "token_loader",
]


def token_loader(paths, batch_size: int, seq_len: int, **kw):
    """Native C++ prefetching token-batch loader for TPU pretraining
    ingest (ray_tpu/native/src/loader.cc — mmap + worker threads filling
    a bounded ring of fixed-shape uint32 batches)."""
    from ray_tpu.native.loader import TokenLoader

    return TokenLoader(paths, batch_size, seq_len, **kw)
