from ray_tpu.ops.attention import flash_attention

__all__ = ["flash_attention"]
