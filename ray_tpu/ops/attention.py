"""Attention ops: Pallas flash-attention forward for TPU + reference path.

The reference framework has no attention kernels (it orchestrates external
libraries); on TPU the kernel must be native (SURVEY.md §2.9). Design:

- ``flash_attention``: blocked online-softmax forward as a Pallas kernel
  (MXU-shaped 128-tiles, fp32 accumulation), with a custom VJP whose
  backward recomputes via the XLA reference path (flash backward kernel is a
  later optimization; recompute keeps memory O(seq·d) instead of O(seq²)).
- ``reference_attention``: straight jnp implementation used for CPU tests,
  as the VJP recompute path, and as the numerical oracle.

Layouts: q, k, v are [batch, heads, seq, head_dim]; GQA is handled by the
caller (kv heads repeated before the call or via q head grouping).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


def reference_attention(q, k, v, causal: bool = True, scale: Optional[float] = None):
    *_, q_len, head_dim = q.shape
    k_len = k.shape[-2]
    scale = scale if scale is not None else head_dim**-0.5
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
    logits = logits * scale
    if causal:
        mask = jnp.tril(jnp.ones((q_len, k_len), dtype=bool), k=k_len - q_len)
        logits = jnp.where(mask, logits, DEFAULT_MASK_VALUE)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v)


# ---------------------------------------------------------------------------
# Pallas forward kernel
# ---------------------------------------------------------------------------


def _flash_fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool, scale: float, k_len_actual: int
):
    """One (batch·head, q-block) program: online softmax over k blocks.

    ``k_ref`` is padded to a multiple of ``block_k`` by the wrapper so
    dynamic k-block slices never clamp (a clamped slice would silently
    shift key rows); padded columns are masked via ``k_len_actual``.
    """
    q = q_ref[0].astype(jnp.float32) * scale  # [block_q, d]
    block_q, head_dim = q.shape
    k_len = k_ref.shape[1]  # padded length, multiple of block_k
    q_blk = pl.program_id(1)
    q_start = q_blk * block_q

    num_k_blocks = k_len // block_k
    if causal:
        # Only k blocks at or before the diagonal contribute.
        num_k_blocks_needed = jax.lax.div(q_start + block_q - 1, block_k) + 1
    else:
        num_k_blocks_needed = num_k_blocks

    def body(kb, carry):
        acc, m_prev, l_prev = carry
        k_start = kb * block_k
        kblk = k_ref[0, pl.ds(k_start, block_k), :].astype(jnp.float32)
        vblk = v_ref[0, pl.ds(k_start, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kblk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [block_q, block_k]
        # k_len/k_len_actual are trace-time ints: unpadded non-causal runs
        # skip masking entirely.
        needs_pad_mask = k_len_actual < k_len
        if causal or needs_pad_mask:
            k_ids = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            valid = (k_ids < k_len_actual) if needs_pad_mask else True
            if causal:
                q_ids = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
                valid = valid & (q_ids >= k_ids)
            s = jnp.where(valid, s, DEFAULT_MASK_VALUE)
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new[:, None])
        correction = jnp.exp(m_prev - m_new)
        l_new = l_prev * correction + jnp.sum(p, axis=-1)
        acc = acc * correction[:, None] + jax.lax.dot_general(
            p, vblk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return acc, m_new, l_new

    init = (
        jnp.zeros((block_q, head_dim), jnp.float32),
        jnp.full((block_q,), -jnp.inf, jnp.float32),
        jnp.zeros((block_q,), jnp.float32),
    )
    acc, _, l = jax.lax.fori_loop(0, num_k_blocks_needed, body, init)
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal: bool, scale: float, block_q: int, block_k: int, interpret: bool):
    batch, heads, q_len, head_dim = q.shape
    k_len = k.shape[2]
    bq = min(block_q, q_len)
    bk = min(block_k, k_len)
    qr = q.reshape(batch * heads, q_len, head_dim)
    kr = k.reshape(batch * heads, k_len, head_dim)
    vr = v.reshape(batch * heads, k_len, head_dim)
    # Pad K/V so every k-block slice is in bounds (see kernel docstring).
    k_pad = (-k_len) % bk
    if k_pad:
        kr = jnp.pad(kr, ((0, 0), (0, k_pad), (0, 0)))
        vr = jnp.pad(vr, ((0, 0), (0, k_pad), (0, 0)))
    k_len_padded = k_len + k_pad
    grid = (batch * heads, pl.cdiv(q_len, bq))
    out = pl.pallas_call(
        functools.partial(
            _flash_fwd_kernel, block_k=bk, causal=causal, scale=scale, k_len_actual=k_len
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, head_dim), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, k_len_padded, head_dim), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, k_len_padded, head_dim), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, head_dim), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((batch * heads, q_len, head_dim), q.dtype),
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(batch, heads, q_len, head_dim)


def _use_pallas() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, causal: bool = True, scale: Optional[float] = None):
    """Flash attention: Pallas kernel on TPU, jnp reference elsewhere."""
    s = scale if scale is not None else q.shape[-1] ** -0.5
    if _use_pallas():
        return _flash_forward(q, k, v, causal, s, block_q=256, block_k=256, interpret=False)
    return reference_attention(q, k, v, causal=causal, scale=s)


def _fwd(q, k, v, causal, scale):
    return flash_attention(q, k, v, causal, scale), (q, k, v)


def _bwd(causal, scale, res, g):
    # Recompute-based backward: O(seq·d) memory, XLA fuses the softmax chain.
    q, k, v = res
    s = scale if scale is not None else q.shape[-1] ** -0.5

    def ref(q, k, v):
        return reference_attention(q, k, v, causal=causal, scale=s)

    _, vjp = jax.vjp(ref, q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)
