"""Attention ops: Pallas flash-attention forward for TPU + reference path.

The reference framework has no attention kernels (it orchestrates external
libraries); on TPU the kernel must be native (SURVEY.md §2.9). Design:

- ``flash_attention``: blocked online-softmax forward as a Pallas kernel
  (MXU-shaped 128-tiles, fp32 accumulation) that also emits the per-row
  logsumexp, with a custom VJP running the flash *backward* as two Pallas
  kernels (dQ over q-blocks; dK/dV over k-blocks) — memory stays
  O(seq·d), no seq² materialization in either direction.
- ``reference_attention``: straight jnp implementation used for CPU tests,
  as the non-TPU VJP path, and as the numerical oracle.

Layouts: q is [batch, q_heads, seq, head_dim]; k/v are
[batch, kv_heads, seq, head_dim] with q_heads % kv_heads == 0 — GQA is
NATIVE: the kernels index the shared kv head per q-head group instead of
the caller repeating K/V, so a Mistral-style 8-kv-head config reads each
K/V head once from HBM (and never materializes the repeated tensors the
old caller-side repeat cost both HBM and VJP traffic for).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


def reference_attention(q, k, v, causal: bool = True, scale: Optional[float] = None):
    *_, q_len, head_dim = q.shape
    if k.shape[1] != q.shape[1]:  # GQA: expand kv heads for the oracle
        rep = q.shape[1] // k.shape[1]
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    k_len = k.shape[-2]
    scale = scale if scale is not None else head_dim**-0.5
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
    logits = logits * scale
    if causal:
        mask = jnp.tril(jnp.ones((q_len, k_len), dtype=bool), k=k_len - q_len)
        logits = jnp.where(mask, logits, DEFAULT_MASK_VALUE)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v)


# ---------------------------------------------------------------------------
# Pallas forward kernel
# ---------------------------------------------------------------------------


def _flash_fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int, causal: bool, scale: float,
    k_len_actual: int
):
    """One (batch·head, q-block) program: online softmax over k blocks.

    ``k_ref`` is padded to a multiple of ``block_k`` by the wrapper so
    dynamic k-block slices never clamp (a clamped slice would silently
    shift key rows); padded columns are masked via ``k_len_actual``.
    """
    q = q_ref[0].astype(jnp.float32) * scale  # [block_q, d]
    block_q, head_dim = q.shape
    k_len = k_ref.shape[1]  # padded length, multiple of block_k
    q_blk = pl.program_id(1)
    q_start = q_blk * block_q

    num_k_blocks = k_len // block_k
    if causal:
        # Only k blocks at or before the diagonal contribute. Clamp: with
        # block_q > block_k a partial final q-block would otherwise
        # overshoot and issue a clamped (row-shifting) slice.
        num_k_blocks_needed = jnp.minimum(
            jax.lax.div(q_start + block_q - 1, block_k) + 1, num_k_blocks
        )
    else:
        num_k_blocks_needed = num_k_blocks

    def make_body(masked: bool):
        def body(kb, carry):
            acc, m_prev, l_prev = carry
            k_start = kb * block_k
            kblk = k_ref[0, pl.ds(k_start, block_k), :].astype(jnp.float32)
            vblk = v_ref[0, pl.ds(k_start, block_k), :].astype(jnp.float32)
            s = jax.lax.dot_general(
                q, kblk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )  # [block_q, block_k]
            needs_pad_mask = k_len_actual < k_len
            if masked and (causal or needs_pad_mask):
                k_ids = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
                valid = (k_ids < k_len_actual) if needs_pad_mask else True
                if causal:
                    q_ids = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
                    valid = valid & (q_ids >= k_ids)
                s = jnp.where(valid, s, DEFAULT_MASK_VALUE)
            m_cur = jnp.max(s, axis=-1)
            m_new = jnp.maximum(m_prev, m_cur)
            p = jnp.exp(s - m_new[:, None])
            correction = jnp.exp(m_prev - m_new)
            l_new = l_prev * correction + jnp.sum(p, axis=-1)
            acc = acc * correction[:, None] + jax.lax.dot_general(
                p, vblk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
            )
            return acc, m_new, l_new

        return body

    init = (
        jnp.zeros((block_q, head_dim), jnp.float32),
        jnp.full((block_q,), -jnp.inf, jnp.float32),
        jnp.zeros((block_q,), jnp.float32),
    )
    if causal:
        # Two phases: k blocks fully below the diagonal need no mask (the
        # mask's iota/compare/select is VPU work comparable to the MXU
        # matmul at these block shapes); only diagonal-crossing blocks pay
        # for it. The clamp to whole real-K blocks keeps the unmasked
        # phase off the zero padding AND in-bounds when q_len > k_len
        # (self-attention never hits either, cross-length causal does).
        num_full = jnp.minimum(
            jax.lax.div(q_start, block_k), k_len_actual // block_k
        )
        carry = jax.lax.fori_loop(0, num_full, make_body(False), init)
        acc, m, l = jax.lax.fori_loop(
            num_full, num_k_blocks_needed, make_body(True), carry
        )
    else:
        acc, m, l = jax.lax.fori_loop(0, num_k_blocks_needed, make_body(True), init)
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)
    # logsumexp of the scaled scores — the backward kernels rebuild
    # P = exp(S - lse) from it instead of re-running the softmax.
    lse_ref[0, 0] = m + jnp.log(jnp.maximum(l, 1e-30))


def _kv_index_map(q_heads: int, kv_heads: int):
    """Program id over batch·q_heads → the [batch·kv_heads] row holding
    that q head's shared K/V (the GQA mapping; identity when MHA)."""
    group = q_heads // kv_heads

    def imap(b, i):
        return ((b // q_heads) * kv_heads + (b % q_heads) // group, 0, 0)

    return imap


def _flash_forward(q, k, v, causal: bool, scale: float, block_q: int, block_k: int, interpret: bool):
    batch, heads, q_len, head_dim = q.shape
    kv_heads = k.shape[1]
    assert heads % kv_heads == 0, (heads, kv_heads)
    k_len = k.shape[2]
    bq = min(block_q, q_len)
    bk = min(block_k, k_len)
    qr = q.reshape(batch * heads, q_len, head_dim)
    kr = k.reshape(batch * kv_heads, k_len, head_dim)
    vr = v.reshape(batch * kv_heads, k_len, head_dim)
    # Pad K/V so every k-block slice is in bounds (see kernel docstring).
    k_pad = (-k_len) % bk
    if k_pad:
        kr = jnp.pad(kr, ((0, 0), (0, k_pad), (0, 0)))
        vr = jnp.pad(vr, ((0, 0), (0, k_pad), (0, 0)))
    k_len_padded = k_len + k_pad
    kv_map = _kv_index_map(heads, kv_heads)
    grid = (batch * heads, pl.cdiv(q_len, bq))
    out, lse = pl.pallas_call(
        functools.partial(
            _flash_fwd_kernel, block_k=bk, causal=causal, scale=scale, k_len_actual=k_len
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, head_dim), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, k_len_padded, head_dim), kv_map),
            pl.BlockSpec((1, k_len_padded, head_dim), kv_map),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, head_dim), lambda b, i: (b, i, 0)),
            # [bh, 1, q_len] with a unit middle dim keeps the (8,128) TPU
            # tile constraint satisfied: block dims (1, bq).
            pl.BlockSpec((1, 1, bq), lambda b, i: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((batch * heads, q_len, head_dim), q.dtype),
            jax.ShapeDtypeStruct((batch * heads, 1, q_len), jnp.float32),
        ],
        interpret=interpret,
    )(qr, kr, vr)
    return (
        out.reshape(batch, heads, q_len, head_dim),
        lse.reshape(batch, heads, q_len),
    )


# ---------------------------------------------------------------------------
# Pallas backward kernels
# ---------------------------------------------------------------------------


def _flash_bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
    block_k: int, causal: bool, scale: float, k_len_actual: int
):
    """One (batch·head, q-block) program: dQ = scale · Σ_k dS·K over k
    blocks, with dS = P ∘ (dO·Vᵀ − Δ) and P rebuilt from the saved lse."""
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, 0].astype(jnp.float32)
    delta = delta_ref[0, 0].astype(jnp.float32)
    block_q, head_dim = q.shape
    k_len = k_ref.shape[1]
    q_start = pl.program_id(1) * block_q
    num_k_blocks = k_len // block_k
    if causal:
        num_k_blocks_needed = jnp.minimum(
            jax.lax.div(q_start + block_q - 1, block_k) + 1, num_k_blocks
        )
    else:
        num_k_blocks_needed = num_k_blocks

    def make_body(masked: bool):
        def body(kb, acc):
            k_start = kb * block_k
            kblk = k_ref[0, pl.ds(k_start, block_k), :].astype(jnp.float32)
            vblk = v_ref[0, pl.ds(k_start, block_k), :].astype(jnp.float32)
            s = jax.lax.dot_general(
                q, kblk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            ) * scale
            needs_pad_mask = k_len_actual < k_len
            if masked and (causal or needs_pad_mask):
                k_ids = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
                valid = (k_ids < k_len_actual) if needs_pad_mask else True
                if causal:
                    q_ids = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
                    valid = valid & (q_ids >= k_ids)
                s = jnp.where(valid, s, DEFAULT_MASK_VALUE)
            p = jnp.exp(s - lse[:, None])  # masked entries underflow to 0
            dp = jax.lax.dot_general(
                do, vblk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )
            ds = p * (dp - delta[:, None])
            return acc + jax.lax.dot_general(
                ds, kblk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
            )

        return body

    init = jnp.zeros((block_q, head_dim), jnp.float32)
    if causal:
        # Same two-phase split + clamp as the forward kernel (see there).
        num_full = jnp.minimum(
            jax.lax.div(q_start, block_k), k_len_actual // block_k
        )
        acc = jax.lax.fori_loop(0, num_full, make_body(False), init)
        acc = jax.lax.fori_loop(num_full, num_k_blocks_needed, make_body(True), acc)
    else:
        acc = jax.lax.fori_loop(0, num_k_blocks_needed, make_body(True), init)
    dq_ref[0] = (acc * scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, *,
    block_q: int, causal: bool, scale: float, grouped: bool = False
):
    """One (batch·kv_head, k-block[, group]) program: dK/dV accumulated
    over q blocks.

    GQA (``grouped``): a third, innermost grid dim walks the kv head's
    group of q heads; each program sees ONE q head's (padded) rows — the
    same VMEM footprint as MHA — and accumulates into the shared
    (batch·kv_head, k-block) output block, which stays resident across
    the group steps (output index map constant along the group dim).

    Padded q rows (q/do/delta zero-padded, lse zero) contribute nothing:
    dO = 0 kills the dV term and dP − Δ = 0 kills the dK term.
    """
    k = k_ref[0].astype(jnp.float32)  # [block_k, d]
    v = v_ref[0].astype(jnp.float32)
    block_k, head_dim = k.shape
    q_len = q_ref.shape[1]  # one q head's rows, padded to block_q multiple
    k_start = pl.program_id(1) * block_k
    num_q_blocks = q_len // block_q
    # Causal: q blocks strictly before this k block see none of it.
    start_qb = jax.lax.div(k_start, block_q) if causal else 0

    def make_body(masked: bool):
        def body(qb, carry):
            dk, dv = carry
            q_start = qb * block_q
            qblk = q_ref[0, pl.ds(q_start, block_q), :].astype(jnp.float32)
            doblk = do_ref[0, pl.ds(q_start, block_q), :].astype(jnp.float32)
            lse = lse_ref[0, 0, pl.ds(q_start, block_q)].astype(jnp.float32)
            delta = delta_ref[0, 0, pl.ds(q_start, block_q)].astype(jnp.float32)
            s = jax.lax.dot_general(
                qblk, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            ) * scale
            if masked and causal:
                q_ids = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
                k_ids = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
                s = jnp.where(q_ids >= k_ids, s, DEFAULT_MASK_VALUE)
            p = jnp.exp(s - lse[:, None])
            dv = dv + jax.lax.dot_general(
                p, doblk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
            )
            dp = jax.lax.dot_general(
                doblk, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )
            ds = p * (dp - delta[:, None])
            dk = dk + jax.lax.dot_general(
                ds, qblk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
            )
            return dk, dv

        return body

    init = (
        jnp.zeros((block_k, head_dim), jnp.float32),
        jnp.zeros((block_k, head_dim), jnp.float32),
    )
    if causal:
        # Masked head phase: q blocks overlapping this k block's diagonal
        # span; everything after q_start >= k_start + block_k is fully
        # above the diagonal and needs no mask.
        first_full = jnp.minimum(
            jax.lax.div(k_start + block_k + block_q - 1, block_q), num_q_blocks
        )
        carry = jax.lax.fori_loop(start_qb, first_full, make_body(True), init)
        dk, dv = jax.lax.fori_loop(first_full, num_q_blocks, make_body(False), carry)
    else:
        dk, dv = jax.lax.fori_loop(start_qb, num_q_blocks, make_body(True), init)
    dk = dk * scale
    if grouped:
        # fp32 outputs accumulate across the group grid dim
        @pl.when(pl.program_id(2) == 0)
        def _init():
            dk_ref[0] = dk.astype(dk_ref.dtype)
            dv_ref[0] = dv.astype(dv_ref.dtype)

        @pl.when(pl.program_id(2) != 0)
        def _acc():
            dk_ref[0] += dk.astype(dk_ref.dtype)
            dv_ref[0] += dv.astype(dv_ref.dtype)
    else:
        dk_ref[0] = dk.astype(dk_ref.dtype)
        dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_backward(q, k, v, o, lse, do, causal: bool, scale: float,
                    block_q: int, block_k: int, interpret: bool):
    batch, heads, q_len, head_dim = q.shape
    kv_heads = k.shape[1]
    group = heads // kv_heads
    k_len = k.shape[2]
    bq = min(block_q, q_len)
    bk = min(block_k, k_len)
    bh = batch * heads

    qr = q.reshape(bh, q_len, head_dim)
    kr = k.reshape(batch * kv_heads, k_len, head_dim)
    vr = v.reshape(batch * kv_heads, k_len, head_dim)
    dor = do.reshape(bh, q_len, head_dim)
    lser = lse.reshape(bh, 1, q_len)
    # Δ = rowsum(dO ∘ O): one fused elementwise+reduce, cheap in XLA.
    delta = jnp.sum(
        dor.astype(jnp.float32) * o.reshape(bh, q_len, head_dim).astype(jnp.float32),
        axis=-1,
    ).reshape(bh, 1, q_len)

    k_pad = (-k_len) % bk
    if k_pad:
        kr = jnp.pad(kr, ((0, 0), (0, k_pad), (0, 0)))
        vr = jnp.pad(vr, ((0, 0), (0, k_pad), (0, 0)))
    k_len_p = k_len + k_pad
    kv_map = _kv_index_map(heads, kv_heads)

    # dQ: grid over q blocks, K/V resident (GQA: shared kv head indexed).
    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel, block_k=bk, causal=causal, scale=scale,
            k_len_actual=k_len,
        ),
        grid=(bh, pl.cdiv(q_len, bq)),
        in_specs=[
            pl.BlockSpec((1, bq, head_dim), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, k_len_p, head_dim), kv_map),
            pl.BlockSpec((1, k_len_p, head_dim), kv_map),
            pl.BlockSpec((1, bq, head_dim), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 1, bq), lambda b, i: (b, 0, i)),
            pl.BlockSpec((1, 1, bq), lambda b, i: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, bq, head_dim), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, q_len, head_dim), q.dtype),
        interpret=interpret,
    )(qr, kr, vr, dor, lser, delta)

    # dK/dV: grid over (batch·kv_heads, k blocks[, q-head group]); each
    # program streams ONE q head's blocks (same VMEM footprint as MHA);
    # for GQA the group is the innermost grid dim and dK/dV accumulate in
    # the resident fp32 output block. Q-side arrays must be padded to a
    # block_q multiple for the dynamic slices (padded rows are harmless
    # per the kernel docstring).
    q_pad = (-q_len) % bq
    if q_pad:
        qr = jnp.pad(qr, ((0, 0), (0, q_pad), (0, 0)))
        dor = jnp.pad(dor, ((0, 0), (0, q_pad), (0, 0)))
        lser = jnp.pad(lser, ((0, 0), (0, 0), (0, q_pad)))
        delta = jnp.pad(delta, ((0, 0), (0, 0), (0, q_pad)))
    q_len_p = q_len + q_pad

    if group > 1:
        bkv = batch * kv_heads
        # [b·H, q_len_p, d] -> [b·KV, group·q_len_p, d]; block index g on
        # the row axis selects one q head's segment
        qr = qr.reshape(bkv, group * q_len_p, head_dim)
        dor = dor.reshape(bkv, group * q_len_p, head_dim)
        lser = lser.reshape(bkv, 1, group * q_len_p)
        delta = delta.reshape(bkv, 1, group * q_len_p)
        grid = (bkv, k_len_p // bk, group)
        q_spec = pl.BlockSpec((1, q_len_p, head_dim), lambda b, j, g: (b, g, 0))
        r_spec = pl.BlockSpec((1, 1, q_len_p), lambda b, j, g: (b, 0, g))
        kv_in = pl.BlockSpec((1, bk, head_dim), lambda b, j, g: (b, j, 0))
        kv_out = pl.BlockSpec((1, bk, head_dim), lambda b, j, g: (b, j, 0))
        out_dtype = jnp.float32  # group accumulation stays full precision
    else:
        bkv = bh
        grid = (bkv, k_len_p // bk)
        q_spec = pl.BlockSpec((1, q_len_p, head_dim), lambda b, j: (b, 0, 0))
        r_spec = pl.BlockSpec((1, 1, q_len_p), lambda b, j: (b, 0, 0))
        kv_in = pl.BlockSpec((1, bk, head_dim), lambda b, j: (b, j, 0))
        kv_out = pl.BlockSpec((1, bk, head_dim), lambda b, j: (b, j, 0))
        out_dtype = None

    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel, block_q=bq, causal=causal, scale=scale,
            grouped=group > 1,
        ),
        grid=grid,
        in_specs=[q_spec, kv_in, kv_in, q_spec, r_spec, r_spec],
        out_specs=[kv_out, kv_out],
        out_shape=[
            jax.ShapeDtypeStruct((bkv, k_len_p, head_dim), out_dtype or k.dtype),
            jax.ShapeDtypeStruct((bkv, k_len_p, head_dim), out_dtype or v.dtype),
        ],
        interpret=interpret,
    )(qr, kr, vr, dor, lser, delta)
    if k_pad:
        dk = dk[:, :k_len]
        dv = dv[:, :k_len]
    if group > 1:
        dk = dk.astype(k.dtype)
        dv = dv.astype(v.dtype)
    return (
        dq.reshape(batch, heads, q_len, head_dim),
        dk.reshape(batch, kv_heads, k_len, head_dim),
        dv.reshape(batch, kv_heads, k_len, head_dim),
    )


def _env_blocks(var: str):
    import os

    raw = os.environ.get(var)
    if not raw:
        return None
    bq, bk = raw.split(",")
    return int(bq), int(bk)


def _default_blocks(q_len: int, k_len: int, head_dim: int, bwd: bool = False):
    """Shape-adaptive Pallas block sizes, measured on v5e (bf16):
    (1024, 512) beats (256, 256) by ~35-40%% at head_dim 64 across
    2k-8k sequence; at head_dim 128 (512, 512) beats (512, 256) by ~4
    points of end-to-end train MFU on the 750M flagship bench, and the
    round-3 sweep (benchmarks/tune_flash.py) confirmed it still wins
    against (1024,512)/(512,1024)/(256,512) variants there.
    Larger head dims multiply per-program VMEM (blocks plus the resident
    K/V), so they step down conservatively.

    Env overrides for tuning sweeps: RAY_TPU_FLASH_BLOCKS="bq,bk" and
    RAY_TPU_FLASH_BWD_BLOCKS="bq,bk" (backward kernels only)."""
    override = _env_blocks("RAY_TPU_FLASH_BWD_BLOCKS" if bwd else "RAY_TPU_FLASH_BLOCKS")
    if override is None and bwd:
        override = _env_blocks("RAY_TPU_FLASH_BLOCKS")
    if override is not None:
        return override
    if head_dim <= 64:
        return 1024, 512
    if head_dim <= 128:
        return 512, 512
    return 256, 256


def _use_pallas() -> bool:
    import os

    # AOT compiles against a TPU *topology* run with a CPU default
    # backend — the env override lets them force the TPU lowering
    # (benchmarks/compile_7b.py --backend tpu).
    force = os.environ.get("RAY_TPU_FORCE_PALLAS")
    if force is not None:
        return force == "1"
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, causal: bool = True, scale: Optional[float] = None):
    """Flash attention: Pallas kernels on TPU, jnp reference elsewhere."""
    return _fwd(q, k, v, causal, scale)[0]


def _fwd(q, k, v, causal, scale):
    s = scale if scale is not None else q.shape[-1] ** -0.5
    if _use_pallas():
        bq, bk = _default_blocks(q.shape[-2], k.shape[-2], q.shape[-1])
        out, lse = _flash_forward(q, k, v, causal, s, block_q=bq, block_k=bk, interpret=False)
        return out, (q, k, v, out, lse)
    return reference_attention(q, k, v, causal=causal, scale=s), (q, k, v, None, None)


def _bwd(causal, scale, res, g):
    q, k, v, o, lse = res
    s = scale if scale is not None else q.shape[-1] ** -0.5
    if o is not None:
        bq, bk = _default_blocks(q.shape[-2], k.shape[-2], q.shape[-1], bwd=True)
        return _flash_backward(
            q, k, v, o, lse, g, causal, s, block_q=bq, block_k=bk, interpret=False
        )

    # Non-TPU: recompute via the reference path; XLA fuses the softmax chain.
    def ref(q, k, v):
        return reference_attention(q, k, v, causal=causal, scale=s)

    _, vjp = jax.vjp(ref, q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)


def make_flash_attn_fn(mesh, causal: bool = True):
    """Flash attention for MULTI-DEVICE meshes: Mosaic (Pallas) kernels
    cannot be auto-partitioned by GSPMD, so the kernel must run inside a
    shard_map that makes the batch/head axes manual — each device runs
    the kernel on its local [b/(dp·fsdp), h/tp, s, d] shard (sequence
    stays whole; sp>1 uses ring/Ulysses instead). Falls back to a direct
    call on single-device meshes and when no known axes are present.

    Same construction-time-mesh/ambient-mesh convention as
    ring.make_ring_attn_fn so it nests under the pp pipeline shard_map.
    """

    def attn(q, k, v):
        from ray_tpu.utils import jax_compat

        cur = jax_compat.get_abstract_mesh()
        use = cur if (cur is not None and cur.shape) else mesh
        if getattr(use, "size", 1) <= 1:
            return flash_attention(q, k, v, causal, None)
        # Mosaic's lowering requires the union of manual axes to cover
        # EVERY mesh axis (tpu_custom_call.py) — manualize all axes not
        # already manual in the ambient context (e.g. pp inside the
        # pipeline body); size-1 axes cost nothing.
        types = getattr(use, "axis_types", None)
        if types is None:
            manual = set(use.axis_names)
        else:
            from jax.sharding import AxisType

            manual = {
                n for n, t in zip(use.axis_names, types) if t != AxisType.Manual
            }
        if not manual:
            # fully-manual context already: data is per-device local
            return flash_attention(q, k, v, causal, None)
        batch_axes = tuple(a for a in ("dp", "fsdp") if a in manual)
        head_axis = None
        if "tp" in manual:
            tp_size = dict(use.shape)["tp"]
            if q.shape[1] % tp_size == 0:
                head_axis = "tp"
                if k.shape[1] != q.shape[1] and k.shape[1] % tp_size:
                    # kv heads don't shard over tp: expand to MHA so each
                    # tp shard's local q↔kv mapping stays contiguous
                    # (native GQA under tp requires tp | kv_heads)
                    rep = q.shape[1] // k.shape[1]
                    k = jnp.repeat(k, rep, axis=1)
                    v = jnp.repeat(v, rep, axis=1)
            # else: heads don't divide tp — leave them unsharded; each tp
            # shard computes all heads (redundant but correct, like the
            # GSPMD partial-replication this replaces)
        from jax.sharding import PartitionSpec as P

        qspec = P(batch_axes or None, head_axis, None, None)
        fn = jax_compat.shard_map(
            lambda q, k, v: flash_attention(q, k, v, causal, None),
            mesh=use,
            in_specs=(qspec, qspec, qspec),
            out_specs=qspec,
            axis_names=manual,
            check_vma=False,
        )
        return fn(q, k, v)

    attn.supports_gqa = True  # kernel handles kv_heads != q_heads natively
    return attn
