"""In-graph XLA collectives: the TPU fast path.

The reference's NCCL group (python/ray/util/collective/collective_group/
nccl_collective_group.py) launches per-call CUDA kernels; on TPU there is
no eager collective — collectives are *compiled into* the program and ride
ICI. So the XLA "group" hands out the two things a compiled program needs:
a ``jax.sharding.Mesh`` and an axis name. User code then writes

    mesh, axis = xla_group.mesh_for_group("g")
    @functools.partial(shard_map, mesh=mesh, in_specs=P(axis), out_specs=P())
    def step(x):
        return lax.psum(x, axis)

and XLA lowers psum onto the ICI ring. ``in_graph_allreduce`` below is the
ready-made wrapper for the common case.
"""
from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple


def mesh_for_group(
    group_name: str = "default",
    axis_name: str = "ranks",
    devices: Optional[Sequence] = None,
):
    """Build a 1-axis Mesh over this process's devices for in-graph
    collectives. For multi-host meshes use ray_tpu.parallel.MeshPlan with a
    gang-scheduled worker group (SURVEY.md §7 hard parts)."""
    import jax
    from jax.sharding import Mesh
    import numpy as np

    devs = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devs), (axis_name,)), axis_name


def in_graph_allreduce(x, mesh=None, axis_name: str = "ranks"):
    """Jitted psum over a device mesh: ``x``'s leading axis is sharded
    across devices and fully reduced (local sum + psum); result replicated."""
    import jax
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.collective import diagnostics
    from ray_tpu.utils import jax_compat

    if mesh is None:
        mesh, axis_name = mesh_for_group(axis_name=axis_name)

    @functools.partial(
        jax_compat.shard_map, mesh=mesh, in_specs=P(axis_name), out_specs=P()
    )
    def _psum(shard):
        return lax.psum(shard.sum(axis=0), axis_name)

    # Times the DISPATCH only (compile included on first call — the
    # compile tracker attributes that part): blocking on the result here
    # would force a host sync on a hot path purely for a gauge. Rank 0 =
    # this process; in-graph collectives are SPMD within it.
    with diagnostics.timed_op(
        f"xla:{axis_name}", "in_graph_allreduce", 0, getattr(x, "nbytes", None)
    ):
        x = jax.device_put(x, NamedSharding(mesh, P(axis_name)))
        return jax.jit(_psum)(x)
