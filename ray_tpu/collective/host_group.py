"""Host (DCN) collective group: TCP ring collectives with KV rendezvous.

Replaces the reference's GLOO group (python/ray/util/collective/
collective_group/gloo_collective_group.py) and its Redis rendezvous
(`gloo_util.py`); rendezvous here rides the controller KV, the same
pattern as the reference NCCL group's GCS-KV `Rendezvous`
(collective_group/nccl_collective_group.py:29).

Data plane is rank↔rank TCP sockets (no controller in the loop):
- allreduce: chunked ring reduce-scatter + ring all-gather (bandwidth
  optimal, 2·(n-1)/n · bytes per link).
- allgather / reducescatter: the corresponding ring halves.
- broadcast: ring pass-along from src.
- send/recv: direct p2p with matching tags.

All ops run on flattened numpy buffers; dtype/shape ride a JSON header.
"""
from __future__ import annotations

import json
import socket
import struct
import threading
import time
from queue import Empty, Queue
from typing import Dict, List, Optional

import numpy as np

from ray_tpu.collective.types import ReduceOp

_HDR = struct.Struct("!I")

_REDUCE = {
    ReduceOp.SUM: lambda a, b: np.add(a, b, out=a),
    ReduceOp.PRODUCT: lambda a, b: np.multiply(a, b, out=a),
    ReduceOp.MIN: lambda a, b: np.minimum(a, b, out=a),
    ReduceOp.MAX: lambda a, b: np.maximum(a, b, out=a),
}


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError("collective peer closed connection")
        got += r
    return bytes(buf)


def _send_msg(sock: socket.socket, header: dict, payload: bytes | memoryview):
    hdr = json.dumps(header).encode()
    with_len = _HDR.pack(len(hdr)) + hdr + _HDR.pack(len(payload))
    sock.sendall(with_len)
    if len(payload):
        sock.sendall(payload)


def _recv_msg(sock: socket.socket):
    hdr_len = _HDR.unpack(_recv_exact(sock, 4))[0]
    header = json.loads(_recv_exact(sock, hdr_len))
    payload_len = _HDR.unpack(_recv_exact(sock, 4))[0]
    payload = _recv_exact(sock, payload_len) if payload_len else b""
    return header, payload


class HostGroup:
    """One rank's membership in a named host collective group."""

    def __init__(self, kv, group_name: str, world_size: int, rank: int, timeout: float = 60.0):
        self.group_name = group_name
        self.world_size = world_size
        self.rank = rank
        self._kv = kv
        self._ns = f"collective/{group_name}"
        self._out: Dict[int, socket.socket] = {}
        self._out_lock = threading.Lock()
        self._dial_locks: Dict[int, threading.Lock] = {}
        self._inbox: Dict[int, Queue] = {r: Queue() for r in range(world_size)}
        self._closed = False

        # Listener for inbound peers.
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # Multi-host: bind all interfaces so cross-host peers can reach the
        # advertised external IP; single-host stays loopback-only.
        self._server.bind(("0.0.0.0" if _multi_host() else "127.0.0.1", 0))
        self._server.listen(world_size + 2)
        port = self._server.getsockname()[1]
        host = socket.gethostbyname(socket.gethostname()) if _multi_host() else "127.0.0.1"
        self._kv.kv_put(self._ns, f"rank_{rank}".encode(), f"{host}:{port}".encode())
        self._accept_thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._accept_thread.start()
        self._wait_members(timeout)

    # -- rendezvous ------------------------------------------------------
    def _wait_members(self, timeout: float):
        deadline = time.time() + timeout
        while time.time() < deadline:
            keys = self._kv.kv_keys(self._ns, b"rank_")
            if len(keys) >= self.world_size:
                return
            time.sleep(0.02)
        raise TimeoutError(
            f"collective group '{self.group_name}': only "
            f"{len(self._kv.kv_keys(self._ns, b'rank_'))}/{self.world_size} ranks joined"
        )

    def _addr(self, peer: int) -> tuple:
        raw = self._kv.kv_get(self._ns, f"rank_{peer}".encode())
        if raw is None:
            raise RuntimeError(f"rank {peer} not registered in group {self.group_name}")
        host, port = raw.decode().rsplit(":", 1)
        return host, int(port)

    # -- connections -----------------------------------------------------
    def _accept_loop(self):
        while not self._closed:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            try:
                header, _ = _recv_msg(conn)
                peer = int(header["rank"])
            except Exception:
                conn.close()
                continue
            threading.Thread(
                target=self._reader_loop, args=(conn, peer), daemon=True
            ).start()

    def _reader_loop(self, conn: socket.socket, peer: int):
        while not self._closed:
            try:
                header, payload = _recv_msg(conn)
            except (ConnectionError, OSError):
                # Peer died: fail any blocked recv immediately instead of
                # letting it run out its timeout (fast failure detection —
                # the gang restarts sooner).
                if not self._closed:
                    self._inbox[peer].put((None, None))
                return
            # bytearray keeps the array writable — callers mutate results.
            arr = np.frombuffer(bytearray(payload), dtype=np.dtype(header["dtype"])).reshape(
                header["shape"]
            )
            self._inbox[peer].put((header.get("tag", 0), arr))

    def _conn(self, peer: int) -> socket.socket:
        with self._out_lock:
            sock = self._out.get(peer)
            if sock is not None:
                return sock
            dial_lock = self._dial_locks.setdefault(peer, threading.Lock())
        # Dial OUTSIDE _out_lock: a slow peer handshake (up to the 30s
        # connect timeout) must not stall sends to every other rank. A
        # per-peer dial lock serializes racing dialers instead — a second
        # socket must never be handshaken and discarded, because the peer
        # has already spawned a reader for it and closing it would push a
        # disconnect poison pill into their inbox for this rank.
        with dial_lock:
            with self._out_lock:
                sock = self._out.get(peer)
                if sock is not None:
                    return sock
            # intentionally held: only dialers to this same not-yet-
            # connected peer wait here  # ray-tpu: lint-ignore[RTL001]
            sock = socket.create_connection(self._addr(peer), timeout=30)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            _send_msg(sock, {"rank": self.rank}, b"")
            with self._out_lock:
                self._out[peer] = sock
            return sock

    # -- p2p -------------------------------------------------------------
    def send(self, arr: np.ndarray, dst: int, tag: int = 0):
        from ray_tpu.collective import diagnostics

        arr = np.ascontiguousarray(arr)
        diagnostics.record_p2p(self.group_name, "send", arr.nbytes)
        _send_msg(
            self._conn(dst),
            {"dtype": arr.dtype.str, "shape": list(arr.shape), "tag": tag},
            memoryview(arr).cast("B"),
        )

    def recv(self, src: int, tag: int = 0, timeout: float = 60.0) -> np.ndarray:
        deadline = time.time() + timeout
        stash = []
        try:
            while True:
                remaining = deadline - time.time()
                if remaining <= 0:
                    raise TimeoutError(f"recv from rank {src} (tag {tag}) timed out")
                try:
                    got_tag, arr = self._inbox[src].get(timeout=remaining)
                except Empty:
                    raise TimeoutError(f"recv from rank {src} (tag {tag}) timed out")
                if got_tag is None:
                    self._inbox[src].put((None, None))  # re-arm for other waiters
                    raise ConnectionError(
                        f"collective peer rank {src} disconnected"
                    )
                if got_tag == tag:
                    from ray_tpu.collective import diagnostics

                    diagnostics.record_p2p(self.group_name, "recv", arr.nbytes)
                    return arr
                stash.append((got_tag, arr))
        finally:
            for item in stash:
                self._inbox[src].put(item)

    def _send_async(self, arr: np.ndarray, dst: int, tag: int) -> threading.Thread:
        t = threading.Thread(target=self.send, args=(arr, dst, tag), daemon=True)
        t.start()
        return t

    @staticmethod
    def _join_sender(sender: threading.Thread, timeout: float = 60.0):
        """Bounded ring-step join: the peer pulling our chunk may be dead
        or partitioned away — the collective must fail loudly (and let the
        gang's failure detector take over) rather than hang this rank."""
        sender.join(timeout=timeout)
        if sender.is_alive():
            raise TimeoutError(
                f"collective send did not complete within {timeout}s "
                "(peer dead or partitioned?)"
            )

    # -- collectives -----------------------------------------------------
    def barrier(self, tag: int = 0):
        from ray_tpu.collective import diagnostics

        with diagnostics.timed_op(self.group_name, "barrier", self.rank):
            self._allreduce(np.zeros(1, np.float32), ReduceOp.SUM, tag=tag | (1 << 24))

    def allreduce(self, arr: np.ndarray, op: ReduceOp = ReduceOp.SUM, tag: int = 0) -> np.ndarray:
        """Ring reduce-scatter + ring all-gather over flattened chunks."""
        from ray_tpu.collective import diagnostics

        with diagnostics.timed_op(
            self.group_name, "allreduce", self.rank, arr.nbytes
        ):
            return self._allreduce(arr, op, tag)

    def _allreduce(self, arr: np.ndarray, op: ReduceOp = ReduceOp.SUM, tag: int = 0) -> np.ndarray:
        ws, rank = self.world_size, self.rank
        if ws == 1:
            return arr
        shape, dtype = arr.shape, arr.dtype
        flat = np.ascontiguousarray(arr).reshape(-1).copy()
        n = flat.size
        chunk = -(-n // ws)  # ceil
        padded = np.zeros(chunk * ws, dtype)
        padded[:n] = flat
        chunks = padded.reshape(ws, chunk)
        nxt, prv = (rank + 1) % ws, (rank - 1) % ws
        reduce_fn = _REDUCE[op]
        # reduce-scatter: after ws-1 steps, rank owns fully reduced chunk
        # (rank+1)%ws.
        for step in range(ws - 1):
            send_idx = (rank - step) % ws
            recv_idx = (rank - step - 1) % ws
            sender = self._send_async(chunks[send_idx], nxt, tag + step)
            incoming = self.recv(prv, tag + step)
            reduce_fn(chunks[recv_idx], incoming)
            self._join_sender(sender)
        # all-gather the reduced chunks.
        for step in range(ws - 1):
            send_idx = (rank - step + 1) % ws
            recv_idx = (rank - step) % ws
            sender = self._send_async(chunks[send_idx], nxt, tag + 1000 + step)
            chunks[recv_idx] = self.recv(prv, tag + 1000 + step)
            self._join_sender(sender)
        return chunks.reshape(-1)[:n].reshape(shape)

    def reducescatter(
        self, arr: np.ndarray, op: ReduceOp = ReduceOp.SUM, tag: int = 0
    ) -> np.ndarray:
        """Input split into world_size equal parts along axis 0; returns
        this rank's reduced part."""
        from ray_tpu.collective import diagnostics

        with diagnostics.timed_op(
            self.group_name, "reducescatter", self.rank, arr.nbytes
        ):
            return self._reducescatter(arr, op, tag)

    def _reducescatter(
        self, arr: np.ndarray, op: ReduceOp = ReduceOp.SUM, tag: int = 0
    ) -> np.ndarray:
        ws, rank = self.world_size, self.rank
        if arr.shape[0] % ws:
            raise ValueError(f"reducescatter dim0 {arr.shape[0]} not divisible by {ws}")
        if ws == 1:
            return arr
        parts = [np.ascontiguousarray(p).copy() for p in np.split(arr, ws, axis=0)]
        nxt, prv = (rank + 1) % ws, (rank - 1) % ws
        reduce_fn = _REDUCE[op]
        # Shifted ring so the fully reduced part landing on rank r is part r.
        for step in range(ws - 1):
            send_idx = (rank - step - 1) % ws
            recv_idx = (rank - step - 2) % ws
            sender = self._send_async(parts[send_idx], nxt, tag + step)
            reduce_fn(parts[recv_idx], self.recv(prv, tag + step))
            self._join_sender(sender)
        return parts[rank]

    def allgather(self, arr: np.ndarray, tag: int = 0) -> List[np.ndarray]:
        from ray_tpu.collective import diagnostics

        with diagnostics.timed_op(
            self.group_name, "allgather", self.rank, arr.nbytes
        ):
            return self._allgather(arr, tag)

    def _allgather(self, arr: np.ndarray, tag: int = 0) -> List[np.ndarray]:
        ws, rank = self.world_size, self.rank
        if ws == 1:
            return [arr]
        out: List[Optional[np.ndarray]] = [None] * ws
        out[rank] = np.ascontiguousarray(arr)
        nxt, prv = (rank + 1) % ws, (rank - 1) % ws
        for step in range(ws - 1):
            send_idx = (rank - step) % ws
            recv_idx = (rank - step - 1) % ws
            sender = self._send_async(out[send_idx], nxt, tag + step)
            out[recv_idx] = self.recv(prv, tag + step)
            self._join_sender(sender)
        return out  # type: ignore[return-value]

    def broadcast(self, arr: np.ndarray, src: int, tag: int = 0) -> np.ndarray:
        from ray_tpu.collective import diagnostics

        with diagnostics.timed_op(
            self.group_name, "broadcast", self.rank, arr.nbytes
        ):
            return self._broadcast(arr, src, tag)

    def _broadcast(self, arr: np.ndarray, src: int, tag: int = 0) -> np.ndarray:
        ws, rank = self.world_size, self.rank
        if ws == 1:
            return arr
        # Pass along the ring starting at src; (src-1)%ws is the tail.
        if rank == src:
            self.send(np.ascontiguousarray(arr), (rank + 1) % ws, tag)
            return arr
        got = self.recv((rank - 1) % ws, tag)
        if (rank + 1) % ws != src:
            self.send(got, (rank + 1) % ws, tag)
        return got

    def reduce(self, arr: np.ndarray, dst: int, op: ReduceOp = ReduceOp.SUM, tag: int = 0):
        # Host groups are small; allreduce and keep the value at dst. The
        # extra all-gather half is the price of code we don't duplicate.
        from ray_tpu.collective import diagnostics

        with diagnostics.timed_op(
            self.group_name, "reduce", self.rank, arr.nbytes
        ):
            out = self._allreduce(arr, op, tag=tag)
        return out if self.rank == dst else arr

    def abort(self):
        """Unblock every thread parked in this group's recv/collective
        with ConnectionError, WITHOUT closing sockets — the poison pill
        the elastic-train repair path uses to break survivors out of a
        barrier whose peer died on a non-adjacent ring position (only
        ring neighbors observe the socket death directly). The group
        stays destroyable afterwards."""
        for q in self._inbox.values():
            q.put((None, None))

    def destroy(self):
        self._closed = True
        try:
            self._server.close()
        except OSError:
            pass
        with self._out_lock:
            for sock in self._out.values():
                try:
                    sock.close()
                except OSError:
                    pass
            self._out.clear()
        self._kv.kv_del(self._ns, f"rank_{self.rank}".encode())


def _multi_host() -> bool:
    import os

    return bool(os.environ.get("RAY_TPU_MULTI_HOST"))
