"""Collective types (reference: python/ray/util/collective/types.py).

ReduceOp + option dataclasses. Tensors may be numpy arrays or jax arrays;
jax arrays are converted to host numpy for the host (DCN) backend and
placed back on device afterwards. The fast path on TPU is *in-graph*
(``lax.psum`` inside a pjit program over the group's mesh) — see
ray_tpu/collective/xla_group.py.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass


class ReduceOp(enum.Enum):
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"


class Backend(str, enum.Enum):
    """Supported backends (reference: collective/types.py Backend).

    - ``HOST``: host-memory ring collectives over TCP with KV rendezvous —
      the DCN / non-compiled path (replaces the reference's GLOO backend).
    - ``XLA``: in-graph ICI collectives; the group hands out a
      ``jax.sharding.Mesh`` + axis name and eager calls jit a shard_map'd
      ``lax.p*`` when all ranks live in one process, else fall back to HOST.
    """

    HOST = "host"
    XLA = "xla"


@dataclass
class AllReduceOptions:
    reduce_op: ReduceOp = ReduceOp.SUM
    timeout_ms: int = 30000


@dataclass
class BarrierOptions:
    timeout_ms: int = 30000


@dataclass
class ReduceOptions:
    reduce_op: ReduceOp = ReduceOp.SUM
    root_rank: int = 0
    timeout_ms: int = 30000


@dataclass
class BroadcastOptions:
    src_rank: int = 0
    timeout_ms: int = 30000


@dataclass
class AllGatherOptions:
    timeout_ms: int = 30000


@dataclass
class ReduceScatterOptions:
    reduce_op: ReduceOp = ReduceOp.SUM
    timeout_ms: int = 30000


@dataclass
class SendOptions:
    dst_rank: int = 0
    timeout_ms: int = 30000


@dataclass
class RecvOptions:
    src_rank: int = 0
    timeout_ms: int = 30000
