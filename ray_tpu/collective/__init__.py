"""ray_tpu.collective — collective communication between tasks/actors.

Reference: python/ray/util/collective/ (API in collective.py). Eager host
path: TCP ring collectives with KV rendezvous. Compiled TPU path: mesh +
axis handout for lax.p* inside pjit programs (xla_group.py).
"""
from ray_tpu.collective.collective import (
    GroupManager,
    abort_collective_group,
    allgather,
    allreduce,
    allreduce_multigpu,
    barrier,
    broadcast,
    broadcast_multigpu,
    create_collective_group,
    declare_collective_group,
    destroy_collective_group,
    get_collective_group_size,
    get_rank,
    get_world_size,
    init_collective_group,
    is_group_initialized,
    recv,
    reduce,
    reducescatter,
    send,
)
from ray_tpu.collective.types import Backend, ReduceOp
from ray_tpu.collective import xla_group

__all__ = [
    "init_collective_group",
    "create_collective_group",
    "declare_collective_group",
    "destroy_collective_group",
    "abort_collective_group",
    "is_group_initialized",
    "get_rank",
    "get_world_size",
    "get_collective_group_size",
    "allreduce",
    "allreduce_multigpu",
    "reduce",
    "broadcast",
    "broadcast_multigpu",
    "allgather",
    "reducescatter",
    "barrier",
    "send",
    "recv",
    "ReduceOp",
    "Backend",
    "GroupManager",
    "xla_group",
]
