"""Collective & transfer diagnostics: per-op latency/bytes + straggler skew.

Reference: the reference's collective groups expose no metrics at all —
debugging a slow ring means printf. Here every eager collective op
records a latency histogram and byte counter, and each rank additionally
publishes its LAST op latency as a gauge keyed {group, op, rank}; the
controller derives ``collective_skew_ms`` (max-min across ranks per
(group, op)) at snapshot time, which is the per-ring straggler view
`ray-tpu status` renders (Pathways-style multi-slice skew reporting,
PAPERS.md).

Recording sites:
- host_group.HostGroup ring ops  → ``collective_op_ms`` / ``_last_op_ms``
- collective.py eager wrappers   → ``collective_bytes_total`` (tensor volume)
- xla_group.in_graph_allreduce   → same series, group="xla"
- core/object_transfer.py        → ``object_transfer_*`` (node↔node pulls)

All metrics are lazy per-process singletons (the registry keeps every
constructed Metric alive) and tag cardinality is bounded by the registry
cap (util/metrics.py) — rank is a tag, so a 1024-rank ring tops out at
the per-metric series cap, not at 1024 series per op.
"""
from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Optional

# Sub-ms ring steps on loopback up to multi-minute cross-DCN transfers.
MS_BOUNDARIES = (
    0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500,
    1000, 2500, 5000, 10000, 30000, 60000,
)

_lock = threading.Lock()
_metrics = None
_transfer = None


class _CollectiveMetrics:
    def __init__(self):
        from ray_tpu.util.metrics import Counter, Gauge, Histogram

        gor = ("group", "op", "rank")
        self.op_ms = Histogram(
            "collective_op_ms",
            "Eager collective op latency (host ring / in-graph dispatch)",
            MS_BOUNDARIES, gor,
        )
        self.last_ms = Gauge(
            "collective_last_op_ms",
            "This rank's most recent op latency — the controller derives "
            "cross-rank skew (collective_skew_ms) from these",
            gor,
        )
        self.ops = Counter(
            "collective_ops_total", "Eager collective ops", ("group", "op")
        )
        self.bytes = Counter(
            "collective_bytes_total",
            "Tensor bytes through eager collective ops",
            ("group", "op"),
        )
        self.p2p_bytes = Counter(
            "collective_p2p_bytes_total",
            "Point-to-point bytes through collective groups (ring steps + send/recv)",
            ("group", "dir"),
        )


class _TransferMetrics:
    def __init__(self):
        from ray_tpu.util.metrics import Counter, Histogram

        self.fetch_ms = Histogram(
            "object_transfer_fetch_ms",
            "Node-to-node object pull duration (chunked fetch)",
            MS_BOUNDARIES,
        )
        self.bytes = Counter(
            "object_transfer_bytes_total", "Bytes pulled across nodes"
        )
        self.chunks = Counter(
            "object_transfer_chunks_total", "Chunks fetched across nodes"
        )
        self.chunks_served = Counter(
            "object_transfer_chunks_served_total",
            "Chunks served to pulling peers (source side)",
        )


def collective_metrics() -> _CollectiveMetrics:
    global _metrics
    if _metrics is None:
        with _lock:
            if _metrics is None:
                _metrics = _CollectiveMetrics()
    return _metrics


def transfer_metrics() -> _TransferMetrics:
    global _transfer
    if _transfer is None:
        with _lock:
            if _transfer is None:
                _transfer = _TransferMetrics()
    return _transfer


def record_op(group: str, op: str, rank, seconds: float,
              nbytes: Optional[int] = None):
    m = collective_metrics()
    ms = seconds * 1000.0
    tags = {"group": group, "op": op, "rank": str(rank)}
    m.op_ms.observe(ms, tags)
    m.last_ms.set(ms, tags)
    m.ops.inc(1, {"group": group, "op": op})
    if nbytes:
        m.bytes.inc(nbytes, {"group": group, "op": op})


def record_bytes(group: str, op: str, nbytes: int):
    if nbytes:
        collective_metrics().bytes.inc(nbytes, {"group": group, "op": op})


def record_p2p(group: str, direction: str, nbytes: int):
    if nbytes:
        collective_metrics().p2p_bytes.inc(nbytes, {"group": group, "dir": direction})


@contextmanager
def timed_op(group: str, op: str, rank, nbytes: Optional[int] = None):
    t0 = time.perf_counter()
    try:
        yield
    finally:
        record_op(group, op, rank, time.perf_counter() - t0, nbytes)
