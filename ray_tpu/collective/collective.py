"""Process-group style collectives between tasks/actors.

Reference API surface: python/ray/util/collective/collective.py —
``init_collective_group`` :120, ``create_collective_group`` :151,
``allreduce`` :258, ``barrier`` :298, ``reduce/broadcast/allgather/
reducescatter`` :311-502, p2p ``send/recv`` :531-615, plus the
``GroupManager`` :40 pattern.

TPU-first split (SURVEY.md §5.8): the *fast* path is in-graph — a group
hands out a ``jax.sharding.Mesh`` + axis name and collectives are
``lax.psum`` et al. inside a pjit program riding ICI. The *eager* API
below is the host/DCN path: ring collectives over TCP with controller-KV
rendezvous (host_group.py), accepting numpy or jax arrays (jax arrays
round-trip through host memory and are put back on their devices).
"""
from __future__ import annotations

import json
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

from ray_tpu.collective.host_group import HostGroup
from ray_tpu.collective.types import Backend, ReduceOp

_DECL_NS = "collective_decl"


class GroupManager:
    """Per-process registry of collective groups (reference:
    collective.py:40)."""

    def __init__(self):
        self._groups: Dict[str, HostGroup] = {}
        self._meta: Dict[str, dict] = {}
        self._lock = threading.Lock()

    def create_group(
        self, group_name: str, world_size: int, rank: int, backend: str
    ) -> HostGroup:
        with self._lock:
            if group_name in self._groups:
                raise RuntimeError(f"collective group '{group_name}' already initialized")
            return self._create_locked(group_name, world_size, rank, backend)

    def _create_locked(self, group_name, world_size, rank, backend) -> HostGroup:
        backend = Backend(backend)
        group = HostGroup(_kv(), group_name, world_size, rank)
        self._groups[group_name] = group
        self._meta[group_name] = {
            "world_size": world_size,
            "rank": rank,
            "backend": backend.value,
        }
        return group

    def get_group(self, group_name: str) -> HostGroup:
        # Check + lazy declarative join under one lock so concurrent actor
        # tasks (max_concurrency > 1) can't double-create the group.
        with self._lock:
            group = self._groups.get(group_name)
            if group is None:
                group = self._try_declared_locked(group_name)
        if group is None:
            raise RuntimeError(
                f"collective group '{group_name}' is not initialized in this "
                "process; call init_collective_group() or declare it with "
                "create_collective_group()"
            )
        return group

    def _try_declared_locked(self, group_name: str) -> Optional[HostGroup]:
        """Lazy join for declaratively created groups (reference:
        collective.py:151 create_collective_group): look up this actor's
        rank by actor id in the KV declaration. Caller holds the lock."""
        from ray_tpu.runtime_context import get_runtime_context

        actor_id = get_runtime_context().get_actor_id()
        if actor_id is None:
            return None
        raw = _kv().kv_get(_DECL_NS, f"{group_name}/{actor_id}".encode())
        if raw is None:
            return None
        decl = json.loads(raw)
        return self._create_locked(
            group_name, decl["world_size"], decl["rank"], decl["backend"]
        )

    def is_group_exist(self, group_name: str) -> bool:
        return group_name in self._groups

    def destroy_group(self, group_name: str):
        with self._lock:
            group = self._groups.pop(group_name, None)
            self._meta.pop(group_name, None)
        if group is not None:
            group.destroy()

    def abort_group(self, group_name: str) -> bool:
        with self._lock:
            group = self._groups.get(group_name)
        if group is None:
            return False
        group.abort()
        return True


_group_mgr = GroupManager()


def _kv():
    from ray_tpu.core.api import _require_worker

    return _require_worker()


# ---------------------------------------------------------------------------
# Group lifecycle
# ---------------------------------------------------------------------------
def init_collective_group(
    world_size: int,
    rank: int,
    backend: str = "host",
    group_name: str = "default",
):
    """Join a named collective group from inside a task/actor (reference:
    collective.py:120)."""
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} out of range for world_size {world_size}")
    _group_mgr.create_group(group_name, world_size, rank, backend)


def create_collective_group(
    actors: Sequence,
    world_size: int,
    ranks: Sequence[int],
    backend: str = "host",
    group_name: str = "default",
):
    """Declare a group over actor handles from the driver (reference:
    collective.py:151). Actors join lazily on their first collective call."""
    if len(actors) != len(ranks) or len(set(ranks)) != len(ranks):
        raise ValueError("ranks must be unique and match actors")
    if sorted(ranks) != list(range(world_size)):
        raise ValueError(f"ranks {ranks} must cover 0..{world_size - 1}")
    kv = _kv()
    for actor, rank in zip(actors, ranks):
        decl = json.dumps(
            {"world_size": world_size, "rank": rank, "backend": backend}
        ).encode()
        kv.kv_put(_DECL_NS, f"{group_name}/{actor._actor_id.hex()}".encode(), decl)


# Declarative alias kept for surface parity with the reference.
declare_collective_group = create_collective_group


def destroy_collective_group(group_name: str = "default"):
    _group_mgr.destroy_group(group_name)


def abort_collective_group(group_name: str = "default") -> bool:
    """Fail-fast every blocked collective op in this process's membership
    of ``group_name`` (each raises ConnectionError). Used by gang repair
    to break surviving ranks out of a barrier a dead peer will never
    complete; the group remains to be destroyed normally."""
    return _group_mgr.abort_group(group_name)


def is_group_initialized(group_name: str = "default") -> bool:
    return _group_mgr.is_group_exist(group_name)


def get_rank(group_name: str = "default") -> int:
    return _group_mgr.get_group(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _group_mgr.get_group(group_name).world_size


get_world_size = get_collective_group_size


# ---------------------------------------------------------------------------
# Tensor conversion: numpy passthrough; jax arrays round-trip via host.
# ---------------------------------------------------------------------------
def _to_host(tensor):
    if isinstance(tensor, np.ndarray):
        return tensor, None
    mod = type(tensor).__module__
    if mod.startswith("jax"):
        import jax

        sharding = tensor.sharding if hasattr(tensor, "sharding") else None
        return np.asarray(tensor), ("jax", sharding)
    return np.asarray(tensor), None


def _restore(arr: np.ndarray, token):
    if token is None:
        return arr
    kind, sharding = token
    if kind == "jax":
        import jax

        return jax.device_put(arr, sharding) if sharding is not None else jax.numpy.asarray(arr)
    return arr


# ---------------------------------------------------------------------------
# Eager collectives
# ---------------------------------------------------------------------------
def allreduce(tensor, group_name: str = "default", op: ReduceOp = ReduceOp.SUM):
    group = _group_mgr.get_group(group_name)
    arr, token = _to_host(tensor)
    return _restore(group.allreduce(arr, op), token)


def reduce(
    tensor, dst_rank: int = 0, group_name: str = "default", op: ReduceOp = ReduceOp.SUM
):
    group = _group_mgr.get_group(group_name)
    arr, token = _to_host(tensor)
    return _restore(group.reduce(arr, dst_rank, op), token)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    group = _group_mgr.get_group(group_name)
    arr, token = _to_host(tensor)
    return _restore(group.broadcast(arr, src_rank), token)


def allgather(tensor, group_name: str = "default") -> List:
    group = _group_mgr.get_group(group_name)
    arr, token = _to_host(tensor)
    return [_restore(a, token) for a in group.allgather(arr)]


def reducescatter(tensor, group_name: str = "default", op: ReduceOp = ReduceOp.SUM):
    group = _group_mgr.get_group(group_name)
    arr, token = _to_host(tensor)
    return _restore(group.reducescatter(arr, op), token)


def barrier(group_name: str = "default"):
    _group_mgr.get_group(group_name).barrier()


def send(tensor, dst_rank: int, group_name: str = "default", tag: int = 0):
    from ray_tpu.collective import diagnostics

    group = _group_mgr.get_group(group_name)
    if dst_rank == group.rank:
        raise ValueError("cannot send to self")
    arr, _ = _to_host(tensor)
    # P2P tags live in their own space so they never collide with the
    # per-step tags used by ring collectives.
    with diagnostics.timed_op(group_name, "send", group.rank, arr.nbytes):
        group.send(arr, dst_rank, tag=tag + 2_000_000)


def recv(src_rank: int, group_name: str = "default", tag: int = 0):
    """Receive a tensor from ``src_rank``. Unlike the reference (which
    fills a preallocated tensor), returns the received array — shapes
    travel on the wire, so preallocation is unnecessary."""
    from ray_tpu.collective import diagnostics

    group = _group_mgr.get_group(group_name)
    if src_rank == group.rank:
        raise ValueError("cannot recv from self")
    with diagnostics.timed_op(group_name, "recv", group.rank):
        return group.recv(src_rank, tag=tag + 2_000_000)


# Multi-tensor variants (reference has *_multigpu; on TPU host path these
# just apply the op per tensor over the same ring).
def allreduce_multigpu(tensors, group_name: str = "default", op: ReduceOp = ReduceOp.SUM):
    return [allreduce(t, group_name, op) for t in tensors]


def broadcast_multigpu(tensors, src_rank: int = 0, group_name: str = "default"):
    return [broadcast(t, src_rank, group_name) for t in tensors]
