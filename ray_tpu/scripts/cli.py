"""``ray-tpu`` CLI.

Reference: python/ray/scripts/scripts.py (:2548-2579 — start/stop/status/
submit/memory/timeline/logs/microbenchmark). argparse instead of click;
subcommands connect to the running cluster via the address file
``<temp_dir>/ray_current_cluster`` that ``start --head`` writes.

Usage:
  ray-tpu start --head [--num-cpus N] [--resources JSON] [--block]
  ray-tpu start --address HOST:PORT [--num-cpus N]   # join as a node
  ray-tpu stop
  ray-tpu status
  ray-tpu submit -- python my_script.py              # run as a job
  ray-tpu job list | job logs ID | job stop ID
  ray-tpu summary tasks|actors|objects|memory|lifecycle|rl|train|profiling|errors
  ray-tpu timeline [--output FILE]
  ray-tpu profile stacks|cpu|device|incidents|captures [...]
  ray-tpu memory [--node N] [--leaks] [--limit K] [--offline] [--json]
  ray-tpu logs [FILENAME] [--node N] [--task T] [--actor A] [--grep RE]
               [--err] [--tail N] [--follow] [--offline]
  ray-tpu microbenchmark
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _addr_file() -> str:
    from ray_tpu.config import get_config

    return os.path.join(get_config().temp_dir, "ray_current_cluster")


def _connect():
    import ray_tpu

    ray_tpu.init(address="auto")
    return ray_tpu


# ---------------------------------------------------------------------------
def cmd_start(args):
    from ray_tpu.core import api

    if args.head:
        resources = json.loads(args.resources) if args.resources else {}
        resources.setdefault("CPU", args.num_cpus or os.cpu_count() or 1)
        if args.num_tpus:
            resources["TPU"] = args.num_tpus
        address, proc, session_dir = api._start_controller(resources, {}, owned=False)
        os.makedirs(os.path.dirname(_addr_file()), exist_ok=True)
        with open(_addr_file(), "w") as f:
            f.write(address)
        print(f"started head at {address} (session: {session_dir})")
        print(f"connect with ray_tpu.init(address='auto') or --address {address}")
        if args.block:
            try:
                proc.wait()
            except KeyboardInterrupt:
                pass
        return 0
    if not args.address:
        print("either --head or --address is required", file=sys.stderr)
        return 1
    import subprocess

    from ray_tpu.core.node_agent import child_env

    res = json.loads(args.resources) if args.resources else {}
    res.setdefault("CPU", args.num_cpus or os.cpu_count() or 1)
    if args.num_tpus:
        res["TPU"] = args.num_tpus
    cmd = [
        sys.executable,
        "-m",
        "ray_tpu.core.node_agent",
        "--controller",
        args.address,
        "--session-dir",
        args.session_dir or "/tmp/ray_tpu/cli_node",
        "--resources",
        json.dumps(res),
    ]
    os.makedirs(os.path.join(args.session_dir or "/tmp/ray_tpu/cli_node", "logs"), exist_ok=True)
    proc = subprocess.Popen(cmd, env=child_env(needs_tpu=bool(args.num_tpus)))
    print(f"node agent joining {args.address} (pid {proc.pid})")
    if args.block:
        proc.wait()
    return 0


def cmd_stop(args):
    import ray_tpu

    try:
        ray_tpu.init(address="auto")
    except ConnectionError as e:
        print(str(e), file=sys.stderr)
        return 1
    from ray_tpu.core.api import _require_worker

    try:
        _require_worker()._call("shutdown_cluster", timeout=5)
    except Exception:
        pass
    try:
        os.unlink(_addr_file())
    except FileNotFoundError:
        pass
    print("cluster stopped")
    return 0


def _gb(n) -> str:
    return f"{(n or 0) / (1 << 30):.1f}"


def _render_status(summary: dict, total: dict, avail: dict, out=print):
    """The `ray-tpu status` cluster view (reference: `ray status` +
    the dashboard's cluster page): resource availability, per-node host/
    store/HBM/compile telemetry, and the top-skew collectives table."""
    nodes = summary.get("nodes", {})
    totals = summary.get("totals", {})
    alive = sum(1 for n in nodes.values() if n.get("state") == "ALIVE")
    out(f"nodes: {len(nodes)} ({alive} alive)")
    for k in sorted(total):
        out(f"  {k}: {avail.get(k, 0):g}/{total[k]:g} available")
    out(
        f"host memory: {_gb(totals.get('mem_used_bytes'))}/"
        f"{_gb(totals.get('mem_total_bytes'))} GB  "
        f"object store: {_gb(totals.get('object_store_used'))}/"
        f"{_gb(totals.get('object_store_capacity'))} GB"
    )
    if totals.get("num_devices"):
        out(
            f"device HBM: {_gb(totals.get('hbm_used_bytes'))}/"
            f"{_gb(totals.get('hbm_limit_bytes'))} GB over "
            f"{totals['num_devices']} device(s) "
            f"(peak {_gb(totals.get('hbm_peak_bytes'))} GB)"
        )
    out("")
    hdr = f"{'node':<14}{'host':<16}{'cpu%':>6}{'mem GB':>12}{'store GB':>11}{'compiles/min':>14}  devices (HBM used/limit GB)"
    out(hdr)
    for nid, row in nodes.items():
        host = row.get("host", {})
        store = row.get("object_store", {})
        comp = row.get("compile", {})
        devs = row.get("devices", [])
        dev_str = " ".join(
            f"{d['id']}:{_gb(d['bytes_in_use'])}/{_gb(d['bytes_limit'])}"
            for d in devs
        ) or "-"
        name = nid[:10] + ("*" if row.get("is_head") else "")
        mem = f"{_gb(host.get('mem_used_bytes'))}/{_gb(host.get('mem_total_bytes'))}"
        st = f"{_gb(store.get('used'))}/{_gb(store.get('capacity'))}"
        out(
            f"{name:<14}{row.get('hostname', '?')[:15]:<16}"
            f"{host.get('cpu_percent', 0):>6.1f}{mem:>12}{st:>11}"
            f"{comp.get('compiles_per_min', 0):>14.1f}  {dev_str}"
        )
        for storm in comp.get("active_storms", ()):
            out(f"    !! recompilation storm: {storm}")
    skew = totals.get("collective_skew_ms") or []
    if skew:
        out("")
        out("top-skew collectives (max-min last op latency per ring):")
        out(f"  {'group':<16}{'op':<14}{'skew ms':>9}{'max ms':>9}{'min ms':>9}  slowest rank")
        for r in skew[:8]:
            out(
                f"  {r['group'][:15]:<16}{r['op']:<14}{r['skew_ms']:>9.2f}"
                f"{r['max_ms']:>9.2f}{r['min_ms']:>9.2f}  {r['slowest_rank']}"
            )


def _status_fixture() -> tuple:
    """Canned summarize_resources()-shaped data for `status --offline`:
    exercises every rendering path (devices, storms, skew) with no
    cluster — the tier-1 smoke that keeps the view from rotting."""
    summary = {
        "nodes": {
            "aabbccddee00": {
                "hostname": "tpu-host-0", "is_head": True, "state": "ALIVE",
                "num_workers": 4,
                "host": {"cpu_percent": 37.5, "mem_used_bytes": 9 << 30,
                         "mem_total_bytes": 64 << 30, "load_1m": 2.5},
                "object_store": {"used": 1 << 28, "capacity": 2 << 30,
                                 "num_objects": 12, "num_spilled": 0},
                "resources": {"total": {"CPU": 8, "TPU": 4},
                              "available": {"CPU": 6, "TPU": 2}},
                "telemetry_age_s": 1.2,
                "devices": [
                    {"id": i, "platform": "tpu", "kind": "TPU v5e", "pid": 1234,
                     "bytes_in_use": (11 + i) << 30,
                     "peak_bytes_in_use": (12 + i) << 30,
                     "bytes_limit": 16 << 30}
                    for i in range(2)
                ],
                "compile": {"compiles": 42, "compile_seconds": 31.5,
                            "compiles_per_min": 6.0, "storms_total": 1,
                            "active_storms": ["decode_step"]},
            },
        },
        "totals": {
            "mem_used_bytes": 9 << 30, "mem_total_bytes": 64 << 30,
            "hbm_used_bytes": 23 << 30, "hbm_limit_bytes": 32 << 30,
            "hbm_peak_bytes": 25 << 30, "num_devices": 2,
            "object_store_used": 1 << 28, "object_store_capacity": 2 << 30,
            "compiles": 42, "compile_seconds": 31.5,
            "active_storms": ["decode_step"],
            "collective_skew_ms": [
                {"group": "train-ring", "op": "allreduce", "skew_ms": 18.4,
                 "max_ms": 42.1, "min_ms": 23.7, "slowest_rank": "3",
                 "ranks": 8},
            ],
        },
    }
    total = {"CPU": 8.0, "TPU": 4.0}
    avail = {"CPU": 6.0, "TPU": 2.0}
    return summary, total, avail


def cmd_status(args):
    if args.offline:
        summary, total, avail = _status_fixture()
        _render_status(summary, total, avail)
        return 0
    rt = _connect()
    from ray_tpu.util import state as state_api

    total = rt.cluster_resources()
    avail = rt.available_resources()
    _render_status(state_api.summarize_resources(), total, avail)
    return 0


def cmd_dashboard(args):
    """Print the dashboard URL (reference: `ray dashboard`)."""
    _connect()
    from ray_tpu.util import state as state_api

    url = state_api.dashboard_url()
    if url is None:
        print("dashboard disabled (dashboard_port=-1)")
        return 1
    print(url)
    return 0


def cmd_submit(args):
    from ray_tpu.job import JobSubmissionClient

    _connect()
    client = JobSubmissionClient()
    entrypoint = " ".join(args.entrypoint)
    job_id = client.submit_job(entrypoint=entrypoint)
    print(f"submitted {job_id}")
    if args.no_wait:
        return 0
    status = client.wait_until_finished(job_id, timeout=args.timeout)
    print(client.get_job_logs(job_id), end="")
    print(f"job {job_id}: {status}")
    return 0 if status == "SUCCEEDED" else 1


def cmd_job(args):
    from ray_tpu.job import JobSubmissionClient

    _connect()
    client = JobSubmissionClient()
    if args.action == "list":
        for j in client.list_jobs():
            print(f"{j['job_id']}  {j['status']:10s}  {j['entrypoint']}")
    elif args.action == "logs":
        print(client.get_job_logs(args.job_id), end="")
    elif args.action == "stop":
        print(client.stop_job(args.job_id))
    return 0


def cmd_summary(args):
    from ray_tpu.util import state

    _connect()
    fn = {
        "tasks": state.summarize_tasks,
        "actors": state.summarize_actors,
        "objects": state.summarize_objects,
        "memory": state.summarize_memory,
        "lifecycle": state.summarize_lifecycle,
        "rl": state.summarize_rl,
        "train": state.summarize_train,
        "profiling": state.summarize_profiling,
        "errors": state.summarize_errors,
    }[args.what]
    print(json.dumps(fn(), indent=2))
    return 0


def cmd_timeline(args):
    from ray_tpu.util import state

    _connect()
    out = args.output or f"timeline-{int(time.time())}.json"
    trace = state.timeline_chrome(
        out,
        include_lifecycle=not args.no_lifecycle,
        include_spans=not args.no_spans,
        include_device=not args.no_device,
    )
    by_cat = {}
    for ev in trace:
        cat = ev.get("cat", "span")
        by_cat[cat] = by_cat.get(cat, 0) + 1
    detail = ", ".join(f"{n} {cat}" for cat, n in sorted(by_cat.items()))
    print(
        f"wrote {len(trace)} events ({detail or 'none'}) to {out} "
        "(load in chrome://tracing or perfetto)"
    )
    return 0


def cmd_stack(args):
    """Live stacks of every cluster process (reference: `ray stack`)."""
    from ray_tpu.util import state

    _connect()
    dumps = state.get_stack_traces(timeout_s=args.timeout)
    for name in sorted(dumps):
        print(f"===== {name} =====")
        print(dumps[name])
    return 0


def _render_memory(summary: dict, leaks_only: bool = False, out=print):
    """The `ray-tpu memory` census view (reference: `ray memory` + the
    dashboard memory view): per-node store occupancy, open objects
    grouped by creation call-site across all tiers, process censuses,
    and the leak detector's flags."""
    totals = summary.get("totals", {})
    leaks = summary.get("leaks", [])
    if not leaks_only:
        out(
            f"objects: {totals.get('objects', 0)}  "
            f"inline {_gb(totals.get('inline_bytes'))} GB  "
            f"shm {_gb(totals.get('shm_bytes'))} GB  "
            f"spilled {_gb(totals.get('spilled_bytes'))} GB"
        )
        out(
            f"open local refs: {totals.get('open_refs', 0)}  "
            f"zero-copy pins: {totals.get('pins', 0)} "
            f"({_gb(totals.get('pin_bytes'))} GB)  "
            f"memory-store entries: {totals.get('memory_store_entries', 0)}"
        )
        out("")
        out(
            f"{'node':<14}{'store GB':>12}{'objects':>9}{'spilled GB':>12}"
            f"{'pins':>6}{'deferred':>10}"
        )
        for nid, store in summary.get("nodes", {}).items():
            st = f"{_gb(store.get('used'))}/{_gb(store.get('capacity'))}"
            out(
                f"{nid[:12]:<14}{st:>12}{store.get('num_objects', 0):>9}"
                f"{_gb(store.get('spilled_bytes')):>12}"
                f"{store.get('pinned_slots', 0):>6}"
                f"{store.get('deferred_deletes', 0):>10}"
            )
        out("")
        rows = summary.get("by_callsite", {})
        if rows:
            out("open objects by creation call-site"
                + (" (truncated)" if summary.get("truncated") else "") + ":")
            out(
                f"  {'objects':>8}{'refs':>7}{'pins':>6}{'MB':>10}"
                f"{'spilled MB':>12}  call-site"
            )
            for site, r in rows.items():
                out(
                    f"  {r.get('objects', 0):>8}{r.get('local_refs', 0):>7}"
                    f"{r.get('pins', 0):>6}"
                    f"{(r.get('bytes', 0) or 0) / (1 << 20):>10.1f}"
                    f"{(r.get('spilled_bytes', 0) or 0) / (1 << 20):>12.1f}"
                    f"  {site}"
                )
        procs = summary.get("procs", {})
        if procs:
            out("")
            out("per-process census:")
            for name, p in sorted(procs.items()):
                if p.get("error"):
                    out(f"  {name}: !! {p['error']}")
                    continue
                ms = p.get("memory_store", {})
                pins = p.get("pins", {})
                out(
                    f"  {name}: {p.get('open_refs', 0)} open refs, "
                    f"{ms.get('entries', 0)} memory-store entries "
                    f"({(ms.get('ready_bytes', 0) or 0) / (1 << 20):.1f} MB), "
                    f"{pins.get('count', 0)} pins"
                )
    if leaks:
        out("")
        out("!! leak suspects (open refs rising monotonically):")
        for r in leaks:
            out(
                f"  {r.get('count', 0):>7} open (+{r.get('growth', 0)})  "
                f"{r.get('callsite', '?')}"
            )
    elif leaks_only:
        out("no leak suspects flagged")


def _memory_fixture() -> dict:
    """Canned summarize_memory()-shaped data for `memory --offline`:
    exercises every rendering path (tiers, pins, procs, leaks) with no
    cluster — the tier-1 smoke that keeps the view from rotting."""
    return {
        "totals": {
            "objects": 1312, "inline_bytes": 3 << 20,
            "shm_bytes": 6 << 30, "spilled_bytes": 2 << 30,
            "open_refs": 1840, "pins": 3, "pin_bytes": 192 << 20,
            "memory_store_entries": 24, "memory_store_bytes": 1 << 20,
        },
        "nodes": {
            "aabbccddee00": {
                "used": 5 << 30, "capacity": 8 << 30, "num_objects": 900,
                "num_spilled": 120, "spilled_bytes": 2 << 30,
                "pinned_slots": 3, "pinned_bytes": 192 << 20,
                "deferred_deletes": 2, "spill_ops": 804,
            },
            "ffee00112233": {
                "used": 1 << 30, "capacity": 8 << 30, "num_objects": 412,
                "num_spilled": 0, "spilled_bytes": 0,
                "pinned_slots": 0, "pinned_bytes": 0,
                "deferred_deletes": 0, "spill_ops": 0,
            },
        },
        "by_callsite": {
            "app/train.py:91:load_shards": {
                "objects": 800, "bytes": 5 << 30, "spilled_bytes": 2 << 30,
                "local_refs": 820, "pins": 3,
                "tiers": {"shm": 680, "spilled": 120},
            },
            "(task) preprocess": {
                "objects": 400, "bytes": 1 << 30, "spilled_bytes": 0,
                "local_refs": 400, "pins": 0, "tiers": {"shm": 400},
            },
            "app/eval.py:12:collect": {
                "objects": 112, "bytes": 3 << 20, "spilled_bytes": 0,
                "local_refs": 620, "pins": 0, "tiers": {"inline": 112},
            },
        },
        "truncated": False,
        "procs": {
            "driver:0": {
                "open_refs": 1220,
                "memory_store": {"entries": 24, "ready_bytes": 1 << 20,
                                 "pending": 2, "shm": 4},
                "pins": {"count": 0, "bytes": 0},
            },
            "worker:aaaa0000:pid201": {
                "open_refs": 620,
                "memory_store": {"entries": 0, "ready_bytes": 0},
                "pins": {"count": 3, "bytes": 192 << 20},
            },
            "worker:bbbb0000:pid202": {"error": "timed out"},
        },
        "leaks": [
            {"callsite": "app/eval.py:12:collect", "count": 620,
             "growth": 480, "first_flagged": 0.0},
        ],
    }


def cmd_memory(args):
    if args.offline:
        _render_memory(_memory_fixture(), leaks_only=args.leaks)
        return 0
    from ray_tpu.util import state

    _connect()
    summary = state.summarize_memory(limit=args.limit, node=args.node)
    if args.json:
        print(json.dumps(summary, indent=2, default=str))
        return 0
    _render_memory(summary, leaks_only=args.leaks)
    return 0


def _health_fixture() -> dict:
    """Canned summarize_health()-shaped data for `health --offline`:
    exercises every rendering path (actuator table, outcomes, actions,
    avoids, remote actions) with no cluster — the tier-1 smoke that
    keeps the view from rotting."""
    return {
        "enabled": True,
        "max_actions_per_min": 6,
        "actuators": [
            {"name": "leak_backpressure", "triggers": ["memory_leak"],
             "cooldown_s": 30.0, "dry_run": False},
            {"name": "pressure_spill", "triggers": ["memory_pressure"],
             "cooldown_s": 30.0, "dry_run": False},
            {"name": "storm_pin", "triggers": ["recompile_storm"],
             "cooldown_s": 30.0, "dry_run": True},
            {"name": "spike_quarantine", "triggers": ["error_spike"],
             "cooldown_s": 30.0, "dry_run": False},
        ],
        "signals": {"memory_pressure": 4, "error_spike": 1},
        "outcomes": {
            "pressure_spill": {"acted": 2, "cooldown": 2},
            "spike_quarantine": {"acted": 1},
            "storm_pin": {"dry_run": 1},
        },
        "actions_recent": [
            {"id": "act-1-100", "ts": 1700000000.0,
             "actuator": "pressure_spill", "trigger": "memory_pressure",
             "key": "aabbccddee00", "target": "aabbccddee00",
             "dry_run": False, "outcome": "acted",
             "detail": {"reason": "occupancy", "spilled": 41,
                        "freed_bytes": 2 << 30}},
            {"id": "act-2-250", "ts": 1700000012.5,
             "actuator": "spike_quarantine", "trigger": "error_spike",
             "key": "ffee00112233", "target": "ffee00112233",
             "dry_run": False, "outcome": "acted",
             "detail": {"signature": "ValueError@Loader.fetch",
                        "quarantine_s": 60.0}},
            {"id": "act-3-311", "ts": 1700000031.1,
             "actuator": "storm_pin", "trigger": "recompile_storm",
             "key": "aabbccddee00/pid201:train_step",
             "target": "aabbccddee00/pid201", "dry_run": True,
             "outcome": "dry_run", "detail": {"function": "train_step"}},
        ],
        "avoids": {
            "ffee00112233": {"mode": "quarantine", "remaining_s": 41.2},
        },
        "remote_actions": [
            {"ts": 1700000044.0, "kind": "action", "id": "padr-1",
             "state": "FINISHED", "actuator": "podracer_cadence",
             "trigger": "policy_lag", "target": "learner",
             "outcome": "acted", "remote": True},
        ],
    }


def _render_health(summary: dict, out=print):
    """The `ray-tpu health` self-healing view: actuator configs, live
    avoids, and the recent trigger → action → outcome audit."""
    if not summary.get("enabled", False):
        out("health actuators disabled (health_actuators=False)")
        return
    out(f"{'actuator':<20}{'triggers':<22}{'cooldown':>9}{'dry-run':>9}  outcomes")
    outcomes = summary.get("outcomes", {})
    for a in summary.get("actuators", []):
        tally = outcomes.get(a["name"], {})
        tstr = " ".join(f"{k}:{n}" for k, n in sorted(tally.items())) or "-"
        out(
            f"{a['name']:<20}{','.join(a['triggers']):<22}"
            f"{a['cooldown_s']:>8.0f}s{('yes' if a['dry_run'] else 'no'):>9}  {tstr}"
        )
    sig = summary.get("signals", {})
    if sig:
        out("")
        out("signals seen: " + "  ".join(f"{k}={n}" for k, n in sorted(sig.items())))
    avoids = summary.get("avoids", {})
    if avoids:
        out("")
        out("active avoids:")
        for nid, row in avoids.items():
            out(f"  {nid}  {row['mode']:<11} {row['remaining_s']:.0f}s remaining")
    rows = summary.get("actions_recent", []) + summary.get("remote_actions", [])
    if rows:
        out("")
        out("recent actions:")
        for r in rows:
            det = r.get("detail", {})
            extra = " ".join(
                f"{k}={v}" for k, v in sorted(det.items()) if k != "signature"
            )
            out(
                f"  {r.get('actuator', '?'):<20}{r.get('trigger', '?'):<18}"
                f"→ {r.get('target', '?')[:24]:<26}{r.get('outcome', '?'):<10}"
                + (f" {extra}" if extra else "")
            )
    else:
        out("")
        out("no actions taken")


def cmd_health(args):
    if args.offline:
        summary = _health_fixture()
    else:
        from ray_tpu.util import state

        _connect()
        summary = state.summarize_health(limit=args.limit)
    if args.json:
        print(json.dumps(summary, indent=2, default=str))
        return 0
    _render_health(summary)
    return 0


def cmd_drain_node(args):
    import ray_tpu

    ray_tpu.init(address="auto")
    ray_tpu.drain_node(args.node_id, timeout_s=args.timeout)
    print(f"draining {args.node_id}")


def _logs_fixture() -> list:
    """Canned search_logs()-shaped records for `logs --offline`:
    exercises the record renderer (severity, node/worker attribution,
    task tags, raw-grep fallback rows) with no cluster — the tier-1
    smoke that keeps the view from rotting."""
    return [
        {"ts": 1700000000.103, "sev": "INFO", "node": "aabbccddee00",
         "worker": "aaaa0000", "pid": 201, "task": "train_loop",
         "task_id": "11" * 16, "actor_id": None,
         "msg": "step 41 loss 2.31", "file": "worker-aaaa0000.jsonl",
         "line": 7},
        {"ts": 1700000000.250, "sev": "STDOUT", "node": "aabbccddee00",
         "worker": "aaaa0000", "pid": 201, "task": "train_loop",
         "task_id": "11" * 16, "actor_id": None,
         "msg": "checkpoint saved to /tmp/ck-41",
         "file": "worker-aaaa0000.jsonl", "line": 8},
        {"ts": 1700000000.912, "sev": "ERROR", "node": "ffee00112233",
         "worker": "bbbb0000", "pid": 202, "task": "Loader.fetch",
         "task_id": "22" * 16, "actor_id": "33" * 16,
         "exc": "ValueError",
         "msg": "task Loader.fetch failed: Traceback (most recent call "
                "last):\n  ...\nValueError: bad shard 7",
         "file": "worker-bbbb0000.jsonl", "line": 3},
        {"ts": None, "sev": None, "node": None, "worker": None,
         "msg": "[controller] WARNING lease queue deep",
         "file": "controller.log", "line": 4021},
    ]


def _render_log_records(rows: list, out=print) -> int:
    from ray_tpu.core.log_plane import format_record

    for rec in rows:
        out(format_record(rec))
    return 0


def cmd_logs(args):
    """``ray-tpu logs``: list files, fetch one, search with attribution
    filters, or live-follow (reference: `ray logs` + the StateHead logs
    API; `--task/--actor/--grep/--err` need the structured sidecars the
    log plane writes — core/log_plane.py)."""
    severity = "ERROR" if args.err else args.severity
    filtered = any((args.grep, args.task, args.actor, severity))
    if args.offline:
        from ray_tpu.core.log_plane import match_record

        rows = [
            r for r in _logs_fixture()
            if match_record(r, pattern=args.grep, severity=severity,
                            task=args.task, actor=args.actor,
                            node=args.node)
        ]
        return _render_log_records(rows)
    from ray_tpu.util import state

    _connect()
    if args.follow:
        import queue as _q

        records: "_q.Queue" = _q.Queue()
        stop = state.follow_logs(
            records.put, pattern=args.grep, severity=severity,
            task=args.task, actor=args.actor, node=args.node,
        )
        print("following cluster logs (ctrl-c to stop)...", file=sys.stderr)
        try:
            while True:
                _render_log_records(records.get())
        except KeyboardInterrupt:
            stop()
            return 0
    if args.filename and not filtered:
        print(state.get_log(args.filename, tail=args.tail, node=args.node),
              end="")
        return 0
    if filtered:
        rows = state.search_logs(
            args.grep, severity=severity,
            task=args.task, actor=args.actor, node=args.node,
            limit=args.tail,
        )
        return _render_log_records(rows)
    for row in state.list_log_files(node=args.node):
        mark = "*" if row.get("structured") else " "
        node = (row.get("node") or "?")[:12]
        print(f"{row['filename']:<40} {mark} {row['size']:>12}  {node}")
    return 0


def cmd_metrics(args):
    """``ray-tpu metrics dashboard``: importable Grafana dashboard JSON
    generated from the LIVE metric registry (reference:
    dashboard/modules/metrics/grafana_dashboard_factory.py)."""
    _connect()
    from ray_tpu.util.grafana import dashboard_json

    text = dashboard_json()
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        print(f"wrote {args.out}")
    else:
        print(text)
    return 0


_PROFILE_ACTIONS = ("stacks", "cpu", "device", "incidents", "captures")


def _profile_stacks_fixture() -> dict:
    """Canned fan-out dumps for `profile stacks --offline`: exercises the
    merge/dedup/held-lock rendering with no cluster (the tier-1 smoke
    that keeps the report from rotting)."""
    idle = [
        {"file": "/usr/lib/python3.10/threading.py", "line": 324,
         "func": "wait"},
    ]
    busy = [
        {"file": "/app/train.py", "line": 91, "func": "train_loop"},
        {"file": "/app/train.py", "line": 44, "func": "loss_fn"},
    ]

    def dump(proc, pid, threads):
        return {"process": proc, "pid": pid, "ts": 0.0, "threads": threads}

    return {
        "controller": dump("controller", 100, [
            {"ident": 1, "name": "MainThread", "daemon": False, "task": None,
             "idle": True, "frames": idle, "held_locks": []},
        ]),
        "worker:aaaa0000:pid201": dump("worker-aaaa0000", 201, [
            {"ident": 2, "name": "task-exec", "daemon": True,
             "task": "train_loop", "idle": False, "frames": busy,
             "held_locks": [{"lock": "Lock@train.py:12",
                             "acquired_at": "train.py:90",
                             "held_ms": 1503.2}]},
            {"ident": 3, "name": "metrics-flush", "daemon": True,
             "task": None, "idle": True, "frames": idle, "held_locks": []},
        ]),
        "worker:bbbb0000:pid202": dump("worker-bbbb0000", 202, [
            {"ident": 2, "name": "task-exec", "daemon": True, "task": None,
             "idle": True, "frames": idle, "held_locks": []},
        ]),
        "agent:cccc0000": "<unavailable: timed out>",
    }


def _profile_cpu_fixture() -> dict:
    from ray_tpu.util import profiling

    results = {
        "worker:aaaa0000:pid201": {
            "samples": 480, "duration_s": 5.0,
            "task_cpu_ms": {"train_loop": 4200.0},
            "stacks": [
                {"thread": "task-exec", "task": "train_loop", "count": 420,
                 "busy": 420, "frames": ["train.train_loop", "train.loss_fn"]},
                {"thread": "metrics-flush", "task": None, "count": 60,
                 "busy": 0, "frames": ["threading.wait"]},
            ],
        },
        "controller": {
            "samples": 500, "duration_s": 5.0, "task_cpu_ms": {},
            "stacks": [
                {"thread": "MainThread", "task": None, "count": 500,
                 "busy": 120, "frames": ["controller.run", "selectors.select"]},
            ],
        },
    }
    merged = profiling.merge_cpu_results(results)
    merged.update(hz=100.0, duration_s=5.0, ms_per_sample=10.0)
    return merged


def _print_cpu_profile(res: dict, args) -> int:
    from ray_tpu.util import profiling

    print(
        f"{res.get('samples', 0)} samples @ {res.get('hz', '?')} Hz over "
        f"{res.get('duration_s', '?')}s from {len(res.get('procs', {}))} "
        "process(es)"
    )
    task_cpu = res.get("task_cpu_ms", {})
    if task_cpu:
        print("task CPU attribution (sampled busy ms):")
        for name, ms in list(task_cpu.items())[:15]:
            print(f"  {ms:>10.1f} ms  {name}")
    for proc, err in res.get("errors", {}).items():
        print(f"!! {proc}: {err}")
    if args.out:
        if args.format == "collapsed":
            text = profiling.collapsed_text(res)
        else:
            text = json.dumps(profiling.speedscope_json(
                res, ms_per_sample=res.get("ms_per_sample", 10.0)
            ))
        with open(args.out, "w") as f:
            f.write(text)
        print(f"wrote {args.format} profile to {args.out} "
              "(collapsed: flamegraph.pl; speedscope: speedscope.app)")
    else:
        top = sorted(
            res.get("collapsed", {}).items(), key=lambda kv: -kv[1]
        )[:15]
        if top:
            print("top stacks (collapsed; use --out for the full profile):")
            for line, n in top:
                print(f"  {n:>6}  {line[:160]}")
    return 0


def _profile_captures(args):
    """Legacy list/fetch of jax.profiler captures (both per-task
    runtime_env={'jax_profiler': True} and on-demand device traces)."""
    from ray_tpu.util import state

    _connect()
    if args.target_id:
        info = state.get_profile(args.target_id)
        print(json.dumps({k: v for k, v in info.items() if k != "files"}, indent=1))
        for f in info["files"]:
            print(f)
    else:
        rows = state.list_profiles()
        if not rows:
            print("no profiles captured (use runtime_env={'jax_profiler': "
                  "True} or `ray-tpu profile device`)")
        for r in rows:
            print(f"{r['id']}  task={r.get('task_id', '?')[:12]}  "
                  f"dur={r.get('duration_s', '?')}s  {r['path']}")
    return 0


def cmd_profile(args):
    """On-demand distributed profiling (reference: `ray stack` + the
    dashboard reporter's per-worker py-spy stack/CPU-profile endpoints):

      ray-tpu profile stacks [--node N | --actor ID]
      ray-tpu profile cpu --duration 10 [--hz 100] [--out f --format ...]
      ray-tpu profile device [--workers W1,W2] --duration 5
      ray-tpu profile incidents [ID]
      ray-tpu profile captures [ID]        (also: legacy `profile [ID]`)
    """
    from ray_tpu.util import profiling

    action = args.action
    if action not in _PROFILE_ACTIONS:
        # legacy invocation: `ray-tpu profile [capture_id]`
        args.target_id = action
        return _profile_captures(args)
    if action == "stacks":
        if args.offline:
            print(profiling.merge_stack_dumps(_profile_stacks_fixture()))
            return 0
        from ray_tpu.util import state

        _connect()
        res = state.profile_stacks(
            node=args.node, actor=args.actor, timeout_s=args.timeout
        )
        print(res["merged"])
        return 0
    if action == "cpu":
        if args.offline:
            return _print_cpu_profile(_profile_cpu_fixture(), args)
        from ray_tpu.util import state

        _connect()
        workers = args.workers.split(",") if args.workers else None
        res = state.profile_cpu(
            duration_s=args.duration, hz=args.hz, node=args.node,
            workers=workers,
        )
        return _print_cpu_profile(res, args)
    if action == "device":
        from ray_tpu.util import state

        _connect()
        workers = args.workers.split(",") if args.workers else None
        res = state.profile_device(workers=workers, duration_s=args.duration)
        print(f"capture {res['capture']} ({res['duration_s']}s):")
        ok = 0
        for name, r in sorted(res.get("workers", {}).items()):
            if r.get("ok"):
                ok += 1
                print(f"  {name}: {r.get('dir')}")
            else:
                print(f"  {name}: FAILED — {r.get('error')}")
        print(f"{ok} capture(s); list with `ray-tpu profile captures`, "
              "merge into one trace with `ray-tpu timeline`")
        return 0 if ok or not res.get("workers") else 1
    if action == "incidents":
        from ray_tpu.util import state

        _connect()
        if args.target_id:
            info = state.get_incident(args.target_id)
            print(json.dumps(
                {k: v for k, v in info.items() if k != "contents"}, indent=1
            ))
            for name, content in info.get("contents", {}).items():
                print(f"===== {name} =====")
                print(content)
        else:
            rows = state.list_incidents()
            if not rows:
                print("no incidents captured")
            for r in rows:
                print(f"{r['id']}  trigger={r.get('trigger', '?')}  "
                      f"proc={r.get('process', '?')}  {r['path']}")
        return 0
    return _profile_captures(args)


def cmd_microbenchmark(args):
    """Core perf smoke (reference: `ray microbenchmark`,
    python/ray/_private/ray_perf.py:93)."""
    import numpy as np

    import ray_tpu

    ray_tpu.init(num_cpus=4)
    results = {}

    @ray_tpu.remote
    def noop():
        return 0

    # warm the worker pool
    ray_tpu.get([noop.remote() for _ in range(20)])
    t0 = time.perf_counter()
    n = 300
    ray_tpu.get([noop.remote() for _ in range(n)])
    results["tasks_per_s"] = n / (time.perf_counter() - t0)

    @ray_tpu.remote
    class A:
        def ping(self):
            return 0

    a = A.remote()
    ray_tpu.wait_actor_ready(a)
    t0 = time.perf_counter()
    for _ in range(100):
        ray_tpu.get(a.ping.remote())
    results["sync_actor_calls_per_s"] = 100 / (time.perf_counter() - t0)

    t0 = time.perf_counter()
    ray_tpu.get([a.ping.remote() for _ in range(500)])
    results["async_actor_calls_per_s"] = 500 / (time.perf_counter() - t0)

    data = np.zeros(16 * 1024 * 1024, dtype=np.uint8)
    t0 = time.perf_counter()
    for _ in range(10):
        ref = ray_tpu.put(data)
        ray_tpu.get(ref)
    gib = 10 * data.nbytes / (1 << 30)
    results["put_get_GiB_per_s"] = gib / (time.perf_counter() - t0)

    ray_tpu.shutdown()
    print(json.dumps({k: round(v, 1) for k, v in results.items()}, indent=2))
    return 0


# ---------------------------------------------------------------------------


def cmd_lint(args):
    """Project-aware static analysis (see ray_tpu/tools/lint/)."""
    from ray_tpu.tools.lint.cli import cmd_lint as run

    return run(args)


def cmd_sanitize(args):
    """Concurrency sanitizer gate (see ray_tpu/tools/sanitizer/)."""
    from ray_tpu.tools.sanitizer.cli import cmd_sanitize as run

    return run(args)


def cmd_up(args):
    from ray_tpu.autoscaler.commands import create_or_update_cluster

    state = create_or_update_cluster(args.cluster_config)
    print(f"cluster {state['cluster_name']} up at {state['address']}")
    print(f"session: {state['session_dir']}")
    print(f"attach:  ray-tpu attach {state['cluster_name']}")
    print(f"exec:    ray-tpu exec {state['cluster_name']} -- <cmd...>")
    print(f"down:    ray-tpu down {state['cluster_name']}")
    return 0


def cmd_down(args):
    from ray_tpu.autoscaler.commands import teardown_cluster

    state = teardown_cluster(args.cluster)
    print(f"cluster {state['cluster_name']} torn down")
    return 0


def cmd_exec(args):
    from ray_tpu.autoscaler.commands import exec_on_cluster

    cmd = list(args.command)
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        print("usage: ray-tpu exec <cluster> -- <cmd...>", file=sys.stderr)
        return 1
    return exec_on_cluster(args.cluster, cmd).returncode


def cmd_attach(args):
    from ray_tpu.autoscaler.commands import attach_cluster

    return attach_cluster(args.cluster)


def main(argv=None):
    p = argparse.ArgumentParser(prog="ray-tpu", description=__doc__.split("\n")[0])
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("start", help="start a head node or join as a worker node")
    sp.add_argument("--head", action="store_true")
    sp.add_argument("--address")
    sp.add_argument("--num-cpus", type=int)
    sp.add_argument("--num-tpus", type=int)
    sp.add_argument("--resources")
    sp.add_argument("--session-dir")
    sp.add_argument("--block", action="store_true")
    sp.set_defaults(fn=cmd_start)

    sub.add_parser("stop", help="stop the running cluster").set_defaults(fn=cmd_stop)

    sp = sub.add_parser("up", help="launch a cluster from a cluster YAML")
    sp.add_argument("cluster_config")
    sp.set_defaults(fn=cmd_up)

    sp = sub.add_parser("down", help="tear down a launched cluster (name or YAML)")
    sp.add_argument("cluster")
    sp.set_defaults(fn=cmd_down)

    sp = sub.add_parser("exec", help="run a command against a launched cluster")
    sp.add_argument("cluster")
    sp.add_argument("command", nargs=argparse.REMAINDER)
    sp.set_defaults(fn=cmd_exec)

    sp = sub.add_parser("attach", help="interactive shell wired to a launched cluster")
    sp.add_argument("cluster")
    sp.set_defaults(fn=cmd_attach)
    sp = sub.add_parser(
        "status",
        help="cluster table: resources, host/HBM telemetry, compiles, skew",
    )
    sp.add_argument(
        "--offline", action="store_true",
        help="render from a built-in fixture (no cluster; smoke-tests the view)",
    )
    sp.set_defaults(fn=cmd_status)

    sp = sub.add_parser("submit", help="submit a job: ray-tpu submit -- python x.py")
    sp.add_argument("--no-wait", action="store_true")
    sp.add_argument("--timeout", type=float, default=600.0)
    sp.add_argument("entrypoint", nargs=argparse.REMAINDER)
    sp.set_defaults(fn=cmd_submit)

    sp = sub.add_parser("job", help="manage jobs")
    sp.add_argument("action", choices=["list", "logs", "stop"])
    sp.add_argument("job_id", nargs="?")
    sp.set_defaults(fn=cmd_job)

    sp = sub.add_parser("summary", help="state summaries")
    sp.add_argument(
        "what",
        choices=["tasks", "actors", "objects", "memory", "lifecycle", "rl",
                 "train", "profiling", "errors"],
    )
    sp.set_defaults(fn=cmd_summary)

    sp = sub.add_parser(
        "timeline",
        help="chrome trace: task slices + control-plane lifecycle + user spans",
    )
    sp.add_argument("--output", "-o")
    sp.add_argument(
        "--no-lifecycle", action="store_true",
        help="omit flight-recorder lifecycle rows",
    )
    sp.add_argument(
        "--no-spans", action="store_true",
        help="omit RAY_TPU_TRACE span files",
    )
    sp.add_argument(
        "--no-device", action="store_true",
        help="omit captured XLA device-trace events",
    )
    sp.set_defaults(fn=cmd_timeline)

    sp = sub.add_parser(
        "memory",
        help="cluster memory census: objects by call-site, store "
             "occupancy, pins, leak suspects",
    )
    sp.add_argument("--node", help="filter to one node (node-id hex prefix)")
    sp.add_argument("--leaks", action="store_true",
                    help="show only the leak detector's flagged call-sites")
    sp.add_argument("--limit", type=int, default=20,
                    help="call-site rows to show (default 20)")
    sp.add_argument("--json", action="store_true",
                    help="raw summarize_memory() JSON")
    sp.add_argument("--offline", action="store_true",
                    help="render from a built-in fixture (no cluster)")
    sp.set_defaults(fn=cmd_memory)

    sp = sub.add_parser(
        "profile",
        help="on-demand profiling: stacks|cpu|device|incidents|captures",
    )
    sp.add_argument(
        "action", nargs="?",
        help="stacks|cpu|device|incidents|captures (or a capture id — "
             "the legacy `profile [ID]` list/fetch still works)",
    )
    sp.add_argument("target_id", nargs="?", help="incident or capture id")
    sp.add_argument("--duration", type=float, default=5.0,
                    help="cpu/device: capture seconds")
    sp.add_argument("--hz", type=float,
                    help="cpu: sample rate (default: profiling_sample_hz)")
    sp.add_argument("--node", help="filter to one node (node-id hex prefix)")
    sp.add_argument("--actor",
                    help="stacks: filter to one actor's worker (id prefix)")
    sp.add_argument("--workers",
                    help="cpu/device: comma-separated worker-id prefixes")
    sp.add_argument("--out", help="cpu: write the full profile here")
    sp.add_argument("--format", choices=["speedscope", "collapsed"],
                    default="speedscope", help="cpu --out format")
    sp.add_argument("--timeout", type=float, default=10.0)
    sp.add_argument("--offline", action="store_true",
                    help="render from built-in fixtures (no cluster)")
    sp.set_defaults(fn=cmd_profile)

    sp = sub.add_parser(
        "metrics", help="metrics tooling (dashboard: emit Grafana JSON)"
    )
    sp.add_argument("action", choices=["dashboard"])
    sp.add_argument("--out", default="", help="write JSON here (default: stdout)")
    sp.set_defaults(fn=cmd_metrics)
    sub.add_parser("dashboard", help="print the dashboard URL").set_defaults(
        fn=cmd_dashboard
    )

    sp = sub.add_parser("stack", help="live thread stacks of all cluster processes")
    sp.add_argument("--timeout", type=float, default=10.0)
    sp.set_defaults(fn=cmd_stack)

    sp = sub.add_parser("drain-node", help="gracefully drain a node")
    sp.add_argument("node_id", help="node id (hex, from `ray-tpu status`)")
    sp.add_argument("--timeout", type=float, default=300.0)
    sp.set_defaults(fn=cmd_drain_node)

    sp = sub.add_parser(
        "logs",
        help="cluster logs: list/tail files, search with task/actor/"
             "severity attribution, or live-follow",
    )
    sp.add_argument("filename", nargs="?")
    sp.add_argument("--tail", type=int, default=1000,
                    help="lines to fetch / search-result cap")
    sp.add_argument("--node", help="filter to one node (node-id hex prefix)")
    sp.add_argument("--task",
                    help="filter to one task (name substring or id prefix)")
    sp.add_argument("--actor", help="filter to one actor (id prefix)")
    sp.add_argument("--grep", help="regex over structured log messages")
    sp.add_argument("--severity",
                    help="severity floor (DEBUG/INFO/WARNING/ERROR)")
    sp.add_argument("--err", action="store_true",
                    help="shortcut for --severity ERROR")
    sp.add_argument("--follow", "-f", action="store_true",
                    help="stream matching records live (ctrl-c to stop)")
    sp.add_argument("--offline", action="store_true",
                    help="render from a built-in fixture (no cluster)")
    sp.set_defaults(fn=cmd_logs)

    sp = sub.add_parser(
        "health",
        help="self-healing plane: actuators, recent actions, active avoids",
    )
    sp.add_argument("--limit", type=int, default=50,
                    help="recent actions to show")
    sp.add_argument("--json", action="store_true", help="raw JSON summary")
    sp.add_argument("--offline", action="store_true",
                    help="render from a built-in fixture (no cluster)")
    sp.set_defaults(fn=cmd_health)

    sub.add_parser("microbenchmark", help="core perf smoke").set_defaults(fn=cmd_microbenchmark)

    sp = sub.add_parser(
        "lint",
        help="static analysis: concurrency/asyncio/jit-recompile/metrics rules",
    )
    from ray_tpu.tools.lint.cli import add_lint_args

    add_lint_args(sp)
    sp.set_defaults(fn=cmd_lint)

    sp = sub.add_parser(
        "sanitize",
        help="concurrency sanitizer: guard-annotation checks (RTL009-011), "
        "lock-order cross-check, runtime witness reports",
    )
    from ray_tpu.tools.sanitizer.cli import add_sanitize_args

    add_sanitize_args(sp)
    sp.set_defaults(fn=cmd_sanitize)

    args = p.parse_args(argv)
    entry = getattr(args, "entrypoint", None)
    if entry and entry[0] == "--":
        args.entrypoint = entry[1:]
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
