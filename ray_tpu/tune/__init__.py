"""ray_tpu.tune: hyperparameter search over cluster-scheduled trials.

Reference: python/ray/tune (Tuner/tune.run, search spaces, schedulers).
"""
from ray_tpu.tune.schedulers import (
    AsyncHyperBandScheduler,
    DistributeResources,
    FIFOScheduler,
    HyperBandScheduler,
    MedianStoppingRule,
    PB2,
    PopulationBasedTraining,
    ResourceChangingScheduler,
    TrialScheduler,
)
from ray_tpu.tune.search import (
    BasicVariantGenerator,
    ConcurrencyLimiter,
    Searcher,
    choice,
    grid_search,
    loguniform,
    randint,
    sample_from,
    uniform,
)
from ray_tpu.tune.suggest import (
    BayesOptSearcher,
    BOHBSearcher,
    EvolutionarySearcher,
    Repeater,
    TPESearcher,
)
from ray_tpu.tune.trial import (
    Trial,
    get_checkpoint_dir,
    make_checkpoint_dir,
    report,
)
from ray_tpu.tune.tuner import ResultGrid, TuneConfig, TuneController, Tuner


def run(trainable, *, config=None, num_samples=1, metric="score", mode="max", scheduler=None, **kw):
    """tune.run legacy-style entry (reference: tune/tune.py)."""
    tuner = Tuner(
        trainable,
        param_space=config or {},
        tune_config=TuneConfig(
            metric=metric, mode=mode, num_samples=num_samples, scheduler=scheduler, **kw
        ),
    )
    return tuner.fit()


__all__ = [
    "Tuner",
    "TuneConfig",
    "TuneController",
    "ResultGrid",
    "Trial",
    "report",
    "get_checkpoint_dir",
    "make_checkpoint_dir",
    "run",
    "grid_search",
    "choice",
    "uniform",
    "loguniform",
    "randint",
    "sample_from",
    "Searcher",
    "BasicVariantGenerator",
    "ConcurrencyLimiter",
    "TPESearcher",
    "BayesOptSearcher",
    "Repeater",
    "TrialScheduler",
    "FIFOScheduler",
    "AsyncHyperBandScheduler",
    "HyperBandScheduler",
    "BOHBSearcher",
    "EvolutionarySearcher",
    "PB2",
    "MedianStoppingRule",
    "PopulationBasedTraining",
]
