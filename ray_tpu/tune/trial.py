"""Trial state + the trial-runner actor.

Reference: python/ray/tune/experiment/trial.py (Trial FSM) and
tune/trainable/function_trainable.py:36 (FunctionTrainable: user fn in a
thread + result queue — the same mechanism ray_tpu.train's session uses).
"""
from __future__ import annotations

import os
import queue
import threading
import traceback
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

# Trial statuses (reference: trial.py Trial.PENDING/RUNNING/...)
PENDING = "PENDING"
RUNNING = "RUNNING"
PAUSED = "PAUSED"
TERMINATED = "TERMINATED"
ERROR = "ERROR"


@dataclass
class Trial:
    trial_id: str
    config: Dict[str, Any]
    status: str = PENDING
    last_result: Optional[dict] = None
    results: List[dict] = field(default_factory=list)
    error: Optional[str] = None
    num_failures: int = 0
    actor: Any = None
    checkpoint_dir: Optional[str] = None  # last checkpoint (for restore/PBT)
    iteration: int = 0
    paused_at_iteration: int = 0
    # Per-trial resource override (ResourceChangingScheduler); None →
    # the experiment's resources_per_trial.
    resources: Optional[Dict[str, float]] = None

    @property
    def is_finished(self) -> bool:
        return self.status in (TERMINATED, ERROR)

    def metric(self, name: str, default=None):
        if self.last_result is None:
            return default
        return self.last_result.get(name, default)


class _TuneSession:
    """Per-trial worker-side session: report()/get_checkpoint() plumbing."""

    def __init__(self, config, local_dir, restored_checkpoint, remote_dir=None):
        self.config = config
        self.local_dir = local_dir
        # cloud experiment dir (reference: storage_path URIs): reported
        # checkpoints upload here and the REMOTE path is what the
        # controller records/restores from
        self.remote_dir = remote_dir
        self.result_queue: "queue.Queue" = queue.Queue(maxsize=4)
        self.finished = threading.Event()
        self.error: Optional[BaseException] = None
        self.restored_checkpoint = restored_checkpoint
        # continue numbering past any pre-restore checkpoints in the
        # local dir — a reset would re-upload to colliding remote names
        self.ckpt_seq = 0
        try:
            existing = [
                int(d.split("_")[-1])
                for d in os.listdir(local_dir)
                if d.startswith("checkpoint_")
            ]
            if existing:
                self.ckpt_seq = max(existing) + 1
        except (OSError, ValueError):
            pass


_session: Optional[_TuneSession] = None


def report(metrics: dict, checkpoint_dir: Optional[str] = None):
    """tune.report inside a trainable (reference: ray.tune.report)."""
    if _session is None:
        raise RuntimeError("tune.report() called outside a Tune trial")
    if checkpoint_dir and _session.remote_dir:
        from ray_tpu.utils import cloudfs

        dest = cloudfs.join(_session.remote_dir, os.path.basename(checkpoint_dir))
        cloudfs.copy_dir(checkpoint_dir, dest)
        checkpoint_dir = dest  # the durable path is what gets recorded
    _session.result_queue.put({"metrics": dict(metrics), "checkpoint": checkpoint_dir})


def get_checkpoint_dir() -> Optional[str]:
    if _session is None:
        raise RuntimeError("not inside a Tune trial")
    return _session.restored_checkpoint


def make_checkpoint_dir() -> str:
    """A fresh directory the trainable can write a checkpoint into."""
    if _session is None:
        raise RuntimeError("not inside a Tune trial")
    d = os.path.join(_session.local_dir, f"checkpoint_{_session.ckpt_seq:06d}")
    _session.ckpt_seq += 1
    os.makedirs(d, exist_ok=True)
    return d


class TrialRunner:
    """The per-trial actor: runs the trainable fn in a thread, streams
    results to the controller (reference: FunctionTrainable + the
    ray.air.execution actor manager's train-result polling)."""

    def __init__(self, fn_blob: bytes, config: dict, local_dir: str, restored_checkpoint,
                 remote_dir=None):
        from ray_tpu.utils.serialization import deserialize_function

        self._fn = deserialize_function(fn_blob)
        self._setup(config, local_dir, restored_checkpoint, remote_dir)

    def _setup(self, config: dict, local_dir: str, restored_checkpoint, remote_dir):
        global _session
        os.makedirs(local_dir, exist_ok=True)
        if restored_checkpoint:
            from ray_tpu.utils import cloudfs

            if cloudfs.is_uri(restored_checkpoint):
                # download the durable checkpoint into a FIXED slot in the
                # trial's local dir — restarts overwrite it instead of
                # leaking one mkdtemp download per attempt
                local = os.path.join(local_dir, "_restored")
                import shutil as _sh

                _sh.rmtree(local, ignore_errors=True)
                cloudfs.copy_dir(restored_checkpoint, local)
                restored_checkpoint = local
        self._session = _TuneSession(config, local_dir, restored_checkpoint,
                                     remote_dir=remote_dir)
        _session = self._session
        self._thread = threading.Thread(target=self._run, daemon=True, name="trial-fn")
        self._thread.start()

    def reset(self, config: dict, local_dir: str, restored_checkpoint, remote_dir=None):
        """Reuse this actor process for a NEW trial of the same
        experiment (reference: tune/tune.py:297 ``reuse_actors`` +
        Trainable.reset) — skips the per-trial process spawn, the
        dominant cost on spawn-bound hosts. Only valid once the previous
        trainable has returned (the controller reuses only cleanly-
        finished runners).

        The session is POISONED (None) until _setup succeeds: the
        controller fire-and-forgets reset before next_result, so a
        failed reset must surface through next_result (which the
        controller observes) rather than silently replaying the previous
        trial's finished session as a zero-iteration success."""
        self._session = None
        if self._thread.is_alive():
            # The previous fn may still be inside its last instants (the
            # controller observed the final result before the thread's
            # finally block ran) — give it a bounded grace instead of
            # poisoning the actor for a benign exit race.
            self._thread.join(timeout=5)
        if self._thread.is_alive():
            raise RuntimeError("reset() while the previous trial fn is still running")
        self._setup(config, local_dir, restored_checkpoint, remote_dir)
        return True

    def _run(self):
        try:
            self._fn(self._session.config)
        except BaseException as e:  # noqa: BLE001
            self._session.error = e
            self._session.error_tb = traceback.format_exc()
        finally:
            self._session.finished.set()

    def next_result(self) -> Optional[dict]:
        """One report, or None when the trainable returned. Raises the
        trainable's error."""
        if self._session is None:
            raise RuntimeError(
                "trial runner has no active session (a preceding reset() "
                "failed); the controller restarts the trial on a fresh actor"
            )
        while True:
            try:
                return self._session.result_queue.get(timeout=0.2)
            except queue.Empty:
                if self._session.finished.is_set() and self._session.result_queue.empty():
                    if self._session.error is not None:
                        raise RuntimeError(
                            f"trial fn failed: {self._session.error}\n"
                            + getattr(self._session, "error_tb", "")
                        )
                    return None
