"""Trial schedulers: FIFO, ASHA, median-stopping, PBT.

Reference: python/ray/tune/schedulers/ — async_hyperband.py (ASHA),
median_stopping_rule.py, pbt.py. The scheduler sees every reported result
and decides CONTINUE/STOP/PAUSE; PBT additionally mutates paused trials'
configs (exploit+explore) before they resume.
"""
from __future__ import annotations

import math
import random
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.tune.trial import Trial

CONTINUE = "CONTINUE"
STOP = "STOP"
PAUSE = "PAUSE"


class TrialScheduler:
    def set_search_properties(self, metric: str, mode: str):
        self._metric = metric
        self._mode = mode

    def _score(self, result: dict) -> float:
        v = result.get(self._metric, float("-inf"))
        return v if self._mode == "max" else -v

    def on_trial_result(self, trial: Trial, result: dict) -> str:
        return CONTINUE

    def on_trial_complete(self, trial: Trial, result: Optional[dict]):
        pass

    def choose_config(self, trial: Trial) -> Optional[Dict[str, Any]]:
        """PBT hook: new config for a resuming trial (None = unchanged)."""
        return None

    def on_trial_pending_resume(self, trial: Trial) -> str:
        """Gate for PAUSED trials: CONTINUE = resume now, PAUSE = keep
        waiting (synchronous bracket not filled yet), STOP = terminate
        without resuming. Default: resume immediately."""
        return CONTINUE

    def on_search_exhausted(self):
        """The searcher will produce no more trials — synchronous
        schedulers close any partially-filled brackets."""


class FIFOScheduler(TrialScheduler):
    pass


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA (reference: tune/schedulers/async_hyperband.py): successive
    halving with rungs at grace_period * reduction_factor^k; a trial
    reaching a rung is stopped unless it is in the top 1/reduction_factor
    of results recorded at that rung."""

    def __init__(
        self,
        time_attr: str = "training_iteration",
        grace_period: int = 1,
        max_t: int = 100,
        reduction_factor: float = 4,
    ):
        self._time_attr = time_attr
        self._grace = grace_period
        self._max_t = max_t
        self._rf = reduction_factor
        # rung milestones: grace, grace*rf, grace*rf^2, ... < max_t
        self._rungs: Dict[int, List[float]] = {}
        self._reached: Dict[str, set] = {}  # trial_id -> rungs already recorded
        t = grace_period
        while t < max_t:
            self._rungs[int(t)] = []
            t *= reduction_factor

    def on_trial_result(self, trial: Trial, result: dict) -> str:
        t = result.get(self._time_attr, trial.iteration)
        if t >= self._max_t:
            return STOP
        score = self._score(result)
        reached = self._reached.setdefault(trial.trial_id, set())
        for milestone in sorted(self._rungs, reverse=True):
            if t >= milestone:
                # one entry per trial per rung — re-reports between
                # milestones neither re-record nor re-evaluate
                if milestone in reached:
                    break
                reached.add(milestone)
                recorded = self._rungs[milestone]
                recorded.append(score)
                # top 1/rf cutoff among scores seen at this rung
                k = max(1, int(len(recorded) / self._rf))
                cutoff = sorted(recorded, reverse=True)[k - 1]
                if score < cutoff:
                    return STOP
                break
        return CONTINUE


class _Bracket:
    """One synchronous successive-halving bracket: rungs at
    r, r*eta, r*eta^2, ... <= max_t; each rung keeps the top 1/eta."""

    def __init__(self, r0: int, max_t: int, eta: float, size: int):
        self.size = size  # trials this bracket admits
        self.eta = eta
        self.max_t = max_t
        self.rungs: List[int] = []
        r = max(1, int(r0))
        while r < max_t:
            self.rungs.append(r)
            r = int(math.ceil(r * eta))
        self.members: List[str] = []
        self.rung_idx: Dict[str, int] = {}  # trial -> next rung index
        self.recorded: Dict[int, Dict[str, float]] = {i: {} for i in range(len(self.rungs))}
        self.resumable: set = set()
        self.doomed: set = set()
        self.done: set = set()  # completed/errored trials
        self.closed = False  # no more members will join
        self.decided: set = set()  # rung indices already cut
        self.cutoffs: Dict[int, float] = {}  # rung -> lowest promoted score

    @property
    def full(self) -> bool:
        return self.closed or len(self.members) >= self.size

    def milestone_for(self, trial_id: str) -> Optional[int]:
        i = self.rung_idx.get(trial_id, 0)
        return self.rungs[i] if i < len(self.rungs) else None

    def record(self, trial_id: str, score: float):
        i = self.rung_idx.get(trial_id, 0)
        self.resumable.discard(trial_id)
        if i in self.decided:
            # Late arrival at an already-cut rung (restored trial): judge
            # against the cutoff that the original cut established.
            if score >= self.cutoffs.get(i, float("-inf")):
                self.rung_idx[trial_id] = i + 1
                self.resumable.add(trial_id)
            else:
                self.doomed.add(trial_id)
            return
        self.recorded[i][trial_id] = score

    def try_promote(self):
        """If the lowest undecided rung has every live member recorded,
        promote its top 1/eta and doom the rest. Each rung is cut exactly
        once (``decided``); doomed trials never re-enter the pool."""
        for i in range(len(self.rungs)):
            if i in self.decided:
                continue
            waiting = {
                t: s for t, s in self.recorded[i].items()
                if t not in self.done and t not in self.doomed
                and self.rung_idx.get(t, 0) == i
            }
            expected = [
                t for t in self.members
                if t not in self.done and t not in self.doomed
                and self.rung_idx.get(t, 0) == i
            ]
            if not expected:
                continue
            if not self.full or len(waiting) < len(expected):
                return  # rung not decidable yet
            ranked = sorted(waiting, key=waiting.get, reverse=True)
            keep = max(1, int(len(ranked) / self.eta))
            self.decided.add(i)
            self.cutoffs[i] = waiting[ranked[keep - 1]]
            for t in ranked[:keep]:
                self.rung_idx[t] = i + 1
                self.resumable.add(t)
            for t in ranked[keep:]:
                self.doomed.add(t)
            return


class HyperBandScheduler(TrialScheduler):
    """Synchronous HyperBand (reference: tune/schedulers/hyperband.py).

    Brackets s = s_max..0 trade off #configs vs budget: bracket s starts
    n = ceil((s_max+1)/(s+1) * eta^s) trials at r = max_t * eta^-s.
    Unlike ASHA, halving waits for the whole rung (trials PAUSE at
    milestones; the resume gate releases winners once the rung fills)."""

    def __init__(
        self,
        time_attr: str = "training_iteration",
        max_t: int = 81,
        reduction_factor: float = 3,
    ):
        self._time_attr = time_attr
        self._max_t = max_t
        self._eta = reduction_factor
        s_max = int(math.log(max_t) / math.log(reduction_factor))
        self._brackets: List[_Bracket] = []
        for s in range(s_max, -1, -1):
            n = int(math.ceil((s_max + 1) / (s + 1) * reduction_factor**s))
            r0 = max_t * reduction_factor**-s
            self._brackets.append(_Bracket(int(math.ceil(r0)), max_t, reduction_factor, n))
        self._trial_bracket: Dict[str, _Bracket] = {}

    def _bracket_of(self, trial: Trial) -> _Bracket:
        b = self._trial_bracket.get(trial.trial_id)
        if b is None:
            b = next((bk for bk in self._brackets if not bk.full), self._brackets[-1])
            b.members.append(trial.trial_id)
            self._trial_bracket[trial.trial_id] = b
        return b

    def on_trial_result(self, trial: Trial, result: dict) -> str:
        b = self._bracket_of(trial)
        if trial.trial_id in b.doomed:
            return STOP
        t = result.get(self._time_attr, trial.iteration)
        if t >= self._max_t:
            return STOP
        milestone = b.milestone_for(trial.trial_id)
        if milestone is None:
            return CONTINUE
        if t >= milestone:
            b.record(trial.trial_id, self._score(result))
            b.try_promote()
            if trial.trial_id in b.doomed:
                return STOP
            if trial.trial_id in b.resumable:
                b.resumable.discard(trial.trial_id)
                return CONTINUE  # promoted instantly, keep running
            # Pausing kills the actor; without a checkpoint the trial
            # would restart from step 0 with a stale iteration count
            # (reference: HyperBand requires checkpointable trainables).
            # Keep unchecked trials running — they are reaped via the
            # doomed fast-path on their next report once the rung is cut.
            if trial.checkpoint_dir is None:
                return CONTINUE
            return PAUSE
        return CONTINUE

    def on_trial_pending_resume(self, trial: Trial) -> str:
        known = trial.trial_id in self._trial_bracket
        b = self._bracket_of(trial)
        if not known and trial.results:
            # Restored experiment: this scheduler instance never scored the
            # trial — resume it and let it re-enter at its next milestone.
            return CONTINUE
        b.try_promote()
        if trial.trial_id in b.doomed:
            return STOP
        if trial.trial_id in b.resumable:
            b.resumable.discard(trial.trial_id)
            return CONTINUE
        return PAUSE

    def on_trial_complete(self, trial: Trial, result: Optional[dict]):
        b = self._trial_bracket.get(trial.trial_id)
        if b is not None:
            b.done.add(trial.trial_id)
            b.try_promote()

    def on_search_exhausted(self):
        for b in self._brackets:
            b.closed = True
            b.try_promote()


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best result is below the median of running
    averages (reference: tune/schedulers/median_stopping_rule.py)."""

    def __init__(self, time_attr: str = "training_iteration", grace_period: int = 1, min_samples_required: int = 3):
        self._time_attr = time_attr
        self._grace = grace_period
        self._min_samples = min_samples_required
        self._avgs: Dict[str, List[float]] = {}

    def on_trial_result(self, trial: Trial, result: dict) -> str:
        scores = self._avgs.setdefault(trial.trial_id, [])
        scores.append(self._score(result))
        t = result.get(self._time_attr, trial.iteration)
        if t < self._grace or len(self._avgs) < self._min_samples:
            return CONTINUE
        my_avg = sum(scores) / len(scores)
        others = [sum(v) / len(v) for k, v in self._avgs.items() if k != trial.trial_id and v]
        if len(others) < self._min_samples - 1:
            return CONTINUE
        others.sort()
        median = others[len(others) // 2]
        return STOP if my_avg < median else CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference: tune/schedulers/pbt.py): every
    ``perturbation_interval`` iterations, bottom-quantile trials PAUSE,
    clone the checkpoint+config of a top-quantile trial (exploit) and
    perturb hyperparameters (explore), then resume."""

    def __init__(
        self,
        time_attr: str = "training_iteration",
        perturbation_interval: int = 5,
        hyperparam_mutations: Optional[Dict[str, Any]] = None,
        quantile_fraction: float = 0.25,
        resample_probability: float = 0.25,
        seed: Optional[int] = None,
    ):
        self._time_attr = time_attr
        self._interval = perturbation_interval
        self._mutations = hyperparam_mutations or {}
        self._quantile = quantile_fraction
        self._resample_prob = resample_probability
        self._rng = random.Random(seed)
        self._last_perturb: Dict[str, int] = {}
        self._population: Dict[str, Trial] = {}
        self._exploit_from: Dict[str, Trial] = {}

    def on_trial_result(self, trial: Trial, result: dict) -> str:
        self._population[trial.trial_id] = trial
        t = result.get(self._time_attr, trial.iteration)
        last = self._last_perturb.get(trial.trial_id, 0)
        if t - last < self._interval:
            return CONTINUE
        self._last_perturb[trial.trial_id] = t
        trials = [tr for tr in self._population.values() if tr.last_result]
        if len(trials) < 2:
            return CONTINUE
        trials.sort(key=lambda tr: self._score(tr.last_result), reverse=True)
        k = max(1, int(len(trials) * self._quantile))
        top, bottom = trials[:k], trials[-k:]
        if trial in bottom and trial not in top:
            donor = self._rng.choice(top)
            if donor.checkpoint_dir is not None:
                self._exploit_from[trial.trial_id] = donor
                return PAUSE
        return CONTINUE

    def choose_config(self, trial: Trial) -> Optional[Dict[str, Any]]:
        donor = self._exploit_from.pop(trial.trial_id, None)
        if donor is None:
            return None
        # exploit: clone donor config + checkpoint; explore: perturb
        cfg = dict(donor.config)
        trial.checkpoint_dir = donor.checkpoint_dir
        for k, spec in self._mutations.items():
            if self._rng.random() < self._resample_prob:
                cfg[k] = spec() if callable(spec) else self._rng.choice(spec)
            elif isinstance(cfg.get(k), (int, float)):
                factor = self._rng.choice([0.8, 1.2])
                cfg[k] = cfg[k] * factor
                if isinstance(donor.config[k], int):
                    cfg[k] = max(1, int(round(cfg[k])))
        return cfg


class PB2(PopulationBasedTraining):
    """PBT with a GP-bandit explore step (reference:
    tune/schedulers/pb2.py, Parker-Holder et al. 2020): instead of
    random perturbation, new hyperparameters maximize a GP-UCB
    acquisition fit on (config -> score delta) from population history.
    ``hyperparam_bounds`` maps names to (low, high) continuous bounds."""

    def __init__(
        self,
        time_attr: str = "training_iteration",
        perturbation_interval: int = 5,
        hyperparam_bounds: Optional[Dict[str, tuple]] = None,
        quantile_fraction: float = 0.25,
        seed: Optional[int] = None,
    ):
        super().__init__(
            time_attr=time_attr,
            perturbation_interval=perturbation_interval,
            hyperparam_mutations={},
            quantile_fraction=quantile_fraction,
            seed=seed,
        )
        self._bounds = hyperparam_bounds or {}
        # (normalized hyperparam vector, score *improvement*) history —
        # PB2 models the per-interval delta, not the raw score, so late
        # observations don't dominate just because training ran longer
        self._observations: List[tuple] = []
        self._prev_score: Dict[str, float] = {}

    def _normalize(self, cfg: Dict[str, Any]):
        xs = []
        for k, (lo, hi) in self._bounds.items():
            v = float(cfg.get(k, lo))
            xs.append((v - lo) / max(hi - lo, 1e-12))
        return xs

    def on_trial_result(self, trial: Trial, result: dict) -> str:
        if all(k in trial.config for k in self._bounds):
            score = self._score(result)
            prev = self._prev_score.get(trial.trial_id)
            self._prev_score[trial.trial_id] = score
            if prev is not None:
                self._observations.append(
                    (self._normalize(trial.config), score - prev)
                )
                self._observations = self._observations[-256:]
        return super().on_trial_result(trial, result)

    _ELL = 0.3  # RBF length scale

    def _gp_fit(self, X, y):
        """Candidate-independent part of the GP posterior: kernel Cholesky
        + weights, computed once per exploit step (not per candidate)."""
        import numpy as np

        X = np.asarray(X, dtype=float)
        y = np.asarray(y, dtype=float)
        if len(y) == 0:
            return None
        y = (y - y.mean()) / (y.std() + 1e-9)
        K = np.exp(
            -0.5 * ((X[:, None, :] - X[None, :, :]) ** 2).sum(-1) / self._ELL**2
        )
        K += 1e-3 * np.eye(len(X))
        L = np.linalg.cholesky(K)
        # alpha = K^-1 y via two triangular solves
        from numpy.linalg import solve

        alpha = solve(L.T, solve(L, y))
        return X, L, alpha

    def _gp_ucb_fit(self, cand, fit, beta: float = 2.0) -> float:
        import numpy as np
        from numpy.linalg import solve

        if fit is None:
            return 0.0
        X, L, alpha = fit
        c = np.asarray(cand, dtype=float)
        k_star = np.exp(-0.5 * ((X - c[None, :]) ** 2).sum(-1) / self._ELL**2)
        mu = k_star @ alpha
        v = solve(L, k_star)
        var = max(1e-9, 1.0 - v @ v)
        return float(mu + beta * math.sqrt(var))

    def _gp_ucb(self, cand, X, y, beta: float = 2.0) -> float:
        """GP posterior UCB with an RBF kernel (pure numpy; the reference
        uses a time-varying kernel — the stationary RBF is the core)."""
        return self._gp_ucb_fit(cand, self._gp_fit(X, y), beta)

    def choose_config(self, trial: Trial) -> Optional[Dict[str, Any]]:
        donor = self._exploit_from.pop(trial.trial_id, None)
        if donor is None:
            return None
        # The trial restarts from the donor's checkpoint: its next score is
        # discontinuous, so the first post-exploit delta must not be recorded.
        self._prev_score.pop(trial.trial_id, None)
        cfg = dict(donor.config)
        trial.checkpoint_dir = donor.checkpoint_dir
        if self._bounds:
            fit = self._gp_fit(
                [o[0] for o in self._observations],
                [o[1] for o in self._observations],
            )
            best, best_ucb = None, float("-inf")
            for _ in range(64):  # random-search acquisition maximization
                cand = [self._rng.random() for _ in self._bounds]
                ucb = self._gp_ucb_fit(cand, fit)
                if ucb > best_ucb:
                    best, best_ucb = cand, ucb
            for (k, (lo, hi)), v in zip(self._bounds.items(), best):
                val = lo + v * (hi - lo)
                if isinstance(donor.config.get(k), int):
                    val = max(1, int(round(val)))
                cfg[k] = val
        return cfg


class DistributeResources:
    """Default allocation policy for ResourceChangingScheduler
    (reference: tune/schedulers/resource_changing_scheduler.py
    DistributeResources): spread the cluster's CPUs evenly over the
    currently-RUNNING trials, never dropping below the experiment's base
    request. Returns None when the trial's allocation is already right.
    """

    def __call__(self, controller, trial, result, scheduler):
        import ray_tpu

        base_res = dict(controller._cfg.resources_per_trial or {})
        total = ray_tpu.cluster_resources().get("CPU", 1)
        base = base_res.get("num_cpus", 1) or 1
        running = [t for t in controller._trials if t.status == "RUNNING"] or [trial]
        share = max(base, int(total // max(len(running), 1)))
        # Merge OVER the experiment base so non-CPU keys (num_tpus,
        # custom resources) survive the first resize.
        merged = {**base_res, **(trial.resources or {})}
        if share != merged.get("num_cpus", base):
            return {**merged, "num_cpus": share}
        return None


class ResourceChangingScheduler(TrialScheduler):
    """Reallocate trial resources mid-experiment (reference:
    tune/schedulers/resource_changing_scheduler.py:592): wraps a base
    scheduler; after each result the ``resources_allocation_function``
    (signature ``fn(tune_controller, trial, result, scheduler)``, the
    reference's) may return a new resource dict for the trial. A changed
    request PAUSEs the trial (checkpoint-based, like PBT exploit) and it
    resumes on its new allocation."""

    def __init__(self, base_scheduler: Optional[TrialScheduler] = None,
                 resources_allocation_function=DistributeResources()):
        self._base = base_scheduler or FIFOScheduler()
        self._fn = resources_allocation_function
        self._controller = None

    def set_search_properties(self, metric: str, mode: str):
        super().set_search_properties(metric, mode)
        self._base.set_search_properties(metric, mode)

    def set_tune_controller(self, controller):
        self._controller = controller

    def on_trial_result(self, trial: Trial, result: dict) -> str:
        decision = self._base.on_trial_result(trial, result)
        if decision == CONTINUE and self._fn is not None:
            new = self._fn(self._controller, trial, result, self)
            if new and dict(new) != (trial.resources or {}):
                trial.resources = dict(new)
                return PAUSE  # resume lands on the new allocation
        return decision

    def on_trial_complete(self, trial: Trial, result: Optional[dict]):
        self._base.on_trial_complete(trial, result)

    def choose_config(self, trial: Trial) -> Optional[Dict[str, Any]]:
        return self._base.choose_config(trial)

    def on_trial_pending_resume(self, trial: Trial) -> str:
        return self._base.on_trial_pending_resume(trial)

    def on_search_exhausted(self):
        self._base.on_search_exhausted()
