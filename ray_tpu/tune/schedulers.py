"""Trial schedulers: FIFO, ASHA, median-stopping, PBT.

Reference: python/ray/tune/schedulers/ — async_hyperband.py (ASHA),
median_stopping_rule.py, pbt.py. The scheduler sees every reported result
and decides CONTINUE/STOP/PAUSE; PBT additionally mutates paused trials'
configs (exploit+explore) before they resume.
"""
from __future__ import annotations

import math
import random
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.tune.trial import Trial

CONTINUE = "CONTINUE"
STOP = "STOP"
PAUSE = "PAUSE"


class TrialScheduler:
    def set_search_properties(self, metric: str, mode: str):
        self._metric = metric
        self._mode = mode

    def _score(self, result: dict) -> float:
        v = result.get(self._metric, float("-inf"))
        return v if self._mode == "max" else -v

    def on_trial_result(self, trial: Trial, result: dict) -> str:
        return CONTINUE

    def on_trial_complete(self, trial: Trial, result: Optional[dict]):
        pass

    def choose_config(self, trial: Trial) -> Optional[Dict[str, Any]]:
        """PBT hook: new config for a resuming trial (None = unchanged)."""
        return None


class FIFOScheduler(TrialScheduler):
    pass


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA (reference: tune/schedulers/async_hyperband.py): successive
    halving with rungs at grace_period * reduction_factor^k; a trial
    reaching a rung is stopped unless it is in the top 1/reduction_factor
    of results recorded at that rung."""

    def __init__(
        self,
        time_attr: str = "training_iteration",
        grace_period: int = 1,
        max_t: int = 100,
        reduction_factor: float = 4,
    ):
        self._time_attr = time_attr
        self._grace = grace_period
        self._max_t = max_t
        self._rf = reduction_factor
        # rung milestones: grace, grace*rf, grace*rf^2, ... < max_t
        self._rungs: Dict[int, List[float]] = {}
        self._reached: Dict[str, set] = {}  # trial_id -> rungs already recorded
        t = grace_period
        while t < max_t:
            self._rungs[int(t)] = []
            t *= reduction_factor

    def on_trial_result(self, trial: Trial, result: dict) -> str:
        t = result.get(self._time_attr, trial.iteration)
        if t >= self._max_t:
            return STOP
        score = self._score(result)
        reached = self._reached.setdefault(trial.trial_id, set())
        for milestone in sorted(self._rungs, reverse=True):
            if t >= milestone:
                # one entry per trial per rung — re-reports between
                # milestones neither re-record nor re-evaluate
                if milestone in reached:
                    break
                reached.add(milestone)
                recorded = self._rungs[milestone]
                recorded.append(score)
                # top 1/rf cutoff among scores seen at this rung
                k = max(1, int(len(recorded) / self._rf))
                cutoff = sorted(recorded, reverse=True)[k - 1]
                if score < cutoff:
                    return STOP
                break
        return CONTINUE


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best result is below the median of running
    averages (reference: tune/schedulers/median_stopping_rule.py)."""

    def __init__(self, time_attr: str = "training_iteration", grace_period: int = 1, min_samples_required: int = 3):
        self._time_attr = time_attr
        self._grace = grace_period
        self._min_samples = min_samples_required
        self._avgs: Dict[str, List[float]] = {}

    def on_trial_result(self, trial: Trial, result: dict) -> str:
        scores = self._avgs.setdefault(trial.trial_id, [])
        scores.append(self._score(result))
        t = result.get(self._time_attr, trial.iteration)
        if t < self._grace or len(self._avgs) < self._min_samples:
            return CONTINUE
        my_avg = sum(scores) / len(scores)
        others = [sum(v) / len(v) for k, v in self._avgs.items() if k != trial.trial_id and v]
        if len(others) < self._min_samples - 1:
            return CONTINUE
        others.sort()
        median = others[len(others) // 2]
        return STOP if my_avg < median else CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference: tune/schedulers/pbt.py): every
    ``perturbation_interval`` iterations, bottom-quantile trials PAUSE,
    clone the checkpoint+config of a top-quantile trial (exploit) and
    perturb hyperparameters (explore), then resume."""

    def __init__(
        self,
        time_attr: str = "training_iteration",
        perturbation_interval: int = 5,
        hyperparam_mutations: Optional[Dict[str, Any]] = None,
        quantile_fraction: float = 0.25,
        resample_probability: float = 0.25,
        seed: Optional[int] = None,
    ):
        self._time_attr = time_attr
        self._interval = perturbation_interval
        self._mutations = hyperparam_mutations or {}
        self._quantile = quantile_fraction
        self._resample_prob = resample_probability
        self._rng = random.Random(seed)
        self._last_perturb: Dict[str, int] = {}
        self._population: Dict[str, Trial] = {}
        self._exploit_from: Dict[str, Trial] = {}

    def on_trial_result(self, trial: Trial, result: dict) -> str:
        self._population[trial.trial_id] = trial
        t = result.get(self._time_attr, trial.iteration)
        last = self._last_perturb.get(trial.trial_id, 0)
        if t - last < self._interval:
            return CONTINUE
        self._last_perturb[trial.trial_id] = t
        trials = [tr for tr in self._population.values() if tr.last_result]
        if len(trials) < 2:
            return CONTINUE
        trials.sort(key=lambda tr: self._score(tr.last_result), reverse=True)
        k = max(1, int(len(trials) * self._quantile))
        top, bottom = trials[:k], trials[-k:]
        if trial in bottom and trial not in top:
            donor = self._rng.choice(top)
            if donor.checkpoint_dir is not None:
                self._exploit_from[trial.trial_id] = donor
                return PAUSE
        return CONTINUE

    def choose_config(self, trial: Trial) -> Optional[Dict[str, Any]]:
        donor = self._exploit_from.pop(trial.trial_id, None)
        if donor is None:
            return None
        # exploit: clone donor config + checkpoint; explore: perturb
        cfg = dict(donor.config)
        trial.checkpoint_dir = donor.checkpoint_dir
        for k, spec in self._mutations.items():
            if self._rng.random() < self._resample_prob:
                cfg[k] = spec() if callable(spec) else self._rng.choice(spec)
            elif isinstance(cfg.get(k), (int, float)):
                factor = self._rng.choice([0.8, 1.2])
                cfg[k] = cfg[k] * factor
                if isinstance(donor.config[k], int):
                    cfg[k] = max(1, int(round(cfg[k])))
        return cfg
