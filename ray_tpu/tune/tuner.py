"""Tuner + TuneController: the experiment event loop.

Reference: python/ray/tune/tuner.py:44 (Tuner.fit :344) and
tune/execution/tune_controller.py:68 (step :666 — schedule trial actors,
poll results, drive scheduler decisions, save/restore trials). Trials run
as TrialRunner actors; one in-flight ``next_result`` call per running
trial keeps the control loop non-blocking.
"""
from __future__ import annotations

import json
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train.trainer import Result

logger = logging.getLogger("ray_tpu.tune")
from ray_tpu.tune import trial as trial_mod
from ray_tpu.tune.schedulers import CONTINUE, PAUSE, STOP, FIFOScheduler, TrialScheduler
from ray_tpu.tune.search import PENDING_SUGGESTION, BasicVariantGenerator, Searcher
from ray_tpu.tune.trial import ERROR, PAUSED, PENDING, RUNNING, TERMINATED, Trial, TrialRunner
from ray_tpu.utils.serialization import serialize_function


@dataclass
class TuneConfig:
    """Reference: tune/tune_config.py."""

    metric: str = "score"
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: int = 0  # 0 → resource-bound
    scheduler: Optional[TrialScheduler] = None
    search_alg: Optional[Searcher] = None
    seed: Optional[int] = None
    max_failures: int = 0
    resources_per_trial: Dict[str, float] = field(default_factory=lambda: {"num_cpus": 1})
    # Reuse cleanly-finished TrialRunner actors for new trials
    # (reference: tune/tune.py:297 reuse_actors) — skips per-trial
    # process spawns, the dominant cost on spawn-bound hosts.
    reuse_actors: bool = False


class ResultGrid:
    """Reference: tune/result_grid.py."""

    def __init__(self, trials: List[Trial], metric: str, mode: str):
        self.trials = trials
        self._metric = metric
        self._mode = mode

    def get_best_result(self, metric: Optional[str] = None, mode: Optional[str] = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        best: Optional[Trial] = None
        best_v = None
        for t in self.trials:
            v = t.metric(metric)
            if v is None:
                continue
            if best is None or (v > best_v if mode == "max" else v < best_v):
                best, best_v = t, v
        if best is None:
            raise RuntimeError("no trial reported the metric " + metric)
        from ray_tpu.train.checkpoint import Checkpoint

        return Result(
            metrics=best.last_result,
            checkpoint=Checkpoint(best.checkpoint_dir) if best.checkpoint_dir else None,
            path=best.checkpoint_dir or "",
            metrics_history=best.results,
        )

    def get_dataframe(self):
        import pandas as pd

        return pd.DataFrame([t.last_result for t in self.trials if t.last_result])

    @property
    def num_errors(self) -> int:
        return sum(1 for t in self.trials if t.status == ERROR)

    def __len__(self):
        return len(self.trials)

    def __getitem__(self, i):
        t = self.trials[i]
        from ray_tpu.train.checkpoint import Checkpoint

        return Result(
            metrics=t.last_result,
            checkpoint=Checkpoint(t.checkpoint_dir) if t.checkpoint_dir else None,
            path=t.checkpoint_dir or "",
            metrics_history=t.results,
        )


def _json_np(o):
    """numpy scalars/arrays in metrics/configs must not kill _save_state."""
    import numpy as _np

    if isinstance(o, _np.generic):
        return o.item()
    if isinstance(o, _np.ndarray):
        return o.tolist()
    return repr(o)


class TuneController:
    def __init__(
        self,
        trainable: Callable,
        param_space: Dict[str, Any],
        tune_config: TuneConfig,
        experiment_dir: str,
        restore_state: Optional[dict] = None,
    ):
        from ray_tpu.utils import cloudfs

        self._fn_blob = serialize_function(trainable)
        self._cfg = tune_config
        self._dir = experiment_dir
        # Cloud experiment dirs (reference: storage_path via pyarrow.fs):
        # tuner state + reported checkpoints persist to the URI; trials
        # get a LOCAL scratch working dir.
        self._dir_is_uri = cloudfs.is_uri(experiment_dir)
        cloudfs.makedirs(experiment_dir)
        if self._dir_is_uri:
            import hashlib
            import tempfile

            tag = hashlib.blake2s(experiment_dir.encode()).hexdigest()[:12]
            self._scratch = os.path.join(
                tempfile.gettempdir(), "ray_tpu", "tune_scratch", tag
            )
            os.makedirs(self._scratch, exist_ok=True)
        else:
            self._scratch = experiment_dir
        self._searcher = tune_config.search_alg or BasicVariantGenerator(
            param_space, tune_config.num_samples, seed=tune_config.seed
        )
        self._searcher.set_search_properties(tune_config.metric, tune_config.mode)
        self._scheduler = tune_config.scheduler or FIFOScheduler()
        self._scheduler.set_search_properties(tune_config.metric, tune_config.mode)
        # ResourceChangingScheduler needs the controller to size shares.
        if hasattr(self._scheduler, "set_tune_controller"):
            self._scheduler.set_tune_controller(self)
        self._actor_cache: List[Any] = []  # finished runners for reuse
        self._trials: List[Trial] = []
        self._pending_result: Dict[str, Any] = {}  # trial_id -> in-flight ref
        self._exhausted = False
        self._next_id = 0
        self._state_dirty = True
        if restore_state:
            self._load_state(restore_state)
            # Skip searcher variants already materialized as trials before
            # the interruption. Searchers that model the config→metric
            # relationship (TPE/BayesOpt) take the real restored pair via
            # observe(); for positional searchers the suggest/complete
            # replay keeps their counters and live-slot accounting right.
            for t in self._trials:
                if hasattr(self._searcher, "observe"):
                    self._searcher.observe(t.trial_id, t.config, t.last_result)
                else:
                    self._searcher.suggest(t.trial_id)
                    self._searcher.on_trial_complete(t.trial_id, t.last_result)
                # A trial interrupted without a checkpoint restarts from
                # scratch — stale history would feed schedulers an inflated
                # time_attr and duplicate metrics_history.
                if not t.is_finished and t.checkpoint_dir is None:
                    t.iteration = 0
                    t.results = []
                    t.last_result = None

    # -- experiment state (save/resume; reference:
    # tune/execution/experiment_state.py) ---------------------------------
    def _save_state(self):
        if not self._state_dirty:
            return
        self._state_dirty = False
        state = {
            "trials": [
                dict(
                    trial_id=t.trial_id,
                    config=t.config,
                    status=t.status if t.is_finished else PENDING,
                    last_result=t.last_result,
                    results=t.results,
                    error=t.error,
                    checkpoint_dir=t.checkpoint_dir,
                    iteration=t.iteration,
                )
                for t in self._trials
            ],
            "exhausted": self._exhausted,
            "next_id": self._next_id,
        }
        from ray_tpu.utils import cloudfs

        if self._dir_is_uri:
            cloudfs.write_text(
                cloudfs.join(self._dir, "tuner_state.json"),
                json.dumps(state, default=_json_np),
            )  # object PUT is atomic
            return
        tmp = os.path.join(self._dir, ".tuner_state.json.tmp")
        with open(tmp, "w") as f:
            json.dump(state, f, default=_json_np)
        os.replace(tmp, os.path.join(self._dir, "tuner_state.json"))

    def _load_state(self, state: dict):
        for td in state["trials"]:
            t = Trial(trial_id=td["trial_id"], config=td["config"])
            t.status = td["status"]
            t.last_result = td["last_result"]
            t.results = td["results"]
            t.error = td["error"]
            t.checkpoint_dir = td["checkpoint_dir"]
            t.iteration = td["iteration"]
            self._trials.append(t)
        self._exhausted = state["exhausted"]
        self._next_id = state["next_id"]

    # -- trial lifecycle ---------------------------------------------------
    def _max_concurrent(self) -> int:
        if self._cfg.max_concurrent_trials:
            return self._cfg.max_concurrent_trials
        cpus_per = self._cfg.resources_per_trial.get("num_cpus", 1) or 1
        total = ray_tpu.cluster_resources().get("CPU", 1)
        return max(1, int(total // cpus_per))

    def _start_trial(self, t: Trial, restore: bool = False):
        # Per-trial override (ResourceChangingScheduler) wins over the
        # experiment default.
        res = t.resources or self._cfg.resources_per_trial
        new_cfg = self._scheduler.choose_config(t)
        if new_cfg is not None:
            t.config = new_cfg
        from ray_tpu.utils import cloudfs

        remote_dir = cloudfs.join(self._dir, t.trial_id) if self._dir_is_uri else None
        local_dir = os.path.join(self._scratch, t.trial_id)
        ckpt = t.checkpoint_dir if restore else None
        # reuse_actors: only default-resourced trials share runners (a
        # cached runner holds the default allocation).
        if (
            self._cfg.reuse_actors
            and self._actor_cache
            and res == self._cfg.resources_per_trial
        ):
            t.actor = self._actor_cache.pop()
            t.actor.reset.remote(t.config, local_dir, ckpt, remote_dir=remote_dir)
        else:
            runner_cls = ray_tpu.remote(
                num_cpus=res.get("num_cpus", 1),
                num_tpus=res.get("num_tpus", 0),
                resources={k: v for k, v in res.items() if k not in ("num_cpus", "num_tpus")},
                max_restarts=0,
            )(TrialRunner)
            t.actor = runner_cls.remote(
                self._fn_blob, t.config, local_dir, ckpt, remote_dir=remote_dir
            )
        t.status = RUNNING
        self._state_dirty = True
        self._pending_result[t.trial_id] = t.actor.next_result.remote()

    def _stop_trial(self, t: Trial, status: str, error: Optional[str] = None):
        if t.actor is not None:
            try:
                ray_tpu.kill(t.actor)
            except Exception:
                pass
            t.actor = None
        self._pending_result.pop(t.trial_id, None)
        t.status = status
        t.error = error
        self._state_dirty = True
        if t.is_finished:
            self._searcher.on_trial_complete(t.trial_id, t.last_result, error=status == ERROR)
            self._scheduler.on_trial_complete(t, t.last_result)

    def _maybe_create_trials(self):
        live = sum(1 for t in self._trials if t.status == RUNNING)
        cap = self._max_concurrent()
        # resume paused/pending-restored trials first; synchronous
        # schedulers (HyperBand) can hold a paused trial until its bracket
        # rung fills, or terminate it without resuming
        for t in self._trials:
            if live >= cap:
                return
            if t.status == PAUSED or (t.status == PENDING and t.actor is None and t.results):
                verdict = self._scheduler.on_trial_pending_resume(t)
                if verdict == STOP:
                    self._stop_trial(t, TERMINATED)
                    continue
                if verdict == PAUSE:
                    continue
                self._start_trial(t, restore=True)
                live += 1
        for t in self._trials:
            if live >= cap:
                return
            if t.status == PENDING and t.actor is None:
                self._start_trial(t, restore=t.checkpoint_dir is not None)
                live += 1
        while not self._exhausted and live < cap:
            tid = f"trial_{self._next_id:05d}"
            cfg = self._searcher.suggest(tid)
            if cfg is None:
                self._exhausted = True
                # synchronous schedulers stop waiting for bracket mates
                # that will never arrive
                self._scheduler.on_search_exhausted()
                return
            if cfg is PENDING_SUGGESTION:
                return
            self._next_id += 1
            t = Trial(trial_id=tid, config=cfg)
            self._trials.append(t)
            self._start_trial(t)
            live += 1

    def _process_result(self, t: Trial, payload: Optional[dict]):
        if payload is None:  # trainable returned
            # Cleanly-finished runner (fn thread exited): cache it for the
            # next trial instead of killing the process. Only default-
            # resourced runners are cacheable (see _start_trial).
            if (
                self._cfg.reuse_actors
                and t.actor is not None
                and (t.resources or self._cfg.resources_per_trial)
                == self._cfg.resources_per_trial
            ):
                self._actor_cache.append(t.actor)
                t.actor = None  # _stop_trial must not kill it
            self._stop_trial(t, TERMINATED)
            return
        metrics = payload["metrics"]
        t.iteration += 1
        metrics.setdefault("training_iteration", t.iteration)
        metrics.setdefault("trial_id", t.trial_id)
        metrics.setdefault("config", t.config)
        t.last_result = metrics
        t.results.append(metrics)
        self._state_dirty = True
        if payload.get("checkpoint"):
            t.checkpoint_dir = payload["checkpoint"]
        decision = self._scheduler.on_trial_result(t, metrics)
        if decision == STOP:
            self._stop_trial(t, TERMINATED)
        elif decision == PAUSE:
            t.paused_at_iteration = t.iteration
            self._stop_trial(t, PAUSED)
        else:
            self._pending_result[t.trial_id] = t.actor.next_result.remote()

    def _handle_failure(self, t: Trial, err: Exception):
        t.num_failures += 1
        if t.num_failures <= self._cfg.max_failures:
            # retry, restoring from the last checkpoint (reference:
            # tune_controller.py:1791 trial restore)
            self._pending_result.pop(t.trial_id, None)
            if t.actor is not None:
                try:
                    ray_tpu.kill(t.actor)
                except Exception as e:  # noqa: BLE001 — trial actor already dead
                    logger.debug("trial actor kill failed: %s", e)
                t.actor = None
            self._start_trial(t, restore=t.checkpoint_dir is not None)
        else:
            self._stop_trial(t, ERROR, error=str(err))

    def step(self) -> bool:
        """One controller tick; True when the experiment is done."""
        self._maybe_create_trials()
        if self._pending_result:
            refs = list(self._pending_result.values())
            ready, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=0.1)
            ready_set = set(ready)
            for t in list(self._trials):
                ref = self._pending_result.get(t.trial_id)
                if ref is None or ref not in ready_set:
                    continue
                del self._pending_result[t.trial_id]
                try:
                    payload = ray_tpu.get(ref)
                except Exception as e:  # trial actor died / fn raised
                    self._handle_failure(t, e)
                    continue
                self._process_result(t, payload)
        else:
            time.sleep(0.05)
        self._save_state()
        return self._exhausted and all(
            t.is_finished for t in self._trials
        ) and not self._pending_result

    def run(self) -> List[Trial]:
        try:
            while not self.step():
                pass
        finally:
            for actor in self._actor_cache:
                try:
                    ray_tpu.kill(actor)
                except Exception as e:  # noqa: BLE001 — already dead
                    logger.debug("cached trial actor kill failed: %s", e)
            self._actor_cache.clear()
        return self._trials


class Tuner:
    """Reference: python/ray/tune/tuner.py:44."""

    def __init__(
        self,
        trainable: Callable,
        *,
        param_space: Optional[Dict[str, Any]] = None,
        tune_config: Optional[TuneConfig] = None,
        run_config: Optional[Any] = None,
        _experiment_dir: Optional[str] = None,
        _restore_state: Optional[dict] = None,
    ):
        self._trainable = trainable
        self._param_space = param_space or {}
        self._tune_config = tune_config or TuneConfig()
        name = getattr(run_config, "name", None) or f"tune_{int(time.time())}"
        storage = getattr(run_config, "storage_path", None) or os.path.expanduser(
            "~/ray_tpu_results"
        )
        self._dir = _experiment_dir or os.path.join(storage, name)
        self._restore_state = _restore_state

    def fit(self) -> ResultGrid:
        ctrl = TuneController(
            self._trainable,
            self._param_space,
            self._tune_config,
            self._dir,
            restore_state=self._restore_state,
        )
        trials = ctrl.run()
        return ResultGrid(trials, self._tune_config.metric, self._tune_config.mode)

    @classmethod
    def restore(
        cls,
        path: str,
        trainable: Callable,
        *,
        param_space: Optional[Dict[str, Any]] = None,
        tune_config: Optional[TuneConfig] = None,
    ) -> "Tuner":
        """Resume an interrupted experiment from its directory (reference:
        Tuner.restore). Unfinished trials restart (from their last
        checkpoint when one was reported). ``param_space`` must be re-passed
        when the search was not yet exhausted, so remaining variants can
        still be generated."""
        from ray_tpu.utils import cloudfs

        state = json.loads(
            cloudfs.read_text(cloudfs.join(path, "tuner_state.json"))
        )
        return cls(
            trainable,
            param_space=param_space,
            tune_config=tune_config,
            _experiment_dir=path,
            _restore_state=state,
        )
