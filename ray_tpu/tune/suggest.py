"""Model-based search algorithms: TPE and GP-based Bayesian optimization.

Reference: python/ray/tune/search/ ships these as external-library
wrappers (hyperopt → TPE, bayesopt → GP-EI, optuna → TPE by default).
There is no external dependency to wrap here, so the algorithms are
implemented natively in numpy against the same ``Searcher`` interface —
functionally covering the hyperopt/optuna/bayesopt searcher family.

Both searchers optimize over the same search-space primitives
(Uniform/LogUniform/Randint/Choice) by mapping every dimension to a unit
hypercube internally; Choice dimensions are one-hot-scored (TPE) or
indicator-embedded (GP).
"""
from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.tune.search import (
    Choice,
    Domain,
    GridSearch,
    LogUniform,
    Randint,
    Searcher,
    Uniform,
)


class _Space:
    """Search space ↔ unit-hypercube codec."""

    def __init__(self, param_space: Dict[str, Any]):
        self.fixed: Dict[str, Any] = {}
        self.dims: List[Tuple[str, Any]] = []
        for k, v in param_space.items():
            if isinstance(v, GridSearch):
                raise ValueError("grid_search is not supported by model-based searchers")
            if isinstance(v, (Uniform, LogUniform, Randint, Choice)):
                self.dims.append((k, v))
            elif isinstance(v, Domain):
                raise ValueError(f"unsupported domain for model-based search: {k}")
            else:
                self.fixed[k] = v

    @property
    def ndim(self) -> int:
        return len(self.dims)

    def decode(self, u: np.ndarray) -> Dict[str, Any]:
        cfg = dict(self.fixed)
        for x, (k, dom) in zip(u, self.dims):
            if isinstance(dom, Uniform):
                cfg[k] = dom.low + x * (dom.high - dom.low)
            elif isinstance(dom, LogUniform):
                cfg[k] = math.exp(dom.lo + x * (dom.hi - dom.lo))
            elif isinstance(dom, Randint):
                cfg[k] = min(dom.high - 1, int(dom.low + x * (dom.high - dom.low)))
            elif isinstance(dom, Choice):
                n = len(dom.categories)
                cfg[k] = dom.categories[min(n - 1, int(x * n))]
        return cfg

    def encode(self, cfg: Dict[str, Any]) -> Optional[np.ndarray]:
        """Inverse of decode (experiment-restore path): a concrete config
        back to unit-cube coordinates. None if any dimension is absent."""
        u = np.zeros(len(self.dims))
        for i, (k, dom) in enumerate(self.dims):
            if k not in cfg:
                return None
            v = cfg[k]
            if isinstance(dom, Uniform):
                span = dom.high - dom.low
                u[i] = (v - dom.low) / span if span else 0.5
            elif isinstance(dom, LogUniform):
                span = dom.hi - dom.lo
                u[i] = (math.log(v) - dom.lo) / span if span else 0.5
            elif isinstance(dom, Randint):
                span = dom.high - dom.low
                u[i] = (v - dom.low + 0.5) / span if span else 0.5
            elif isinstance(dom, Choice):
                try:
                    idx = dom.categories.index(v)
                except ValueError:
                    return None
                u[i] = (idx + 0.5) / len(dom.categories)
        return np.clip(u, 0.0, 1.0)


class TPESearcher(Searcher):
    """Tree-structured Parzen Estimator (the hyperopt/optuna-default
    algorithm; Bergstra et al. 2011). Observations are split into good/bad
    by the γ-quantile; candidates maximize the density ratio l(x)/g(x)
    under per-dimension Parzen (KDE) estimates in the unit cube."""

    def __init__(
        self,
        param_space: Dict[str, Any],
        metric: str = "loss",
        mode: str = "min",
        n_startup: int = 10,
        n_candidates: int = 24,
        gamma: float = 0.25,
        num_samples: int = 64,
        seed: Optional[int] = None,
    ):
        self._space = _Space(param_space)
        self.metric, self.mode = metric, mode
        self.n_startup = n_startup
        self.n_candidates = n_candidates
        self.gamma = gamma
        self.num_samples = num_samples
        self._suggested = 0
        self._rng = np.random.default_rng(seed)
        self._live: Dict[str, np.ndarray] = {}
        self._obs: List[Tuple[np.ndarray, float]] = []

    def set_search_properties(self, metric: Optional[str], mode: Optional[str]):
        if metric:
            self.metric = metric
        if mode:
            self.mode = mode

    # -- KDE machinery ----------------------------------------------------
    def _kde_logpdf(self, pts: np.ndarray, x: np.ndarray) -> float:
        """Sum over dims of log Parzen density at x given points [n, d].

        Per-dimension Scott's-rule bandwidth (σ_j · n^{-1/5}): a tight good
        set gets a sharp density while a spread-out bad set stays broad —
        fixed bandwidths let boundary effects dominate the l/g ratio."""
        n, d = pts.shape
        total = 0.0
        for j in range(d):
            sd = float(pts[:, j].std())
            bw = max(1e-2, (sd if sd > 1e-6 else 0.1) * max(1, n) ** -0.2)
            z = (x[j] - pts[:, j]) / bw
            comp = np.exp(-0.5 * z * z) / (bw * math.sqrt(2 * math.pi))
            total += math.log(max(float(comp.mean()), 1e-12))
        return total

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._suggested >= self.num_samples:
            return None
        self._suggested += 1
        d = self._space.ndim
        if len(self._obs) < self.n_startup or d == 0:
            u = self._rng.uniform(size=d)
        else:
            scores = np.array([s for _, s in self._obs])
            order = np.argsort(scores)  # ascending; minimize internally
            n_good = max(1, min(int(self.gamma * len(order)), len(order) - 1))
            if n_good < 1 or len(order) - n_good < 1:
                # Not enough observations to form both densities.
                u = self._rng.uniform(size=d)
            else:
                good = np.stack([self._obs[i][0] for i in order[:n_good]])
                bad = np.stack([self._obs[i][0] for i in order[n_good:]])
                # Candidates drawn around good points; pick max l(x)/g(x).
                best_u, best_score = None, -np.inf
                for _ in range(self.n_candidates):
                    center = good[self._rng.integers(len(good))]
                    cand = np.clip(
                        center + self._rng.normal(0, 0.2, size=d), 0.0, 1.0
                    )
                    score = self._kde_logpdf(good, cand) - self._kde_logpdf(bad, cand)
                    if score > best_score:
                        best_u, best_score = cand, score
                u = best_u
        self._live[trial_id] = u
        return self._space.decode(u)

    def on_trial_complete(self, trial_id: str, result: Optional[dict] = None, error: bool = False):
        u = self._live.pop(trial_id, None)
        if u is None or error or not result or self.metric not in result:
            return
        val = float(result[self.metric])
        self._obs.append((u, val if self.mode == "min" else -val))

    def observe(self, trial_id: str, config: Dict[str, Any], result: Optional[dict]):
        """Experiment-restore path: feed a restored (config, metric) pair
        into the model without generating a suggestion."""
        self._suggested += 1
        if not result or self.metric not in result:
            return
        u = self._space.encode(config)
        if u is not None:
            val = float(result[self.metric])
            self._obs.append((u, val if self.mode == "min" else -val))


class BayesOptSearcher(Searcher):
    """GP + Expected Improvement (reference: tune/search/bayesopt wraps
    bayes_opt; same algorithm natively). Squared-exponential kernel GP
    posterior over the unit cube; suggestions maximize EI over random
    candidates."""

    def __init__(
        self,
        param_space: Dict[str, Any],
        metric: str = "loss",
        mode: str = "min",
        n_startup: int = 8,
        n_candidates: int = 256,
        length_scale: float = 0.25,
        noise: float = 1e-4,
        num_samples: int = 64,
        seed: Optional[int] = None,
    ):
        self._space = _Space(param_space)
        self.metric, self.mode = metric, mode
        self.n_startup = n_startup
        self.n_candidates = n_candidates
        self.num_samples = num_samples
        self._suggested = 0
        self.ls = length_scale
        self.noise = noise
        self._rng = np.random.default_rng(seed)
        self._live: Dict[str, np.ndarray] = {}
        self._X: List[np.ndarray] = []
        self._y: List[float] = []

    def set_search_properties(self, metric: Optional[str], mode: Optional[str]):
        if metric:
            self.metric = metric
        if mode:
            self.mode = mode

    def _kernel(self, A: np.ndarray, B: np.ndarray) -> np.ndarray:
        d2 = ((A[:, None, :] - B[None, :, :]) ** 2).sum(-1)
        return np.exp(-0.5 * d2 / (self.ls**2))

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._suggested >= self.num_samples:
            return None
        self._suggested += 1
        d = self._space.ndim
        if len(self._y) < self.n_startup or d == 0:
            u = self._rng.uniform(size=d)
        else:
            X = np.stack(self._X)
            y = np.asarray(self._y)
            mu_y, sd_y = y.mean(), max(y.std(), 1e-8)
            yn = (y - mu_y) / sd_y
            K = self._kernel(X, X) + self.noise * np.eye(len(X))
            try:
                L = np.linalg.cholesky(K)
                alpha = np.linalg.solve(L.T, np.linalg.solve(L, yn))
            except np.linalg.LinAlgError:
                alpha = np.linalg.lstsq(K, yn, rcond=None)[0]
                L = None
            cand = self._rng.uniform(size=(self.n_candidates, d))
            Ks = self._kernel(cand, X)  # [c, n]
            mu = Ks @ alpha
            if L is not None:
                v = np.linalg.solve(L, Ks.T)
                var = np.clip(1.0 - (v**2).sum(0), 1e-12, None)
            else:
                var = np.full(len(cand), 1e-2)
            sd = np.sqrt(var)
            best = yn.min()
            z = (best - mu) / sd
            # EI for minimization of the normalized objective.
            from math import erf

            cdf = 0.5 * (1.0 + np.vectorize(erf)(z / math.sqrt(2)))
            pdf = np.exp(-0.5 * z * z) / math.sqrt(2 * math.pi)
            ei = sd * (z * cdf + pdf)
            u = cand[int(np.argmax(ei))]
        self._live[trial_id] = u
        return self._space.decode(u)

    def on_trial_complete(self, trial_id: str, result: Optional[dict] = None, error: bool = False):
        u = self._live.pop(trial_id, None)
        if u is None or error or not result or self.metric not in result:
            return
        val = float(result[self.metric])
        self._X.append(u)
        self._y.append(val if self.mode == "min" else -val)

    def observe(self, trial_id: str, config: Dict[str, Any], result: Optional[dict]):
        """Experiment-restore path (see TPESearcher.observe)."""
        self._suggested += 1
        if not result or self.metric not in result:
            return
        u = self._space.encode(config)
        if u is not None:
            val = float(result[self.metric])
            self._X.append(u)
            self._y.append(val if self.mode == "min" else -val)


class Repeater(Searcher):
    """Runs each underlying suggestion ``repeat`` times and reports the
    averaged metric to the wrapped searcher (reference:
    tune/search/repeater.py)."""

    def __init__(self, searcher: Searcher, repeat: int = 3, metric: str = "loss"):
        self.searcher = searcher
        self.repeat = repeat
        self.metric = metric
        self._group_of: Dict[str, str] = {}
        self._groups: Dict[str, dict] = {}

    def set_search_properties(self, metric: Optional[str], mode: Optional[str]):
        if metric:
            self.metric = metric
        self.searcher.set_search_properties(metric, mode)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        for gid, g in self._groups.items():
            if len(g["members"]) < self.repeat:
                g["members"].append(trial_id)
                self._group_of[trial_id] = gid
                return dict(g["config"])
        cfg = self.searcher.suggest(trial_id)
        if cfg is None or not isinstance(cfg, dict):
            return cfg
        gid = trial_id
        self._groups[gid] = {
            "config": dict(cfg),
            "members": [trial_id],
            "results": [],
            "finished": 0,
        }
        self._group_of[trial_id] = gid
        return cfg

    def on_trial_complete(self, trial_id: str, result: Optional[dict] = None, error: bool = False):
        gid = self._group_of.pop(trial_id, None)
        if gid is None or gid not in self._groups:
            return
        g = self._groups[gid]
        g["finished"] += 1
        if not error and result and self.metric in result:
            g["results"].append(float(result[self.metric]))
        if g["finished"] >= self.repeat:
            # Report once every member is accounted for, even if some
            # errored; all-errored groups report an error so the wrapped
            # searcher can release the suggestion.
            if g["results"]:
                avg = {self.metric: float(np.mean(g["results"]))}
                self.searcher.on_trial_complete(gid, avg, error=False)
            else:
                self.searcher.on_trial_complete(gid, None, error=True)
            del self._groups[gid]


class BOHBSearcher(TPESearcher):
    """BOHB's model-based half (Falkner et al. 2018; reference:
    tune/search/bohb wraps hpbandster): TPE/KDE models maintained *per
    fidelity*; suggestions come from the highest budget that has enough
    observations, so early low-fidelity results guide the search until
    full-budget data accumulates. Pair with ``HyperBandScheduler`` for
    the bandit half (the reference pairs TuneBOHB with HyperBandForBOHB).
    """

    def __init__(
        self,
        param_space: Dict[str, Any],
        metric: str = "loss",
        mode: str = "min",
        time_attr: str = "training_iteration",
        min_points_in_model: int = 6,
        **kw,
    ):
        # The parent's n_startup gates model activation on len(self._obs);
        # align it with min_points_in_model so the per-budget model turns
        # on exactly when a budget has enough points (unless the caller
        # overrides n_startup explicitly).
        kw.setdefault("n_startup", min_points_in_model)
        super().__init__(param_space, metric=metric, mode=mode, **kw)
        self._time_attr = time_attr
        self._min_points = min_points_in_model
        self._obs_by_budget: Dict[int, List[Tuple[np.ndarray, float]]] = {}

    def on_trial_complete(self, trial_id: str, result: Optional[dict] = None, error: bool = False):
        u = self._live.pop(trial_id, None)
        if u is None or error or not result or self.metric not in result:
            return
        budget = int(result.get(self._time_attr, 1))
        val = float(result[self.metric])
        self._obs_by_budget.setdefault(budget, []).append(
            (u, val if self.mode == "min" else -val)
        )

    def observe(self, trial_id: str, config: Dict[str, Any], result: Optional[dict]):
        self._suggested += 1
        if not result or self.metric not in result:
            return
        u = self._space.encode(config)
        if u is not None:
            budget = int(result.get(self._time_attr, 1))
            val = float(result[self.metric])
            self._obs_by_budget.setdefault(budget, []).append(
                (u, val if self.mode == "min" else -val)
            )

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        # model = highest budget with enough points (BOHB's rule)
        self._obs = []
        for budget in sorted(self._obs_by_budget, reverse=True):
            pts = self._obs_by_budget[budget]
            if len(pts) >= self._min_points:
                self._obs = pts
                break
        return super().suggest(trial_id)


class EvolutionarySearcher(Searcher):
    """Differential evolution in the unit cube — the native stand-in for
    the reference's evolutionary/derivative-free wrappers (nevergrad,
    zoopt: tune/search/nevergrad.py, zoopt.py). DE/rand/1/bin: trial
    vector a + F·(b−c) with binomial crossover against a population
    member; better offspring replace their targets."""

    def __init__(
        self,
        param_space: Dict[str, Any],
        metric: str = "loss",
        mode: str = "min",
        population_size: int = 10,
        mutation: float = 0.6,
        crossover: float = 0.8,
        num_samples: int = 64,
        seed: Optional[int] = None,
    ):
        if population_size < 3:
            raise ValueError("EvolutionarySearcher needs population_size >= 3 (DE/rand/1)")
        self._space = _Space(param_space)
        self.metric, self.mode = metric, mode
        self._pop_size = population_size
        self._f = mutation
        self._cr = crossover
        self.num_samples = num_samples
        self._rng = np.random.default_rng(seed)
        self._suggested = 0
        self._live: Dict[str, Tuple[np.ndarray, Optional[int]]] = {}  # u, target idx
        self._pop: List[np.ndarray] = []
        self._fit: List[float] = []
        self._next_target = 0

    def set_search_properties(self, metric: Optional[str], mode: Optional[str]):
        if metric:
            self.metric = metric
        if mode:
            self.mode = mode

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._suggested >= self.num_samples:
            return None
        self._suggested += 1
        d = self._space.ndim
        if len(self._pop) < self._pop_size or d == 0:
            u = self._rng.uniform(size=d)
            self._live[trial_id] = (u, None)
            return self._space.decode(u)
        target = self._next_target % len(self._pop)
        self._next_target += 1
        a, b, c = self._rng.choice(len(self._pop), size=3, replace=False)
        mutant = np.clip(self._pop[a] + self._f * (self._pop[b] - self._pop[c]), 0, 1)
        cross = self._rng.uniform(size=d) < self._cr
        cross[self._rng.integers(d)] = True  # at least one mutant dim
        u = np.where(cross, mutant, self._pop[target])
        self._live[trial_id] = (u, target)
        return self._space.decode(u)

    def on_trial_complete(self, trial_id: str, result: Optional[dict] = None, error: bool = False):
        entry = self._live.pop(trial_id, None)
        if entry is None or error or not result or self.metric not in result:
            return
        u, target = entry
        val = float(result[self.metric])
        score = val if self.mode == "min" else -val
        if len(self._pop) < self._pop_size:
            self._pop.append(u)
            self._fit.append(score)
        elif target is not None and score <= self._fit[target]:
            self._pop[target] = u
            self._fit[target] = score

    def observe(self, trial_id: str, config: Dict[str, Any], result: Optional[dict]):
        self._suggested += 1
        u = self._space.encode(config)
        if u is None or not result or self.metric not in result:
            return
        val = float(result[self.metric])
        score = val if self.mode == "min" else -val
        if len(self._pop) < self._pop_size:
            self._pop.append(u)
            self._fit.append(score)
