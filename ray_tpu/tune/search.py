"""Search spaces + search algorithms.

Reference: python/ray/tune/search/ — sample-space primitives
(tune/search/sample.py), BasicVariantGenerator (grid/random,
tune/search/basic_variant.py), ConcurrencyLimiter, Repeater. The external
searcher integrations (hyperopt/optuna/...) are out of capability scope;
the Searcher interface is the plug point.
"""
from __future__ import annotations

import itertools
import random
from typing import Any, Callable, Dict, List, Optional


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Uniform(Domain):
    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class LogUniform(Domain):
    def __init__(self, low: float, high: float):
        import math

        self.lo, self.hi = math.log(low), math.log(high)

    def sample(self, rng):
        import math

        return math.exp(rng.uniform(self.lo, self.hi))


class Randint(Domain):
    def __init__(self, low: int, high: int):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class Choice(Domain):
    def __init__(self, categories: List[Any]):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class Function(Domain):
    def __init__(self, fn: Callable[[], Any]):
        self.fn = fn

    def sample(self, rng):
        return self.fn()


class GridSearch:
    def __init__(self, values: List[Any]):
        self.values = list(values)


def uniform(low, high) -> Uniform:
    return Uniform(low, high)


def loguniform(low, high) -> LogUniform:
    return LogUniform(low, high)


def randint(low, high) -> Randint:
    return Randint(low, high)


def choice(categories) -> Choice:
    return Choice(categories)


def sample_from(fn) -> Function:
    return Function(fn)


def grid_search(values) -> GridSearch:
    return GridSearch(values)


# Sentinel returned by back-pressuring searchers when no slot is free
# (compare by identity: ``cfg is PENDING_SUGGESTION``).
PENDING_SUGGESTION = "__pending__"


class Searcher:
    """Pluggable suggestion interface (reference: tune/search/searcher.py)."""

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str, result: Optional[dict], error: bool = False):
        pass

    def set_search_properties(self, metric: Optional[str], mode: Optional[str]):
        pass


class BasicVariantGenerator(Searcher):
    """Grid-cross-product × num_samples random sampling (reference:
    tune/search/basic_variant.py)."""

    def __init__(self, param_space: Dict[str, Any], num_samples: int = 1, seed: Optional[int] = None):
        self._rng = random.Random(seed)
        self._variants = self._expand(param_space, num_samples)
        self._i = 0

    def _expand(self, space: Dict[str, Any], num_samples: int) -> List[Dict[str, Any]]:
        grid_keys = [k for k, v in space.items() if isinstance(v, GridSearch)]
        grids = [space[k].values for k in grid_keys]
        variants = []
        for _ in range(num_samples):
            for combo in itertools.product(*grids) if grids else [()]:
                cfg = {}
                for k, v in space.items():
                    if isinstance(v, GridSearch):
                        cfg[k] = combo[grid_keys.index(k)]
                    elif isinstance(v, Domain):
                        cfg[k] = v.sample(self._rng)
                    else:
                        cfg[k] = v
                variants.append(cfg)
        return variants

    @property
    def total_trials(self) -> int:
        return len(self._variants)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._i >= len(self._variants):
            return None
        cfg = self._variants[self._i]
        self._i += 1
        return cfg


class ConcurrencyLimiter(Searcher):
    """Caps concurrent suggestions (reference:
    tune/search/concurrency_limiter.py)."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set = set()

    def suggest(self, trial_id: str):
        if len(self._live) >= self.max_concurrent:
            return PENDING_SUGGESTION
        cfg = self.searcher.suggest(trial_id)
        if cfg is not None and cfg is not PENDING_SUGGESTION:
            self._live.add(trial_id)
        return cfg

    def on_trial_complete(self, trial_id: str, result=None, error: bool = False):
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result, error)
