"""Runtime environments: per-task/actor execution environments.

Reference: python/ray/_private/runtime_env/ — a plugin system (env_vars,
working_dir, py_modules, pip, conda, container, mpi, nsight) applied by a
per-node agent; the raylet keys idle workers by runtime-env hash so
environments never cross-contaminate (src/ray/raylet/worker_pool.h:174).

Rebuild: the same two pieces, trimmed to what a TPU pod needs —

- a **plugin registry** (:func:`register_plugin`): each key in the env dict
  maps to a setup function applied inside the worker process before the
  first task of that env runs. Built-ins: ``env_vars``, ``working_dir``,
  ``py_modules``, ``config``. ``pip``/``conda`` raise
  :class:`RuntimeEnvSetupError` — workers share the host interpreter and
  the fleet has no package egress; bake deps into the image (the TPU-pod
  deployment model) or use ``py_modules`` with local paths.
- **worker affinity by env hash**: the controller only dispatches an
  env-tagged task to a worker already in that env or to a pristine worker
  (which then becomes env-tagged) — reference behavior, collapsed into the
  central scheduler.

Env application is sticky per worker (the reference dedicates workers the
same way); a worker never switches between two non-empty envs.
"""
from __future__ import annotations

import hashlib
import json
import os
import sys
from typing import Any, Callable, Dict, Optional

from ray_tpu.exceptions import RuntimeEnvSetupError

_INTERNAL_KEYS = {"__actor_name__", "__trace_ctx__"}

_plugins: Dict[str, Callable[[Any], None]] = {}


def register_plugin(key: str, setup: Callable[[Any], None]):
    """Register a runtime-env key handler (reference: RuntimeEnvPlugin)."""
    _plugins[key] = setup


class RuntimeEnv(dict):
    """Validated runtime-env mapping (reference: ray.runtime_env.RuntimeEnv)."""

    def __init__(
        self,
        *,
        env_vars: Optional[Dict[str, str]] = None,
        working_dir: Optional[str] = None,
        py_modules: Optional[list] = None,
        config: Optional[dict] = None,
        **extra,
    ):
        super().__init__()
        if env_vars is not None:
            if not all(isinstance(k, str) and isinstance(v, str) for k, v in env_vars.items()):
                raise ValueError("env_vars must be a str→str mapping")
            self["env_vars"] = dict(env_vars)
        if working_dir is not None:
            self["working_dir"] = working_dir
        if py_modules is not None:
            self["py_modules"] = list(py_modules)
        if config is not None:
            self["config"] = dict(config)
        for k, v in extra.items():
            if k not in _plugins and k not in ("pip", "conda"):
                raise ValueError(f"unknown runtime_env key: {k!r}")
            self[k] = v


def strip_internal(env: Optional[dict]) -> dict:
    return {k: v for k, v in (env or {}).items() if k not in _INTERNAL_KEYS}


def env_hash(env: Optional[dict]) -> str:
    """Stable hash keying worker reuse (reference: worker_pool runtime-env
    hash in the lease request)."""
    e = strip_internal(env)
    if not e:
        return ""
    blob = json.dumps(e, sort_keys=True, default=str).encode()
    return hashlib.blake2b(blob, digest_size=8).hexdigest()


# ---------------------------------------------------------------------------
# Built-in plugins (applied inside the worker process)
# ---------------------------------------------------------------------------
def _setup_env_vars(value: Dict[str, str]):
    os.environ.update(value)


def _setup_working_dir(value: str):
    # Local-path working dirs only: in the single-image TPU-pod deployment
    # all hosts share the filesystem layout, so there is no URI
    # upload/download step (reference's GCS packaging,
    # _private/runtime_env/working_dir.py, is an artifact of heterogeneous
    # clusters). Zip archives are extracted beside the session.
    path = value
    if path.endswith(".zip"):
        import tempfile
        import zipfile

        dest = tempfile.mkdtemp(prefix="rt_env_wd_")
        with zipfile.ZipFile(path) as z:
            z.extractall(dest)
        path = dest
    if not os.path.isdir(path):
        raise RuntimeEnvSetupError(f"working_dir does not exist: {value}")
    os.chdir(path)
    sys.path.insert(0, path)


def _setup_py_modules(value: list):
    for mod in value:
        if not os.path.exists(mod):
            raise RuntimeEnvSetupError(f"py_modules path does not exist: {mod}")
        parent = mod if os.path.isdir(mod) else os.path.dirname(mod)
        if parent not in sys.path:
            sys.path.insert(0, parent)


def _setup_config(value: dict):
    pass  # setup-timeout etc.; carried for API parity


def _setup_unsupported(kind: str):
    def fail(value):
        raise RuntimeEnvSetupError(
            f"runtime_env[{kind!r}] is not supported: workers share the host "
            "interpreter and TPU fleets run hermetic images with no package "
            "egress. Bake dependencies into the image, or ship local code "
            "with py_modules/working_dir."
        )

    return fail


register_plugin("env_vars", _setup_env_vars)
register_plugin("working_dir", _setup_working_dir)
register_plugin("py_modules", _setup_py_modules)
register_plugin("config", _setup_config)
register_plugin("pip", _setup_unsupported("pip"))
register_plugin("conda", _setup_unsupported("conda"))

# ---------------------------------------------------------------------------
# Worker-side application
# ---------------------------------------------------------------------------
_applied_hash: Optional[str] = None


def ensure_applied(env: Optional[dict]):
    """Apply ``env`` in this worker once; sticky thereafter.

    The controller's env-affinity dispatch guarantees we are only ever
    asked to apply one non-empty env per worker lifetime.
    """
    global _applied_hash
    h = env_hash(env)
    if not h or h == _applied_hash:
        return
    if _applied_hash is not None and _applied_hash != h:
        raise RuntimeEnvSetupError(
            "worker already holds a different runtime env (scheduler bug)"
        )
    for key, value in strip_internal(env).items():
        plugin = _plugins.get(key)
        if plugin is None:
            raise RuntimeEnvSetupError(f"no plugin for runtime_env key {key!r}")
        plugin(value)
    _applied_hash = h
