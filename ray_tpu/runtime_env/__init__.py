"""Runtime environments: per-task/actor execution environments.

Reference: python/ray/_private/runtime_env/ — a plugin system (env_vars,
working_dir, py_modules, pip, conda, container, mpi, nsight) applied by a
per-node agent; the raylet keys idle workers by runtime-env hash so
environments never cross-contaminate (src/ray/raylet/worker_pool.h:174).

Rebuild: the same two pieces, trimmed to what a TPU pod needs —

- a **plugin registry** (:func:`register_plugin`): each key in the env dict
  maps to a setup function applied inside the worker process before the
  first task of that env runs. Built-ins: ``env_vars``, ``working_dir``,
  ``py_modules``, ``config``, and ``pip`` (per-hash ``pip install
  --target`` — offline-capable with local wheels/dirs or gs:// wheels;
  see :func:`_setup_pip`). ``image_uri`` launches the WORKER ITSELF
  inside a container image (spawn-time, not in-process — see
  ray_tpu/runtime_env/container.py; env hashes prefix ``img:`` so the
  scheduler never lets a pristine host worker adopt one). ``conda``
  raises :class:`RuntimeEnvSetupError` — workers share the host
  interpreter; use ``image_uri`` (or bake deps into the pod image).
- **worker affinity by env hash**: the controller only dispatches an
  env-tagged task to a worker already in that env or to a pristine worker
  (which then becomes env-tagged) — reference behavior, collapsed into the
  central scheduler.

Env application is sticky per worker (the reference dedicates workers the
same way); a worker never switches between two non-empty envs.
"""
from __future__ import annotations

import hashlib
import json
import os
import sys
from typing import Any, Callable, Dict, Optional

from ray_tpu.exceptions import RuntimeEnvSetupError

_INTERNAL_KEYS = {"__actor_name__", "__trace_ctx__"}

_plugins: Dict[str, Callable[[Any], None]] = {}


def register_plugin(key: str, setup: Callable[[Any], None]):
    """Register a runtime-env key handler (reference: RuntimeEnvPlugin)."""
    _plugins[key] = setup


class RuntimeEnv(dict):
    """Validated runtime-env mapping (reference: ray.runtime_env.RuntimeEnv)."""

    def __init__(
        self,
        *,
        env_vars: Optional[Dict[str, str]] = None,
        working_dir: Optional[str] = None,
        py_modules: Optional[list] = None,
        config: Optional[dict] = None,
        image_uri: Optional[str] = None,
        **extra,
    ):
        super().__init__()
        if image_uri is not None:
            if not isinstance(image_uri, str) or not image_uri:
                raise ValueError("image_uri must be a non-empty string")
            self["image_uri"] = image_uri
        if env_vars is not None:
            if not all(isinstance(k, str) and isinstance(v, str) for k, v in env_vars.items()):
                raise ValueError("env_vars must be a str→str mapping")
            self["env_vars"] = dict(env_vars)
        if working_dir is not None:
            self["working_dir"] = working_dir
        if py_modules is not None:
            self["py_modules"] = list(py_modules)
        if config is not None:
            self["config"] = dict(config)
        for k, v in extra.items():
            if k not in _plugins and k not in ("pip", "conda"):
                raise ValueError(f"unknown runtime_env key: {k!r}")
            self[k] = v


def strip_internal(env: Optional[dict]) -> dict:
    return {k: v for k, v in (env or {}).items() if k not in _INTERNAL_KEYS}


def env_hash(env: Optional[dict]) -> str:
    """Stable hash keying worker reuse (reference: worker_pool runtime-env
    hash in the lease request). Container envs hash with an ``img:``
    prefix — the scheduler uses it to require spawn-time (exact-match)
    workers instead of letting a pristine host worker adopt the env."""
    e = strip_internal(env)
    if not e:
        return ""
    blob = json.dumps(e, sort_keys=True, default=str).encode()
    digest = hashlib.blake2b(blob, digest_size=8).hexdigest()
    return f"img:{digest}" if e.get("image_uri") else digest


# ---------------------------------------------------------------------------
# Built-in plugins (applied inside the worker process)
# ---------------------------------------------------------------------------
def _setup_env_vars(value: Dict[str, str]):
    os.environ.update(value)


def _setup_working_dir(value: str):
    # Local-path working dirs only: in the single-image TPU-pod deployment
    # all hosts share the filesystem layout, so there is no URI
    # upload/download step (reference's GCS packaging,
    # _private/runtime_env/working_dir.py, is an artifact of heterogeneous
    # clusters). Zip archives are extracted beside the session.
    path = value
    if path.endswith(".zip"):
        import tempfile
        import zipfile

        dest = tempfile.mkdtemp(prefix="rt_env_wd_")
        with zipfile.ZipFile(path) as z:
            z.extractall(dest)
        path = dest
    if not os.path.isdir(path):
        raise RuntimeEnvSetupError(f"working_dir does not exist: {value}")
    os.chdir(path)
    sys.path.insert(0, path)


def _setup_py_modules(value: list):
    for mod in value:
        if not os.path.exists(mod):
            raise RuntimeEnvSetupError(f"py_modules path does not exist: {mod}")
        parent = mod if os.path.isdir(mod) else os.path.dirname(mod)
        if parent not in sys.path:
            sys.path.insert(0, parent)


def _setup_config(value: dict):
    pass  # setup-timeout etc.; carried for API parity


def _setup_pip(value):
    """Per-env-hash pip install into a --target directory prepended to
    sys.path (reference: _private/runtime_env/pip.py builds a venv per
    env; workers here share the interpreter, so a target dir gives the
    same isolation-by-precedence at a fraction of the cost).

    Specs may be package names (needs an index — TPU fleets usually run
    hermetic, so expect local use), LOCAL paths (wheels or source dirs;
    built with --no-build-isolation against the image's setuptools —
    fully offline), or gs://-style URIs staged through cloudfs. The
    install runs once per unique spec list; concurrent workers wait on
    the winner (reference: the runtime-env agent's per-URI refcounts)."""
    import hashlib
    import json as _json
    import subprocess
    import tempfile
    import time

    if isinstance(value, dict):
        packages = list(value.get("packages", []))
        extra_args = list(value.get("pip_install_options", []))
    else:
        packages = list(value)
        extra_args = []
    if not packages:
        return
    def _spec_key(spec: str):
        # local specs key on (path, mtime, size) so a rebuilt wheel or
        # edited source dir gets a fresh env instead of the stale cache
        # (per-file content hashing is the reference's heavier answer);
        # dir mtime only tracks top-level changes — `touch` the dir after
        # deep edits, or bump the package version.
        try:
            st = os.stat(spec)
            return [spec, int(st.st_mtime_ns), st.st_size]
        except OSError:
            return [spec]

    digest = hashlib.blake2s(
        _json.dumps([sorted(map(_spec_key, packages)), sorted(extra_args)]).encode()
    ).hexdigest()[:16]
    base = os.path.join(tempfile.gettempdir(), "ray_tpu", "pip_envs")
    root = os.path.join(base, digest)
    done = os.path.join(root, ".done")
    lock = root + ".lock"
    while not os.path.exists(done):
        os.makedirs(base, exist_ok=True)
        try:
            os.close(os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY))
            owner = True
        except FileExistsError:
            # stale lock from a crashed owner (OOM-killed mid-install)
            # must not wedge the env forever — take it over past the
            # staleness horizon
            try:
                # live owners heartbeat the lock mtime every 5s, so 120s
                # of silence really means a dead owner
                if time.time() - os.path.getmtime(lock) > 120:
                    os.unlink(lock)
                    continue
            except FileNotFoundError:
                continue  # owner just finished/failed — re-evaluate
            owner = False
        if owner:
            import threading

            # heartbeat thread keeps the lock mtime fresh through BOTH
            # staging and the pip run — the 120s takeover check must only
            # ever fire on a genuinely dead owner
            stop_hb = threading.Event()

            def _hb():
                while not stop_hb.is_set():
                    try:
                        os.utime(lock)
                    except OSError:
                        return
                    stop_hb.wait(5)

            threading.Thread(target=_hb, daemon=True).start()
            try:
                os.makedirs(root, exist_ok=True)
                staged = []
                for i, spec in enumerate(packages):
                    from ray_tpu.utils import cloudfs

                    if cloudfs.is_uri(spec):
                        # index prefix: same-basename URIs must not collide
                        local = os.path.join(
                            root, f"{i}-{os.path.basename(spec)}"
                        )
                        cloudfs.download_file(spec, local)  # streamed
                        staged.append(local)
                    else:
                        staged.append(spec)
                cmd = [
                    sys.executable, "-m", "pip", "install", "--quiet",
                    "--no-build-isolation",  # offline: ambient setuptools
                    "--target", root, *extra_args, *staged,
                ]
                r = subprocess.run(cmd, capture_output=True, text=True, timeout=600)
                if r.returncode != 0:
                    raise RuntimeEnvSetupError(
                        f"pip install failed: {r.stderr[-800:] or r.stdout[-800:]}"
                    )
                open(done, "w").close()
            finally:
                stop_hb.set()
                try:
                    os.unlink(lock)
                except FileNotFoundError:
                    pass
            break
        else:
            # waiter deadline keys off the owner's lock heartbeat (the
            # owner utimes the lock every 5s through BOTH gs:// staging —
            # which has no timeout of its own — and the pip run): a
            # slow-but-alive install is never failed. A stale heartbeat
            # means a dead owner: loop back to the acquisition path, whose
            # 120s takeover check unlinks the stale lock so THIS worker
            # finishes the install itself instead of failing its task.
            stale = False
            while not os.path.exists(done):
                try:
                    if time.time() - os.path.getmtime(lock) > 120:
                        stale = True
                        break
                except OSError:
                    pass  # lock vanished — the exists() checks decide
                if not os.path.exists(lock):
                    # owner exited: success wrote .done FIRST, so re-check
                    # it before declaring failure (TOCTOU)
                    if os.path.exists(done):
                        break
                    raise RuntimeEnvSetupError(
                        "concurrent pip env install failed (no .done marker)"
                    )
                time.sleep(0.25)
            if stale:
                continue
            break
    if root not in sys.path:
        sys.path.insert(0, root)


def _setup_unsupported(kind: str):
    def fail(value):
        raise RuntimeEnvSetupError(
            f"runtime_env[{kind!r}] is not supported: workers share the host "
            "interpreter and TPU fleets run hermetic images with no package "
            "egress. Bake dependencies into the image, or ship local code "
            "with py_modules/working_dir/pip (local wheels)."
        )

    return fail


def _setup_jax_profiler_hook(value):
    from ray_tpu.runtime_env.jax_profiler import _setup_jax_profiler

    _setup_jax_profiler(value)


def _setup_image_uri(value):
    # No-op INSIDE the worker: the image took effect at spawn time (the
    # node wrapped the worker command via the container runtime —
    # runtime_env/container.py); by the time a task applies its env, the
    # process is already in the image.
    pass


register_plugin("image_uri", _setup_image_uri)
register_plugin("env_vars", _setup_env_vars)
register_plugin("jax_profiler", _setup_jax_profiler_hook)
register_plugin("working_dir", _setup_working_dir)
register_plugin("py_modules", _setup_py_modules)
register_plugin("config", _setup_config)
register_plugin("pip", _setup_pip)
register_plugin("conda", _setup_unsupported("conda"))

# ---------------------------------------------------------------------------
# Worker-side application
# ---------------------------------------------------------------------------
_applied_hash: Optional[str] = None


def ensure_applied(env: Optional[dict]):
    """Apply ``env`` in this worker once; sticky thereafter.

    The controller's env-affinity dispatch guarantees we are only ever
    asked to apply one non-empty env per worker lifetime.
    """
    global _applied_hash
    h = env_hash(env)
    if not h or h == _applied_hash:
        return
    if _applied_hash is not None and _applied_hash != h:
        raise RuntimeEnvSetupError(
            "worker already holds a different runtime env (scheduler bug)"
        )
    for key, value in strip_internal(env).items():
        plugin = _plugins.get(key)
        if plugin is None:
            raise RuntimeEnvSetupError(f"no plugin for runtime_env key {key!r}")
        plugin(value)
    _applied_hash = h
