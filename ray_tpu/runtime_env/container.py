"""Container runtime env: launch workers inside an OCI image.

Reference: python/ray/_private/runtime_env/image_uri.py — the reference
wraps worker startup in ``podman run`` with the session tmpfs and shm
mounted so the containerized worker still speaks to the local raylet.
Same shape here: ``runtime_env={"image_uri": ...}`` makes the node spawn
that task/actor's worker via the container runtime, sharing the host
network, the session dir, and /dev/shm (the object-store arena), so the
worker participates in the cluster exactly like a host worker.

Differences from the reference, by design:
- The runtime binary is pluggable (``RAY_TPU_CONTAINER_RUNTIME``:
  ``podman`` | ``docker`` | any compatible shim). Tests inject a FAKE
  runtime (a script that records its argv and execs the worker command
  directly) the same way the autoscaler tests use the fake TPU API —
  CI needs no container daemon.
- Workers in images are spawned PRE-TAGGED with their runtime-env hash
  (``img:<digest>``, see ``env_hash``): a pristine host worker can never
  adopt a container env in-process, so the scheduler's usual
  pristine-adoption fallback is disabled for these hashes and matching
  is exact — the reference's worker-pool-keyed-by-env behavior.
- Image pulls are cached per node with the same lock-file protocol as
  pip envs (one puller, others wait).
"""
from __future__ import annotations

import hashlib
import logging
import os
import shlex
import shutil
import subprocess
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional

from ray_tpu.exceptions import RuntimeEnvSetupError

# Env vars forwarded into the container (the worker's cluster identity
# plus interpreter/TPU config).
_FORWARD_PREFIXES = (
    "RAY_TPU_", "PYTHON", "JAX_", "XLA_", "PALLAS_", "TPU_", "LD_LIBRARY",
)


def resolve_runtime() -> Optional[str]:
    rt = os.environ.get("RAY_TPU_CONTAINER_RUNTIME")
    if rt:
        return rt
    for cand in ("podman", "docker"):
        if shutil.which(cand):
            return cand
    return None


def _image_marker(rt: str, image_uri: str) -> str:
    digest = hashlib.blake2s(f"{rt}|{image_uri}".encode()).hexdigest()[:16]
    base = os.path.join(tempfile.gettempdir(), "ray_tpu", "images")
    os.makedirs(base, exist_ok=True)
    return os.path.join(base, digest + ".pulled")


def ensure_image(image_uri: str, runtime: Optional[str] = None, timeout: float = 600.0):
    """Pull ``image_uri`` once per node (lock-file cache; the puller
    heartbeats the lock mtime so waiters never mistake a slow-but-alive
    pull for a dead one). Preflight helper — the SPAWN path does not
    call this synchronously (see wrap_command: the pull runs inside the
    spawned command, off the control-plane loop)."""
    rt = runtime or resolve_runtime()
    if rt is None:
        raise RuntimeEnvSetupError(
            "runtime_env['image_uri'] requires a container runtime "
            "(podman/docker on PATH, or RAY_TPU_CONTAINER_RUNTIME)"
        )
    done = _image_marker(rt, image_uri)
    lock = done + ".lock"
    deadline = time.time() + timeout
    while not os.path.exists(done):
        try:
            os.close(os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY))
        except FileExistsError:
            if time.time() > deadline:
                raise RuntimeEnvSetupError(f"timed out waiting for pull of {image_uri}")
            # The live puller refreshes the lock mtime every 5s; only a
            # genuinely dead one goes silent long enough to take over.
            try:
                if time.time() - os.path.getmtime(lock) > 120:
                    os.unlink(lock)
            except FileNotFoundError:
                pass
            time.sleep(0.25)
            continue
        stop_hb = threading.Event()

        def _hb():
            while not stop_hb.is_set():
                try:
                    os.utime(lock)
                except OSError:
                    return
                stop_hb.wait(5)

        threading.Thread(target=_hb, daemon=True).start()
        try:
            r = subprocess.run(
                [rt, "pull", image_uri], capture_output=True, text=True,
                timeout=timeout,
            )
            if r.returncode != 0:
                raise RuntimeEnvSetupError(
                    f"{rt} pull {image_uri} failed: {r.stderr[-500:] or r.stdout[-500:]}"
                )
            open(done, "w").close()
        finally:
            stop_hb.set()
            try:
                os.unlink(lock)
            except FileNotFoundError:
                pass
    return rt


def wrap_command(
    image_uri: str,
    cmd: List[str],
    env: Dict[str, str],
    session_dir: str,
    shm_dir: str,
) -> List[str]:
    """Build the command that runs ``cmd`` inside ``image_uri`` with
    cluster plumbing mounted (host network for RPC, session dir for
    logs/sockets, shm dir for the object-store arena, and the framework
    source so the image need not bundle ray_tpu).

    The image pull happens INSIDE the spawned shell (cached via a
    per-node marker file), never on the caller: the controller/agent
    loop must not block minutes on a registry. The spawned shell calls
    back into ``ensure_image`` (``python -m ray_tpu.runtime_env.container``)
    so concurrent worker spawns share its lock/marker protocol — one
    puller, the rest wait — instead of N racing ``pull`` processes. A
    failed pull simply means the worker never registers — the
    scheduler's stale-spawn accounting retries.

    The in-container command uses the IMAGE's interpreter from PATH
    (reference: the reference's ``--entrypoint python``), overridable via
    ``RAY_TPU_CONTAINER_PYTHON`` — the host's absolute ``sys.executable``
    usually does not exist inside the image."""
    rt = resolve_runtime()
    if rt is None:
        raise RuntimeEnvSetupError(
            "runtime_env['image_uri'] requires a container runtime "
            "(podman/docker on PATH, or RAY_TPU_CONTAINER_RUNTIME)"
        )
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    argv = [
        rt, "run", "--rm", "--network=host", "--ipc=host",
        "-v", f"{session_dir}:{session_dir}",
        "-v", f"{shm_dir}:{shm_dir}",
        "-v", f"{pkg_root}:{pkg_root}:ro",
    ]
    for k, v in env.items():
        if k.startswith(_FORWARD_PREFIXES):
            argv += ["-e", f"{k}={v}"]
    argv.append(image_uri)
    cmd = list(cmd)
    if cmd and (cmd[0] == sys.executable or (
        os.path.isabs(cmd[0]) and os.path.basename(cmd[0]).startswith("python")
    )):
        # python3, not python: many images (debian/ubuntu slim) ship only
        # the versioned name. The per-worker env (runtime_env env_vars)
        # wins over the node agent's own environment.
        cmd[0] = (
            env.get("RAY_TPU_CONTAINER_PYTHON")
            or os.environ.get("RAY_TPU_CONTAINER_PYTHON")
            or "python3"
        )
    argv += cmd
    # Fast path: marker present → skip the python hook entirely (it pays
    # a full ray_tpu import); otherwise ensure_image elects one puller
    # via its lock file and everyone else waits on it.
    marker = _image_marker(rt, image_uri)
    pull = (
        f"test -f {shlex.quote(marker)} || "
        + shlex.join([sys.executable, "-m", "ray_tpu.runtime_env.container",
                      image_uri])
    )
    return ["/bin/sh", "-c", f"{pull} && exec {shlex.join(argv)}"]


def _main(argv: List[str]) -> int:
    """``python -m ray_tpu.runtime_env.container <image_uri>`` — the
    spawn-path pull hook: runs ``ensure_image`` (lock-file protocol, so
    N concurrently spawning workers elect one puller) on the HOST before
    the shell execs the container runtime."""
    if len(argv) != 1:
        sys.stderr.write(
            "usage: python -m ray_tpu.runtime_env.container <image_uri>\n"
        )
        return 2
    try:
        ensure_image(argv[0])
    except RuntimeEnvSetupError as e:
        # this hook runs inside the spawned worker's shell — its stderr
        # IS the worker log, and a leveled record reaches the log plane
        logging.basicConfig(level=logging.INFO)
        logging.getLogger("ray_tpu.runtime_env.container").error(
            "image pull failed: %s", e
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(_main(sys.argv[1:]))
