"""Per-task JAX profiler capture via runtime_env.

Reference: python/ray/_private/runtime_env/nsight.py — the reference
wraps a worker with the nsight CUDA profiler when
``runtime_env={"nsight": ...}``. The TPU-native analogue is
``runtime_env={"jax_profiler": True}`` (or ``{"jax_profiler": {"dir":
...}}``): the worker captures a ``jax.profiler`` trace around each task
of that env, written under ``<session>/profiles/<task>-<id>/`` in the
TensorBoard trace format (xplane; open with TensorBoard's profile
plugin or xprof). Captures are listed by ``ray_tpu.util.state
.list_profiles`` and the ``ray-tpu profile`` CLI.
"""
from __future__ import annotations

import contextlib
import json
import logging
import os
import time
from typing import Any

from ray_tpu.exceptions import RuntimeEnvSetupError


def _setup_jax_profiler(value: Any):
    """Plugin hook: validates the env value (the capture itself wraps
    task execution in worker_main — a per-task concern, not a one-time
    env application)."""
    if value in (True, False) or value is None:
        return
    if isinstance(value, dict):
        unknown = set(value) - {"dir"}
        if unknown:
            raise RuntimeEnvSetupError(
                f"jax_profiler options not understood: {sorted(unknown)}"
            )
        return
    raise RuntimeEnvSetupError(
        "runtime_env['jax_profiler'] must be True or {'dir': path}"
    )


def profiles_root(session_dir: str | None = None) -> str:
    session_dir = session_dir or os.environ.get("RAY_TPU_SESSION_DIR", "/tmp/ray_tpu")
    return os.path.join(session_dir, "profiles")


@contextlib.contextmanager
def task_trace(spec, value: Any):
    """Capture a jax.profiler trace around one task execution."""
    if not value:
        yield None
        return
    base = None
    if isinstance(value, dict):
        base = value.get("dir")
    safe_name = "".join(c if c.isalnum() or c in "._-" else "_" for c in spec.name)[:48]
    out_dir = os.path.join(
        base or profiles_root(), f"{safe_name}-{spec.task_id.hex()[:8]}"
    )
    os.makedirs(out_dir, exist_ok=True)
    import jax

    t0 = time.time()
    jax.profiler.start_trace(out_dir)
    try:
        yield out_dir
    finally:
        try:
            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001 — a failed stop must not mask the task error
            logging.getLogger("ray_tpu.profiler").debug(
                "jax.profiler.stop_trace failed: %s", e
            )
        meta = {
            "task_id": spec.task_id.hex(),
            "name": spec.name,
            "captured_at": t0,
            "duration_s": round(time.time() - t0, 4),
            "pid": os.getpid(),
        }
        try:
            with open(os.path.join(out_dir, "profile.json"), "w") as f:
                json.dump(meta, f)
            if base:
                # custom dir: leave a pointer in the session profiles
                # root so list_profiles / the CLI still discover it
                root = profiles_root()
                os.makedirs(root, exist_ok=True)
                marker = os.path.join(
                    root, os.path.basename(out_dir) + ".external.json"
                )
                with open(marker, "w") as f:
                    json.dump({**meta, "path": out_dir}, f)
        except OSError:
            pass
