"""General pub/sub over the controller (reference: src/ray/pubsub/ —
Publisher/Subscriber used for object locations, errors, logs; the
reference batches long-polls, here messages push over each subscriber's
existing control connection).

    sub = pubsub.subscribe("events")
    pubsub.publish("events", {"x": 1})
    msg = sub.get(timeout=5)       # {"x": 1}
    sub.close()

Works from drivers and workers alike.
"""
from __future__ import annotations

import queue
import threading
from typing import Any, Dict, List, Optional

_lock = threading.Lock()
_subscribers: Dict[str, List["Subscriber"]] = {}


class Subscriber:
    def __init__(self, channel: str):
        self.channel = channel
        self._q: "queue.Queue" = queue.Queue()
        self._closed = False

    def get(self, timeout: Optional[float] = None) -> Any:
        """Next message (blocking). Raises queue.Empty on timeout."""
        return self._q.get(timeout=timeout)

    def get_nowait(self) -> Any:
        return self._q.get_nowait()

    def close(self):
        if self._closed:
            return
        self._closed = True
        # Controller RPC happens UNDER the lock: subscribe/unsubscribe
        # reach the controller in registry order, so a racing subscribe
        # on the same channel can never be cancelled out by this close.
        with _lock:
            subs = _subscribers.get(self.channel, [])
            if self in subs:
                subs.remove(self)
            if not subs:
                _subscribers.pop(self.channel, None)
                from ray_tpu.core.api import _require_worker

                try:
                    _require_worker()._call("unsubscribe", self.channel)
                except Exception:  # noqa: BLE001 — teardown
                    pass


def subscribe(channel: str) -> Subscriber:
    from ray_tpu.core.api import _require_worker

    sub = Subscriber(channel)
    with _lock:
        first = channel not in _subscribers
        _subscribers.setdefault(channel, []).append(sub)
        if first:
            try:
                _require_worker()._call("subscribe", channel)
            except BaseException:
                # roll back so a later subscribe() re-issues the RPC
                # instead of assuming the channel is live
                _subscribers[channel].remove(sub)
                if not _subscribers[channel]:
                    del _subscribers[channel]
                raise
    return sub


def publish(channel: str, message: Any) -> int:
    """Publish; returns the number of remote subscriber PROCESSES
    reached (local subscribers in other processes each count once)."""
    from ray_tpu.core.api import _require_worker

    return _require_worker()._call("publish", channel, message)


def _deliver(channel: str, message: Any):
    """Called by the process's RPC handler on pubsub_msg pushes."""
    with _lock:
        subs = list(_subscribers.get(channel, ()))
    for s in subs:
        s._q.put(message)


def _resubscribe(core):
    """Re-issue subscriptions on a fresh controller connection (called
    by CoreWorker after a reconnect — the restarted controller has no
    memory of this process's channels)."""
    with _lock:
        channels = [ch for ch, subs in _subscribers.items() if subs]
    for ch in channels:
        core._call("subscribe", ch)
