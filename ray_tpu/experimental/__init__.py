"""ray_tpu.experimental: semi-public APIs (reference: ray.experimental)."""
