"""Internal KV over the controller's namespaced KV store.

Reference: python/ray/experimental/internal_kv.py — thin module-level
functions over the GCS KV table (gcs_kv_manager.cc). Entries persist
across controller restarts via the GCS journal
(ray_tpu/core/persistence.py).
"""
from __future__ import annotations

from typing import List, Optional

from ray_tpu.core.api import _require_worker

_NS = "default"


def _internal_kv_initialized() -> bool:
    from ray_tpu.core import api

    return api._global_worker is not None


def _internal_kv_put(key: bytes, value: bytes, overwrite: bool = True, namespace: str = _NS) -> bool:
    """Returns True if the key was newly written (reference returns whether
    it already existed — inverted there; we follow kv_put semantics)."""
    return _require_worker().kv_put(namespace, bytes(key), bytes(value), overwrite)


def _internal_kv_get(key: bytes, namespace: str = _NS) -> Optional[bytes]:
    return _require_worker().kv_get(namespace, bytes(key))


def _internal_kv_exists(key: bytes, namespace: str = _NS) -> bool:
    return _internal_kv_get(key, namespace) is not None


def _internal_kv_del(key: bytes, namespace: str = _NS) -> bool:
    return _require_worker().kv_del(namespace, bytes(key))


def _internal_kv_list(prefix: bytes, namespace: str = _NS) -> List[bytes]:
    return _require_worker().kv_keys(namespace, bytes(prefix))
