"""ray_tpu: a TPU-native distributed compute and ML framework.

Core surface mirrors the reference's (reference: python/ray/__init__.py):
``init/shutdown/remote/get/put/wait/kill/cancel/get_actor`` plus placement
groups, collectives, Train, Data, Tune, and RL subpackages.

The top-level package deliberately does NOT import jax: the tasks/actors
core is accelerator-agnostic and worker processes must start fast. JAX loads
when you import ray_tpu.parallel / ray_tpu.ops / ray_tpu.models /
ray_tpu.train et al.
"""
import os as _os

if _os.environ.get("RAY_TPU_CONCSAN", "") == "1":
    # Opt-in concurrency sanitizer (ConcSan): every cluster process —
    # controller, agents, workers are subprocesses inheriting the env —
    # self-arms on import, BEFORE any locks or guarded containers are
    # created, so lockwatch wraps them all and the checked container
    # variants get selected at construction.
    from ray_tpu.tools.sanitizer import runtime as _concsan

    _concsan.maybe_enable()
del _os

from ray_tpu.core.api import (
    available_resources,
    cancel,
    cluster_resources,
    free,
    get,
    get_actor,
    init,
    is_initialized,
    kill,
    drain_node,
    nodes,
    put,
    remote,
    shutdown,
    timeline,
    wait,
    wait_actor_ready,
)
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.actor import ActorClass, ActorHandle, method
from ray_tpu import exceptions

__version__ = "0.1.0"

__all__ = [
    "init",
    "shutdown",
    "remote",
    "get",
    "put",
    "wait",
    "kill",
    "cancel",
    "get_actor",
    "free",
    "wait_actor_ready",
    "is_initialized",
    "cluster_resources",
    "available_resources",
    "nodes",
    "drain_node",
    "timeline",
    "ObjectRef",
    "ActorClass",
    "ActorHandle",
    "method",
    "exceptions",
    "__version__",
]
