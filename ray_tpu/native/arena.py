"""ctypes wrapper over the C++ shared-memory arena.

Zero-copy: readers get a memoryview directly into the mapped arena at the
object's offset (reference: plasma's fd-passing + client mmap —
src/ray/object_manager/plasma/client.cc — collapsed here into one shared
mapping per process).
"""
from __future__ import annotations

import ctypes
import os
from typing import Optional, Tuple

from ray_tpu.native import build as _build


def available() -> bool:
    return _build.load() is not None


class ArenaBuffer:
    """View into the arena; same interface as object_store.PlasmaBuffer."""

    def __init__(self, view: memoryview, size: int):
        self._view = view
        self.size = size

    def view(self) -> memoryview:
        return self._view

    def close(self):
        # The arena mapping is process-lifetime; releasing the memoryview
        # is enough (no fd per object — that's the point).
        self._view.release()


class Arena:
    def __init__(self, handle, lib):
        self._h = handle
        self._lib = lib
        self._base = lib.rt_arena_base(handle)

    # -- constructors -----------------------------------------------------
    @classmethod
    def create(cls, path: str, capacity: int, table_slots: int = 0) -> "Arena":
        lib = _build.load()
        if lib is None:
            raise RuntimeError(f"native arena unavailable: {_build.build_error()}")
        if table_slots <= 0:
            # ~1 slot per 256KB of capacity, at least 4096
            table_slots = max(4096, capacity // (256 * 1024))
        h = lib.rt_arena_create(path.encode(), capacity, table_slots)
        if not h:
            raise OSError(f"failed to create arena at {path}")
        return cls(h, lib)

    @classmethod
    def open(cls, path: str) -> "Arena":
        lib = _build.load()
        if lib is None:
            raise RuntimeError(f"native arena unavailable: {_build.build_error()}")
        h = lib.rt_arena_open(path.encode())
        if not h:
            raise OSError(f"failed to open arena at {path}")
        return cls(h, lib)

    def close(self):
        if self._h:
            self._lib.rt_arena_close(self._h)
            self._h = None

    # -- object lifecycle -------------------------------------------------
    def _mv(self, offset: int, size: int, writable: bool) -> memoryview:
        buf = (ctypes.c_ubyte * size).from_address(self._base + offset)
        mv = memoryview(buf).cast("B")
        return mv if writable else mv.toreadonly()

    def create_object(self, oid: bytes, size: int) -> Optional[ArenaBuffer]:
        """None when the arena is out of space (caller evicts/falls back);
        FileExistsError on duplicate create (matches PlasmaStore.create)."""
        off = self._lib.rt_arena_alloc(self._h, oid, size)
        if off == -2:
            raise FileExistsError(f"object {oid.hex()} already in arena")
        if off < 0:
            return None
        return ArenaBuffer(self._mv(off, size, writable=True), size)

    def seal(self, oid: bytes) -> bool:
        return self._lib.rt_arena_seal(self._h, oid) == 0

    def get(self, oid: bytes) -> Optional[ArenaBuffer]:
        size = ctypes.c_uint64()
        off = self._lib.rt_arena_lookup(self._h, oid, ctypes.byref(size))
        if off < 0:
            return None
        return ArenaBuffer(self._mv(off, size.value, writable=False), size.value)

    def contains(self, oid: bytes) -> bool:
        size = ctypes.c_uint64()
        return self._lib.rt_arena_lookup(self._h, oid, ctypes.byref(size)) >= 0

    def delete(self, oid: bytes) -> bool:
        return self._lib.rt_arena_delete(self._h, oid) == 0

    def pin(self, oid: bytes, delta: int = 1) -> int:
        return self._lib.rt_arena_pin(self._h, oid, delta)

    def sweep_pins(self) -> int:
        """Drop pins held by dead processes; returns pins reclaimed."""
        return self._lib.rt_arena_sweep_pins(self._h)

    def lru_victim(self) -> Optional[Tuple[bytes, int]]:
        out = (ctypes.c_uint8 * 16)()
        size = ctypes.c_uint64()
        if self._lib.rt_arena_lru_victim(self._h, out, ctypes.byref(size)) != 0:
            return None
        return bytes(out), size.value

    def stats(self) -> dict:
        used = ctypes.c_uint64()
        cap = ctypes.c_uint64()
        n = ctypes.c_uint64()
        self._lib.rt_arena_stats(
            self._h, ctypes.byref(used), ctypes.byref(cap), ctypes.byref(n)
        )
        return {"used": used.value, "heap_capacity": cap.value, "num_objects": n.value}
