"""ctypes wrapper over the native scheduling core.

Reference: src/ray/raylet/scheduling/cluster_resource_scheduler.cc — the
C++ half of scheduling, consumed here by ray_tpu.core.scheduler. Keeps
interned resource-id mapping on the Python side so call sites pass dense
uint32 ids + int64 fixed-point amounts.
"""
from __future__ import annotations

import ctypes
import itertools
from typing import Dict, Iterable, Optional, Tuple

from ray_tpu.native import build as _build


def available() -> bool:
    lib = _build.load()
    return lib is not None and hasattr(lib, "rt_sched_create")


class NativeSched:
    """One authoritative native cluster view (controller-owned)."""

    def __init__(self):
        self._lib = _build.load()
        if self._lib is None:
            raise RuntimeError(f"native lib unavailable: {_build.build_error()}")
        self._h = self._lib.rt_sched_create()
        self._ids: Dict[str, int] = {}
        self._node_keys = itertools.count(1)
        self._key_of: Dict[object, int] = {}
        self._node_of: Dict[int, object] = {}
        # Group-resource names whose interned id could not be recycled yet
        # (still held by a running task at PG-removal time).
        self._deferred_forgets: set = set()

    def close(self):
        if getattr(self, "_h", None):
            self._lib.rt_sched_destroy(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover — interpreter teardown
        try:
            self.close()
        except Exception:
            pass

    # -- id plumbing --------------------------------------------------------
    def _rid(self, name: str) -> int:
        rid = self._ids.get(name)
        if rid is None:
            rid = self._ids[name] = self._lib.rt_sched_intern(self._h, name.encode())
        return rid

    def _arrays(self, items: Iterable[Tuple[str, int]]):
        pairs = [(self._rid(k), v) for k, v in items]
        n = len(pairs)
        rids = (ctypes.c_uint32 * n)(*(r for r, _ in pairs))
        amts = (ctypes.c_int64 * n)(*(v for _, v in pairs))
        return rids, amts, n

    def _key(self, node_id) -> Optional[int]:
        return self._key_of.get(node_id)

    # -- node lifecycle -----------------------------------------------------
    def add_node(self, node_id, totals_fp: Iterable[Tuple[str, int]]):
        # Re-registration (agent reconnect): overwrite the existing native
        # node in place — no ghost entry, and the node keeps its pack-order
        # slot exactly like the Python ``ClusterState._order`` does.
        if node_id in self._key_of:
            totals = list(totals_fp)
            self.sync_node(node_id, totals, totals)
            # Fresh registration means a fresh (non-draining) NodeResources
            # on the Python side — the native flag must match.
            self.set_draining(node_id, False)
            return
        key = next(self._node_keys)
        self._key_of[node_id] = key
        self._node_of[key] = node_id
        rids, amts, n = self._arrays(totals_fp)
        self._lib.rt_sched_add_node(self._h, key, rids, amts, n)

    def remove_node(self, node_id):
        key = self._key_of.pop(node_id, None)
        if key is not None:
            self._node_of.pop(key, None)
            self._lib.rt_sched_remove_node(self._h, key)

    # -- accounting (write-through from NodeResources) ----------------------
    def acquire(self, node_id, items_fp) -> bool:
        key = self._key(node_id)
        if key is None:
            return False
        rids, amts, n = self._arrays(items_fp)
        return self._lib.rt_sched_acquire(self._h, key, rids, amts, n) == 0

    def sync_node(self, node_id, totals_fp, avails_fp):
        """Overwrite the native mirror for one node from the Python
        source of truth (desync repair)."""
        key = self._key(node_id)
        if key is None:
            return
        totals = dict(totals_fp)
        avails = dict(avails_fp)
        names = sorted(set(totals) | set(avails))
        items = [(k, 0) for k in names]
        rids, _, n = self._arrays(items)
        tot = (ctypes.c_int64 * n)(*(totals.get(k, 0) for k in names))
        av = (ctypes.c_int64 * n)(*(avails.get(k, 0) for k in names))
        self._lib.rt_sched_sync_node(self._h, key, rids, tot, av, n)

    def forget(self, name: str) -> bool:
        """Recycle an interned resource id (e.g. after PG removal).
        Only succeeds when no live node holds capacity under it; refusals
        are queued and retried on later forget/release calls so ids held
        by still-running tasks are reclaimed once they finish."""
        rc = self._lib.rt_sched_forget(self._h, name.encode())
        if rc == -2:
            self._deferred_forgets.add(name)
        else:
            self._deferred_forgets.discard(name)
            self._ids.pop(name, None)
        self._drain_deferred()
        return rc == 0

    def _drain_deferred(self):
        if not self._deferred_forgets:
            return
        for name in list(self._deferred_forgets):
            rc = self._lib.rt_sched_forget(self._h, name.encode())
            if rc != -2:  # recycled now, or already gone
                self._deferred_forgets.discard(name)
                self._ids.pop(name, None)

    def release(self, node_id, items_fp):
        key = self._key(node_id)
        if key is None:
            return
        rids, amts, n = self._arrays(items_fp)
        self._lib.rt_sched_release(self._h, key, rids, amts, n)
        # A release may be the moment a deferred PG-id recycle becomes safe.
        self._drain_deferred()

    def add_total(self, node_id, items_fp):
        key = self._key(node_id)
        if key is None:
            return
        rids, amts, n = self._arrays(items_fp)
        self._lib.rt_sched_add_total(self._h, key, rids, amts, n)

    def remove_total(self, node_id, items_fp):
        key = self._key(node_id)
        if key is None:
            return
        rids, amts, n = self._arrays(items_fp)
        self._lib.rt_sched_remove_total(self._h, key, rids, amts, n)

    # -- decisions ----------------------------------------------------------
    def schedule_hybrid(self, demand_fp, threshold: float):
        """(node_id, infeasible): node_id None when nothing fits now."""
        rids, amts, n = self._arrays(demand_fp)
        out = ctypes.c_uint64()
        rc = self._lib.rt_sched_schedule_hybrid(
            self._h, rids, amts, n, threshold, ctypes.byref(out)
        )
        if rc == 0:
            return self._node_of.get(out.value), False
        return None, rc == -2

    def schedule_spread(self, demand_fp):
        rids, amts, n = self._arrays(demand_fp)
        out = ctypes.c_uint64()
        rc = self._lib.rt_sched_schedule_spread(self._h, rids, amts, n, ctypes.byref(out))
        if rc == 0:
            return self._node_of.get(out.value), False
        return None, rc == -2

    def set_draining(self, node_id, draining: bool = True):
        key = self._key(node_id)
        if key is not None:
            self._lib.rt_sched_set_draining(self._h, key, 1 if draining else 0)

    def utilization(self, node_id) -> float:
        key = self._key(node_id)
        return self._lib.rt_sched_utilization(self._h, key) if key is not None else 0.0

    def get_avail(self, node_id, name: str) -> int:
        key = self._key(node_id)
        if key is None:
            return 0
        return self._lib.rt_sched_get_avail(self._h, key, self._rid(name))
