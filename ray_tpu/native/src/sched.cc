// Native cluster-resource scheduling core.
//
// Reference: src/ray/raylet/scheduling/ — ClusterResourceScheduler /
// ClusterResourceManager over fixed-point resources
// (common/scheduling/fixed_point.h, cluster_resource_data.h) with interned
// resource ids (scheduling_ids.cc) and the hybrid pack/spread policy
// (policy/hybrid_scheduling_policy.cc).
//
// The controller's scheduling pump is the control-plane hot loop: every
// pending task scans nodes for feasibility/availability each tick. This
// core keeps the authoritative {total, available} vectors per node as
// dense int64 fixed-point arrays keyed by interned resource ids, so one
// schedule() call is a few linear scans with no allocation — the same
// reason the reference keeps this in C++.
//
// C ABI (ctypes): all quantities are fixed-point (caller scales by 1e4).

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Node {
  uint64_t key = 0;
  bool alive = false;
  bool draining = false;  // excluded from placement, accounting live
  // Dense by interned resource id; size grows lazily.
  std::vector<int64_t> total;
  std::vector<int64_t> avail;

  int64_t get_total(size_t rid) const {
    return rid < total.size() ? total[rid] : 0;
  }
  int64_t get_avail(size_t rid) const {
    return rid < avail.size() ? avail[rid] : 0;
  }
  void ensure(size_t rid) {
    if (rid >= total.size()) {
      total.resize(rid + 1, 0);
      avail.resize(rid + 1, 0);
    }
  }
};

struct Sched {
  std::mutex mu;
  std::unordered_map<std::string, uint32_t> intern;
  std::vector<uint32_t> free_rids;              // recycled interned ids
  uint32_t next_rid = 0;
  std::vector<Node> nodes;                      // insertion order == pack order
  std::unordered_map<uint64_t, size_t> by_key;  // node key -> index
  uint64_t spread_rr = 0;
  size_t dead = 0;

  Node* find(uint64_t key) {
    auto it = by_key.find(key);
    if (it == by_key.end()) return nullptr;
    Node* n = &nodes[it->second];
    return n->alive ? n : nullptr;
  }

  // Drop tombstones once they outnumber live nodes; preserves insertion
  // (pack) order, amortized O(1) per removal.
  void maybe_compact() {
    if (dead == 0 || dead * 2 < nodes.size()) return;
    std::vector<Node> live;
    live.reserve(nodes.size() - dead);
    by_key.clear();
    for (auto& n : nodes) {
      if (!n.alive) continue;
      by_key[n.key] = live.size();
      live.push_back(std::move(n));
    }
    nodes.swap(live);
    dead = 0;
  }
};

// Drop trailing zero-capacity slots so vectors do not stay grown to the
// max resource id ever touched (PG group-resources churn).
void shrink(Node& n) {
  size_t sz = n.total.size();
  while (sz > 0 && n.total[sz - 1] == 0 && n.avail[sz - 1] == 0) sz--;
  if (sz < n.total.size()) {
    n.total.resize(sz);
    n.avail.resize(sz);
  }
}

bool fits(const Node& n, const uint32_t* rid, const int64_t* amt, int cnt) {
  for (int i = 0; i < cnt; i++) {
    if (amt[i] > 0 && n.get_avail(rid[i]) < amt[i]) return false;
  }
  return true;
}

bool feasible(const Node& n, const uint32_t* rid, const int64_t* amt, int cnt) {
  for (int i = 0; i < cnt; i++) {
    if (amt[i] > 0 && n.get_total(rid[i]) < amt[i]) return false;
  }
  return true;
}

// Max utilization across resource kinds (reference:
// hybrid_scheduling_policy.cc node scoring).
double utilization(const Node& n) {
  double best = 0.0;
  for (size_t r = 0; r < n.total.size(); r++) {
    if (n.total[r] <= 0) continue;
    double used = double(n.total[r] - n.get_avail(r)) / double(n.total[r]);
    if (used > best) best = used;
  }
  return best;
}

}  // namespace

extern "C" {

void* rt_sched_create() { return new Sched(); }

void rt_sched_destroy(void* h) { delete static_cast<Sched*>(h); }

// Intern a resource name -> dense id.
uint32_t rt_sched_intern(void* h, const char* name) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->intern.find(name);
  if (it != s->intern.end()) return it->second;
  uint32_t id;
  if (!s->free_rids.empty()) {
    id = s->free_rids.back();
    s->free_rids.pop_back();
  } else {
    id = s->next_rid++;
  }
  s->intern.emplace(name, id);
  return id;
}

// Recycle an interned name (e.g. a removed placement group's renamed
// resources). Safe only when no node holds capacity under the id; returns
// 0 on success, -1 if unknown, -2 if still in use.
int rt_sched_forget(void* h, const char* name) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->intern.find(name);
  if (it == s->intern.end()) return -1;
  uint32_t rid = it->second;
  for (auto& n : s->nodes) {
    if (n.alive && (n.get_total(rid) != 0 || n.get_avail(rid) != 0)) return -2;
  }
  s->intern.erase(it);
  s->free_rids.push_back(rid);
  return 0;
}

int rt_sched_add_node(void* h, uint64_t key, const uint32_t* rid,
                      const int64_t* amt, int cnt) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  if (s->by_key.count(key)) return -1;
  Node n;
  n.key = key;
  n.alive = true;
  for (int i = 0; i < cnt; i++) {
    n.ensure(rid[i]);
    n.total[rid[i]] = amt[i];
    n.avail[rid[i]] = amt[i];
  }
  s->by_key[key] = s->nodes.size();
  s->nodes.push_back(std::move(n));
  return 0;
}

int rt_sched_remove_node(void* h, uint64_t key) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  Node* n = s->find(key);
  if (!n) return -1;
  n->alive = false;
  n->total.clear();
  n->total.shrink_to_fit();
  n->avail.clear();
  n->avail.shrink_to_fit();
  s->by_key.erase(key);
  s->dead++;
  s->maybe_compact();
  return 0;
}

// Atomic fit-check + subtract. Returns 0 on success, -1 when it does not fit.
int rt_sched_acquire(void* h, uint64_t key, const uint32_t* rid,
                     const int64_t* amt, int cnt) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  Node* n = s->find(key);
  if (!n || !fits(*n, rid, amt, cnt)) return -1;
  for (int i = 0; i < cnt; i++) {
    n->ensure(rid[i]);
    n->avail[rid[i]] -= amt[i];
  }
  return 0;
}

void rt_sched_release(void* h, uint64_t key, const uint32_t* rid,
                      const int64_t* amt, int cnt) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  Node* n = s->find(key);
  if (!n) return;
  for (int i = 0; i < cnt; i++) {
    n->ensure(rid[i]);
    n->avail[rid[i]] += amt[i];
    // Clamp dynamic resources to capacity (mirrors NodeResources.release).
    if (n->total[rid[i]] > 0 && n->avail[rid[i]] > n->total[rid[i]])
      n->avail[rid[i]] = n->total[rid[i]];
  }
}

// PG bundle commit/return: grow/shrink a node's capacity (renamed group
// resources; reference: placement_group_resource_manager.h).
void rt_sched_add_total(void* h, uint64_t key, const uint32_t* rid,
                        const int64_t* amt, int cnt) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  Node* n = s->find(key);
  if (!n) return;
  for (int i = 0; i < cnt; i++) {
    n->ensure(rid[i]);
    n->total[rid[i]] += amt[i];
    n->avail[rid[i]] += amt[i];
  }
}

void rt_sched_remove_total(void* h, uint64_t key, const uint32_t* rid,
                           const int64_t* amt, int cnt) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  Node* n = s->find(key);
  if (!n) return;
  for (int i = 0; i < cnt; i++) {
    n->ensure(rid[i]);
    n->total[rid[i]] -= amt[i];
    n->avail[rid[i]] -= amt[i];
  }
  shrink(*n);
}

// Overwrite one node's vectors from the Python source of truth (mirror
// repair after a detected write-through disagreement).
int rt_sched_sync_node(void* h, uint64_t key, const uint32_t* rid,
                       const int64_t* total, const int64_t* avail, int cnt) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  Node* n = s->find(key);
  if (!n) return -1;
  n->total.assign(n->total.size(), 0);
  n->avail.assign(n->avail.size(), 0);
  for (int i = 0; i < cnt; i++) {
    n->ensure(rid[i]);
    n->total[rid[i]] = total[i];
    n->avail[rid[i]] = avail[i];
  }
  shrink(*n);
  return 0;
}

// Hybrid policy: pack (insertion order) while utilization < threshold,
// else least-utilized available node. Returns node key via *out.
//   0 = placed, -1 = feasible but currently full, -2 = infeasible.
int rt_sched_schedule_hybrid(void* h, const uint32_t* rid, const int64_t* amt,
                             int cnt, double threshold, uint64_t* out) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  bool any_feasible = false;
  Node* best = nullptr;
  double best_util = 2.0;
  for (auto& n : s->nodes) {
    if (!n.alive || n.draining || !feasible(n, rid, amt, cnt)) continue;
    any_feasible = true;
    if (!fits(n, rid, amt, cnt)) continue;
    double u = utilization(n);
    if (u < threshold) {  // pack: first node under threshold wins
      *out = n.key;
      return 0;
    }
    if (u < best_util) {
      best_util = u;
      best = &n;
    }
  }
  if (best) {
    *out = best->key;
    return 0;
  }
  return any_feasible ? -1 : -2;
}

int rt_sched_schedule_spread(void* h, const uint32_t* rid, const int64_t* amt,
                             int cnt, uint64_t* out) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  std::vector<Node*> avail;
  bool any_feasible = false;
  for (auto& n : s->nodes) {
    if (!n.alive || n.draining || !feasible(n, rid, amt, cnt)) continue;
    any_feasible = true;
    if (fits(n, rid, amt, cnt)) avail.push_back(&n);
  }
  if (avail.empty()) return any_feasible ? -1 : -2;
  *out = avail[s->spread_rr++ % avail.size()]->key;
  return 0;
}

int rt_sched_set_draining(void* h, uint64_t key, int draining) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  Node* n = s->find(key);
  if (!n) return -1;
  n->draining = draining != 0;
  return 0;
}

double rt_sched_utilization(void* h, uint64_t key) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  Node* n = s->find(key);
  return n ? utilization(*n) : 0.0;
}

int64_t rt_sched_get_avail(void* h, uint64_t key, uint32_t rid) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  Node* n = s->find(key);
  return n ? n->get_avail(rid) : 0;
}

}  // extern "C"
