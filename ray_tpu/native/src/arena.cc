// Shared-memory object arena: the native core of the plasma-equivalent
// store.
//
// Reference (structure, not code): src/ray/object_manager/plasma/store.cc
// (object lifecycle created->sealed->evictable), plasma_allocator.cc +
// dlmalloc.cc (arena allocator over mmap), eviction_policy.cc (LRU).
//
// Design: one mmap'd file on /dev/shm per node. Every process maps the
// same file; readers get zero-copy views at (base + offset). Layout:
//
//   [ Header | object table (open addressing) | data heap ]
//
// The data heap uses a boundary-tag first-fit allocator with coalescing
// (dlmalloc-lite), and the object table keys are 16-byte binary ids. A
// robust process-shared pthread mutex guards table + allocator: if a
// worker dies holding the lock, EOWNERDEAD recovery keeps the node alive
// (the reference restarts workers, not the store, on crash).
//
// Exposed as a C ABI for ctypes (no pybind11 in the image).

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <cerrno>
#include <cstdio>

#include <fcntl.h>
#include <pthread.h>
#include <signal.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

// Layout v2 (pid-attributed pins); old-magic files refuse to open so a
// stale arena from a previous build can never be mapped with the wrong
// slot stride.
constexpr uint64_t kMagic = 0x52545455504c4155ull;  // "RTTUPLAU"
constexpr uint64_t kAlign = 64;                     // cacheline
constexpr uint64_t kMinSplit = 128;
constexpr uint32_t kIdBytes = 16;
// Per-slot pin attribution: enough for the handful of reader processes
// that realistically share one block; further pinners fall into an
// untracked (unsweepable) overflow count.
constexpr uint32_t kMaxPinners = 4;

enum SlotState : uint32_t {
  kEmpty = 0,
  kCreated = 1,
  kSealed = 2,
  kTombstone = 3,
};

struct Slot {
  uint8_t id[kIdBytes];
  uint64_t offset;  // data offset from arena base
  uint64_t size;
  uint32_t state;
  uint32_t pinned;          // total pin count (tracked + untracked)
  uint64_t lru_tick;
  // Pin ownership, so a reader that dies without unpinning (OOM-kill,
  // segfault) doesn't make the slot unevictable forever: sweep_pins
  // drops pins whose pid no longer exists.
  uint32_t pinner_pid[kMaxPinners];
  uint32_t pinner_count[kMaxPinners];
  // Liveness token per pinner: pid-namespace inode + process start time.
  // A raw pid is ambiguous — containerized readers (own pid namespace,
  // same mounted arena) report pids that alias unrelated host processes,
  // and a recycled pid would keep a dead reader's pins alive (or sweep a
  // live one's). Sweeping only trusts pids from its OWN namespace whose
  // start time still matches.
  uint64_t pinner_ns[kMaxPinners];
  uint64_t pinner_start[kMaxPinners];
  uint32_t pin_untracked;   // overflow pins with no pid attribution
  uint32_t _pad;
};

struct Header {
  uint64_t magic;
  uint64_t capacity;      // whole file size
  uint64_t table_slots;
  uint64_t table_off;
  uint64_t heap_off;
  uint64_t heap_size;
  uint64_t used;          // bytes allocated to live objects
  uint64_t num_objects;
  uint64_t lru_clock;
  uint64_t free_head;     // offset of first free block (0 = none)
  pthread_mutex_t mutex;
};

// Every heap block, free or allocated, carries boundary tags so free()
// can coalesce both directions in O(1).
struct BlockHeader {
  uint64_t size;       // payload size (excluding header)
  uint64_t prev_size;  // payload size of the physically previous block
  uint32_t free;
  uint32_t has_prev;
  uint64_t next_free;  // offset of next free block (free blocks only)
};

constexpr uint64_t kBlockHdr = sizeof(BlockHeader);

struct Arena {
  uint8_t* base;
  uint64_t mapped;
  Header* hdr;
  Slot* table;
};

inline BlockHeader* block_at(Arena* a, uint64_t off) {
  return reinterpret_cast<BlockHeader*>(a->base + off);
}

inline uint64_t align_up(uint64_t v, uint64_t align) {
  return (v + align - 1) & ~(align - 1);
}

// -- pinner liveness tokens --------------------------------------------------

// starttime (field 22 of /proc/<pid>/stat, clock ticks since boot) — the
// canonical pid-reuse discriminator. 0 = unknown.
uint64_t proc_start_time_path(const char* path) {
  FILE* f = fopen(path, "re");
  if (f == nullptr) return 0;
  char buf[1024];
  size_t n = fread(buf, 1, sizeof(buf) - 1, f);
  fclose(f);
  if (n == 0) return 0;
  buf[n] = '\0';
  // comm (field 2) may itself contain spaces/parens: parse past the
  // LAST ')' and count space-separated fields from state (field 3).
  const char* p = strrchr(buf, ')');
  if (p == nullptr) return 0;
  p++;
  int field = 2;
  while (*p != '\0') {
    while (*p == ' ') p++;
    if (*p == '\0') break;
    field++;
    if (field == 22) return strtoull(p, nullptr, 10);
    while (*p != '\0' && *p != ' ') p++;
  }
  return 0;
}

uint64_t self_pid_ns_inode() {
  struct stat st;
  if (stat("/proc/self/ns/pid", &st) != 0) return 0;
  return static_cast<uint64_t>(st.st_ino);
}

// Per-process cache (post-fork the pid check invalidates it). Concurrent
// first callers write identical values, so the race is benign.
uint32_t g_tok_pid = 0;
uint64_t g_tok_ns = 0;
uint64_t g_tok_start = 0;

void self_pin_token(uint32_t pid, uint64_t* ns, uint64_t* start) {
  if (g_tok_pid != pid) {
    g_tok_ns = self_pid_ns_inode();
    g_tok_start = proc_start_time_path("/proc/self/stat");
    g_tok_pid = pid;
  }
  *ns = g_tok_ns;
  *start = g_tok_start;
}

void lock(Arena* a) {
  int rc = pthread_mutex_lock(&a->hdr->mutex);
  if (rc == EOWNERDEAD) {
    // A process died mid-critical-section. State is still structurally
    // consistent for our operations (single-word updates dominate);
    // mark recovered and continue — matches the reference's stance that
    // the store outlives worker crashes.
    pthread_mutex_consistent(&a->hdr->mutex);
  }
}

void unlock(Arena* a) { pthread_mutex_unlock(&a->hdr->mutex); }

uint64_t hash_id(const uint8_t* id) {
  // FNV-1a over the 16-byte id
  uint64_t h = 1469598103934665603ull;
  for (uint32_t i = 0; i < kIdBytes; i++) {
    h ^= id[i];
    h *= 1099511628211ull;
  }
  return h;
}

// Find slot for id; if absent and want_insert, returns an insertable slot.
Slot* find_slot(Arena* a, const uint8_t* id, bool want_insert) {
  uint64_t n = a->hdr->table_slots;
  uint64_t idx = hash_id(id) % n;
  Slot* first_tomb = nullptr;
  for (uint64_t probe = 0; probe < n; probe++) {
    Slot* s = &a->table[(idx + probe) % n];
    if (s->state == kEmpty) {
      if (!want_insert) return nullptr;
      return first_tomb ? first_tomb : s;
    }
    if (s->state == kTombstone) {
      if (want_insert && !first_tomb) first_tomb = s;
      continue;
    }
    if (memcmp(s->id, id, kIdBytes) == 0) return s;
  }
  return first_tomb;  // table full (nullptr if no tombstone either)
}

// -- allocator ------------------------------------------------------------

int64_t heap_alloc(Arena* a, uint64_t want) {
  want = align_up(want, kAlign);
  uint64_t prev_off = 0;
  uint64_t off = a->hdr->free_head;
  while (off != 0) {
    BlockHeader* b = block_at(a, off);
    if (b->size >= want) {
      uint64_t remainder = b->size - want;
      if (remainder >= kBlockHdr + kMinSplit) {
        // split: allocate the front, keep the tail free
        uint64_t tail_off = off + kBlockHdr + want;
        BlockHeader* tail = block_at(a, tail_off);
        tail->size = remainder - kBlockHdr;
        tail->prev_size = want;
        tail->has_prev = 1;
        tail->free = 1;
        tail->next_free = b->next_free;
        // fix the next physical block's prev_size. The block AFTER the
        // tail starts where this block's payload used to end (b->size is
        // still the pre-split size here) — its header is at
        // off + kBlockHdr + b->size, NOT one extra header past it: the
        // old +kBlockHdr form wrote tail->size 8 bytes into the next
        // block's PAYLOAD, corrupting any live object physically after a
        // split free block (exposed by free-then-realloc patterns like
        // the health plane's proactive spill).
        uint64_t after = off + kBlockHdr + b->size;
        if (after + kBlockHdr <= a->hdr->heap_off + a->hdr->heap_size) {
          BlockHeader* an = block_at(a, after);
          an->prev_size = tail->size;
          an->has_prev = 1;
        }
        b->size = want;
        if (prev_off)
          block_at(a, prev_off)->next_free = tail_off;
        else
          a->hdr->free_head = tail_off;
      } else {
        if (prev_off)
          block_at(a, prev_off)->next_free = b->next_free;
        else
          a->hdr->free_head = b->next_free;
      }
      b->free = 0;
      b->next_free = 0;
      return static_cast<int64_t>(off + kBlockHdr);
    }
    prev_off = off;
    off = b->next_free;
  }
  return -1;  // no block fits
}

void freelist_remove(Arena* a, uint64_t target) {
  uint64_t prev = 0, off = a->hdr->free_head;
  while (off != 0) {
    if (off == target) {
      BlockHeader* b = block_at(a, off);
      if (prev)
        block_at(a, prev)->next_free = b->next_free;
      else
        a->hdr->free_head = b->next_free;
      return;
    }
    prev = off;
    off = block_at(a, off)->next_free;
  }
}

void heap_free(Arena* a, uint64_t payload_off) {
  uint64_t off = payload_off - kBlockHdr;
  BlockHeader* b = block_at(a, off);
  uint64_t heap_end = a->hdr->heap_off + a->hdr->heap_size;

  // coalesce with next block if free
  uint64_t next_off = off + kBlockHdr + b->size;
  if (next_off < heap_end) {
    BlockHeader* next = block_at(a, next_off);
    if (next->free) {
      freelist_remove(a, next_off);
      b->size += kBlockHdr + next->size;
    }
  }
  // coalesce with previous block if free
  if (b->has_prev) {
    uint64_t prev_off = off - kBlockHdr - b->prev_size;
    BlockHeader* prev = block_at(a, prev_off);
    if (prev->free) {
      freelist_remove(a, prev_off);
      prev->size += kBlockHdr + b->size;
      b = prev;
      off = prev_off;
    }
  }
  // fix next physical block's prev tag
  uint64_t after = off + kBlockHdr + b->size;
  if (after < heap_end) {
    BlockHeader* an = block_at(a, after);
    an->prev_size = b->size;
    an->has_prev = 1;
  }
  b->free = 1;
  b->next_free = a->hdr->free_head;
  a->hdr->free_head = off;
}

}  // namespace

extern "C" {

// Create a new arena file of `capacity` bytes with `table_slots` object
// slots. Returns an opaque handle or null.
void* rt_arena_create(const char* path, uint64_t capacity, uint64_t table_slots) {
  int fd = open(path, O_RDWR | O_CREAT | O_EXCL, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, static_cast<off_t>(capacity)) != 0) {
    close(fd);
    unlink(path);
    return nullptr;
  }
  void* mem = mmap(nullptr, capacity, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) {
    unlink(path);
    return nullptr;
  }
  Arena* a = new Arena;
  a->base = static_cast<uint8_t*>(mem);
  a->mapped = capacity;
  a->hdr = reinterpret_cast<Header*>(a->base);

  Header* h = a->hdr;
  memset(h, 0, sizeof(Header));
  h->capacity = capacity;
  h->table_slots = table_slots;
  h->table_off = align_up(sizeof(Header), kAlign);
  uint64_t table_bytes = table_slots * sizeof(Slot);
  h->heap_off = align_up(h->table_off + table_bytes, kAlign);
  h->heap_size = capacity - h->heap_off;
  h->used = 0;
  h->num_objects = 0;
  h->lru_clock = 1;

  a->table = reinterpret_cast<Slot*>(a->base + h->table_off);
  memset(a->table, 0, table_bytes);

  // one giant free block spanning the heap
  BlockHeader* b = reinterpret_cast<BlockHeader*>(a->base + h->heap_off);
  b->size = h->heap_size - kBlockHdr;
  b->prev_size = 0;
  b->has_prev = 0;
  b->free = 1;
  b->next_free = 0;
  h->free_head = h->heap_off;

  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->mutex, &attr);
  pthread_mutexattr_destroy(&attr);

  h->magic = kMagic;  // written last: open() validates this
  return a;
}

void* rt_arena_open(const char* path) {
  int fd = open(path, O_RDWR);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* mem = mmap(nullptr, st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  Arena* a = new Arena;
  a->base = static_cast<uint8_t*>(mem);
  a->mapped = st.st_size;
  a->hdr = reinterpret_cast<Header*>(a->base);
  if (a->hdr->magic != kMagic) {
    munmap(mem, st.st_size);
    delete a;
    return nullptr;
  }
  a->table = reinterpret_cast<Slot*>(a->base + a->hdr->table_off);
  return a;
}

void rt_arena_close(void* handle) {
  Arena* a = static_cast<Arena*>(handle);
  munmap(a->base, a->mapped);
  delete a;
}

uint8_t* rt_arena_base(void* handle) {
  return static_cast<Arena*>(handle)->base;
}

// Allocate space for object `id`. Returns payload offset, or
// -1 = out of space, -2 = already exists, -3 = table full.
int64_t rt_arena_alloc(void* handle, const uint8_t* id, uint64_t size) {
  Arena* a = static_cast<Arena*>(handle);
  lock(a);
  Slot* s = find_slot(a, id, true);
  if (s == nullptr) {
    unlock(a);
    return -3;
  }
  if (s->state == kCreated || s->state == kSealed) {
    unlock(a);
    return -2;
  }
  int64_t off = heap_alloc(a, size ? size : 1);
  if (off < 0) {
    unlock(a);
    return -1;
  }
  memcpy(s->id, id, kIdBytes);
  s->offset = static_cast<uint64_t>(off);
  s->size = size;
  s->state = kCreated;
  s->pinned = 0;
  memset(s->pinner_pid, 0, sizeof(s->pinner_pid));
  memset(s->pinner_count, 0, sizeof(s->pinner_count));
  memset(s->pinner_ns, 0, sizeof(s->pinner_ns));
  memset(s->pinner_start, 0, sizeof(s->pinner_start));
  s->pin_untracked = 0;
  s->lru_tick = a->hdr->lru_clock++;
  a->hdr->used += size;
  a->hdr->num_objects++;
  unlock(a);
  return off;
}

int rt_arena_seal(void* handle, const uint8_t* id) {
  Arena* a = static_cast<Arena*>(handle);
  lock(a);
  Slot* s = find_slot(a, id, false);
  int rc = -1;
  if (s && s->state == kCreated) {
    s->state = kSealed;
    rc = 0;
  } else if (s && s->state == kSealed) {
    rc = 0;
  }
  unlock(a);
  return rc;
}

// Look up a sealed object; touches LRU. Returns payload offset or -1.
int64_t rt_arena_lookup(void* handle, const uint8_t* id, uint64_t* size_out) {
  Arena* a = static_cast<Arena*>(handle);
  lock(a);
  Slot* s = find_slot(a, id, false);
  if (s == nullptr || s->state != kSealed) {
    unlock(a);
    return -1;
  }
  s->lru_tick = a->hdr->lru_clock++;
  if (size_out) *size_out = s->size;
  int64_t off = static_cast<int64_t>(s->offset);
  unlock(a);
  return off;
}

int rt_arena_pin(void* handle, const uint8_t* id, int delta) {
  Arena* a = static_cast<Arena*>(handle);
  uint32_t pid = static_cast<uint32_t>(getpid());
  lock(a);
  Slot* s = find_slot(a, id, false);
  int rc = -1;
  if (s && (s->state == kSealed || s->state == kCreated)) {
    if (delta > 0) {
      // Attribute to the calling pid so sweep_pins can reclaim pins of
      // dead readers; overflow goes untracked (never swept).
      uint64_t tok_ns, tok_start;
      self_pin_token(pid, &tok_ns, &tok_start);
      Slot* slot = s;
      int idx = -1;
      for (uint32_t i = 0; i < kMaxPinners; i++) {
        if (slot->pinner_count[i] > 0 && slot->pinner_pid[i] == pid) {
          idx = static_cast<int>(i);
          break;
        }
        if (idx < 0 && slot->pinner_count[i] == 0) idx = static_cast<int>(i);
      }
      if (idx >= 0 && slot->pinner_count[idx] > 0 &&
          slot->pinner_pid[idx] == pid &&
          (slot->pinner_ns[idx] != tok_ns ||
           slot->pinner_start[idx] != tok_start)) {
        // Same pid, different liveness token: the entry belongs to a
        // DEAD process whose pid we recycled — reclaim its pins before
        // taking over the entry.
        uint32_t stale = slot->pinner_count[idx];
        s->pinned = s->pinned >= stale ? s->pinned - stale : 0;
        slot->pinner_count[idx] = 0;
      }
      if (idx >= 0 && (slot->pinner_count[idx] == 0 ||
                       slot->pinner_pid[idx] == pid)) {
        slot->pinner_pid[idx] = pid;
        slot->pinner_ns[idx] = tok_ns;
        slot->pinner_start[idx] = tok_start;
        slot->pinner_count[idx] += static_cast<uint32_t>(delta);
      } else {
        slot->pin_untracked += static_cast<uint32_t>(delta);
      }
      s->pinned += static_cast<uint32_t>(delta);
    } else if (delta < 0) {
      uint32_t dec = static_cast<uint32_t>(-delta);
      bool found = false;
      for (uint32_t i = 0; i < kMaxPinners; i++) {
        if (s->pinner_count[i] > 0 && s->pinner_pid[i] == pid) {
          uint32_t d = dec < s->pinner_count[i] ? dec : s->pinner_count[i];
          s->pinner_count[i] -= d;
          found = true;
          break;
        }
      }
      if (!found && s->pin_untracked > 0) {
        uint32_t d = dec < s->pin_untracked ? dec : s->pin_untracked;
        s->pin_untracked -= d;
      }
      s->pinned = s->pinned >= dec ? s->pinned - dec : 0;
    }
    rc = static_cast<int>(s->pinned);
  }
  unlock(a);
  return rc;
}

// Drop pins owned by processes that no longer exist (reader crashed
// before its finalizers ran); returns the number of pins reclaimed.
// The reference plasma releases a client's pins when its store
// connection drops — mapped-file readers have no connection, so
// liveness is checked by pid instead.
int rt_arena_sweep_pins(void* handle) {
  Arena* a = static_cast<Arena*>(handle);
  uint64_t my_ns = self_pid_ns_inode();
  if (my_ns == 0) return 0;  // cannot establish a namespace: judge nothing
  lock(a);
  int reclaimed = 0;
  for (uint64_t i = 0; i < a->hdr->table_slots; i++) {
    Slot* s = &a->table[i];
    if (s->state == kEmpty || s->state == kTombstone || s->pinned == 0)
      continue;
    for (uint32_t j = 0; j < kMaxPinners; j++) {
      uint32_t pid = s->pinner_pid[j];
      uint32_t cnt = s->pinner_count[j];
      if (cnt == 0) continue;
      // Pins from another pid namespace (containerized reader over the
      // mounted arena) are unjudgeable here — kill() would probe an
      // unrelated host pid and could sweep a LIVE reader's pin out from
      // under its mapped views. Never touch them.
      if (s->pinner_ns[j] == 0 || s->pinner_ns[j] != my_ns) continue;
      bool dead = false;
      if (kill(static_cast<pid_t>(pid), 0) == -1 && errno == ESRCH) {
        dead = true;
      } else if (s->pinner_start[j] != 0) {
        char path[64];
        snprintf(path, sizeof(path), "/proc/%u/stat", pid);
        uint64_t now = proc_start_time_path(path);
        if (now != 0 && now != s->pinner_start[j]) dead = true;  // pid reused
      }
      if (dead) {
        s->pinner_count[j] = 0;
        s->pinned = s->pinned >= cnt ? s->pinned - cnt : 0;
        reclaimed += static_cast<int>(cnt);
      }
    }
  }
  unlock(a);
  return reclaimed;
}

int rt_arena_delete(void* handle, const uint8_t* id) {
  Arena* a = static_cast<Arena*>(handle);
  lock(a);
  Slot* s = find_slot(a, id, false);
  if (s == nullptr || s->state == kEmpty || s->state == kTombstone) {
    unlock(a);
    return -1;
  }
  if (s->pinned > 0) {
    // A reader took a pin (rt_arena_pin) between the caller's victim
    // scan and this delete — freeing now would recycle memory a mapped
    // numpy view still reads. Refuse; the caller picks another victim.
    unlock(a);
    return -2;
  }
  heap_free(a, s->offset);
  a->hdr->used -= s->size;
  a->hdr->num_objects--;
  s->state = kTombstone;
  unlock(a);
  return 0;
}

// Least-recently-used sealed, unpinned object (eviction candidate).
// Writes its id and size; returns 0, or -1 if none.
int rt_arena_lru_victim(void* handle, uint8_t* id_out, uint64_t* size_out) {
  Arena* a = static_cast<Arena*>(handle);
  lock(a);
  Slot* best = nullptr;
  for (uint64_t i = 0; i < a->hdr->table_slots; i++) {
    Slot* s = &a->table[i];
    if (s->state == kSealed && s->pinned == 0) {
      if (best == nullptr || s->lru_tick < best->lru_tick) best = s;
    }
  }
  int rc = -1;
  if (best) {
    memcpy(id_out, best->id, kIdBytes);
    if (size_out) *size_out = best->size;
    rc = 0;
  }
  unlock(a);
  return rc;
}

void rt_arena_stats(void* handle, uint64_t* used, uint64_t* capacity,
                    uint64_t* num_objects) {
  Arena* a = static_cast<Arena*>(handle);
  lock(a);
  if (used) *used = a->hdr->used;
  if (capacity) *capacity = a->hdr->heap_size;
  if (num_objects) *num_objects = a->hdr->num_objects;
  unlock(a);
}

}  // extern "C"
