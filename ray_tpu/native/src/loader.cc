// Native token-batch data loader.
//
// Reference analogue: the reference ships native data-path components
// (object_manager chunked transfer, plasma block IO) and Ray Data's hot
// block ops run in Arrow's C++ — here the training-ingest hot loop is
// native: mmap'd token files, worker threads assembling fixed-shape
// [batch, seq+1] uint32 batches into a bounded ring, consumer copies one
// slot per next() call. The fixed shapes keep the jitted TPU train step
// static; the threads keep the host input pipeline off the GIL.
//
// File format: raw little-endian uint32 tokens, concatenated documents.
// Sampling: each worker draws random windows (seeded, per-thread RNG) —
// the standard infinite-stream LM pretraining sampler.
//
// C ABI (ctypes): see rt_loader_* below.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <condition_variable>
#include <fcntl.h>
#include <mutex>
#include <string>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct MappedFile {
  const uint32_t* data = nullptr;
  size_t n_tokens = 0;
  size_t bytes = 0;
  int fd = -1;
};

struct Loader {
  std::vector<MappedFile> files;
  size_t total_tokens = 0;
  int batch = 0;
  int seqlen = 0;  // tokens per row = seqlen (caller includes +1 if wanted)
  size_t row_elems = 0;

  // Ring of filled batch buffers.
  std::vector<std::vector<uint32_t>> slots;
  std::vector<int> ready;  // indices of filled slots
  std::vector<int> free_;  // indices of empty slots
  std::mutex mu;
  std::condition_variable cv_ready, cv_free;
  std::atomic<bool> stop{false};
  std::vector<std::thread> workers;
  uint64_t seed = 0;

  ~Loader() {
    stop.store(true);
    cv_free.notify_all();
    cv_ready.notify_all();
    for (auto& t : workers) {
      if (t.joinable()) t.join();
    }
    for (auto& f : files) {
      if (f.data) munmap(const_cast<uint32_t*>(f.data), f.bytes);
      if (f.fd >= 0) close(f.fd);
    }
  }
};

// xorshift64* — deterministic per-thread stream.
inline uint64_t next_rand(uint64_t& s) {
  s ^= s >> 12;
  s ^= s << 25;
  s ^= s >> 27;
  return s * 0x2545F4914F6CDD1DULL;
}

void worker_fill(Loader* L, int tid) {
  uint64_t rng = L->seed * 0x9E3779B97F4A7C15ULL + tid + 1;
  // Precompute cumulative token counts for file pick.
  std::vector<size_t> cum;
  cum.reserve(L->files.size());
  size_t acc = 0;
  for (auto& f : L->files) {
    acc += f.n_tokens;
    cum.push_back(acc);
  }
  while (!L->stop.load(std::memory_order_relaxed)) {
    int slot;
    {
      std::unique_lock<std::mutex> lk(L->mu);
      L->cv_free.wait(lk, [&] { return L->stop.load() || !L->free_.empty(); });
      if (L->stop.load()) return;
      slot = L->free_.back();
      L->free_.pop_back();
    }
    uint32_t* out = L->slots[slot].data();
    for (int b = 0; b < L->batch; b++) {
      // Pick a file weighted by token count, then a window inside it.
      size_t target = next_rand(rng) % L->total_tokens;
      size_t fi = 0;
      while (cum[fi] <= target) fi++;
      const MappedFile& f = L->files[fi];
      size_t span = (size_t)L->seqlen;
      // Files smaller than one window were rejected at create time, so
      // n_tokens >= span always; +1 makes the final window reachable.
      size_t start = next_rand(rng) % (f.n_tokens - span + 1);
      std::memcpy(out + (size_t)b * L->row_elems, f.data + start,
                  span * sizeof(uint32_t));
    }
    {
      std::lock_guard<std::mutex> lk(L->mu);
      L->ready.push_back(slot);
    }
    L->cv_ready.notify_one();
  }
}

}  // namespace

extern "C" {

// paths: '\n'-separated file list. Returns nullptr on failure.
void* rt_loader_create(const char* paths, int batch, int seqlen,
                       uint64_t seed, int n_threads, int queue_depth) {
  auto* L = new Loader();
  L->batch = batch;
  L->seqlen = seqlen;
  L->row_elems = (size_t)seqlen;
  L->seed = seed ? seed : 1;

  std::string all(paths);
  size_t pos = 0;
  while (pos < all.size()) {
    size_t nl = all.find('\n', pos);
    if (nl == std::string::npos) nl = all.size();
    std::string p = all.substr(pos, nl - pos);
    pos = nl + 1;
    if (p.empty()) continue;
    int fd = open(p.c_str(), O_RDONLY);
    if (fd < 0) {
      delete L;
      return nullptr;
    }
    struct stat st;
    if (fstat(fd, &st) != 0 || (size_t)st.st_size < sizeof(uint32_t)) {
      close(fd);
      delete L;
      return nullptr;
    }
    void* m = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
    if (m == MAP_FAILED) {
      close(fd);
      delete L;
      return nullptr;
    }
    MappedFile mf;
    mf.data = static_cast<const uint32_t*>(m);
    mf.bytes = st.st_size;
    mf.n_tokens = st.st_size / sizeof(uint32_t);
    mf.fd = fd;
    if (mf.n_tokens < (size_t)seqlen) {
      // A file shorter than one window can never produce a full batch
      // row; admitting it would read past its mapping (SIGBUS).
      munmap(const_cast<uint32_t*>(mf.data), mf.bytes);
      close(fd);
      delete L;
      return nullptr;
    }
    L->total_tokens += mf.n_tokens;
    L->files.push_back(mf);
  }
  if (L->files.empty()) {
    delete L;
    return nullptr;
  }

  if (queue_depth < 2) queue_depth = 2;
  L->slots.resize(queue_depth);
  for (int i = 0; i < queue_depth; i++) {
    L->slots[i].resize((size_t)batch * L->row_elems);
    L->free_.push_back(i);
  }
  if (n_threads < 1) n_threads = 1;
  for (int t = 0; t < n_threads; t++) {
    L->workers.emplace_back(worker_fill, L, t);
  }
  return L;
}

// Wake any blocked rt_loader_next callers (they return -1) and stop the
// workers. Call before destroy when another thread may be consuming —
// deleting with live waiters would destroy a condvar in use (UB).
void rt_loader_stop(void* h) {
  Loader* L = static_cast<Loader*>(h);
  L->stop.store(true);
  L->cv_ready.notify_all();
  L->cv_free.notify_all();
}

void rt_loader_destroy(void* h) { delete static_cast<Loader*>(h); }

uint64_t rt_loader_total_tokens(void* h) {
  return static_cast<Loader*>(h)->total_tokens;
}

// Copy the next ready batch into out ([batch * seqlen] uint32).
// Returns 0 on success, -1 on shutdown.
int rt_loader_next(void* h, uint32_t* out) {
  Loader* L = static_cast<Loader*>(h);
  int slot;
  {
    std::unique_lock<std::mutex> lk(L->mu);
    L->cv_ready.wait(lk, [&] { return L->stop.load() || !L->ready.empty(); });
    if (L->stop.load() && L->ready.empty()) return -1;
    slot = L->ready.front();
    L->ready.erase(L->ready.begin());
  }
  std::memcpy(out, L->slots[slot].data(),
              (size_t)L->batch * L->row_elems * sizeof(uint32_t));
  {
    std::lock_guard<std::mutex> lk(L->mu);
    L->free_.push_back(slot);
  }
  L->cv_free.notify_one();
  return 0;
}

}  // extern "C"
