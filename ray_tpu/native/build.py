"""Build + load the native library (g++ → .so, cached by source hash).

The image has no pybind11; the C++ exposes a C ABI consumed via ctypes
(per-environment constraint). The .so is rebuilt only when the source
changes, cached under ~/.cache/ray_tpu_native.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

_SRC_DIR = os.path.join(os.path.dirname(__file__), "src")
_CACHE_DIR = os.path.expanduser(os.environ.get("RAY_TPU_NATIVE_CACHE", "~/.cache/ray_tpu_native"))

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_error: Optional[str] = None


def _source_hash() -> str:
    h = hashlib.blake2b(digest_size=12)
    for name in sorted(os.listdir(_SRC_DIR)):
        if name.endswith((".cc", ".h")):
            with open(os.path.join(_SRC_DIR, name), "rb") as f:
                h.update(name.encode())
                h.update(f.read())
    return h.hexdigest()


def build() -> str:
    """Compile (if needed) and return the .so path."""
    os.makedirs(_CACHE_DIR, exist_ok=True)
    so_path = os.path.join(_CACHE_DIR, f"libray_tpu_{_source_hash()}.so")
    if os.path.exists(so_path):
        return so_path
    srcs = [
        os.path.join(_SRC_DIR, n)
        for n in sorted(os.listdir(_SRC_DIR))
        if n.endswith(".cc")
    ]
    tmp = so_path + f".tmp.{os.getpid()}"
    cmd = [
        "g++", "-O2", "-g", "-std=c++17", "-shared", "-fPIC",
        "-o", tmp, *srcs, "-lpthread",
    ]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(tmp, so_path)  # atomic: concurrent builders race safely
    return so_path


def load() -> Optional[ctypes.CDLL]:
    """The loaded library, or None if the toolchain is unavailable."""
    global _lib, _build_error
    if _lib is not None:
        return _lib
    with _lock:
        if _lib is not None or _build_error is not None:
            return _lib
        try:
            lib = ctypes.CDLL(build())
        except Exception as e:  # no g++ / build failure → Python fallback
            _build_error = str(e)
            return None
        u64, i64, p = ctypes.c_uint64, ctypes.c_int64, ctypes.c_void_p
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.rt_arena_create.restype = p
        lib.rt_arena_create.argtypes = [ctypes.c_char_p, u64, u64]
        lib.rt_arena_open.restype = p
        lib.rt_arena_open.argtypes = [ctypes.c_char_p]
        lib.rt_arena_close.argtypes = [p]
        lib.rt_arena_base.restype = ctypes.c_void_p
        lib.rt_arena_base.argtypes = [p]
        lib.rt_arena_alloc.restype = i64
        lib.rt_arena_alloc.argtypes = [p, ctypes.c_char_p, u64]
        lib.rt_arena_seal.restype = ctypes.c_int
        lib.rt_arena_seal.argtypes = [p, ctypes.c_char_p]
        lib.rt_arena_lookup.restype = i64
        lib.rt_arena_lookup.argtypes = [p, ctypes.c_char_p, ctypes.POINTER(u64)]
        lib.rt_arena_pin.restype = ctypes.c_int
        lib.rt_arena_pin.argtypes = [p, ctypes.c_char_p, ctypes.c_int]
        lib.rt_arena_delete.restype = ctypes.c_int
        lib.rt_arena_delete.argtypes = [p, ctypes.c_char_p]
        lib.rt_arena_sweep_pins.restype = ctypes.c_int
        lib.rt_arena_sweep_pins.argtypes = [p]
        lib.rt_arena_lru_victim.restype = ctypes.c_int
        lib.rt_arena_lru_victim.argtypes = [p, u8p, ctypes.POINTER(u64)]
        lib.rt_arena_stats.argtypes = [p, ctypes.POINTER(u64), ctypes.POINTER(u64), ctypes.POINTER(u64)]
        u32p = ctypes.POINTER(ctypes.c_uint32)
        i64p = ctypes.POINTER(i64)
        lib.rt_sched_create.restype = p
        lib.rt_sched_destroy.argtypes = [p]
        lib.rt_sched_intern.restype = ctypes.c_uint32
        lib.rt_sched_intern.argtypes = [p, ctypes.c_char_p]
        lib.rt_sched_add_node.restype = ctypes.c_int
        lib.rt_sched_add_node.argtypes = [p, u64, u32p, i64p, ctypes.c_int]
        lib.rt_sched_remove_node.restype = ctypes.c_int
        lib.rt_sched_remove_node.argtypes = [p, u64]
        lib.rt_sched_acquire.restype = ctypes.c_int
        lib.rt_sched_acquire.argtypes = [p, u64, u32p, i64p, ctypes.c_int]
        lib.rt_sched_release.argtypes = [p, u64, u32p, i64p, ctypes.c_int]
        lib.rt_sched_add_total.argtypes = [p, u64, u32p, i64p, ctypes.c_int]
        lib.rt_sched_remove_total.argtypes = [p, u64, u32p, i64p, ctypes.c_int]
        lib.rt_sched_schedule_hybrid.restype = ctypes.c_int
        lib.rt_sched_schedule_hybrid.argtypes = [p, u32p, i64p, ctypes.c_int, ctypes.c_double, ctypes.POINTER(u64)]
        lib.rt_sched_schedule_spread.restype = ctypes.c_int
        lib.rt_sched_schedule_spread.argtypes = [p, u32p, i64p, ctypes.c_int, ctypes.POINTER(u64)]
        lib.rt_sched_set_draining.restype = ctypes.c_int
        lib.rt_sched_set_draining.argtypes = [p, u64, ctypes.c_int]
        lib.rt_sched_utilization.restype = ctypes.c_double
        lib.rt_sched_utilization.argtypes = [p, u64]
        lib.rt_sched_forget.restype = ctypes.c_int
        lib.rt_sched_forget.argtypes = [p, ctypes.c_char_p]
        lib.rt_sched_sync_node.restype = ctypes.c_int
        lib.rt_sched_sync_node.argtypes = [p, u64, u32p, i64p, i64p, ctypes.c_int]
        lib.rt_loader_create.restype = p
        lib.rt_loader_create.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, u64, ctypes.c_int, ctypes.c_int,
        ]
        lib.rt_loader_stop.argtypes = [p]
        lib.rt_loader_destroy.argtypes = [p]
        lib.rt_loader_total_tokens.restype = u64
        lib.rt_loader_total_tokens.argtypes = [p]
        lib.rt_loader_next.restype = ctypes.c_int
        lib.rt_loader_next.argtypes = [p, u32p]
        lib.rt_sched_get_avail.restype = i64
        lib.rt_sched_get_avail.argtypes = [p, u64, ctypes.c_uint32]
        _lib = lib
    return _lib


def build_error() -> Optional[str]:
    return _build_error
