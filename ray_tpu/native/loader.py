"""ctypes wrapper over the native token-batch loader.

Reference analogue: the training ingest hot path that the reference
delegates to Arrow C++ / torch DataLoader workers. ``TokenLoader``
streams fixed-shape uint32 token batches from raw binary files through a
C++ prefetch ring (mmap + worker threads, zero GIL in the fill path) —
the host-side input pipeline for TPU pretraining loops, where static
batch shapes keep the jitted step cache-stable.
"""
from __future__ import annotations

import ctypes
from typing import Iterable, List, Optional, Sequence

import numpy as np

from ray_tpu.native import build as _build


def available() -> bool:
    lib = _build.load()
    return lib is not None and hasattr(lib, "rt_loader_create")


class LoaderClosedError(RuntimeError):
    """The loader was closed (or is shutting down)."""


class TokenLoader:
    """Infinite sampler of ``[batch, seq]`` uint32 windows from raw
    token files (little-endian uint32 concatenated documents)."""

    def __init__(
        self,
        paths: Sequence[str],
        batch_size: int,
        seq_len: int,
        *,
        seed: int = 0,
        num_threads: int = 2,
        queue_depth: int = 4,
    ):
        self._lib = _build.load()
        if self._lib is None:
            raise RuntimeError(f"native lib unavailable: {_build.build_error()}")
        joined = "\n".join(paths).encode()
        self._h = self._lib.rt_loader_create(
            joined, batch_size, seq_len, seed, num_threads, queue_depth
        )
        if not self._h:
            raise ValueError(
                f"rt_loader_create failed: check paths exist and hold >= "
                f"{seq_len} uint32 tokens total: {list(paths)!r}"
            )
        self.batch_size = batch_size
        self.seq_len = seq_len
        import threading

        # Serializes next()/close(): destroy must never race a blocked
        # rt_loader_next (condvar destruction with waiters is UB).
        self._lock = threading.Lock()

    @property
    def total_tokens(self) -> int:
        if not getattr(self, "_h", None):
            raise LoaderClosedError("loader is closed")
        return int(self._lib.rt_loader_total_tokens(self._h))

    def next(self) -> np.ndarray:
        """Next prefetched batch — a fresh array, filled directly by the
        native side (one copy total)."""
        out = np.empty((self.batch_size, self.seq_len), dtype=np.uint32)
        with self._lock:
            if not getattr(self, "_h", None):
                raise LoaderClosedError("loader is closed")
            rc = self._lib.rt_loader_next(
                self._h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32))
            )
        if rc != 0:
            raise LoaderClosedError("loader shut down")
        return out

    def __iter__(self) -> Iterable[np.ndarray]:
        while True:
            try:
                yield self.next()
            except LoaderClosedError:
                return

    def close(self):
        if getattr(self, "_h", None):
            # Wake any blocked consumer first; then destroy under the lock
            # so no thread is inside rt_loader_next during delete.
            self._lib.rt_loader_stop(self._h)
            with self._lock:
                if self._h:
                    self._lib.rt_loader_destroy(self._h)
                    self._h = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass


def write_token_file(path: str, tokens: np.ndarray):
    """Write a uint32 token array in the loader's file format."""
    np.asarray(tokens, dtype=np.uint32).tofile(path)
