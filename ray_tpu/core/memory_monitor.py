"""Memory monitor + OOM worker-killing policies.

Reference: src/ray/common/memory_monitor.h:52 (cgroup/system usage
polling) and src/ray/raylet/worker_killing_policy*.h — retriable-FIFO
(default: prefer retriable work, newest first, so long-running
non-retriable work survives) and group-by-owner (kill from the largest
group of same-owner tasks to preserve diversity of progress).

The node agent polls; on pressure it asks the controller (which knows
task retriability) to nominate a victim, then SIGKILLs the worker. The
controller marks the worker OOM so its task failure surfaces as
``OutOfMemoryError`` rather than a generic crash.
"""
from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Tuple

_CGROUP_V2_CUR = "/sys/fs/cgroup/memory.current"
_CGROUP_V2_MAX = "/sys/fs/cgroup/memory.max"
_CGROUP_V1_CUR = "/sys/fs/cgroup/memory/memory.usage_in_bytes"
_CGROUP_V1_MAX = "/sys/fs/cgroup/memory/memory.limit_in_bytes"


def _read_int(path: str) -> Optional[int]:
    try:
        with open(path) as f:
            raw = f.read().strip()
        return None if raw == "max" else int(raw)
    except (FileNotFoundError, ValueError, PermissionError):
        return None


def system_memory() -> Tuple[int, int]:
    """(used_bytes, total_bytes), preferring cgroup limits (containers)."""
    for cur_p, max_p in ((_CGROUP_V2_CUR, _CGROUP_V2_MAX), (_CGROUP_V1_CUR, _CGROUP_V1_MAX)):
        cur, cap = _read_int(cur_p), _read_int(max_p)
        if cur is not None and cap is not None and cap < (1 << 60):
            return cur, cap
    total = avail = 0
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = int(line.split()[1]) * 1024
                elif line.startswith("MemAvailable:"):
                    avail = int(line.split()[1]) * 1024
    except FileNotFoundError:  # pragma: no cover - non-linux
        return 0, 1
    return total - avail, max(total, 1)


def cpu_times() -> Tuple[int, int]:
    """(busy_jiffies, total_jiffies) from the aggregate /proc/stat line.
    Utilization is a DELTA between two samples — see HostCpuSampler."""
    try:
        with open("/proc/stat") as f:
            parts = f.readline().split()[1:]
    except (FileNotFoundError, IndexError):  # pragma: no cover - non-linux
        return 0, 1
    vals = [int(x) for x in parts[:8]]
    idle = vals[3] + (vals[4] if len(vals) > 4 else 0)  # idle + iowait
    total = sum(vals)
    return total - idle, max(total, 1)


class HostCpuSampler:
    """Stateful CPU-utilization sampler (first call returns 0.0; later
    calls return busy fraction over the interval since the previous
    call). One instance per polling loop — the deltas are its state."""

    def __init__(self, reader: Callable[[], Tuple[int, int]] = cpu_times):
        self.reader = reader
        self._prev: Optional[Tuple[int, int]] = None

    def sample(self) -> float:
        busy, total = self.reader()
        prev, self._prev = self._prev, (busy, total)
        if prev is None or total <= prev[1]:
            return 0.0
        return max(0.0, min(1.0, (busy - prev[0]) / (total - prev[1])))


class MemoryMonitor:
    def __init__(
        self,
        threshold: float = 0.95,
        reader: Callable[[], Tuple[int, int]] = system_memory,
        min_kill_interval_s: float = 2.0,
    ):
        self.threshold = threshold
        self.reader = reader
        self.min_kill_interval_s = min_kill_interval_s
        self._last_kill = 0.0

    def usage_fraction(self) -> float:
        used, total = self.reader()
        return used / max(total, 1)

    def should_kill(self) -> bool:
        """True when above threshold and outside the kill cooldown."""
        if self.usage_fraction() < self.threshold:
            return False
        now = time.monotonic()
        if now - self._last_kill < self.min_kill_interval_s:
            return False
        self._last_kill = now
        return True


# ---------------------------------------------------------------------------
# Killing policies
# ---------------------------------------------------------------------------
@dataclass
class KillCandidate:
    worker_id: str
    pid: int
    is_retriable: bool
    start_time: float
    owner_id: str = ""


def retriable_fifo_policy(candidates: List[KillCandidate]) -> Optional[KillCandidate]:
    """Prefer retriable work; among equals kill the newest (reference:
    worker_killing_policy_retriable_fifo.h:31 — last-in-first-killed so the
    oldest, most-progressed work survives)."""
    if not candidates:
        return None
    return max(candidates, key=lambda c: (c.is_retriable, c.start_time))


def group_by_owner_policy(candidates: List[KillCandidate]) -> Optional[KillCandidate]:
    """Kill the newest retriable task from the LARGEST owner group
    (reference: worker_killing_policy_group_by_owner.h:85) — preserves
    at least one task per owner making progress."""
    if not candidates:
        return None
    groups: dict = {}
    for c in candidates:
        groups.setdefault(c.owner_id, []).append(c)
    biggest = max(groups.values(), key=len)
    return max(biggest, key=lambda c: (c.is_retriable, c.start_time))


POLICIES = {
    "retriable_fifo": retriable_fifo_policy,
    "group_by_owner": group_by_owner_policy,
}
