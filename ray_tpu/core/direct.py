"""Direct caller→actor task transport.

Reference: src/ray/core_worker/transport/actor_task_submitter.h:45-75 —
the caller pushes actor tasks STRAIGHT to the actor's worker process over
a dedicated connection (per-actor ordered queues, sequence numbers,
retries, failover re-resolve through the control plane on death). The
controller is only consulted to locate the actor (and again after a
connection loss); the steady-state call path never touches it.

Results come back in the push reply and land in the caller's owner-local
memory store (reference: memory_store.cc) — a follow-up ``get`` is a
process-local lookup.

All submitter state is mutated ONLY on the CoreWorker's asyncio loop
thread (the same single-writer discipline the controller uses).
"""
from __future__ import annotations

import asyncio
import itertools
import logging
from collections import deque
from typing import Dict, List, Optional

from ray_tpu.core.task_spec import TaskSpec
from ray_tpu.exceptions import ActorDiedError, TaskCancelledError
from ray_tpu.utils import rpc
from ray_tpu.utils.ids import ActorID

logger = logging.getLogger("ray_tpu.direct")


class _Call:
    __slots__ = ("seq", "spec", "pins", "attempts_left", "sent_peer")

    def __init__(self, seq: int, spec: TaskSpec, pins, attempts_left: int):
        self.seq = seq
        self.spec = spec
        self.pins = pins  # ObjectRefs pinning args until the reply lands
        self.attempts_left = attempts_left
        # The connection this call is currently in flight on. None = not
        # in flight (loss already processed; safe to resend). Guards
        # against stale reply callbacks from a dead connection racing a
        # resend — loss accounting happens exactly once per attempt.
        self.sent_peer = None


class _PeerHandler:
    """Handler for the caller side of a direct connection (the worker may
    push nothing back besides call replies)."""

    def on_disconnect(self, peer):
        pass


class ActorSubmitter:
    """Per-actor ordered submission queue (reference:
    SequentialActorSubmitQueue, actor_task_submitter.cc)."""

    def __init__(self, core, actor_id: ActorID):
        self.core = core
        self.actor_id = actor_id
        self._seq = itertools.count()
        self.queue: deque = deque()
        self.inflight: Dict[int, _Call] = {}
        self.peer: Optional[rpc.Peer] = None
        self.instance = -1
        self.dead_error: Optional[Exception] = None
        self._draining = False
        self._connect_failures = 0
        self._need_resend = False

    # -- caller thread --------------------------------------------------
    def submit(self, spec: TaskSpec, pins) -> None:
        call = _Call(next(self._seq), spec, pins, spec.max_retries)
        spec.actor_seq_no = call.seq
        # Batched handoff: one loop wakeup flushes every queued submit
        # (a call_soon_threadsafe per call costs a self-pipe write each).
        self.core._queue_direct(self, call)

    def cancel_threadsafe(self, task_id) -> None:
        self.core.loop_runner.loop.call_soon_threadsafe(self._cancel, task_id)

    # -- loop thread ----------------------------------------------------
    def _enqueue(self, call: _Call) -> None:
        if self.dead_error is not None:
            self._fail_call(call, self.dead_error)
            return
        self.queue.append(call)
        self._ensure_drain()

    def _ensure_drain(self) -> None:
        if not self._draining:
            self._draining = True
            asyncio.get_running_loop().create_task(self._drain())

    async def _drain(self) -> None:
        try:
            while True:
                if self.dead_error is not None:
                    self._fail_all(self.dead_error)
                    return
                if not self.queue and not self.inflight:
                    return
                if self.peer is None or self.peer.closed:
                    if not await self._connect():
                        continue  # next iteration fails all or retries
                # Re-push calls whose previous attempt's loss has been
                # PROCESSED (sent_peer reset by _on_reply), in sequence
                # order, BEFORE new ones (reference: resend_queue on actor
                # restart). Calls still bound to a dead peer resend (or
                # fail) when their reply callback fires — resending them
                # earlier would race the stale callback and could
                # double-execute a max_task_retries=0 task. Flag-gated so
                # the steady-state hot loop never scans inflight.
                if self._need_resend:
                    self._need_resend = False
                    resend = sorted(
                        (c for c in self.inflight.values() if c.sent_peer is None),
                        key=lambda c: c.seq,
                    )
                    lost_peer = False
                    for call in resend:
                        try:
                            deps = await self._inline_deps(call)
                        except _DepFailed as e:
                            self.inflight.pop(call.seq, None)
                            self._fail_call(call, None, serialized=e.payload)
                            continue
                        # _inline_deps awaited — a reply callback processing
                        # a connection loss may have cleared self.peer. Loop
                        # back to reconnect rather than send into the void.
                        if self.peer is None or self.peer.closed:
                            lost_peer = True
                            break
                        self._send(call, deps)
                    if lost_peer:
                        self._need_resend = True
                        continue
                if self._need_resend:
                    # a loss callback for a call bound to a STALE peer
                    # fired during an _inline_deps await (self.peer still
                    # healthy, so no reconnect happened) — loop back and
                    # resend rather than exiting with work pending
                    continue
                if not self.queue:
                    return  # connected; replies drive the rest
                call = self.queue.popleft()
                try:
                    inline_deps = await self._inline_deps(call)
                except _DepFailed as e:
                    self._fail_call(call, None, serialized=e.payload)
                    continue
                if self.peer is None or self.peer.closed:
                    # connection dropped while awaiting local deps —
                    # requeue at the front and reconnect first
                    self.queue.appendleft(call)
                    continue
                self.inflight[call.seq] = call
                self._send(call, inline_deps)
        finally:
            self._draining = False
            # work may have raced in while we were exiting
            if (
                self.queue
                or self._need_resend
                or (self.dead_error and self.inflight)
            ) and not self._draining:
                self._ensure_drain()

    async def _inline_deps(self, call: _Call):
        """Ship ready owner-local dependency values with the task so the
        executing worker never round-trips to the controller for them
        (reference: LocalDependencyResolver inlining small args). Waits
        for still-pending local deps — which also gives ordered actors
        correct submission-order execution."""
        ms = self.core.memory_store
        inline = None
        for dep in call.spec.dependencies:
            key = dep.binary()
            e = ms.lookup(key)
            if e is None or e.kind != "inline":
                continue  # global object — the worker fetches it
            if not e.ready:
                await asyncio.wrap_future(_copy_future(e.ensure_future()))
                if e.kind != "inline":
                    continue  # resolved to a shm marker — global now
            payload, is_err = e.value()
            if isinstance(payload, Exception):
                from ray_tpu.utils.serialization import serialize

                raise _DepFailed(serialize(payload))
            if is_err:
                raise _DepFailed(bytes(payload))
            if inline is None:
                inline = {}
            inline[key] = bytes(payload)
        return inline

    def _send(self, call: _Call, inline_deps) -> None:
        from ray_tpu.core.task_spec import pack_actor_task

        peer = self.peer
        call.sent_peer = peer
        fut = peer.call_nowait("push_actor_task", pack_actor_task(call.spec), inline_deps)
        fut.add_done_callback(lambda f, p=peer, c=call: self._on_reply(p, c, f))

    def _on_reply(self, peer: rpc.Peer, call: _Call, fut: asyncio.Future) -> None:
        if call.sent_peer is not peer:
            return  # stale callback from a superseded attempt
        call.sent_peer = None
        if fut.cancelled():
            self._on_connection_loss(peer, call)
            return
        exc = fut.exception()
        if exc is not None:
            self._on_connection_loss(peer, call, exc)
            return
        if call.seq not in self.inflight:
            return  # cancelled/raced
        # already-done future (done-callback): no wait  # ray-tpu: lint-ignore[RTL008]
        results, error = fut.result()
        if (
            error is not None
            and call.spec.retry_exceptions
            and call.attempts_left > 0
        ):
            call.attempts_left -= 1
            self.inflight.pop(call.seq, None)
            self.queue.appendleft(call)
            self._ensure_drain()
            return
        self._complete(call, results, error)

    def _on_connection_loss(self, peer: rpc.Peer, call: _Call, err: Optional[Exception] = None) -> None:
        if self.peer is peer:
            self.peer = None
        if call.seq not in self.inflight:
            return
        if call.attempts_left > 0:
            call.attempts_left -= 1
            # stays in self.inflight with sent_peer=None — resent after
            # reconnect (exactly one loss accounting per attempt: the
            # reply callback fires once, and _on_reply cleared sent_peer)
            self._need_resend = True
            self._ensure_drain()
            return
        self.inflight.pop(call.seq, None)
        self._fail_call(
            call,
            err
            if isinstance(err, ActorDiedError)
            else ActorDiedError(
                self.actor_id.hex(), "actor worker died (connection lost)"
            ),
        )
        self._ensure_drain()

    async def _connect(self) -> bool:
        try:
            info = await self.core.peer.call("actor_locate", self.actor_id)
        except Exception as e:  # noqa: BLE001 — controller gone
            self.dead_error = ActorDiedError(self.actor_id.hex(), f"cluster down: {e}")
            return False
        if info["state"] != "ALIVE":
            self.dead_error = ActorDiedError(
                self.actor_id.hex(), info.get("reason", "actor dead")
            )
            return False
        host, port = info["addr"].rsplit(":", 1)
        try:
            self.peer = await rpc.connect(
                host, int(port), _PeerHandler(), retries=5, delay=0.05
            )
        except rpc.ConnectionLost:
            # Actor may have died between locate and connect; loop back to
            # locate (which observes the restart/death). Bound the spin.
            self._connect_failures += 1
            if self._connect_failures > 20:
                self.dead_error = ActorDiedError(
                    self.actor_id.hex(), "actor worker unreachable"
                )
            else:
                await asyncio.sleep(0.05)
            return False
        self._connect_failures = 0
        self.instance = info.get("instance", 0)
        return True

    # -- completion -----------------------------------------------------
    def _store_result(self, oid, payload, is_err: bool, kind: str, registered: bool) -> None:
        store_result(self.core, oid, payload, is_err, kind, registered)

    def _complete(self, call: _Call, results: List[tuple], error) -> None:
        self.inflight.pop(call.seq, None)
        complete_results(self.core, call.spec, results, error)
        self._done(call)

    def _fail_call(self, call: _Call, exc: Optional[Exception], serialized: Optional[bytes] = None) -> None:
        fail_returns(self.core, call.spec, exc, serialized)
        self._done(call)

    def _fail_all(self, exc: Exception) -> None:
        for call in list(self.inflight.values()):
            self._fail_call(call, exc)
        self.inflight.clear()
        while self.queue:
            self._fail_call(self.queue.popleft(), exc)

    def _done(self, call: _Call) -> None:
        call.pins = None  # releases arg pins (ObjectRef __del__ → ref decs)
        self.core._direct_task_done(call.spec)

    def _cancel(self, task_id) -> None:
        for i, call in enumerate(self.queue):
            if call.spec.task_id == task_id:
                del self.queue[i]
                self._fail_call(call, TaskCancelledError(task_id.hex()))
                return
        for seq, call in list(self.inflight.items()):
            if call.spec.task_id != task_id:
                continue
            if call.sent_peer is None:
                # awaiting resend after a connection loss — cancel locally
                # instead of silently re-executing on the restarted actor
                self.inflight.pop(seq, None)
                self._fail_call(call, TaskCancelledError(task_id.hex()))
            elif self.peer is not None:
                asyncio.get_running_loop().create_task(
                    self.peer.notify("cancel", task_id)
                )
            return


class _DepFailed(Exception):
    def __init__(self, payload: bytes):
        self.payload = payload


# -- shared direct-transport completion helpers (used by the actor path
#    above and the normal-task lease path, normal_direct.py) ------------
def store_result(core, oid, payload, is_err: bool, kind: str, registered: bool) -> None:
    """Resolve a return entry in the owner-local memory store, honoring
    escapes and drops that raced the in-flight call: a deferred promotion
    publishes now; a doomed entry whose object became GLOBAL (shm, or
    registered by the worker) reports the drop so the controller can GC
    it. Loop-thread only."""
    ms = core.memory_store
    key = oid.binary()
    doomed, want_promote = ms.put(key, payload, is_err, kind=kind)
    promoted = registered
    if registered:
        ms.mark_promoted(key)
    if want_promote and kind == "inline" and not registered:
        data, err = payload, is_err
        if isinstance(data, Exception):
            from ray_tpu.utils.serialization import serialize

            data, err = serialize(data), True
        asyncio.ensure_future(
            core.peer.notify("object_put_inline", oid, bytes(data), err, [])
        )
        ms.mark_promoted(key)
        promoted = True
    if doomed and (kind == "shm" or promoted):
        # global object whose local refs all dropped mid-flight — the
        # flush loop skipped the drop (entry was pending local-only)
        asyncio.ensure_future(
            core.peer.notify("ref_update", core.worker_id.hex(), [], [key])
        )


def complete_results(core, spec: TaskSpec, results: List[tuple], error) -> None:
    """Store a push reply's results (same wire shape as _report_direct)."""
    if error is not None:
        from ray_tpu.utils.serialization import serialize

        blob = serialize(error)
        for oid in spec.return_ids():
            store_result(core, oid, blob, True, "inline", False)
        return
    for item in results:
        oid, kind = item[0], item[1]
        if kind == "inline":
            registered = bool(len(item) > 4 and item[4])
            store_result(core, oid, item[2], bool(item[3]), "inline", registered)
        else:
            store_result(core, oid, None, False, "shm", True)


def fail_returns(core, spec: TaskSpec, exc: Optional[Exception], serialized: Optional[bytes] = None) -> None:
    from ray_tpu.utils.serialization import serialize

    blob = serialized if serialized is not None else serialize(exc)
    for oid in spec.return_ids():
        store_result(core, oid, blob, True, "inline", False)


def _copy_future(src):
    """A fresh concurrent Future mirroring ``src`` — asyncio.wrap_future
    refuses to wrap the same concurrent future twice across loops."""
    import concurrent.futures

    dst = concurrent.futures.Future()

    def _copy(f):
        if dst.done():
            return
        exc = f.exception()
        if exc is not None:
            dst.set_exception(exc)
        else:
            # already-done future (done-callback): no wait  # ray-tpu: lint-ignore[RTL008]
            dst.set_result(f.result())

    src.add_done_callback(_copy)
    return dst
