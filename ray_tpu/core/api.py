"""Public core API: init/remote/get/put/wait/kill/cancel/get_actor.

Reference: python/ray/_private/worker.py (``ray.init`` :1240, ``get`` :2601,
``put`` :2737, ``wait`` :2802, ``kill`` :2983, ``cancel`` :3014,
``get_actor`` :2948).
"""
from __future__ import annotations

import atexit
import json
import os
import subprocess
import sys
import time
from typing import Any, Optional, Sequence

from ray_tpu.config import Config, get_config
from ray_tpu.core.actor import ActorClass, ActorHandle
from ray_tpu.core.client import CoreWorker
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.remote_function import RemoteFunction
from ray_tpu.utils import rpc

_global_worker: Optional[CoreWorker] = None
_controller_proc: Optional[subprocess.Popen] = None
_session_dir: Optional[str] = None


def is_initialized() -> bool:
    return _global_worker is not None


def _require_worker() -> CoreWorker:
    if _global_worker is None:
        raise RuntimeError("ray_tpu.init() has not been called")
    return _global_worker


def _attach_worker(core: CoreWorker):
    """Called by worker processes so the public API works inside tasks."""
    global _global_worker
    _global_worker = core


def _detect_tpu_chips() -> int:
    """Count local TPU chips (reference:
    python/ray/_private/accelerators/tpu.py:98-117 — /dev/accel* and vfio)."""
    import glob

    n = len(glob.glob("/dev/accel*"))
    if n == 0:
        n = len(glob.glob("/dev/vfio/*")) - (1 if os.path.exists("/dev/vfio/vfio") else 0)
        n = max(n, 0)
    return n


def init(
    address: Optional[str] = None,
    *,
    num_cpus: Optional[int] = None,
    num_tpus: Optional[int] = None,
    resources: Optional[dict] = None,
    object_store_memory: Optional[int] = None,
    ignore_reinit_error: bool = False,
    _system_config: Optional[dict] = None,
) -> dict:
    """Start (or connect to) a cluster and connect this process as a driver."""
    global _global_worker, _controller_proc, _session_dir
    from ray_tpu.util import chaos, lockwatch

    lockwatch.maybe_install()  # RAY_TPU_LOCKWATCH=1: driver-side watchdog
    chaos.install_fault_plan_from_env()  # RAY_TPU_FAULT_PLAN: deterministic chaos
    if _global_worker is not None:
        if ignore_reinit_error:
            return {"address": _global_worker.address}
        raise RuntimeError("ray_tpu.init() called twice; use ignore_reinit_error=True")

    if address == "auto":
        # Reference: ray.init("auto") resolves the running cluster from the
        # env (set for job drivers) or the address file `ray start` wrote.
        address = os.environ.get("RAY_TPU_ADDRESS")
        if address is None:
            addr_file = os.path.join(get_config().temp_dir, "ray_current_cluster")
            if os.path.exists(addr_file):
                with open(addr_file) as f:
                    address = f.read().strip() or None
        if address is None:
            raise ConnectionError(
                "address='auto' but no running cluster found (no RAY_TPU_ADDRESS "
                "env var and no address file)"
            )

    if address is None:
        head_resources = dict(resources or {})
        head_resources.setdefault("CPU", num_cpus if num_cpus is not None else os.cpu_count() or 1)
        tpus = num_tpus if num_tpus is not None else _detect_tpu_chips()
        if tpus:
            head_resources.setdefault("TPU", tpus)
        cfg_overrides = dict(_system_config or {})
        if object_store_memory:
            cfg_overrides["object_store_memory"] = object_store_memory
        address, _controller_proc, _session_dir = _start_controller(
            head_resources, cfg_overrides, owned=True
        )

    loop_runner = rpc.EventLoopThread("driver-io")
    from ray_tpu.core.client import DriverHandler

    _global_worker = CoreWorker(
        address, mode="driver", loop_runner=loop_runner, handler=DriverHandler()
    )
    # Drivers run jax too (single-process training/bench loops): give
    # them the same device-telemetry + compile-tracking reporting.
    from ray_tpu.core.node_telemetry import start_process_telemetry

    start_process_telemetry(_global_worker)
    # Structured log plane, driver leg: logging records (incl. exception
    # tracebacks the driver logs) get a driver-<pid>.jsonl sidecar and
    # ERROR shipping to the controller's error index. Handler-only — the
    # driver's console streams stay untouched (core/log_plane.py).
    if _global_worker.config.get("log_structured", True):
        from ray_tpu.core import log_plane

        log_plane.install(
            _global_worker.session_dir,
            node_id=_global_worker.node_id.hex(),
            worker_id=None,
            proc=f"driver-{os.getpid()}",
            capture_streams=False,
            rotate_bytes=int(
                _global_worker.config.get("log_rotate_bytes", 64 << 20)
            ),
        )
        log_plane.start_ship_loop(_global_worker)
    # Continuous low-rate CPU sampling for incident auto-capture (no-op
    # unless profiling_continuous_hz is configured).
    from ray_tpu.util import profiling

    profiling.ensure_continuous()
    atexit.register(shutdown)
    return {"address": address, "session_dir": _global_worker.session_dir}


def _start_controller(head_resources: dict, cfg_overrides: dict, owned: bool):
    session_dir = os.path.join(
        get_config().temp_dir, f"session_{int(time.time()*1000)}_{os.getpid()}"
    )
    os.makedirs(os.path.join(session_dir, "logs"), exist_ok=True)
    from ray_tpu.core.node_agent import child_env

    env = child_env(needs_tpu=False)
    log = open(os.path.join(session_dir, "logs", "controller.log"), "ab")
    cmd = [
        sys.executable,
        "-m",
        "ray_tpu.core.controller",
        "--session-dir",
        session_dir,
        "--resources",
        json.dumps(head_resources),
        "--config",
        json.dumps(cfg_overrides),
    ]
    if owned:
        cmd.append("--owned")
    proc = subprocess.Popen(cmd, env=env, stdout=log, stderr=subprocess.STDOUT)
    port_file = os.path.join(session_dir, "controller_port")
    deadline = time.time() + 30
    while time.time() < deadline:
        if os.path.exists(port_file):
            with open(port_file) as f:
                content = f.read().strip()
            if content:
                return f"127.0.0.1:{content}", proc, session_dir
        if proc.poll() is not None:
            raise RuntimeError(
                f"controller exited with {proc.returncode}; see {session_dir}/logs/controller.log"
            )
        time.sleep(0.02)
    raise RuntimeError("timed out waiting for controller to start")


def shutdown():
    global _global_worker, _controller_proc, _session_dir
    if _global_worker is None:
        return
    try:
        if _controller_proc is not None:
            try:
                # Deliberate teardown: the controller dies on receipt, so
                # never ride the reconnect window on its way down.
                _global_worker._reconnect_dead = True
                _global_worker._call("shutdown_cluster", timeout=5)
            except Exception:
                pass
    finally:
        from ray_tpu.core import log_plane

        log_plane.uninstall()  # driver leg: handler off, sidecar closed
        _global_worker.disconnect()
        _global_worker.loop_runner.stop()
        _global_worker = None
        if _controller_proc is not None:
            try:
                _controller_proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                _controller_proc.kill()
            _controller_proc = None
        atexit.unregister(shutdown)


def remote(*args, **kwargs):
    """``@remote`` / ``@remote(num_cpus=..., num_tpus=...)`` decorator for
    functions (→ RemoteFunction) and classes (→ ActorClass)."""

    def wrap(target, options):
        if isinstance(target, type):
            return ActorClass(target, options)
        return RemoteFunction(target, options)

    if len(args) == 1 and not kwargs and (callable(args[0]) or isinstance(args[0], type)):
        return wrap(args[0], {})
    if args:
        raise TypeError("@remote only accepts keyword options")
    return lambda target: wrap(target, kwargs)


def get(refs, timeout: Optional[float] = None):
    return _require_worker().get(refs, timeout=timeout)


def put(value: Any) -> ObjectRef:
    return _require_worker().put(value)


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1, timeout: Optional[float] = None):
    if num_returns > len(refs):
        raise ValueError(
            f"num_returns ({num_returns}) cannot exceed the number of refs ({len(refs)})"
        )
    return _require_worker().wait(refs, num_returns=num_returns, timeout=timeout)


def kill(actor: ActorHandle, *, no_restart: bool = True):
    _require_worker().kill_actor(actor._actor_id, no_restart)


def cancel(ref: ObjectRef, *, force: bool = False):
    # The return object id embeds the producing task id only server-side;
    # look the task up by its return object.
    core = _require_worker()
    core.cancel_by_object(ref.id, force)


def get_actor(name: str) -> ActorHandle:
    info = _require_worker().get_actor_by_name(name)
    if info is None:
        raise ValueError(f"Failed to look up actor with name '{name}'")
    spec = info["creation_spec"]
    return ActorHandle(info["actor_id"], max_task_retries=spec.max_task_retries)


def free(refs: Sequence[ObjectRef]):
    _require_worker().free(refs)


def wait_actor_ready(actor: ActorHandle, timeout: Optional[float] = None):
    """Block until the actor finished __init__ (handy in tests)."""
    return _require_worker().wait_actor_ready(actor._actor_id, timeout=timeout)


def cluster_resources() -> dict:
    return _require_worker().cluster_resources()


def available_resources() -> dict:
    return _require_worker().available_resources()


def nodes() -> list:
    return _require_worker().list_state("nodes")


def drain_node(node_id, timeout_s: float = 300.0) -> bool:
    """Gracefully drain a node: no new placements, running work finishes,
    then the node retires (reference: `ray drain-node` / rpc::DrainNode)."""
    from ray_tpu.utils.ids import NodeID

    if isinstance(node_id, str):
        node_id = NodeID.from_hex(node_id)
    return _require_worker().drain_node(node_id, timeout_s)


def timeline() -> list:
    """Task state-transition events (reference: `ray timeline` CLI →
    chrome_tracing_dump, python/ray/_private/state.py:438)."""
    return _require_worker().list_state("events")
