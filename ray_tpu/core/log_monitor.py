"""Per-node log tailer: stream worker stdout/stderr to the driver.

Reference: python/ray/_private/log_monitor.py — one monitor per node
tails worker log files and publishes new lines; the driver prints them
(worker.py:1924 print_to_stdstream). Here the tailer runs inside the
node agent (and the controller for head-node workers), forwards line
batches over the existing control connection, and the controller fans
them out to connected drivers.

Rotation tolerance: worker logs are size-capped (``log_rotate_bytes``,
core/log_plane.py) — the raw file by copy-truncate, the structured
sidecar by rename, both keeping one ``.1`` half. When a tracked file
shrinks below (or renames out from under) the stored offset, the tailer
first drains the unread suffix of the ``.1`` half — which holds exactly
the pre-rotation content — then resets to offset 0, so rotation emits
neither duplicated nor silently dropped lines.
"""
from __future__ import annotations

import glob
import logging
import os
import threading
from typing import Callable, Dict, List, Optional, Tuple

logger = logging.getLogger("ray_tpu.log_monitor")

# A batch is a list of (source, line) tuples; source is the log file's
# basename (e.g. "worker-ab12cd34.log") which encodes the worker id.
LogBatch = List[Tuple[str, str]]


class LogTailer:
    """Polls ``worker-*.log`` files under a log dir for appended lines."""

    def __init__(
        self,
        log_dir: str,
        publish: Callable[[LogBatch], None],
        poll_interval: float = 0.25,
        pattern: str = "worker-*.log",
        max_batch_lines: int = 1000,
        start_at_end: bool = False,
    ):
        self.log_dir = log_dir
        self.pattern = pattern
        self.publish = publish
        self.poll_interval = poll_interval
        self.max_batch_lines = max_batch_lines
        # Follow mode: files already on disk when the tailer starts are
        # picked up from their current END — a follower wants new lines,
        # not a replay of the whole sidecar.
        self.start_at_end = start_at_end
        self._offsets: Dict[str, int] = {}
        self._inodes: Dict[str, int] = {}
        self._partials: Dict[str, str] = {}
        # Lines read but not yet emitted (batch-cap overflow carry-over).
        self._pending: LogBatch = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(target=self._loop, name="log-tailer", daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)

    # ------------------------------------------------------------------
    def _loop(self):
        while not self._stop.wait(self.poll_interval):
            try:
                batch = self.poll_once()
                if batch:
                    self.publish(batch)
            except Exception as e:  # pragma: no cover — keep tailing
                logger.debug("log tail poll failed: %s", e)
        # Final sweep so lines written just before shutdown still arrive.
        try:
            batch = self.poll_once()
            if batch:
                self.publish(batch)
        except Exception as e:
            logger.debug("final log sweep failed: %s", e)

    def _read_span(self, path: str, offset: int, size: int) -> Optional[bytes]:
        try:
            with open(path, "rb") as f:
                f.seek(offset)
                return f.read(size - offset)
        except OSError:
            return None

    def _append_lines(self, name: str, data: bytes, batch: LogBatch):
        text = self._partials.pop(name, "") + data.decode("utf-8", errors="replace")
        lines = text.split("\n")
        # Trailing element is a partial line (or "" after a newline).
        if lines and lines[-1]:
            self._partials[name] = lines[-1]
        for line in lines[:-1]:
            # Blank lines are preserved — the driver should reproduce
            # worker output faithfully.
            if len(batch) < self.max_batch_lines:
                batch.append((name, line))
            else:
                self._pending.append((name, line))

    def poll_once(self) -> LogBatch:
        # Overflow from the previous poll goes out first — the offset has
        # already advanced past those bytes, so dropping them would lose
        # lines permanently.
        batch: LogBatch = self._pending[: self.max_batch_lines]
        self._pending = self._pending[self.max_batch_lines :]
        if len(batch) >= self.max_batch_lines:
            return batch
        for path in sorted(glob.glob(os.path.join(self.log_dir, self.pattern))):
            name = os.path.basename(path)
            try:
                st = os.stat(path)
            except OSError:
                continue
            size = st.st_size
            if self.start_at_end and name not in self._offsets:
                self._offsets[name] = size
                self._inodes[name] = st.st_ino
                continue
            offset = self._offsets.get(name, 0)
            prev_ino = self._inodes.get(name)
            self._inodes[name] = st.st_ino
            rotated = size < offset or (
                prev_ino is not None and st.st_ino != prev_ino and offset > 0
            )
            if rotated:
                # Drain the unread pre-rotation suffix from the .1 half:
                # copy-truncate copies the full old content there, rename
                # rotation MOVES the old file there — either way bytes
                # [offset:] of <path>.1 are exactly what we had not read.
                old = path + ".1"
                try:
                    old_size = os.path.getsize(old)
                except OSError:
                    old_size = -1
                if old_size > offset:
                    data = self._read_span(old, offset, old_size)
                    if data:
                        self._append_lines(name, data, batch)
                elif old_size < offset:
                    # double rotation between polls (or a plain truncate):
                    # the unread span is gone — resync rather than re-emit
                    self._partials.pop(name, None)
                    logger.debug("log %s rotated past the tail offset", name)
                offset = self._offsets[name] = 0
            if size <= offset:
                continue
            data = self._read_span(path, offset, size)
            if data is None:
                continue
            self._offsets[name] = size
            self._append_lines(name, data, batch)
        return batch


# ---------------------------------------------------------------------------
# Driver-side sinks
# ---------------------------------------------------------------------------
def print_to_driver(batch: LogBatch):
    """Driver-side sink (reference: print_to_stdstream — prefix lines with
    their source worker)."""
    import sys

    out = []
    for source, line in batch:
        tag = source.replace("worker-", "").replace(".log", "")
        out.append(f"({tag}) {line}\n")
    # direct stream write, not print(): this REPRODUCES worker output on
    # the driver console — routing it through a logger would re-format,
    # re-level, and re-capture it
    sys.stderr.write("".join(out))


# Structured follow-mode sink (``ray-tpu logs --follow``): the controller
# pushes filtered record batches over the driver connection
# (rpc_log_records); whoever registered the sink renders them.
_follow_sink: Optional[Callable[[List[dict]], None]] = None


def set_follow_sink(fn: Optional[Callable[[List[dict]], None]]):
    global _follow_sink
    _follow_sink = fn


def deliver_records(batch: List[dict]):
    sink = _follow_sink
    if sink is None:
        import sys

        from ray_tpu.core.log_plane import format_record

        sys.stderr.write("".join(format_record(r) + "\n" for r in batch))
        return
    try:
        sink(batch)
    except Exception as e:  # noqa: BLE001 — a sink bug must not kill the RPC loop
        logger.debug("follow sink failed: %s", e)
