"""Per-node log tailer: stream worker stdout/stderr to the driver.

Reference: python/ray/_private/log_monitor.py — one monitor per node
tails worker log files and publishes new lines; the driver prints them
(worker.py:1924 print_to_stdstream). Here the tailer runs inside the
node agent (and the controller for head-node workers), forwards line
batches over the existing control connection, and the controller fans
them out to connected drivers.
"""
from __future__ import annotations

import glob
import logging
import os
import threading
from typing import Callable, Dict, List, Optional, Tuple

logger = logging.getLogger("ray_tpu.log_monitor")

# A batch is a list of (source, line) tuples; source is the log file's
# basename (e.g. "worker-ab12cd34.log") which encodes the worker id.
LogBatch = List[Tuple[str, str]]


class LogTailer:
    """Polls ``worker-*.log`` files under a log dir for appended lines."""

    def __init__(
        self,
        log_dir: str,
        publish: Callable[[LogBatch], None],
        poll_interval: float = 0.25,
        pattern: str = "worker-*.log",
        max_batch_lines: int = 1000,
    ):
        self.log_dir = log_dir
        self.pattern = pattern
        self.publish = publish
        self.poll_interval = poll_interval
        self.max_batch_lines = max_batch_lines
        self._offsets: Dict[str, int] = {}
        self._partials: Dict[str, str] = {}
        # Lines read but not yet emitted (batch-cap overflow carry-over).
        self._pending: LogBatch = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(target=self._loop, name="log-tailer", daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)

    # ------------------------------------------------------------------
    def _loop(self):
        while not self._stop.wait(self.poll_interval):
            try:
                batch = self.poll_once()
                if batch:
                    self.publish(batch)
            except Exception as e:  # pragma: no cover — keep tailing
                logger.debug("log tail poll failed: %s", e)
        # Final sweep so lines written just before shutdown still arrive.
        try:
            batch = self.poll_once()
            if batch:
                self.publish(batch)
        except Exception as e:
            logger.debug("final log sweep failed: %s", e)

    def poll_once(self) -> LogBatch:
        # Overflow from the previous poll goes out first — the offset has
        # already advanced past those bytes, so dropping them would lose
        # lines permanently.
        batch: LogBatch = self._pending[: self.max_batch_lines]
        self._pending = self._pending[self.max_batch_lines :]
        if len(batch) >= self.max_batch_lines:
            return batch
        for path in sorted(glob.glob(os.path.join(self.log_dir, self.pattern))):
            name = os.path.basename(path)
            try:
                size = os.path.getsize(path)
            except OSError:
                continue
            offset = self._offsets.get(name, 0)
            if size <= offset:
                continue
            try:
                with open(path, "rb") as f:
                    f.seek(offset)
                    data = f.read(size - offset)
            except OSError:
                continue
            self._offsets[name] = size
            text = self._partials.pop(name, "") + data.decode("utf-8", errors="replace")
            lines = text.split("\n")
            # Trailing element is a partial line (or "" after a newline).
            if lines and lines[-1]:
                self._partials[name] = lines[-1]
            for line in lines[:-1]:
                # Blank lines are preserved — the driver should reproduce
                # worker output faithfully.
                if len(batch) < self.max_batch_lines:
                    batch.append((name, line))
                else:
                    self._pending.append((name, line))
        return batch


def print_to_driver(batch: LogBatch):
    """Driver-side sink (reference: print_to_stdstream — prefix lines with
    their source worker)."""
    import sys

    for source, line in batch:
        tag = source.replace("worker-", "").replace(".log", "")
        print(f"({tag}) {line}", file=sys.stderr)
