"""Node↔node object transfer over the RPC layer.

Reference: src/ray/object_manager/object_manager.cc (chunked Push/Pull,
812 L; 64 MB default chunks), object_buffer_pool.cc (chunk framing) and
pull_manager.h:43-52 (pull orchestration). Shape here: the DESTINATION
node's agent pulls chunks from the SOURCE node's agent listener into its
own plasma store (pull-based, like the reference's PullManager), with a
bounded window of in-flight chunks (the reference's PushManager
rate-limits in-flight chunks the same way).

The controller plays the object directory role (reference:
ownership_based_object_directory.cc): it picks the source replica and
records the new location when the pull completes.
"""
from __future__ import annotations

import asyncio
import collections
import logging
import time as _time
from typing import Optional

from ray_tpu.utils import rpc
from ray_tpu.utils.ids import ObjectID

logger = logging.getLogger("ray_tpu.object_transfer")

DEFAULT_WINDOW = 4


async def fetch_into(src_peer, oid: ObjectID, size: int, view, chunk_bytes: int,
                     window: int = DEFAULT_WINDOW,
                     progress=None) -> Optional[BaseException]:
    """Fill ``view`` (a writable memoryview of ``size`` bytes) with the
    object's content fetched from ``src_peer`` in pipelined chunks.

    ``progress(watermark_bytes)``, if given, is called as the CONTIGUOUS
    prefix of the object grows — the hook that lets a broadcast chain
    forward bytes downstream while this node is still receiving
    (reference: push_manager.h streams chunks through intermediate
    nodes).

    Returns the first error (traceback stripped) instead of raising: by
    return time every chunk task has finished, and no frame anywhere
    still exports ``view`` — so the caller can close its buffer without
    BufferError and clean up a torn object."""
    if size <= 0:
        return None
    from ray_tpu.collective.diagnostics import transfer_metrics

    tm = transfer_metrics()
    t0 = _time.perf_counter()
    sem = asyncio.Semaphore(max(1, window))
    done_offsets: set = set()
    watermark = 0

    def _advance(off: int):
        nonlocal watermark
        done_offsets.add(off)
        while watermark in done_offsets:
            done_offsets.discard(watermark)
            watermark += min(chunk_bytes, size - watermark)
        if progress is not None:
            progress(watermark)

    async def one(off: int):
        n = min(chunk_bytes, size - off)
        async with sem:
            data = await src_peer.call("fetch_chunk", oid, off, n)
        if len(data) != n:
            raise IOError(
                f"short chunk for {oid.hex()} at {off}: got {len(data)}, want {n}"
            )
        view[off : off + n] = data
        _advance(off)

    results = await asyncio.gather(
        *(one(off) for off in range(0, size, chunk_bytes)),
        return_exceptions=True,
    )
    tm.fetch_ms.observe((_time.perf_counter() - t0) * 1000.0)
    tm.chunks.inc(len(results))
    for r in results:
        if isinstance(r, BaseException):
            # the traceback chain would pin frames that captured `view`
            return r.with_traceback(None)
    tm.bytes.inc(size)
    return None


class InflightPull:
    """An object mid-pull whose contiguous prefix is readable — lets a
    broadcast chain hop serve chunks downstream while still receiving
    from upstream (reference: push_manager.h chunk streaming through
    intermediate nodes). Loop-thread only."""

    __slots__ = ("view", "size", "watermark", "failed", "_waiters")

    def __init__(self, view, size: int):
        self.view = view
        self.size = size
        self.watermark = 0
        self.failed = False
        self._waiters: list = []

    def advance(self, watermark: int):
        self.watermark = watermark
        if self._waiters:
            for fut in self._waiters:
                if not fut.done():
                    fut.set_result(None)
            self._waiters.clear()

    def fail(self):
        self.failed = True
        self.advance(self.watermark)

    async def wait_for(self, end: int):
        while self.watermark < end and not self.failed:
            fut = asyncio.get_running_loop().create_future()
            self._waiters.append(fut)
            await fut
        if self.failed:
            raise IOError("upstream pull failed mid-chain")

    def read(self, offset: int, length: int) -> bytes:
        return bytes(self.view[offset : offset + length])


class ChunkReader:
    """Source-side chunk server with a small cache of open buffers — a
    1 GiB transfer is 128 chunk RPCs, and re-mmapping the whole object
    per chunk costs more than the copy (reference: ObjectBufferPool
    holds the object's chunks open for the transfer's duration)."""

    def __init__(self, store, capacity: int = 4):
        self.store = store
        self.capacity = capacity
        self._bufs: "collections.OrderedDict[ObjectID, object]" = collections.OrderedDict()

    def read(self, oid: ObjectID, offset: int, length: int) -> bytes:
        from ray_tpu.collective.diagnostics import transfer_metrics

        transfer_metrics().chunks_served.inc()
        buf = self._bufs.pop(oid, None)
        if buf is None:
            self.store.ensure_local(oid)
            buf = self.store.get(oid)
            if buf is None:
                raise KeyError(f"object {oid.hex()} not in store")
        view = buf.view()
        try:
            data = bytes(view[offset : offset + length])
            last = offset + length >= view.nbytes
        finally:
            del view
        if last:
            buf.close()  # final chunk — transfer complete
        else:
            self._bufs[oid] = buf
            while len(self._bufs) > self.capacity:
                _, old = self._bufs.popitem(last=False)
                old.close()
        return data

    def invalidate(self, oid: ObjectID):
        """Drop a cached buffer when the store deletes the object — a
        same-id recreation (lineage reconstruction) must never be served
        stale bytes from the old mapping, and aborted transfers must not
        pin unlinked tmpfs files."""
        buf = self._bufs.pop(oid, None)
        if buf is not None:
            buf.close()

    def close(self):
        while self._bufs:
            _, buf = self._bufs.popitem()
            buf.close()


class FetchPeerCache:
    """Cached connections to other nodes' transfer listeners (used by
    both the node agent and the controller's head-pull path)."""

    class _Handler:
        def on_disconnect(self, peer):
            pass

    def __init__(self):
        self._peers: dict = {}

    async def get(self, addr: str) -> Optional[rpc.Peer]:
        p = self._peers.get(addr)
        if p is None or p.closed:
            host, port = addr.rsplit(":", 1)
            try:
                p = await rpc.connect(
                    host, int(port), FetchPeerCache._Handler(), retries=5, delay=0.05
                )
            except rpc.ConnectionLost:
                return None
            self._peers[addr] = p
        return p



async def pull_into_store(store, oid: ObjectID, size: int, src_peer,
                          chunk_bytes: int) -> bool:
    """Pull a remote object into ``store`` (destination side). Partial
    pulls are deleted on failure so the store never holds torn objects
    (unsealed objects are additionally invisible to readers — arena
    lookups require the sealed state; file-tier objects live under a
    .part name until sealed)."""
    if store.contains(oid) and store.ensure_local(oid):
        return True
    loop = asyncio.get_running_loop()
    # Seal-wait bound scales with object size: a healthy concurrent
    # writer of a multi-GiB object on a slow link must not trip a fixed
    # 30s timeout (floor assumes >= 32 MiB/s effective transfer rate).
    seal_wait = 30.0 + size / (32 * 1024 * 1024)
    deadline = loop.time() + seal_wait
    while True:
        try:
            buf = store.create(oid, size)
            break
        except FileExistsError:
            pass
        # A concurrent pull (or a local task recreating the same object
        # id) holds the unsealed slot. Returning success immediately
        # would let the caller's try_view race the seal — wait until the
        # winner seals, or until its partial entry is deleted (writer
        # crashed), in which case we retry the create ourselves.
        while loop.time() < deadline:
            # ensure_local is sealed-gated (unsealed arena entries don't
            # resolve; file-tier objects live under .part until sealed)
            # and also sees cross-process arena writers.
            if store.ensure_local(oid):
                return True
            if not store.contains(oid):
                # Writer vanished from this process's table — take over
                # the pull. (A cross-process arena writer is invisible to
                # contains(); the sleep keeps the create-retry from
                # busy-spinning against its still-unsealed arena slot.)
                await asyncio.sleep(0.01)
                break
            await asyncio.sleep(0.01)
        else:
            raise TimeoutError(
                f"object {oid.hex()}: concurrent writer never sealed "
                f"within {seal_wait:.0f}s"
            )
    view = buf.view()
    err = await fetch_into(src_peer, oid, size, view, chunk_bytes)
    del view
    buf.close()
    if err is not None:
        store.delete(oid)
        raise err
    store.seal(oid)
    return True
