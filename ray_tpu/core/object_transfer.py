"""Node↔node object transfer over the RPC layer.

Reference: src/ray/object_manager/object_manager.cc (chunked Push/Pull,
812 L; 64 MB default chunks), object_buffer_pool.cc (chunk framing) and
pull_manager.h:43-52 (pull orchestration). Shape here: the DESTINATION
node's agent pulls chunks from the SOURCE node's agent listener into its
own plasma store (pull-based, like the reference's PullManager), with a
bounded window of in-flight chunks (the reference's PushManager
rate-limits in-flight chunks the same way).

The controller plays the object directory role (reference:
ownership_based_object_directory.cc): it picks the source replica and
records the new location when the pull completes.
"""
from __future__ import annotations

import asyncio
import logging

from ray_tpu.utils.ids import ObjectID

logger = logging.getLogger("ray_tpu.object_transfer")

DEFAULT_WINDOW = 4


async def fetch_into(src_peer, oid: ObjectID, size: int, view, chunk_bytes: int,
                     window: int = DEFAULT_WINDOW) -> None:
    """Fill ``view`` (a writable memoryview of ``size`` bytes) with the
    object's content fetched from ``src_peer`` in pipelined chunks."""
    if size <= 0:
        return
    sem = asyncio.Semaphore(max(1, window))

    async def one(off: int):
        n = min(chunk_bytes, size - off)
        async with sem:
            data = await src_peer.call("fetch_chunk", oid, off, n)
        if len(data) != n:
            raise IOError(
                f"short chunk for {oid.hex()} at {off}: got {len(data)}, want {n}"
            )
        view[off : off + n] = data

    await asyncio.gather(*(one(off) for off in range(0, size, chunk_bytes)))


def read_chunk(store, oid: ObjectID, offset: int, length: int) -> bytes:
    """Serve one chunk out of a node's plasma store (source side)."""
    store.ensure_local(oid)
    buf = store.get(oid)
    if buf is None:
        raise KeyError(f"object {oid.hex()} not in store")
    try:
        return bytes(buf.view()[offset : offset + length])
    finally:
        buf.close()


async def pull_into_store(store, oid: ObjectID, size: int, src_peer,
                          chunk_bytes: int) -> bool:
    """Pull a remote object into ``store`` (destination side). Partial
    pulls are deleted on failure so the store never holds torn objects."""
    if store.contains(oid) and store.ensure_local(oid):
        return True
    try:
        buf = store.create(oid, size)
    except FileExistsError:
        return True  # concurrent pull won
    try:
        await fetch_into(src_peer, oid, size, buf.view(), chunk_bytes)
    except BaseException:
        buf.close()
        store.delete(oid)
        raise
    buf.close()
    store.seal(oid)
    return True
