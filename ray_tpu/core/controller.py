"""The controller: head-node control plane.

One process combining what the reference splits across the GCS server
(src/ray/gcs/gcs_server/gcs_server.h:219-297 — node/actor/PG/job/KV/pubsub
managers), the raylet's cluster scheduler (src/ray/raylet/scheduling/
cluster_task_manager.cc, GCS-direct mode per gcs_actor_scheduler.cc:60), and
the object directory (src/ray/object_manager/ownership_based_object_directory.cc).

Everything runs on one asyncio loop — state is mutated only from loop
callbacks, which supplies the single-writer discipline the reference gets
from per-component io_contexts (src/ray/common/asio/instrumented_io_context).

Process topology (cf. reference python/ray/_private/node.py:37):
  controller (this)      — control plane + head-node worker pool
  node agents (0..N)     — extra nodes; spawn/kill worker processes
  workers                — connect directly to the controller for dispatch
  drivers                — connect directly to the controller
"""
from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import signal
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple

from ray_tpu.config import Config, set_config
from ray_tpu.core.lifecycle import DEATH_CHANNEL, LifecycleRecorder
from ray_tpu.core.object_store import PlasmaStore
from ray_tpu.core.placement_group import PlacementGroupManager
from ray_tpu.core.resources import NodeResources, ResourceSet
from ray_tpu.core.scheduler import ClusterResourceScheduler, ClusterState
from ray_tpu.core.task_spec import SchedulingStrategy, TaskSpec, TaskType
from ray_tpu.exceptions import (
    ActorDiedError,
    ObjectLostError,
    OutOfMemoryError,
    TaskCancelledError,
    WorkerCrashedError,
)
from ray_tpu.runtime_env import env_hash as _env_hash
from ray_tpu.util.guards import OWNER_THREAD, GuardedDict, GuardedSet, snapshot
from ray_tpu.utils import rpc
from ray_tpu.utils.ids import ActorID, NodeID, ObjectID, PlacementGroupID, TaskID, WorkerID

logger = logging.getLogger("ray_tpu.controller")


async def _notify_quiet(peer, method: str, *args, what: str = ""):
    """Best-effort notify to a possibly-dead peer. The expected failure
    mode IS the peer being gone (that is usually why we are notifying), so
    failures are logged at debug instead of swallowed silently."""
    try:
        await peer.notify(method, *args)
    except Exception as e:  # noqa: BLE001 — peer already gone
        logger.debug("notify %s(%s) failed: %s", method, what, e)


# Object meta shapes returned to clients:
#   ("inline", bytes, is_error)
#   ("shm", size, node_id_hex, shm_dir, is_error)


_mem_metrics = None

# Max object records walked per memory-census sweep (round 17): the
# object-table census runs in bounded shards across sweeps instead of
# one O(objects) controller-loop stall per publish.
_CENSUS_CHUNK = 25_000


def _get_mem_metrics():
    """Lazy controller-process memory gauges (Grafana "Memory" row).
    Node tags are node-id prefixes (bounded by cluster size); the
    leak-flag call-site tag is bounded by the detector's trend-table cap
    plus the registry cardinality cap."""
    global _mem_metrics
    if _mem_metrics is None:
        from ray_tpu.util.metrics import Counter, Gauge, Histogram

        _mem_metrics = {
            "store_used": Gauge(
                "object_store_used_bytes",
                "Object store bytes in use per node (file tier + arena)",
                ("node",),
            ),
            "store_pinned": Gauge(
                "object_store_pinned_bytes",
                "Bytes of store objects held by store-side pins per node",
                ("node",),
            ),
            "store_spilled": Gauge(
                "object_store_spilled_bytes",
                "Bytes of store objects spilled to disk per node",
                ("node",),
            ),
            "refs_open": Gauge(
                "object_refs_open",
                "Objects in the controller directory by tier",
                ("kind",),
            ),
            "free_latency": Histogram(
                "object_free_latency_ms",
                "Wall time of one object free (directory pop + replica "
                "delete notifies)",
                boundaries=(0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50,
                            100, 250),
            ),
            "leak_flags": Counter(
                "memory_leak_flags_total",
                "Call-sites newly flagged by the open-ref growth detector",
                ("callsite",),
            ),
        }
    return _mem_metrics


_batch_m = None


def _batch_metrics():
    """Lazy batched-control-plane histograms (Grafana "Control Plane"
    row): how many leases each rpc_lease_batch round-trip granted. The
    caller-side twin (task_push_batch_size) lives in normal_direct.py
    and ships over the ordinary metric channel."""
    global _batch_m
    if _batch_m is None:
        from ray_tpu.util.metrics import Histogram

        _batch_m = {
            "lease_batch": Histogram(
                "lease_batch_size",
                "Leases granted per lease_batch round-trip",
                boundaries=(1, 2, 4, 8, 16, 32, 64),
            ),
        }
    return _batch_m


@dataclass
class ObjectRecord:
    oid: ObjectID
    state: str = "PENDING"  # PENDING | READY | FAILED
    inline: Optional[bytes] = None
    size: int = 0
    locations: Set[NodeID] = field(default_factory=set)
    is_error: bool = False
    creating_task: Optional[TaskID] = None
    waiters: List[asyncio.Future] = field(default_factory=list)
    # Distributed ref counting (reference: reference_count.cc ownership):
    # processes currently holding >=1 local ref; refs serialized inside
    # this object (containment pins); whether any process ever held it
    # (guards against freeing refs still in flight to a first holder).
    holders: Set[str] = field(default_factory=set)
    children: List[ObjectID] = field(default_factory=list)
    ever_held: bool = False
    # Two-phase GC: a candidate must survive one full sweep interval
    # after being marked before it is freed — covers the window where a
    # borrower's "held" flush (<= ref_flush_interval) is still in flight
    # when the last known holder drops.
    gc_marked: bool = False
    # Memory-census attribution (reference: reference_count.cc call_site
    # per ref): the creating user frame / task label, interned client-side
    # (bounded vocabulary), plus who created it.
    callsite: str = ""
    creator: str = ""

    def meta(self, shm_dirs: Dict[NodeID, str]):
        if self.inline is not None:
            return ("inline", self.inline, self.is_error)
        # Prefer any LIVE replica (locations may briefly hold a node whose
        # death is still being processed); None = no live copy.
        for nid in self.locations:
            if nid in shm_dirs:
                return ("shm", self.size, nid.hex(), shm_dirs[nid], self.is_error)
        return None


@dataclass
class WorkerRecord:
    worker_id: WorkerID
    node_id: NodeID
    peer: rpc.Peer
    pid: int = 0
    state: str = "IDLE"  # STARTING | IDLE | LEASED | ACTOR | DEAD
    running: Set[TaskID] = field(default_factory=set)
    actor_id: Optional[ActorID] = None
    oom_marked: bool = False  # killed by the memory monitor
    # Runtime-env hash this worker is locked to ("" = pristine). Reference:
    # worker_pool keys idle workers by runtime-env hash (worker_pool.h:174).
    env_hash: str = ""
    # Direct-transport listener address ("host:port"; "" = none) —
    # callers push actor tasks straight to this endpoint (reference:
    # the worker's CoreWorkerService address in ActorTableData).
    listen_addr: str = ""


@dataclass
class NodeRecord:
    node_id: NodeID
    shm_dir: str
    peer: Optional[rpc.Peer]  # None for the head node (controller-managed)
    hostname: str = "localhost"
    agent_pid: int = 0  # node agent process (0 for the head)
    state: str = "ALIVE"
    # Agent's object-transfer listener ("host:port"; "" for the head —
    # head objects are fetched over the controller connection).
    fetch_addr: str = ""
    # Provider instance identity (reference: autoscaler v2
    # instance_manager's cloud_instance_id ↔ ray node mapping) — lets the
    # autoscaler reap ONE idle node instead of waiting for full idleness.
    provider_instance_id: str = ""
    workers: Set[WorkerID] = field(default_factory=set)
    num_starting: int = 0
    max_workers: int = 32
    # Latest telemetry heartbeat from this node's agent (host CPU/mem,
    # object-store occupancy; controller-sampled for the head). Stamped
    # with the CONTROLLER's clock on arrival ("ts").
    telemetry: Dict[str, Any] = field(default_factory=dict)
    # Free TPU chip indices on this host; actors holding TPU resources get
    # concrete chips via TPU_VISIBLE_CHIPS (reference: accelerators/tpu.py
    # :155-195 isolation + resource_instance_set.cc per-instance accounting).
    tpu_free: List[int] = field(default_factory=list)


@dataclass
class LeaseRecord:
    """A granted worker lease for direct normal-task submission
    (reference: the raylet's granted leases in local_task_manager.h). The
    controller's part is placement + resource reservation; the worker
    itself is handed out by the node agent (or by the controller for
    head-node leases, where it doubles as the agent)."""

    lease_id: bytes
    demand: ResourceSet  # translated (PG-renamed) resources, reserved
    node_id: NodeID
    owner: rpc.Peer  # caller connection; lease dies with it
    ehash: str = ""
    worker_id: Optional[WorkerID] = None  # head-node leases only


class _LeaseReq:
    __slots__ = ("demand", "translated", "strategy", "ehash", "dep_keys", "peer",
                 "fut", "req_id", "block_reason")

    def __init__(self, demand, translated, strategy, ehash, dep_keys, peer, fut):
        self.demand = demand
        self.translated = translated
        self.strategy = strategy
        self.ehash = ehash
        self.dep_keys = dep_keys
        self.peer = peer
        self.fut = fut
        self.req_id = ""  # flight-recorder lease chain id
        self.block_reason = None  # why the last grant attempt parked


@dataclass
class TaskRecord:
    spec: TaskSpec
    state: str = "PENDING"  # PENDING | DISPATCHED | RUNNING | FINISHED | FAILED
    worker_id: Optional[WorkerID] = None
    node_id: Optional[NodeID] = None
    retries_left: int = 0
    acquired: Optional[ResourceSet] = None
    submitted_at: float = field(default_factory=time.time)
    # Latest why-pending attribution while blocked (flight recorder
    # vocabulary, core/lifecycle.py PENDING_REASONS).
    pending_reason: str = ""
    # Streaming-generator progress (reference: ObjectRefStream,
    # src/ray/core_worker/task_manager.cc streaming-generator returns).
    stream_count: int = 0
    stream_done: bool = False
    stream_waiters: List[asyncio.Future] = field(default_factory=list)
    # Refs nested inside arg values (pinned until the task is terminal —
    # reference: submitted-task references).
    captures: List[ObjectID] = field(default_factory=list)


@dataclass
class ActorRecord:
    actor_id: ActorID
    creation_spec: TaskSpec
    state: str = "PENDING"  # PENDING | ALIVE | RESTARTING | DEAD
    worker_id: Optional[WorkerID] = None
    node_id: Optional[NodeID] = None
    name: str = ""
    restarts_left: int = 0
    num_restarts: int = 0
    death_reason: str = ""
    tpu_chips: List[int] = field(default_factory=list)
    tpu_node: Optional[NodeID] = None
    # Resources held for the actor's lifetime (explicit requests only).
    held_resources: Optional[ResourceSet] = None
    held_node: Optional[NodeID] = None
    # Tasks queued while the actor is not ALIVE.
    pending_tasks: List[TaskSpec] = field(default_factory=list)
    ready_waiters: List[asyncio.Future] = field(default_factory=list)


class Controller:
    def __init__(self, session_dir: str, head_resources: Dict[str, float], config: Config, owned: bool):
        self.session_dir = session_dir
        self.config = config
        self.owned = owned
        self.cluster = ClusterState()
        self.scheduler = ClusterResourceScheduler(self.cluster)
        # Control-plane flight recorder: every task/actor/PG/lease/worker
        # state transition, with per-state dwell times and why-pending
        # attribution (reference: gcs_task_manager's task-events backend).
        self.lifecycle = LifecycleRecorder(
            ring_size=config.lifecycle_ring_size,
            dwell_samples=config.lifecycle_dwell_samples,
            enabled=config.lifecycle_events,
        )
        self.pg_manager = PlacementGroupManager(self.cluster, recorder=self.lifecycle)
        # Single-writer maps (mutated only from the controller's asyncio
        # loop — the module's no-locks discipline). The OWNER_THREAD
        # guard makes that discipline machine-checked under ConcSan.
        self.objects: Dict[ObjectID, ObjectRecord] = GuardedDict(
            OWNER_THREAD, owner=self, name="objects"
        )
        self.workers: Dict[WorkerID, WorkerRecord] = GuardedDict(
            OWNER_THREAD, owner=self, name="workers"
        )
        self.nodes: Dict[NodeID, NodeRecord] = GuardedDict(
            OWNER_THREAD, owner=self, name="nodes"
        )
        self.tasks: Dict[TaskID, TaskRecord] = GuardedDict(
            OWNER_THREAD, owner=self, name="tasks"
        )
        self.actors: Dict[ActorID, ActorRecord] = GuardedDict(
            OWNER_THREAD, owner=self, name="actors"
        )
        self.named_actors: Dict[str, ActorID] = GuardedDict(
            OWNER_THREAD, owner=self, name="named_actors"
        )
        self.kv: Dict[str, Dict[bytes, bytes]] = GuardedDict(
            OWNER_THREAD, owner=self, name="kv"
        )
        # GCS fault tolerance (reference: gcs/store_client/ Redis FT): an
        # append-only journal of {KV, detached actors, PGs}; a restarting
        # controller on the same session dir replays it.
        from ray_tpu.core.persistence import GcsJournal

        self.journal = GcsJournal(session_dir, sync=config.gcs_journal_fsync)
        self._restored = self.journal.replay()
        if not self._restored.empty:
            self.kv = GuardedDict(
                OWNER_THREAD, self._restored.kv, owner=self, name="kv"
            )
            # Compact on every restart: bounds replay cost for long-lived
            # clusters that overwrite the same KV keys repeatedly.
            self.journal.compact(self._restored)
            logger.info(
                "journal replay: %d kv namespaces, %d detached actors, %d PGs",
                len(self._restored.kv), len(self._restored.actors), len(self._restored.pgs),
            )
        self.pending_tasks: List[TaskID] = []
        # Worker leases for direct normal-task submission (reference:
        # normal_task_submitter.cc leasing; controller = placement only).
        import collections as _c
        import itertools as _it

        # Pending work indexed by (scheduling class, env hash): the pump
        # visits CLASSES and skips a blocked one in O(1), so a deep queue
        # of homogeneous tasks costs O(#classes) per pump instead of
        # O(#tasks) (reference: SchedulingClass queues in
        # cluster_task_manager.cc; fixes the measured O(n²) registration
        # collapse at 10k pending actor records).
        self._class_queues: Dict[Tuple, "_c.deque"] = GuardedDict(
            OWNER_THREAD, owner=self, name="class_queues"
        )
        self._dep_parked: Set[TaskID] = set()
        # dep object → pending tasks that consume it: lets an object free
        # fail its dependents in O(dependents) instead of scanning every
        # pending task (objects free routinely via GC sweeps).
        self._dep_index: Dict[ObjectID, Set[TaskID]] = {}

        self.leases: Dict[bytes, LeaseRecord] = GuardedDict(
            OWNER_THREAD, owner=self, name="leases"
        )
        self._lease_reqs: "_c.deque[_LeaseReq]" = _c.deque()
        self._lease_seq = _it.count(1)
        self._lreq_seq = _it.count(1)  # lease-request ids (flight recorder)
        self._head_direct_free: List[WorkerID] = []
        self._head_direct_waiters: "_c.deque[Tuple[str, asyncio.Future]]" = _c.deque()
        # In-flight spawns per PRESET env hash (container workers): a
        # class whose queued depth is already covered by starting workers
        # must not re-request on every pump pass — over-spawn is benign
        # for pooled host workers but each extra here is a container.
        # Entries are [count, last_update_ts]: spawns that die before
        # registering (pull failure, crash) would otherwise suppress
        # respawns for that env forever, so counts go stale after
        # _SPAWN_STALE_S and the class retries.
        self._starting_by_env: Dict[str, list] = {}
        # Synthesized task rows for direct-push tasks (reference: the GCS
        # task manager's event-derived view) — bounded LRU.
        self._direct_task_rows: "_c.OrderedDict[str, dict]" = _c.OrderedDict()
        # Death reasons for recently-dead workers ("oom" | free-text) —
        # direct-push callers query this to turn a connection loss into
        # the right error (reference: NodeDeathInfo / worker exit detail).
        self._dead_worker_info: "_c.OrderedDict[str, str]" = _c.OrderedDict()
        self.drivers: Set[rpc.Peer] = GuardedSet(
            OWNER_THREAD, owner=self, name="drivers"
        )
        self._drain_tasks: Set[asyncio.Task] = set()
        self._pump_scheduled = False
        self._pump_running = False
        self._pump_rerun = False
        self._shutdown = asyncio.Event()
        self._gc_wanted = asyncio.Event()
        self._live_pin_tasks: Set[TaskID] = set()
        # Node ids THIS controller declared dead: re-registration under
        # the same id is refused (see rpc_register_node).
        self._dead_node_ids: Set[str] = set()
        # Recently-freed object ids (bounded): a get/wait/dep-check on a
        # freed object fails fast instead of hanging on a resurrected
        # empty PENDING record.
        import collections as _collections

        self._freed_lru: "_collections.OrderedDict[ObjectID, None]" = (
            _collections.OrderedDict()
        )
        self._holder_index: Dict[str, Set[ObjectID]] = {}
        # In-flight cross-node object pulls, deduped per (oid, dest node).
        from ray_tpu.core.object_transfer import FetchPeerCache

        self._pulls: Dict[Tuple[ObjectID, NodeID], asyncio.Future] = {}
        self._fetch_peers = FetchPeerCache()
        # Topic bus (core/pubsub.py): DEATH_CHANNEL plus the round-17
        # resource/avoid channels ride the same subscriber registry.
        from ray_tpu.core.pubsub import TopicBus

        self.bus = TopicBus()
        # Per-node monotonic sequence numbers for resource-delta pubsub
        # (subscriber mirrors drop stale/out-of-order deltas by seq).
        self._resource_seq: Dict[NodeID, int] = {}
        self._last_resource_broadcast = 0.0
        self._last_resource_reconcile = 0.0
        self.events: List[dict] = []  # task event ring buffer
        self.finished_specs: Dict[TaskID, TaskSpec] = {}  # lineage for reconstruction
        self.metrics: Dict[str, dict] = {}  # aggregated app metrics
        # Serve engine flight-recorder snapshots, pushed by replicas
        # (rpc_serve_report) and served at /api/serve/engine.
        self.serve_state: Dict[str, dict] = {}
        # Per-process device telemetry (HBM gauges + compile-tracker
        # snapshots) pushed by workers/drivers (rpc_device_telemetry),
        # keyed "node_hex/proc". Stale entries pruned on read.
        self.device_state: Dict[str, dict] = {}
        # Memory census (ray-tpu memory): per-callsite open-object trend
        # windows for the leak detector (bounded vocabulary), live leak
        # flags, and per-node spill-op watermarks for the store-pressure
        # churn trigger.
        self._mem_trends: Dict[str, Any] = {}
        self._leak_flags: Dict[str, dict] = {}
        self._spill_ops_prev: Dict[NodeID, int] = {}
        self._census_tick_n = 0  # sweep counter
        # In-progress sharded object-table census cycle (round 17):
        # {"keys", "pos", "kinds", "by_site"} or None between cycles.
        self._census_cycle: Optional[dict] = None
        # Cluster log plane (core/log_plane.py): error-signature index
        # fed by worker/agent/driver ERROR shipping (rpc_log_errors),
        # follow-mode subscribers (``ray-tpu logs --follow``) keyed by
        # their driver connection, and the spike detector's watermark.
        from ray_tpu.core.log_plane import ErrorIndex

        self._error_index = ErrorIndex(cap=config.log_error_index_size)
        self._log_followers: Dict[rpc.Peer, dict] = {}
        self._record_tailer = None
        self._errors_prev_total = 0
        # Health plane (core/health.py): the actuator half of the
        # detectors above — subscribes to leak/pressure/spike/storm
        # signals and drives bounded, audited remediations.
        from ray_tpu.core.health import HealthEngine

        self.health = HealthEngine(self)
        self.dashboard_port: Optional[int] = None

        # Head node: controller doubles as its node agent.
        self.head_node_id = NodeID.from_random()
        cap = config.object_store_memory or _default_store_bytes()
        self.head_store = PlasmaStore(session_dir, cap)
        from ray_tpu.core.object_transfer import ChunkReader

        self._chunk_reader = ChunkReader(self.head_store)
        head_total = ResourceSet.from_dict(head_resources)
        self.cluster.add_node(self.head_node_id, NodeResources(head_total, labels={"node_type": "head"}))
        import socket

        self.nodes[self.head_node_id] = NodeRecord(
            node_id=self.head_node_id,
            shm_dir=self.head_store.shm_dir,
            peer=None,
            hostname=socket.gethostname(),
        )
        ncpu = int(head_resources.get("CPU", 1))
        self.nodes[self.head_node_id].max_workers = max(4 * max(ncpu, 1), 16)
        self.nodes[self.head_node_id].tpu_free = list(
            range(int(head_resources.get("TPU", 0)))
        )
        self._head_prestart = max(ncpu, 1) if config.prestart_workers else 0

    # =================================================================
    # Connection lifecycle
    # =================================================================
    def on_connect(self, peer: rpc.Peer):
        pass

    async def on_disconnect(self, peer: rpc.Peer):
        kind = peer.meta.get("kind")
        holder = peer.meta.get("holder_id")
        if holder:
            self._drop_holder(holder)
        self._drop_subscriber(peer)
        self._log_followers.pop(peer, None)
        # Leases die with their owner's connection (reference: leased
        # workers are returned when the lease-holder worker dies). The
        # workers may be mid-task on orphaned pushes → kill, don't pool.
        owned = [lid for lid, r in self.leases.items() if r.owner is peer]
        for lid in owned:
            await self.rpc_lease_release(peer, lid, kill_worker=True)
        if kind == "worker":
            await self._on_worker_death(peer.meta["worker_id"], "connection lost")
        elif kind == "agent":
            await self._on_node_death(peer.meta["node_id"])
        elif kind == "driver":
            self.drivers.discard(peer)
            if self.owned and not self.drivers:
                # The driver that owns this cluster is gone — tear down.
                self._shutdown.set()

    # =================================================================
    # Registration RPCs
    # =================================================================
    async def rpc_register_driver(self, peer: rpc.Peer):
        peer.meta.update(kind="driver")
        peer.label = "driver"
        self.drivers.add(peer)
        return {
            "session_dir": self.session_dir,
            "head_node_id": self.head_node_id.hex(),
            "shm_dir": self.head_store.shm_dir,
            "config": self.config.to_dict(),
        }

    async def rpc_register_worker(
        self, peer: rpc.Peer, worker_id: WorkerID, node_id: NodeID, pid: int,
        listen_addr: str = "", pool: str = "", env_hash: str = "",
        rejoining: bool = False,
    ):
        if rejoining and worker_id.hex() in self._dead_worker_info:
            # THIS controller already declared the worker dead (its
            # disconnect ran _on_worker_death: actor restarted / gang
            # repaired). Accepting the rejoin would resurrect a zombie
            # twin of an actor that now lives elsewhere. Refuse; the
            # worker exits. A RESTARTED controller has an empty dead
            # table, so the restart ride-through stays intact.
            raise RuntimeError(
                f"worker {worker_id.hex()[:12]} was declared dead; "
                "re-registration refused"
            )
        peer.meta.update(kind="worker", worker_id=worker_id)
        peer.label = f"worker:{worker_id.hex()[:8]}"
        # Pair the agent/head SPAWNED event with REGISTERED — the dwell is
        # the worker-startup latency. Drain locally-spawned head events
        # first so the pair can't arrive out of order.
        self._drain_spawn_events()
        self.lifecycle.record(
            "worker", worker_id.hex(), "REGISTERED", node=node_id.hex()[:12]
        )
        rec = WorkerRecord(
            worker_id=worker_id, node_id=node_id, peer=peer, pid=pid,
            listen_addr=listen_addr,
            # Spawn-time env (container images): the worker is born into
            # its env hash; dispatch exact-matches it (img: hashes never
            # use the pristine-adoption fallback).
            env_hash=env_hash,
        )
        if rejoining:
            # A surviving worker re-registering after a controller
            # restart (or transient partition). Its actual occupancy is
            # unknown to this (fresh) controller — mark it busy so the
            # pump never dispatches onto it or recycles it as idle; it
            # exits with the cluster like any other worker.
            rec.state = "ACTOR"
        self.workers[worker_id] = rec
        node = self.nodes.get(node_id)
        if node is not None:
            node.workers.add(worker_id)
            if not rejoining:
                node.num_starting = max(0, node.num_starting - 1)
        if env_hash:
            entry = self._starting_by_env.get(env_hash)
            if entry is not None:
                entry[0] -= 1
                entry[1] = time.time()
                if entry[0] <= 0:
                    self._starting_by_env.pop(env_hash, None)
        if pool == "direct":
            # Direct-lease pool: never controller-dispatched. Head-node
            # direct workers feed the controller's own free list (it is
            # the head's agent); agent-node ones are tracked by their
            # agent and merely recorded here (death handling, state API).
            rec.state = "DIRECT"
            if node_id == self.head_node_id:
                self._head_direct_put(rec)
        self._schedule_pump()
        return {"session_dir": self.session_dir, "config": self.config.to_dict()}

    async def rpc_register_node(self, peer: rpc.Peer, node_id: NodeID, resources: Dict[str, float], shm_dir: str, hostname: str = "localhost", pid: int = 0, fetch_addr: str = "", provider_instance_id: str = "", labels: Optional[Dict[str, str]] = None):
        if node_id.hex() in self._dead_node_ids:
            # This controller already declared the node DEAD (connection
            # lapse → _on_node_death: workers reaped, PGs rescheduled,
            # gangs repaired). Accepting a re-register would resurrect
            # the node with pristine availability while its orphaned
            # workers still occupy it. Refuse; the agent exits and a
            # fresh agent (new node id) can join cleanly. A RESTARTED
            # controller has an empty dead-set, so the agent
            # reconnect-window ride-through stays intact.
            raise RuntimeError(
                f"node {node_id.hex()[:12]} was declared dead; "
                "re-registration refused — restart the agent"
            )
        peer.meta.update(kind="agent", node_id=node_id)
        peer.label = f"agent:{node_id.hex()[:8]}"
        self.lifecycle.record("node", node_id.hex(), "ALIVE", name=hostname)
        total = ResourceSet.from_dict(resources)
        self.cluster.add_node(node_id, NodeResources(total, labels=labels))
        ncpu = int(resources.get("CPU", 1))
        rec = NodeRecord(
            node_id=node_id, shm_dir=shm_dir, peer=peer, hostname=hostname,
            fetch_addr=fetch_addr, provider_instance_id=provider_instance_id,
        )
        rec.agent_pid = pid
        rec.max_workers = max(4 * max(ncpu, 1), 16)
        rec.tpu_free = list(range(int(resources.get("TPU", 0))))
        self.nodes[node_id] = rec
        self.pg_manager.retry_pending()
        self._schedule_pump()
        if self.config.prestart_workers:
            await self._request_workers(rec, max(ncpu, 1))
        return {"session_dir": self.session_dir, "config": self.config.to_dict()}

    # =================================================================
    # Worker pool
    # =================================================================
    async def _request_workers(self, node: NodeRecord, n: int,
                               container_image: str = None,
                               preset_env_hash: str = ""):
        live = len(node.workers) + node.num_starting
        n = min(n, node.max_workers - live)
        if n <= 0:
            return
        node.num_starting += n
        if preset_env_hash:
            entry = self._starting_by_env.setdefault(preset_env_hash, [0, 0.0])
            entry[0] += n
            entry[1] = time.time()
        if node.peer is None:
            from ray_tpu.core.node_agent import spawn_worker

            extra = (
                {"RAY_TPU_PRESET_ENV_HASH": preset_env_hash}
                if preset_env_hash else None
            )
            for _ in range(n):
                spawn_worker(self.session_dir, f"127.0.0.1:{self.port}",
                             node.node_id, node.shm_dir, extra_env=extra,
                             container_image=container_image)
        else:
            await node.peer.notify(
                "start_workers", n, container_image, preset_env_hash
            )

    async def _recycle_idle_worker(self, node: NodeRecord, wanted_hash: str) -> bool:
        """Retire one idle worker whose env differs from ``wanted_hash`` so
        a replacement (pristine) worker can be spawned. True if a slot is
        being freed."""
        for wid in list(node.workers):
            w = self.workers.get(wid)
            if w is not None and w.state == "IDLE" and w.env_hash != wanted_hash:
                w.state = "DEAD"
                await _notify_quiet(w.peer, "exit", what="recycle idle worker")
                return True
        return False

    def _idle_worker_on(self, node_id: NodeID, env_hash: str = "") -> Optional[WorkerRecord]:
        node = self.nodes.get(node_id)
        if node is None:
            return None
        fallback = None
        for wid in node.workers:
            w = self.workers.get(wid)
            if w is None or w.state != "IDLE":
                continue
            if w.env_hash == env_hash:
                return w  # exact env match (incl. pristine↔pristine)
            if env_hash and w.env_hash == "" and fallback is None:
                fallback = w  # pristine worker can adopt the env
        # Container envs (img:) apply at SPAWN time — a pristine host
        # worker cannot adopt one in-process; exact match only.
        if env_hash.startswith("img:"):
            return None
        return fallback

    # =================================================================
    # Worker leasing (direct normal-task submission)
    # =================================================================
    async def rpc_lease_request(
        self, peer: rpc.Peer, demand_items: list, strategy: SchedulingStrategy,
        ehash: str, dep_keys: list, queued: int = 0,
    ):
        """Grant a worker lease: pick a node (locality-aware for DEFAULT
        strategy), reserve the lease's resources, and tell the caller
        which agent hands out the worker (reference: RequestWorkerLease,
        raylet/node_manager.cc:1795 — here split controller/agent).
        Parks until grantable; the pump re-tries parked requests whenever
        resources or nodes free up."""
        demand = ResourceSet(dict(demand_items))
        translated = self.scheduler.translated_pg_demand(demand, strategy)
        req = _LeaseReq(
            demand, translated, strategy, ehash, dep_keys, peer,
            asyncio.get_running_loop().create_future(),
        )
        req.req_id = "R%d" % next(self._lreq_seq)
        self.lifecycle.record("lease", req.req_id, "REQUESTED")
        grant = self._try_grant_lease(req)
        if grant is not None:
            self.lifecycle.record(
                "lease", req.req_id, "GRANTED", node=grant["node_id"][:12]
            )
            return grant
        self.lifecycle.pending_reason("lease", req.req_id, req.block_reason)
        self._lease_reqs.append(req)
        return await req.fut

    async def rpc_lease_batch(
        self, peer: rpc.Peer, demand_items: list, strategy: SchedulingStrategy,
        ehash: str, dep_keys: list, queued: int = 0, count: int = 1,
    ):
        """Grant up to ``count`` leases for one scheduling key in ONE
        round-trip (round 17 — the per-task lease RPC was the measured
        submission wall). Placement runs per lease against the live
        resource view (the demand-shape index makes each decision O(1)),
        but the lifecycle recording is ONE batched REQUESTED→GRANTED pair
        and the reply is one frame. Partial fills are normal: the caller
        shrinks its window on them (spillback signal). Zero immediate
        grants parks a single request on the legacy path so the
        pending-reason / ABANDONED semantics stay in one place."""
        count = max(1, min(int(count), self.config.lease_batch_max))
        demand = ResourceSet(dict(demand_items))
        translated = self.scheduler.translated_pg_demand(demand, strategy)
        t0 = time.time()
        req = _LeaseReq(
            demand, translated, strategy, ehash, dep_keys, peer,
            asyncio.get_running_loop().create_future(),
        )
        grants = []
        for _ in range(count):
            grant = self._try_grant_lease(req)
            if grant is None:
                break
            grants.append(grant)
        if grants:
            n = len(grants)
            self.lifecycle.record_batch("lease", "REQUESTED", n, ts=t0)
            self.lifecycle.record_batch(
                "lease", "GRANTED", n, prev="REQUESTED",
                dwell_ms=(time.time() - t0) * 1000.0,
                node=grants[0]["node_id"][:12],
            )
            _batch_metrics()["lease_batch"].observe(n)
            return {"grants": grants}
        req.req_id = "R%d" % next(self._lreq_seq)
        self.lifecycle.record("lease", req.req_id, "REQUESTED")
        self.lifecycle.pending_reason("lease", req.req_id, req.block_reason)
        self._lease_reqs.append(req)
        grant = await req.fut
        _batch_metrics()["lease_batch"].observe(1)
        return {"grants": [grant]}

    def _try_grant_lease(self, req: _LeaseReq) -> Optional[dict]:
        nid = self._locality_choice(req)
        if nid is None:
            result = self.scheduler.schedule(req.demand, req.strategy)
            nid = result.node_id
            if nid is None:
                req.block_reason = self._pending_reason(req.strategy, result)
                return None
        node_res = self.cluster.nodes.get(nid)
        if node_res is None or not node_res.acquire(req.translated):
            req.block_reason = "insufficient_resources"
            return None
        lease_id = b"L%d" % next(self._lease_seq)
        self.leases[lease_id] = LeaseRecord(
            lease_id=lease_id, demand=req.translated, node_id=nid,
            owner=req.peer, ehash=req.ehash,
        )
        node = self.nodes[nid]
        agent_addr = "controller" if node.peer is None else node.fetch_addr
        return {"lease_id": lease_id, "node_id": nid.hex(), "agent_addr": agent_addr}

    def _pending_reason(self, strategy: SchedulingStrategy, result) -> str:
        """Refine the scheduler's attribution with control-plane context
        the policy layer can't see: a PLACEMENT_GROUP miss whose group
        hasn't committed yet is gated on the PG, not on capacity."""
        reason = result.reason or (
            "infeasible" if result.infeasible else "insufficient_resources"
        )
        if (
            strategy.kind == "PLACEMENT_GROUP"
            and reason != "infeasible"
            and not self.pg_manager.is_ready(strategy.placement_group_id)
        ):
            return "pg_unready"
        return reason

    def _attribute_block(self, rec: TaskRecord, spec: TaskSpec, result):
        reason = self._pending_reason(spec.scheduling_strategy, result)
        self._mark_pending(rec, spec, reason)
        self.lifecycle.pending_reason(*self._lc_key(spec), reason)

    def _mark_pending(self, rec: TaskRecord, spec: TaskSpec, reason: str):
        """Blocked-with-a-reason is its own lifecycle state (round 17):
        QUEUED measures decision latency (intake → first verdict),
        PENDING the attributed park time — a ghost-actor storm no longer
        charges its deliberate hold to the scheduler. Guarded so
        re-pumping a still-blocked record doesn't fragment the dwell."""
        if not rec.pending_reason:
            self.lifecycle.record(*self._lc_key(spec), "PENDING")
        rec.pending_reason = reason

    def _mark_class_pending(self, q, reason: str):
        """Extend the head's block verdict to its class-mates: a blocked
        class FIFO blocks every member behind the head. Marked members
        form a queue PREFIX (intake clears the mark, so new arrivals are
        unmarked at the tail), so the reverse walk stops at the first
        marked member — O(new arrivals) amortized, not O(queue) per
        block."""
        for tid in reversed(q):
            rec = self.tasks.get(tid)
            if rec is None or rec.state != "PENDING":
                continue
            if rec.pending_reason:
                break
            rec.pending_reason = reason
            self.lifecycle.record(*self._lc_key(rec.spec), "PENDING")

    def _locality_choice(self, req: _LeaseReq) -> Optional[NodeID]:
        """Prefer the feasible node holding the most dependency bytes
        (reference: lease_policy.cc picks the raylet with the task's
        args). DEFAULT strategy only — explicit placement wins."""
        if req.strategy.kind != "DEFAULT" or not req.dep_keys:
            return None
        per_node: Dict[NodeID, int] = {}
        for k in req.dep_keys:
            orec = self.objects.get(ObjectID(k))
            if orec is None or orec.inline is not None or orec.state != "READY":
                continue
            for nid in orec.locations:
                per_node[nid] = per_node.get(nid, 0) + orec.size
        for nid in sorted(per_node, key=per_node.get, reverse=True):  # type: ignore[arg-type]
            node_res = self.cluster.nodes.get(nid)
            if (
                node_res is not None
                and not getattr(node_res, "draining", False)
                and node_res.fits(req.translated)
            ):
                return nid
        return None

    def _pump_leases(self):
        """Re-try parked lease requests (FIFO) — called from the pump."""
        if not self._lease_reqs:
            return
        still = []
        while self._lease_reqs:
            req = self._lease_reqs.popleft()
            if req.fut.done() or req.peer.closed:
                self.lifecycle.record("lease", req.req_id, "ABANDONED")
                continue  # caller gave up / died
            grant = self._try_grant_lease(req)
            if grant is None:
                self.lifecycle.pending_reason("lease", req.req_id, req.block_reason)
                still.append(req)
            else:
                self.lifecycle.record(
                    "lease", req.req_id, "GRANTED", node=grant["node_id"][:12]
                )
                req.fut.set_result(grant)
        self._lease_reqs.extend(still)

    def _spawn_head_direct(self, node):
        """Spawn one direct-pool worker on the head node (the controller
        doubles as the head's node agent)."""
        from ray_tpu.core.node_agent import spawn_worker

        node.num_starting += 1
        spawn_worker(
            self.session_dir, f"127.0.0.1:{self.port}", node.node_id,
            node.shm_dir, extra_env={"RAY_TPU_WORKER_POOL": "direct"},
        )

    async def rpc_lease_worker(self, peer: rpc.Peer, lease_id: bytes, ehash: str):
        """Hand out a head-node worker for a granted lease — the
        controller doubles as the head's node agent (reference: the
        raylet's WorkerPool PopWorker, worker_pool.h:363). Agent nodes
        serve this same RPC themselves (node_agent.rpc_lease_worker)."""
        rec = self.leases.get(lease_id)
        if rec is None:
            raise ValueError(f"unknown lease {lease_id!r}")
        node = self.nodes[rec.node_id]
        w = self._head_direct_pop(ehash)
        while w is None:
            if len(node.workers) + node.num_starting < node.max_workers:
                self._spawn_head_direct(node)
            else:
                # pool at cap: retire one mismatched free direct worker so
                # a pristine replacement can spawn (reference:
                # _recycle_idle_worker / worker_pool idle eviction)
                await self._retire_mismatched_direct(ehash, node)
            fut = asyncio.get_running_loop().create_future()
            self._head_direct_waiters.append((ehash, fut))
            w = await fut
            if w.state == "DEAD":
                w = self._head_direct_pop(ehash)
        # The awaits above race lease_release: the caller may have timed
        # out and released this lease while we waited — the worker must
        # go back to the pool, not leak as LEASED on a dead lease.
        rec = self.leases.get(lease_id)
        if rec is None:
            self._head_direct_put(w)
            raise ValueError(f"lease {lease_id!r} released while waiting for a worker")
        rec.worker_id = w.worker_id
        w.state = "LEASED"
        w.env_hash = ehash or w.env_hash
        return {"worker_addr": w.listen_addr, "worker_id": w.worker_id.hex()}

    async def rpc_lease_worker_batch(self, peer: rpc.Peer, lease_ids: list,
                                     ehash: str):
        """Hand out head-node workers for a BATCH of granted leases in
        one round-trip (round 17). Strictly non-blocking pops — no await
        between pop and bind, so the lease-release race rpc_lease_worker
        guards against cannot happen here. Misses return None in place;
        the caller falls back to the parking single-worker path for
        those (and shrinks its window — the spillback signal). One
        replacement spawn is triggered per miss so capacity catches up."""
        out = []
        misses = 0
        for lease_id in lease_ids:
            rec = self.leases.get(lease_id)
            if rec is None:
                out.append(None)  # released while the batch was in flight
                continue
            w = self._head_direct_pop(ehash)
            if w is None:
                out.append(None)
                misses += 1
                continue
            rec.worker_id = w.worker_id
            w.state = "LEASED"
            w.env_hash = ehash or w.env_hash
            out.append({"worker_addr": w.listen_addr,
                        "worker_id": w.worker_id.hex()})
        if misses:
            node = self.nodes[self.head_node_id]
            for _ in range(misses):
                if len(node.workers) + node.num_starting < node.max_workers:
                    self._spawn_head_direct(node)
                else:
                    await self._retire_mismatched_direct(ehash, node)
        return out

    async def _retire_mismatched_direct(self, ehash: str, node=None):
        for wid in list(self._head_direct_free):
            w = self.workers.get(wid)
            if w is None or w.state == "DEAD":
                self._head_direct_free.remove(wid)
                continue
            if w.env_hash and w.env_hash != ehash:
                self._head_direct_free.remove(wid)
                w.state = "DEAD"
                await _notify_quiet(w.peer, "exit", what="retire mismatched direct")
                # Pair the kill with a replacement spawn (mirrors
                # NodeAgent._retire_mismatched) so the parked caller isn't
                # left waiting on its own 30s lease timeout for capacity
                # that only frees when the retired worker's death is seen.
                if node is not None:
                    self._spawn_head_direct(node)
                return

    def _head_direct_pop(self, ehash: str) -> Optional[WorkerRecord]:
        fallback = None
        for wid in list(self._head_direct_free):
            w = self.workers.get(wid)
            if w is None or w.state != "DIRECT":
                self._head_direct_free.remove(wid)
                continue
            if w.env_hash == ehash:
                self._head_direct_free.remove(wid)
                return w
            if w.env_hash == "" and fallback is None:
                fallback = wid
        if fallback is not None:
            self._head_direct_free.remove(fallback)
            return self.workers[fallback]
        return None

    _SPAWN_STALE_S = 120.0  # silence horizon for in-flight env spawns

    def _env_starting_count(self, ehash: str) -> int:
        """In-flight spawn count for a preset env, expiring stale
        entries (a spawn that died before registering must not suppress
        respawns forever)."""
        entry = self._starting_by_env.get(ehash)
        if entry is None:
            return 0
        if time.time() - entry[1] > self._SPAWN_STALE_S:
            self._starting_by_env.pop(ehash, None)
            return 0
        return max(0, entry[0])

    async def _claim_direct_for_actor(self, node_id: NodeID, ehash: str):
        """Pop a FREE direct-pool worker on ``node_id`` for actor
        creation (reference: worker_pool.h:363-374 — PopWorker serves
        tasks and actors alike; VERDICT r4 weak #4: actor creation must
        not cold-spawn while prestarted workers sit idle)."""
        if ehash.startswith("img:"):
            return None  # container envs need a spawn-time worker
        if node_id == self.head_node_id:
            return self._head_direct_pop(ehash)
        node = self.nodes.get(node_id)
        if node is None or node.peer is None:
            return None
        try:
            wid_hex = await node.peer.call("claim_direct_worker", ehash)
        except Exception:  # noqa: BLE001 — agent gone; fall back to spawn
            return None
        if not wid_hex:
            return None
        w = self.workers.get(WorkerID(bytes.fromhex(wid_hex)))
        if w is None or w.state != "DIRECT":
            # The agent marked it busy; give it back or the pool slot
            # leaks (e.g. claim raced the worker's controller
            # registration).
            await _notify_quiet(
                node.peer, "release_direct_worker", wid_hex, what="agent gone"
            )
            return None
        return w

    async def _unclaim_direct(self, w: WorkerRecord):
        """Return a claimed-but-undispatched direct worker to its pool."""
        if w.node_id == self.head_node_id:
            self._head_direct_put(w)
            return
        w.state = "DIRECT"
        node = self.nodes.get(w.node_id)
        if node is not None and node.peer is not None:
            await _notify_quiet(
                node.peer, "release_direct_worker", w.worker_id.hex(),
                what="agent gone; worker dies with it",
            )

    def _head_direct_put(self, w: WorkerRecord):
        w.state = "DIRECT"
        for i, (ehash, fut) in enumerate(self._head_direct_waiters):
            if not fut.done() and (w.env_hash in ("", ehash)):
                del self._head_direct_waiters[i]
                fut.set_result(w)
                return
        self._head_direct_free.append(w.worker_id)

    async def rpc_lease_release(self, peer: rpc.Peer, lease_id: bytes,
                                kill_worker: bool = False):
        """``kill_worker``: the release came from the lease-holder DYING,
        not from a drained queue — the worker may be mid-task on an
        orphaned push, so it must be exited, never pooled (a pooled
        busy worker would queue the next caller's task behind it)."""
        rec = self.leases.pop(lease_id, None)
        if rec is None:
            return False
        node_res = self.cluster.nodes.get(rec.node_id)
        if node_res is not None:
            node_res.release(rec.demand)
        if rec.worker_id is not None:
            w = self.workers.get(rec.worker_id)
            if w is not None and w.state != "DEAD":
                if kill_worker:
                    w.state = "DEAD"
                    await _notify_quiet(w.peer, "exit", what="lease release kill")
                    # keep parked head lease_worker callers from hanging
                    node = self.nodes[rec.node_id]
                    if self._head_direct_waiters and (
                        len(node.workers) + node.num_starting < node.max_workers
                    ):
                        self._spawn_head_direct(node)
                else:
                    self._head_direct_put(w)
        else:
            # agent lease: the agent bound a worker we never saw — relay
            # the release so a dead lease-holder can't strand it busy
            node = self.nodes.get(rec.node_id)
            if node is not None and node.peer is not None and not node.peer.closed:
                await _notify_quiet(
                    node.peer, "lease_release", lease_id, kill_worker,
                    what="agent dying too",
                )
        self._schedule_pump()
        return True

    async def rpc_worker_death_info(self, peer: rpc.Peer, worker_id_hex: str):
        return self._dead_worker_info.get(worker_id_hex)

    async def rpc_task_lineage(self, peer: rpc.Peer, spec: TaskSpec):
        """Lineage for a direct-push task whose result went to shm: lets
        the existing reconstruction path (_try_reconstruct) resubmit it if
        the storing node dies (reference: owner-side TaskManager lineage;
        inline results never need reconstruction — they live in the
        owner's memory store)."""
        self.finished_specs[spec.task_id] = spec
        for oid in spec.return_ids():
            self._object(oid).creating_task = spec.task_id
        return True

    # =================================================================
    # Task submission / scheduling pump
    # =================================================================
    async def rpc_submit_task(self, peer: rpc.Peer, spec: TaskSpec, captures: Optional[list] = None):
        # Submission is a fire-and-forget notify (pipelined client): an
        # exception here would only be logged, leaving the return objects
        # PENDING forever — so any failure becomes the objects' error.
        try:
            rec = TaskRecord(spec=spec, retries_left=spec.max_retries)
            if captures:
                rec.captures = [
                    c if isinstance(c, ObjectID) else ObjectID(c) for c in captures
                ]
            if spec.dependencies or rec.captures:
                self._live_pin_tasks.add(spec.task_id)
            self.tasks[spec.task_id] = rec
            for oid in spec.return_ids():
                self._object(oid).creating_task = spec.task_id
            if spec.task_type == TaskType.ACTOR_TASK:
                self.lifecycle.record(
                    "task", spec.task_id.hex(), "SUBMITTED", name=spec.name
                )
                await self._submit_actor_task(spec)
            else:
                self.pending_tasks.append(spec.task_id)
                self._event("task", spec, "PENDING_SCHEDULING")
                self._schedule_pump()
        except Exception as e:  # noqa: BLE001 — surfaced through the refs
            logger.exception("submit_task failed for %s", spec.task_id.hex())
            rec = self.tasks.get(spec.task_id)
            if rec is not None:
                rec.state = "FAILED"
            self._fail_task_objects(spec, e)
        return True

    async def rpc_create_actor(
        self, peer: rpc.Peer, spec: TaskSpec, captures: Optional[list] = None, _journal: bool = True
    ):
        actor = ActorRecord(
            actor_id=spec.actor_id,
            creation_spec=spec,
            restarts_left=spec.max_restarts,
        )
        # The name travels in runtime_env["__actor_name__"] to keep TaskSpec lean.
        name = (spec.runtime_env or {}).get("__actor_name__", "")
        actor.name = name
        if name:
            if name in self.named_actors:
                raise ValueError(f"Actor with name {name!r} already exists")
            self.named_actors[name] = spec.actor_id
        self.actors[spec.actor_id] = actor
        if _journal and spec.lifetime == "detached":
            self.journal.actor_register(spec)
        rec = TaskRecord(spec=spec, retries_left=0)
        if captures:
            rec.captures = [
                c if isinstance(c, ObjectID) else ObjectID(c) for c in captures
            ]
        if spec.dependencies or rec.captures:
            # creation args are pinned until the creation task is terminal
            self._live_pin_tasks.add(spec.task_id)
        self.tasks[spec.task_id] = rec
        self.pending_tasks.append(spec.task_id)
        self._event("actor", spec, "PENDING_CREATION")
        self._schedule_pump()
        return True

    async def _submit_actor_task(self, spec: TaskSpec):
        actor = self.actors.get(spec.actor_id)
        if actor is None or actor.state == "DEAD":
            reason = actor.death_reason if actor else "actor not found"
            rec = self.tasks.get(spec.task_id)
            if rec is not None:
                rec.state = "FAILED"  # terminal → arg pins released
            self._fail_task_objects(spec, ActorDiedError(spec.actor_id.hex(), reason))
            return
        if actor.state != "ALIVE":
            actor.pending_tasks.append(spec)
            self.lifecycle.pending_reason(
                "task", spec.task_id.hex(), "waiting_actor"
            )
            return
        await self._dispatch_actor_task(actor, spec)

    async def _dispatch_actor_task(self, actor: ActorRecord, spec: TaskSpec):
        worker = self.workers.get(actor.worker_id)
        if worker is None or worker.peer.closed:
            actor.pending_tasks.append(spec)
            return
        rec = self.tasks.get(spec.task_id)
        if rec is None:
            rec = TaskRecord(spec=spec, retries_left=spec.max_task_retries)
            self.tasks[spec.task_id] = rec
        rec.state = "RUNNING"
        rec.worker_id = worker.worker_id
        rec.node_id = worker.node_id
        worker.running.add(spec.task_id)
        self._event("task", spec, "RUNNING")
        await worker.peer.notify("execute_actor_task", spec)

    def _schedule_pump(self):
        if self._pump_scheduled:
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return  # loop shutting down
        self._pump_scheduled = True
        loop.call_soon(lambda: asyncio.ensure_future(self._pump()))

    async def _pump(self):
        self._pump_scheduled = False
        # Non-reentrant: the loop awaits (notify/spawn) mid-iteration, and a
        # second concurrent pump would race the pending_tasks rebind below
        # and could drop newly submitted tasks.
        if self._pump_running:
            self._pump_rerun = True
            return
        self._pump_running = True
        try:
            while True:
                self._pump_rerun = False
                await self._pump_once()
                if not self._pump_rerun:
                    break
        finally:
            self._pump_running = False

    async def _pump_once(self):
        self._pump_leases()
        import collections

        # Drain the intake list into per-class FIFOs. The pump then visits
        # CLASSES: a blocked class (infeasible / no worker / no resources)
        # is skipped in O(1) with its whole queue intact, so registration
        # of the n-th pending record costs O(#classes), not O(n).
        # Dispatch eligibility is env-affine (idle-worker match keys on
        # the runtime-env hash), so the class key must include it —
        # otherwise an env-B task with an idle env-B worker is starved
        # because an env-A task of the same class blocks first.
        intake, self.pending_tasks = self.pending_tasks, []
        for tid in intake:
            rec = self.tasks.get(tid)
            if rec is None or rec.state != "PENDING":
                continue
            spec = rec.spec
            key = (spec.scheduling_class(), _env_hash(spec.runtime_env))
            q = self._class_queues.get(key)
            if q is None:
                q = self._class_queues[key] = collections.deque()
            q.append(tid)
            lk, leid = self._lc_key(spec)
            self.lifecycle.record(lk, leid, "QUEUED")
            # Back in the queue = awaiting a fresh verdict: clear any
            # stale block mark so the next verdict re-records PENDING
            # (and keeps _mark_class_pending's marked-prefix invariant).
            rec.pending_reason = ""
            for dep in spec.dependencies:
                self._dep_index.setdefault(dep, set()).add(tid)
        # Keyed by (node, container_image, preset_env_hash): container
        # classes need image-wrapped, pre-tagged spawns; host classes
        # spawn pristine (image=None, hash="").
        spawn_requests: Dict[Tuple, int] = {}
        for key in list(self._class_queues.keys()):
            q = self._class_queues.get(key)
            if q:
                await self._pump_class(key, q, spawn_requests)
            if not q:
                self._class_queues.pop(key, None)
        for (nid, image, preset), n in spawn_requests.items():
            node = self.nodes.get(nid)
            if node is not None:
                await self._request_workers(
                    node, n, container_image=image, preset_env_hash=preset
                )

    async def _pump_class(self, key: Tuple, q, spawn_requests: Dict[NodeID, int]):
        """Dispatch from one scheduling-class FIFO until the class blocks
        (head-of-line blocking per class, reference: SchedulingClass
        queues in cluster_task_manager.cc). Returning with the queue
        non-empty means blocked; a completion/attach/registration re-pump
        retries the head."""
        _sclass, ehash = key
        while q:
            tid = q[0]
            rec = self.tasks.get(tid)
            if rec is None or rec.state != "PENDING":
                q.popleft()  # cancelled/failed/dispatched elsewhere
                continue
            spec = rec.spec
            # 1. dependencies local?
            advance = True
            for dep in spec.dependencies:
                if dep not in self.objects and dep in self._freed_lru:
                    self._fail_task_objects(
                        spec, ObjectLostError(dep.hex(), "dependency was freed")
                    )
                    rec.state = "FAILED"
                    self._unindex_deps(spec)
                    break
                orec = self._object(dep)
                if orec.state == "FAILED":
                    self._fail_task_objects(spec, ObjectLostError(dep.hex(), "dependency failed"))
                    rec.state = "FAILED"
                    self._unindex_deps(spec)
                    break
                if orec.state != "READY":
                    # park OUT of the class queue (a dep-waiting head must
                    # not block class-mates whose deps are ready); any dep
                    # state change re-enqueues through the intake list
                    self._park_on_dep(dep, tid)
                    self._mark_pending(rec, spec, "waiting_deps")
                    self.lifecycle.pending_reason(*self._lc_key(spec), "waiting_deps")
                    advance = False
                    break
            if not advance or rec.state != "PENDING":
                q.popleft()
                continue
            # 2. pick node
            demand = self.scheduler.translated_pg_demand(spec.resources, spec.scheduling_strategy)
            result = self.scheduler.schedule(spec.resources, spec.scheduling_strategy)
            if result.node_id is None:
                self._attribute_block(rec, spec, result)
                self._mark_class_pending(q, rec.pending_reason)
                return  # class blocked: infeasible for now
            # 3. idle worker (env-affine)?
            worker = self._idle_worker_on(result.node_id, ehash)
            claimed_direct = False
            if worker is None and spec.task_type == TaskType.ACTOR_CREATION_TASK:
                # Actor fast path: claim a prestarted direct-pool worker
                # instead of cold-spawning — the reference's PopWorker
                # makes no task/actor distinction (worker_pool.h:363-374).
                worker = await self._claim_direct_for_actor(result.node_id, ehash)
                claimed_direct = worker is not None
            if worker is None:
                # A node whose worker pool is EXHAUSTED (full, nothing
                # recyclable) cannot take the task even though resources
                # are free — spill to other feasible nodes instead of
                # wedging on it (reference: lease spillback re-requests
                # with the rejecting raylet excluded).
                excluded: Set[NodeID] = set()
                while worker is None and result.node_id is not None:
                    node = self.nodes[result.node_id]
                    if len(node.workers) + node.num_starting < node.max_workers:
                        break  # room to spawn here
                    if await self._recycle_idle_worker(node, ehash):
                        break  # a slot is freeing up here
                    excluded.add(result.node_id)
                    result = self.scheduler.schedule(
                        spec.resources, spec.scheduling_strategy, exclude=excluded
                    )
                    if result.node_id is None:
                        break
                    demand = self.scheduler.translated_pg_demand(
                        spec.resources, spec.scheduling_strategy
                    )
                    worker = self._idle_worker_on(result.node_id, ehash)
                if worker is None:
                    reason = "spillback" if excluded else "no_idle_worker"
                    self._mark_pending(rec, spec, reason)
                    self.lifecycle.pending_reason(*self._lc_key(spec), reason)
                    if result.node_id is not None:
                        # Worker ramp-up for the queued depth, capped by
                        # the node's SCHEDULABLE concurrency for this
                        # demand — a deep queue of 1-CPU tasks on a 1-CPU
                        # node must not spawn max_workers processes that
                        # can never run concurrently (reference:
                        # worker_pool soft limit ≈ CPU slots).
                        cap = self._class_slots(result.node_id, demand)
                        image = (spec.runtime_env or {}).get("image_uri")
                        depth = len(q)
                        if image:
                            depth -= self._env_starting_count(ehash)
                        n = min(depth, max(cap, 0))
                        if n > 0:
                            skey = (
                                result.node_id, image, ehash if image else ""
                            )
                            spawn_requests[skey] = spawn_requests.get(skey, 0) + n
                    self._mark_class_pending(q, reason)
                    return  # class blocked until a worker attaches/frees
            # 4. acquire resources + dispatch. The recycle loop above
            # awaited: the task may have been cancelled/failed meanwhile —
            # dispatching it would resurrect a FAILED record whose result
            # objects were already failed.
            if rec.state != "PENDING":
                if claimed_direct:
                    await self._unclaim_direct(worker)
                q.popleft()
                continue
            node_res = self.cluster.nodes[result.node_id]
            if not node_res.acquire(demand):
                if claimed_direct:
                    await self._unclaim_direct(worker)
                self._mark_pending(rec, spec, "insufficient_resources")
                self.lifecycle.pending_reason(
                    *self._lc_key(spec), "insufficient_resources"
                )
                self._mark_class_pending(q, "insufficient_resources")
                return  # class blocked on resources
            rec.pending_reason = ""
            rec.acquired = demand
            rec.node_id = result.node_id
            rec.worker_id = worker.worker_id
            rec.state = "DISPATCHED"
            worker.running.add(tid)
            worker.env_hash = ehash or worker.env_hash
            q.popleft()
            self._unindex_deps(spec)
            if spec.task_type == TaskType.ACTOR_CREATION_TASK:
                worker.state = "ACTOR"
                worker.actor_id = spec.actor_id
                actor = self.actors[spec.actor_id]
                actor.worker_id = worker.worker_id
                actor.node_id = result.node_id
                self._assign_tpu_chips(actor, spec, self.nodes[result.node_id])
                self._event("actor", spec, "CREATING")
                await worker.peer.notify("create_actor", spec)
            else:
                worker.state = "LEASED"
                self._event("task", spec, "RUNNING")
                await worker.peer.notify("execute_task", spec)

    def _unindex_deps(self, spec: TaskSpec):
        for dep in spec.dependencies:
            s = self._dep_index.get(dep)
            if s is not None:
                s.discard(spec.task_id)
                if not s:
                    del self._dep_index[dep]

    def _fail_freed_dependents(self, oid: ObjectID):
        for tid in list(self._dep_index.pop(oid, ())):
            rec = self.tasks.get(tid)
            if rec is None or rec.state != "PENDING":
                continue
            rec.state = "FAILED"
            self._fail_task_objects(
                rec.spec, ObjectLostError(oid.hex(), "dependency was freed")
            )
            self._unindex_deps(rec.spec)

    def _park_on_dep(self, dep: ObjectID, tid: TaskID):
        """Hold a dep-waiting task outside the class FIFOs until the dep
        resolves; any state change (_wake on READY or FAILED) re-enqueues
        it through the intake list for a fresh eligibility pass."""
        self._dep_parked.add(tid)
        orec = self._object(dep)
        fut = asyncio.get_running_loop().create_future()

        def _requeue(_):
            self._dep_parked.discard(tid)
            self.pending_tasks.append(tid)
            self._schedule_pump()

        fut.add_done_callback(_requeue)
        orec.waiters.append(fut)

    def _class_slots(self, node_id: NodeID, demand) -> int:
        """How many MORE tasks of ``demand`` the node could start right
        now (available resources, minus workers already spawning) — the
        worker ramp-up cap for one scheduling class. Prevents a deep
        queue of 1-CPU tasks on a 1-CPU node from spawning max_workers
        processes that can never run concurrently (reference:
        worker_pool.h prestart/soft-limit semantics)."""
        node = self.cluster.nodes.get(node_id)
        if node is None:
            return 1
        starting = self.nodes[node_id].num_starting if node_id in self.nodes else 0
        slots = None
        for name, fp in demand.items_fp():
            if fp <= 0:
                continue
            avail = node.available.get(name)
            s = avail // fp
            slots = s if slots is None else min(slots, s)
        if slots is None:
            slots = 4  # zero-resource tasks: modest default ramp
        return max(0, int(slots) - starting)

    # =================================================================
    # Task completion
    # =================================================================
    async def rpc_task_done(
        self,
        peer: rpc.Peer,
        task_id: TaskID,
        results: List[tuple],  # (oid, "inline", data) | (oid, "shm", size)
        error: Optional[Exception],
    ):
        rec = self.tasks.get(task_id)
        if rec is None:
            return False
        spec = rec.spec
        worker = self.workers.get(rec.worker_id) if rec.worker_id else None
        if worker is not None:
            worker.running.discard(task_id)
        # Release resources — EXCEPT a successful creation of an actor with
        # explicit resource requests, whose acquisition transfers to the
        # actor until it dies (reference: actors hold requested resources).
        if (
            error is None
            and spec.task_type == TaskType.ACTOR_CREATION_TASK
            and spec.hold_resources_while_alive
            and rec.acquired is not None
        ):
            actor = self.actors.get(spec.actor_id)
            if actor is not None:
                actor.held_resources = rec.acquired
                actor.held_node = rec.node_id
                rec.acquired = None
            else:
                self._release_task(rec)
        else:
            self._release_task(rec)
        if error is not None:
            retriable = rec.retries_left > 0 and (
                spec.retry_exceptions or isinstance(error, (WorkerCrashedError,))
            )
            if spec.task_type == TaskType.ACTOR_CREATION_TASK:
                # __init__ raised: the actor is dead on arrival (reference:
                # gcs_actor_manager — creation failure is not retried as a
                # restart). Free the half-initialized worker.
                rec.state = "FAILED"
                self._event("actor", spec, "CREATION_FAILED")
                self._fail_task_objects(spec, error)
                actor = self.actors.get(spec.actor_id)
                if actor is not None:
                    actor.restarts_left = 0
                    await self._on_actor_death(spec.actor_id, f"__init__ failed: {error}")
                if worker is not None:
                    worker.actor_id = None
                    await worker.peer.notify("exit")
            elif retriable:
                rec.retries_left -= 1
                rec.state = "PENDING"
                self.pending_tasks.append(task_id)
                self._event("task", spec, "RETRYING")
            else:
                rec.state = "FAILED"
                self._event("task", spec, "FAILED")
                self._fail_task_objects(spec, error)
        else:
            rec.state = "FINISHED"
            self.finished_specs[task_id] = spec
            self._event("task", spec, "FINISHED")
            node_id = worker.node_id if worker else rec.node_id
            census = getattr(self.config, "memory_census", True)
            for item in results:
                oid, kind = item[0], item[1]
                orec = self._object(oid)
                if census and not orec.callsite:
                    # interned: a generator of unique task names must not
                    # grow an unbounded call-site vocabulary here
                    from ray_tpu.core.memory_census import task_site

                    orec.callsite = task_site(spec.name)
                if census and not orec.creator and worker is not None:
                    orec.creator = f"worker:{worker.worker_id.hex()[:12]}"
                if kind == "inline":
                    orec.inline = item[2]
                    orec.size = len(item[2])
                    orec.is_error = bool(item[3]) if len(item) > 3 else False
                    if len(item) > 4 and item[4]:
                        orec.children = list(item[4])
                else:
                    orec.size = item[2]
                    orec.locations.add(node_id)
                    if len(item) > 3 and item[3]:
                        orec.children = list(item[3])
                    await self._account_object(node_id, oid, item[2])
                orec.state = "READY"
                self._wake(orec)
            if spec.task_type == TaskType.ACTOR_CREATION_TASK:
                await self._on_actor_created(spec)
        # Return worker to pool.
        if worker is not None and worker.state == "LEASED":
            worker.state = "IDLE"
        if rec.state in ("FINISHED", "FAILED"):
            # End-of-stream only on terminal states — a retried streaming
            # task must not signal a premature end to its consumers.
            rec.stream_done = True
            for fut in rec.stream_waiters:
                if not fut.done():
                    fut.set_result(True)
            rec.stream_waiters.clear()
        self._schedule_pump()
        return True

    def _release_task(self, rec: TaskRecord):
        if rec.acquired is not None and rec.node_id in self.cluster.nodes:
            self.cluster.nodes[rec.node_id].release(rec.acquired)
        rec.acquired = None

    async def _on_actor_created(self, spec: TaskSpec):
        actor = self.actors.get(spec.actor_id)
        if actor is None:
            return
        actor.state = "ALIVE"
        self.lifecycle.record(
            "actor", spec.actor_id.hex(), "ALIVE", name=spec.name
        )
        for fut in actor.ready_waiters:
            if not fut.done():
                fut.set_result(True)
        actor.ready_waiters.clear()
        pending, actor.pending_tasks = actor.pending_tasks, []
        for t in pending:
            await self._dispatch_actor_task(actor, t)

    def _fail_task_objects(self, spec: TaskSpec, error: Exception):
        from ray_tpu.utils.serialization import serialize

        blob = serialize(error)
        if spec.is_streaming:
            # Streaming failure: the error becomes the stream's final item
            # (reference: streaming generators surface mid-stream errors as
            # the next yielded ref).
            rec = self.tasks.get(spec.task_id)
            if rec is not None:
                oid = ObjectID.for_task_return(spec.task_id, rec.stream_count)
                orec = self._object(oid)
                orec.inline = blob
                orec.is_error = True
                orec.state = "READY"
                self._wake(orec)
                rec.stream_count += 1
                rec.stream_done = True
                for fut in rec.stream_waiters:
                    if not fut.done():
                        fut.set_result(True)
                rec.stream_waiters.clear()
            return
        for oid in spec.return_ids():
            orec = self._object(oid)
            orec.inline = blob
            orec.is_error = True
            orec.state = "READY"
            self._wake(orec)

    # =================================================================
    # Failure handling
    # =================================================================
    async def _on_worker_death(self, worker_id: WorkerID, reason: str):
        worker = self.workers.pop(worker_id, None)
        if worker is None:
            return
        worker.state = "DEAD"
        node = self.nodes.get(worker.node_id)
        if node is not None:
            node.workers.discard(worker_id)
        if worker_id in self._head_direct_free:
            self._head_direct_free.remove(worker_id)
        self._dead_worker_info[worker_id.hex()] = (
            "oom" if worker.oom_marked else reason
        )
        self.lifecycle.record(
            "worker", worker_id.hex(), "DEAD",
            reason="oom" if worker.oom_marked else reason,
        )
        await self._publish_death(
            "worker", worker_id.hex(), "DEAD",
            reason="oom" if worker.oom_marked else reason,
            node=worker.node_id.hex(),
            actor=worker.actor_id.hex() if worker.actor_id else "",
        )
        while len(self._dead_worker_info) > 1000:
            self._dead_worker_info.popitem(last=False)
        # Fail or retry running tasks FIRST: _on_actor_death below requeues
        # the creation task under the same deterministic task id, and must
        # not have its fresh record clobbered by this loop.
        will_restart = False
        if worker.actor_id is not None:
            actor = self.actors.get(worker.actor_id)
            will_restart = actor is not None and actor.restarts_left > 0
        for tid in list(worker.running):
            rec = self.tasks.get(tid)
            if rec is None:
                continue
            self._release_task(rec)
            spec = rec.spec
            if spec.task_type == TaskType.ACTOR_CREATION_TASK:
                if will_restart:
                    continue  # restart path requeues this same spec
                rec.state = "FAILED"
                self.lifecycle.record(
                    "actor", spec.actor_id.hex(), "FAILED", name=spec.name
                )
                self._fail_task_objects(
                    spec, ActorDiedError(spec.actor_id.hex(), f"died in __init__ ({reason})")
                )
            elif spec.task_type == TaskType.ACTOR_TASK:
                actor = self.actors.get(spec.actor_id)
                actor_alive = actor is not None and (
                    actor.state != "DEAD" or will_restart
                )
                if rec.retries_left > 0 and actor_alive:
                    rec.retries_left -= 1
                    rec.state = "PENDING"
                    actor.pending_tasks.append(spec)
                    self._event("task", spec, "RETRYING")
                else:
                    rec.state = "FAILED"
                    self.lifecycle.record(
                        "task", spec.task_id.hex(), "FAILED", name=spec.name
                    )
                    self._fail_task_objects(
                        spec,
                        ActorDiedError(spec.actor_id.hex(), f"actor worker died ({reason})"),
                    )
            else:
                if rec.retries_left > 0:
                    rec.retries_left -= 1
                    rec.state = "PENDING"
                    self.pending_tasks.append(tid)
                    self._event("task", spec, "RETRYING")
                else:
                    rec.state = "FAILED"
                    if worker.oom_marked:
                        err = OutOfMemoryError(
                            f"task killed by the memory monitor (node over "
                            f"{self.config.memory_usage_threshold:.0%} memory)"
                        )
                    else:
                        err = WorkerCrashedError(
                            f"worker {worker_id.hex()[:8]} died while running task ({reason})"
                        )
                    self.lifecycle.record(
                        "task", spec.task_id.hex(), "FAILED", name=spec.name
                    )
                    self._fail_task_objects(spec, err)
        if worker.actor_id is not None:
            await self._on_actor_death(worker.actor_id, f"worker died: {reason}")
        self._schedule_pump()

    def _assign_tpu_chips(self, actor: ActorRecord, spec: TaskSpec, node: NodeRecord):
        """Give a TPU actor concrete chip indices via TPU_VISIBLE_CHIPS
        (reference: tpu.py:155-195; per-instance accounting,
        resource_instance_set.cc). Applied in-worker before jax loads."""
        from ray_tpu.core.resources import from_fp

        n = int(from_fp(spec.resources.get("TPU")))
        if n <= 0:
            return
        if len(node.tpu_free) < n:
            logger.warning(
                "TPU accounting drift: actor wants %d chips, node %s has %d free",
                n,
                node.node_id.hex()[:8],
                len(node.tpu_free),
            )
            return
        chips, node.tpu_free = node.tpu_free[:n], node.tpu_free[n:]
        actor.tpu_chips = chips
        actor.tpu_node = node.node_id
        renv = dict(spec.runtime_env or {})
        env_vars = dict(renv.get("env_vars") or {})
        env_vars["TPU_VISIBLE_CHIPS"] = ",".join(str(c) for c in chips)
        renv["env_vars"] = env_vars
        spec.runtime_env = renv

    def _release_tpu_chips(self, actor: ActorRecord):
        if actor.tpu_chips and actor.tpu_node is not None:
            node = self.nodes.get(actor.tpu_node)
            if node is not None:
                node.tpu_free.extend(actor.tpu_chips)
        actor.tpu_chips = []
        actor.tpu_node = None

    async def _on_actor_death(self, actor_id: ActorID, reason: str):
        actor = self.actors.get(actor_id)
        if actor is None or actor.state == "DEAD":
            return
        actor.worker_id = None
        self._release_tpu_chips(actor)
        if actor.held_resources is not None:
            if actor.held_node in self.cluster.nodes:
                self.cluster.nodes[actor.held_node].release(actor.held_resources)
            actor.held_resources = None
            actor.held_node = None
        if actor.restarts_left > 0:
            actor.restarts_left -= 1
            actor.num_restarts += 1
            actor.state = "RESTARTING"
            self._event("actor", actor.creation_spec, "RESTARTING")
            await self._publish_death(
                "actor", actor_id.hex(), "RESTARTING", reason=reason
            )
            # Re-run the creation task.
            spec = actor.creation_spec
            rec = TaskRecord(spec=spec, retries_left=0)
            self.tasks[spec.task_id] = rec
            self.pending_tasks.append(spec.task_id)
            self._schedule_pump()
        else:
            actor.state = "DEAD"
            actor.death_reason = reason
            self._event("actor", actor.creation_spec, "DEAD")
            await self._publish_death(
                "actor", actor_id.hex(), "DEAD", reason=reason,
                name=actor.creation_spec.name,
            )
            if actor.creation_spec.lifetime == "detached":
                self.journal.actor_dead(actor_id.hex())
            if actor.name:
                self.named_actors.pop(actor.name, None)
            err = ActorDiedError(actor_id.hex(), reason)
            for spec in actor.pending_tasks:
                rec = self.tasks.get(spec.task_id)
                if rec is not None:
                    rec.state = "FAILED"
                self._fail_task_objects(spec, err)
            actor.pending_tasks.clear()
            for fut in actor.ready_waiters:
                if not fut.done():
                    fut.set_exception(err)
            actor.ready_waiters.clear()

    async def _on_node_death(self, node_id: NodeID):
        node = self.nodes.pop(node_id, None)
        if node is None:
            return
        self._dead_node_ids.add(node_id.hex())
        node.state = "DEAD"
        self.cluster.remove_node(node_id)
        self.lifecycle.record("node", node_id.hex(), "DEAD")
        await self._publish_death("node", node_id.hex(), "DEAD")
        for wid in list(node.workers):
            w = self.workers.get(wid)
            if w is not None:
                await _notify_quiet(w.peer, "exit", what="node died")
            await self._on_worker_death(wid, "node died")
        # Drop the dead node from EVERY record's location set (objects can
        # have multiple replicas since the network data plane copies them
        # on pull); objects left with no copy attempt lineage
        # reconstruction.
        for orec in self.objects.values():
            if orec.state == "READY" and orec.inline is None and node_id in orec.locations:
                orec.locations.discard(node_id)
                if not orec.locations:
                    await self._try_reconstruct(orec)
        self.pg_manager.on_node_removed(node_id)
        self._schedule_pump()

    async def _try_reconstruct(self, orec: ObjectRecord):
        """Lineage reconstruction: resubmit the creating task (reference:
        src/ray/core_worker/object_recovery_manager.h:70-84)."""
        spec = self.finished_specs.get(orec.creating_task) if orec.creating_task else None
        if spec is None or spec.task_type != TaskType.NORMAL_TASK:
            orec.state = "FAILED"
            orec.inline = None
            self._wake(orec)
            return
        # GC may have freed an input after the task finished — lineage is
        # then evicted and reconstruction must fail fast, not hang on an
        # empty recreated dep record (reference:
        # ReconstructionFailedLineageEvictedError, exceptions.py:663-705).
        for dep in spec.dependencies:
            dep_rec = self.objects.get(dep)
            if dep_rec is None or (
                dep_rec.state != "READY" and dep_rec.creating_task is None
            ):
                orec.state = "FAILED"
                orec.inline = None
                self._wake(orec)
                return
        orec.state = "PENDING"
        rec = TaskRecord(spec=spec, retries_left=0)
        self.tasks[spec.task_id] = rec
        if spec.dependencies:
            self._live_pin_tasks.add(spec.task_id)
        self.pending_tasks.append(spec.task_id)
        self._event("task", spec, "RECONSTRUCTING")
        self._schedule_pump()

    # =================================================================
    # Objects
    # =================================================================
    def _object(self, oid: ObjectID) -> ObjectRecord:
        rec = self.objects.get(oid)
        if rec is None:
            rec = ObjectRecord(oid=oid)
            self.objects[oid] = rec
        return rec

    def _wake(self, orec: ObjectRecord):
        for fut in orec.waiters:
            if not fut.done():
                fut.set_result(True)
        orec.waiters.clear()

    def _shm_dirs(self) -> Dict[NodeID, str]:
        return {nid: n.shm_dir for nid, n in self.nodes.items()}

    @staticmethod
    def _peer_identity(peer: Optional[rpc.Peer]) -> str:
        """Short creator label for object attribution rows."""
        if peer is None:
            return ""
        wid = peer.meta.get("worker_id")
        if wid is not None:
            return f"worker:{wid.hex()[:12]}"
        holder = peer.meta.get("holder_id") or ""
        kind = peer.meta.get("kind") or "proc"
        return f"{kind}:{holder[:12]}" if holder else kind

    def _attribute_object(self, orec: ObjectRecord, peer: Optional[rpc.Peer],
                          callsite: str):
        if callsite and not orec.callsite:
            orec.callsite = callsite
        if not orec.creator:
            orec.creator = self._peer_identity(peer)

    async def rpc_object_put_inline(
        self, peer: rpc.Peer, oid: ObjectID, data: bytes, is_error: bool = False,
        contained: Optional[list] = None, callsite: str = "",
    ):
        orec = self._object(oid)
        orec.inline = data
        orec.size = len(data)
        orec.is_error = is_error
        if contained:
            orec.children = list(contained)
        self._attribute_object(orec, peer, callsite)
        orec.state = "READY"
        self._wake(orec)
        return True

    async def rpc_object_put_shm(
        self, peer: rpc.Peer, oid: ObjectID, size: int, node_id: NodeID, is_error: bool = False,
        contained: Optional[list] = None, callsite: str = "",
    ):
        orec = self._object(oid)
        orec.size = size
        orec.is_error = is_error
        orec.locations.add(node_id)
        if contained:
            orec.children = list(contained)
        self._attribute_object(orec, peer, callsite)
        await self._account_object(node_id, oid, size)
        orec.state = "READY"
        self._wake(orec)
        return True

    async def _account_object(self, node_id: NodeID, oid: ObjectID, size: int):
        """Register a worker-written shm object with its node's store so
        capacity accounting and spill/eviction see it."""
        node = self.nodes.get(node_id)
        if node is None:
            return
        if node.peer is None:
            self.head_store.adopt(oid, size)
        else:
            await node.peer.notify("adopt_object", oid, size)

    async def rpc_object_ensure_local(self, peer: rpc.Peer, oid: ObjectID, node_hex: str):
        """Restore a spilled object into its node's shm dir before a reader
        maps it (reference: spilled-object restore via IO workers,
        raylet/local_object_manager.cc)."""
        node = self.nodes.get(NodeID.from_hex(node_hex))
        if node is None:
            return False
        if node.peer is None:
            return self.head_store.ensure_local(oid)
        return await node.peer.call("ensure_local", oid)

    async def rpc_fetch_chunk(self, peer: rpc.Peer, oid: ObjectID, offset: int, length: int):
        """Serve a chunk of a head-node object to a pulling agent
        (reference: ObjectManagerService on every node — the head's
        'agent' is the controller itself)."""
        return rpc.Raw(self._chunk_reader.read(oid, offset, length))

    async def rpc_object_pull(self, peer: rpc.Peer, oid: ObjectID, dest_node_id: NodeID) -> bool:
        """Ensure ``oid`` is readable on ``dest_node_id``, transferring it
        over the network if needed (reference: PullManager + the
        ownership-based object directory picking the source replica).
        Concurrent pulls of the same (object, node) coalesce."""
        orec = self.objects.get(oid)
        if orec is None or orec.state != "READY" or orec.inline is not None:
            return False
        if dest_node_id in orec.locations:
            return await self.rpc_object_ensure_local(peer, oid, dest_node_id.hex())
        key = (oid, dest_node_id)
        existing = self._pulls.get(key)
        if existing is not None:
            return await asyncio.shield(existing)
        fut = asyncio.get_running_loop().create_future()
        self._pulls[key] = fut
        try:
            ok = await self._do_pull(oid, orec, dest_node_id)
            if not fut.done():
                fut.set_result(ok)
            return ok
        except Exception as e:  # noqa: BLE001 — surface as pull failure
            logger.warning("object pull %s -> %s failed: %s", oid.hex()[:8], dest_node_id.hex()[:8], e)
            if not fut.done():
                fut.set_result(False)
            return False
        finally:
            self._pulls.pop(key, None)

    async def _do_pull(self, oid: ObjectID, orec: ObjectRecord, dest_node_id: NodeID) -> bool:
        dest = self.nodes.get(dest_node_id)
        if dest is None:
            return False
        # pick a LIVE replica (locations may briefly hold a dying node)
        src = next(
            (self.nodes[nid] for nid in orec.locations if nid in self.nodes),
            None,
        )
        if src is None:
            return False
        if src.peer is None:
            src_addr = "controller"  # head objects served by rpc_fetch_chunk
        else:
            src_addr = src.fetch_addr
            if not src_addr:
                return False
        if dest.peer is None:
            # destination is the head: the controller pulls into its own store
            from ray_tpu.core.object_transfer import pull_into_store

            src_peer = await self._fetch_peer_for(src_addr)
            if src_peer is None:
                return False
            ok = await pull_into_store(
                self.head_store, oid, orec.size, src_peer,
                self.config.object_transfer_chunk_bytes,
            )
        else:
            ok = await dest.peer.call("pull_object", oid, orec.size, src_addr)
        if ok:
            orec.locations.add(dest_node_id)
        return bool(ok)

    async def _fetch_peer_for(self, addr: str) -> Optional[rpc.Peer]:
        if addr == "controller":
            return None  # head pulling from itself makes no sense
        return await self._fetch_peers.get(addr)

    async def rpc_object_get(self, peer: rpc.Peer, oids: List[ObjectID], timeout: Optional[float]):
        """Long-poll get: resolves when ALL are ready (or raises on timeout)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        metas = {}
        for oid in oids:
            if oid not in self.objects and oid in self._freed_lru:
                metas[oid.hex()] = ("lost", None, True)
                continue
            orec = self._object(oid)
            while orec.state == "PENDING":
                fut = asyncio.get_running_loop().create_future()
                orec.waiters.append(fut)
                remain = None if deadline is None else deadline - time.monotonic()
                if remain is not None and remain <= 0:
                    return {"timeout": True, "metas": metas}
                try:
                    await asyncio.wait_for(asyncio.shield(fut), remain)
                except asyncio.TimeoutError:
                    return {"timeout": True, "metas": metas}
            if orec.state == "FAILED":
                metas[oid.hex()] = ("lost", None, True)
            else:
                meta = orec.meta(self._shm_dirs())
                if meta is None:
                    # every replica's node died; reconstruction (queued by
                    # _on_node_death) will re-resolve it, or it is lost
                    await self._try_reconstruct(orec)
                    if orec.state == "PENDING":
                        # re-wait on the reconstructed object
                        continue_oids = [o for o in oids if o.hex() not in metas]
                        inner = await self.rpc_object_get(
                            peer, continue_oids,
                            None if deadline is None else max(0.0, deadline - time.monotonic()),
                        )
                        metas.update(inner["metas"])
                        return {"timeout": inner["timeout"], "metas": metas}
                    meta = ("lost", None, True)
                metas[oid.hex()] = meta
        return {"timeout": False, "metas": metas}

    async def rpc_object_wait(self, peer: rpc.Peer, oids: List[ObjectID], num_returns: int, timeout: Optional[float]):
        """ray.wait semantics: return when num_returns of oids are ready."""
        deadline = None if timeout is None else time.monotonic() + timeout

        def _resolved(o: ObjectID) -> bool:
            if o not in self.objects and o in self._freed_lru:
                return True  # freed → resolved (get will fail fast)
            return self._object(o).state != "PENDING"

        while True:
            ready = [o for o in oids if _resolved(o)]
            if len(ready) >= num_returns:
                return [o.hex() for o in ready]
            remain = None if deadline is None else deadline - time.monotonic()
            if remain is not None and remain <= 0:
                return [o.hex() for o in ready]
            futs = []
            for o in oids:
                if o not in self.objects and o in self._freed_lru:
                    continue
                orec = self._object(o)
                if orec.state == "PENDING":
                    fut = asyncio.get_running_loop().create_future()
                    orec.waiters.append(fut)
                    futs.append(fut)
            if not futs:
                # Everything resolved but fewer than num_returns exist —
                # nothing more can become ready.
                return [o.hex() for o in oids if _resolved(o)]
            try:
                await asyncio.wait_for(
                    asyncio.wait(futs, return_when=asyncio.FIRST_COMPLETED), remain
                )
            except asyncio.TimeoutError:
                pass

    async def rpc_object_free(self, peer: rpc.Peer, oids: List[ObjectID]):
        for oid in oids:
            await self._free_object(oid)
        return True

    async def _free_object(self, oid: ObjectID):
        orec = self.objects.pop(oid, None)
        if orec is None:
            return
        t0 = time.monotonic()
        self._freed_lru[oid] = None
        while len(self._freed_lru) > 200_000:
            self._freed_lru.popitem(last=False)
        # Wake any in-flight long-poll gets as a loss, not a hang.
        if orec.waiters:
            orec.state = "FAILED"
            self._wake(orec)
        # Tasks queued behind a blocked class head may depend on the freed
        # object; the per-class pump no longer re-scans every pending task
        # each cycle, so fail them here (frees are rare, pending can be
        # huge — this is the right side of that trade).
        self._fail_freed_dependents(oid)
        for nid in orec.locations:
            node = self.nodes.get(nid)
            if node is None:
                continue
            if node.peer is None:
                self._chunk_reader.invalidate(oid)
                self.head_store.delete(oid)
            else:
                await node.peer.notify("delete_object", oid)
        _get_mem_metrics()["free_latency"].observe(
            (time.monotonic() - t0) * 1000.0
        )

    # -- distributed ref counting (reference: reference_count.cc; the
    # controller is the authority the way owners are in the reference) ----
    async def rpc_ref_update(
        self, peer: rpc.Peer, holder: str, held: List[bytes], dropped: List[bytes]
    ):
        peer.meta.setdefault("holder_id", holder)
        index = self._holder_index.setdefault(holder, set())
        for key in held:
            # A held report for an already-freed object is a dangling
            # borrow — do NOT resurrect a record (a later get would hang
            # on an empty PENDING entry instead of failing fast).
            oid = ObjectID(key)
            orec = self.objects.get(oid)
            if orec is not None:
                orec.holders.add(holder)
                orec.ever_held = True
                orec.gc_marked = False
                index.add(oid)
        for key in dropped:
            oid = ObjectID(key)
            index.discard(oid)
            orec = self.objects.get(oid)
            if orec is not None:
                orec.holders.discard(holder)
                orec.ever_held = True
        self._gc_wanted.set()
        return True

    def _drop_holder(self, holder: str):
        """A process died/disconnected: it no longer holds anything.
        O(objects that process held), via the reverse index."""
        held = self._holder_index.pop(holder, None)
        if not held:
            return
        for oid in held:
            orec = self.objects.get(oid)
            if orec is not None:
                orec.holders.discard(holder)
        self._gc_wanted.set()

    def _pinned_objects(self) -> Set[ObjectID]:
        """Objects that must survive regardless of holders: args of live
        tasks (deps + nested captures) and children contained in any live
        object (the borrowing protocol's containment edges).

        ``_live_pin_tasks`` is pruned lazily here so a sweep costs
        O(live tasks + terminal-since-last-sweep), not O(all tasks ever)
        — self.tasks grows monotonically (1M+ in the queueing bench)."""
        pinned: Set[ObjectID] = set()
        dead: List[TaskID] = []
        for tid in self._live_pin_tasks:
            rec = self.tasks.get(tid)
            if rec is None or rec.state in ("FINISHED", "FAILED"):
                dead.append(tid)
                continue
            pinned.update(rec.spec.dependencies)
            pinned.update(rec.captures)
        self._live_pin_tasks.difference_update(dead)
        # Actor creation args stay pinned while a restart could re-run
        # __init__ (reference: restarts re-execute the creation task).
        for actor in self.actors.values():
            if actor.state == "DEAD":
                continue
            if actor.state == "ALIVE" and actor.restarts_left <= 0:
                continue
            spec = actor.creation_spec
            pinned.update(spec.dependencies)
            rec = self.tasks.get(spec.task_id)
            if rec is not None:
                pinned.update(rec.captures)
        for orec in self.objects.values():
            pinned.update(orec.children)
        return pinned

    async def _gc_sweep_loop(self):
        interval = self.config.gc_sweep_interval_ms / 1000.0
        while not self._shutdown.is_set():
            try:
                await asyncio.wait_for(self._gc_wanted.wait(), timeout=30.0)
            except asyncio.TimeoutError:
                continue
            await asyncio.sleep(interval)  # batch a window of updates
            self._gc_wanted.clear()
            try:
                freed = await self._gc_sweep()
            except Exception:
                logger.exception("gc sweep failed")
                continue
            if freed:
                # Freeing a container unpins its children — cascade until
                # a sweep frees nothing.
                self._gc_wanted.set()

    async def _gc_sweep(self) -> int:
        candidates = [
            orec
            for orec in self.objects.values()
            if orec.ever_held and not orec.holders and orec.state != "PENDING"
        ]
        if not candidates:
            return 0
        pinned = self._pinned_objects()
        freed = marked = 0
        for orec in candidates:
            if orec.oid in pinned:
                orec.gc_marked = False
                continue
            if not orec.gc_marked:
                # phase 1: mark; freed only if still unreferenced at the
                # next sweep (in-flight borrow flushes get a full interval
                # to land and clear the mark)
                orec.gc_marked = True
                marked += 1
                continue
            await self._free_object(orec.oid)
            freed += 1
        if marked:
            self._gc_wanted.set()  # guarantee a follow-up sweep
        if freed:
            logger.debug("gc: freed %d unreferenced objects", freed)
        return freed

    async def rpc_object_sealed(self, peer: rpc.Peer, oid: ObjectID, size: int, node_id: NodeID):
        await self._account_object(node_id, oid, size)
        # a sealed copy IS a replica — record it in the directory (chain
        # broadcast hops report through here)
        orec = self.objects.get(oid)
        if orec is not None and orec.state == "READY" and orec.inline is None:
            orec.locations.add(node_id)
        return True

    async def rpc_object_broadcast(self, peer: rpc.Peer, oid: ObjectID,
                                   dest_node_ids: Optional[list] = None):
        """1→N object distribution over a pipelined agent chain
        (reference: push_manager.h broadcast; release/benchmarks
        README.md:18-21 '1 GiB to 50 nodes'). Every link runs at full
        bandwidth concurrently, so N deliveries cost ~1 transfer time
        instead of N sequential (or N bandwidth-sharing) pulls from one
        source. Returns True when EVERY destination holds a replica."""
        orec = self.objects.get(oid)
        if orec is None or orec.state != "READY" or orec.inline is not None:
            return False
        if dest_node_ids is None:
            dests = [
                nid for nid, n in self.nodes.items()
                if n.state == "ALIVE" and n.peer is not None
                and nid not in orec.locations
            ]
        else:
            dests = [
                NodeID.from_hex(d) if isinstance(d, str) else d
                for d in dest_node_ids
            ]
            dests = [
                d for d in dests
                if d in self.nodes and self.nodes[d].state == "ALIVE"
                and self.nodes[d].peer is not None and d not in orec.locations
            ]
        if not dests:
            return True
        # source: any live replica; the head serves over the controller
        # connection ("controller" pseudo-address)
        src_addr = None
        for nid in orec.locations:
            node = self.nodes.get(nid)
            if node is None or node.state != "ALIVE":
                continue
            src_addr = "controller" if node.peer is None else node.fetch_addr
            if src_addr:
                break
        if src_addr is None:
            return False
        first = self.nodes[dests[0]]
        next_addrs = [self.nodes[d].fetch_addr for d in dests[1:]]
        try:
            ok = await first.peer.call(
                "pull_chain", oid, orec.size, src_addr, next_addrs
            )
        except Exception:  # noqa: BLE001 — a hop died mid-chain
            logger.exception("broadcast chain failed for %s", oid.hex()[:8])
            return False
        return bool(ok)

    # =================================================================
    # Actors: kill / get-by-name / wait-ready
    # =================================================================
    async def rpc_kill_actor(self, peer: rpc.Peer, actor_id: ActorID, no_restart: bool):
        actor = self.actors.get(actor_id)
        if actor is None:
            return False
        if no_restart:
            actor.restarts_left = 0
        worker = self.workers.get(actor.worker_id) if actor.worker_id else None
        if worker is not None:
            await worker.peer.notify("exit")
        else:
            await self._on_actor_death(actor_id, "killed via ray_tpu.kill")
        return True

    async def rpc_wait_actor_ready(self, peer: rpc.Peer, actor_id: ActorID):
        actor = self.actors.get(actor_id)
        if actor is None:
            raise ActorDiedError(actor_id.hex(), "unknown actor")
        if actor.state == "ALIVE":
            return True
        if actor.state == "DEAD":
            raise ActorDiedError(actor_id.hex(), actor.death_reason)
        fut = asyncio.get_running_loop().create_future()
        actor.ready_waiters.append(fut)
        return await fut

    async def rpc_actor_locate(self, peer: rpc.Peer, actor_id: ActorID):
        """Resolve an actor's direct-transport address, long-polling
        through PENDING/RESTARTING (reference: the submitter's resolution
        of ActorTableData updates, actor_task_submitter.cc)."""
        actor = self.actors.get(actor_id)
        if actor is None:
            return {"state": "DEAD", "reason": "actor not found"}
        while actor.state in ("PENDING", "RESTARTING"):
            fut = asyncio.get_running_loop().create_future()
            actor.ready_waiters.append(fut)
            try:
                await asyncio.shield(fut)
            except Exception:  # noqa: BLE001 — death surfaces via state
                break
        if actor.state != "ALIVE":
            return {"state": "DEAD", "reason": actor.death_reason or "actor dead"}
        worker = self.workers.get(actor.worker_id)
        if worker is None or not worker.listen_addr:
            return {"state": "DEAD", "reason": "actor worker has no listener"}
        return {
            "state": "ALIVE",
            "addr": worker.listen_addr,
            "instance": actor.num_restarts,
        }

    # -- general pub/sub (reference: src/ray/pubsub/ — long-poll batched
    # publisher/subscriber; here subscribers ride their existing control
    # connection, so publish is a push notify per subscriber). The
    # subscriber registry and fan-out live in core/pubsub.py's TopicBus;
    # these RPCs are thin delegates. On subscribe to the resource
    # channel, the current full snapshot is pushed first so the mirror
    # starts from a consistent base before deltas stream in.
    async def rpc_subscribe(self, peer: rpc.Peer, channel: str):
        from ray_tpu.core import pubsub as _ps

        self.bus.subscribe(channel, peer)
        if channel == _ps.RESOURCES_CHANNEL:
            await peer.notify("pubsub_msg", channel, self._resource_snapshot())
        elif channel == _ps.AVOID_CHANNEL:
            await peer.notify("pubsub_msg", channel, self._avoid_snapshot())
        return True

    async def rpc_unsubscribe(self, peer: rpc.Peer, channel: str):
        self.bus.unsubscribe(channel, peer)
        return True

    async def rpc_publish(self, peer: rpc.Peer, channel: str, msg) -> int:
        """Fan a message out to the channel's subscribers CONCURRENTLY
        (one wedged subscriber's backpressure must not stall the rest or
        the publisher); returns the number of live subscribers."""
        return await self.bus.publish(channel, msg)

    def _drop_subscriber(self, peer: rpc.Peer):
        self.bus.drop_peer(peer)

    async def _publish_death(self, kind: str, eid: str, state: str, **attrs):
        """Push a lifecycle death/drain event to DEATH_CHANNEL
        subscribers (train executors and other gang supervisors watch
        this instead of waiting for a blocked collective to time out —
        a SIGKILLed host is detected in well under a second). No-op
        without subscribers; failures never propagate into the death
        path itself."""
        if not self.bus.has(DEATH_CHANNEL):
            return
        msg = {"kind": kind, "id": eid, "state": state, "ts": time.time()}
        msg.update({k: v for k, v in attrs.items() if v})
        try:
            await self.rpc_publish(None, DEATH_CHANNEL, msg)
        except Exception as e:  # noqa: BLE001 — observers only
            logger.debug("death-event publish failed: %s", e)

    async def rpc_chaos_install(self, peer: rpc.Peer, node_id_hex: str,
                                plan_json: str):
        """Install (or clear, plan_json="") a fault plan on a running
        node agent — the runtime lever for agent-level slow-node
        throttling (`chaos.install_plan_on_node`). Empty node id targets
        the controller process itself."""
        if not node_id_hex:
            from ray_tpu.util import chaos

            chaos.install_fault_plan(plan_json or None)
            return True
        for nid, node in self.nodes.items():
            if nid.hex() == node_id_hex and node.peer is not None:
                return await node.peer.call("install_fault_plan", plan_json)
        raise ValueError(f"no live agent for node {node_id_hex}")

    async def rpc_stack_dump_all(self, peer: rpc.Peer, timeout_s: float = 10.0):
        """Live stacks of every cluster process (reference: `ray stack` +
        the dashboard reporter's py-spy dumps). Controller itself,
        agents, and workers dump over their existing channels."""
        from ray_tpu.utils.stack_dump import dump_all_threads

        out: Dict[str, str] = {"controller": dump_all_threads()}

        async def ask(name: str, p: rpc.Peer):
            try:
                out[name] = await asyncio.wait_for(p.call("stack_dump"), timeout_s)
            except Exception as e:  # noqa: BLE001 — wedged/gone process
                out[name] = f"<unavailable: {e}>"

        calls = []
        for w in self.workers.values():
            if w.state != "DEAD" and not w.peer.closed:
                calls.append(ask(f"worker:{w.worker_id.hex()[:8]}:pid{w.pid}", w.peer))
        for n in self.nodes.values():
            if n.peer is not None and not n.peer.closed:
                calls.append(ask(f"agent:{n.node_id.hex()[:8]}", n.peer))
        await asyncio.gather(*calls)
        return out

    # =================================================================
    # On-demand distributed profiling (util/profiling.py; reference: the
    # dashboard reporter's per-worker py-spy stack/CPU-profile endpoints)
    # =================================================================
    def _profile_targets(self, node: Optional[str] = None,
                         actor: Optional[str] = None,
                         workers: Optional[List[str]] = None):
        """(name, peer) fan-out targets, filterable to one node's
        processes, one actor's worker, or an explicit worker-id list.
        Unfiltered = every live process: workers, agents, drivers (the
        controller profiles itself in-process, not through a peer)."""
        actor_wids = None
        if actor:
            actor_wids = {
                a.worker_id
                for a in self.actors.values()
                if a.worker_id is not None and a.actor_id.hex().startswith(actor)
            }
        out = []
        for w in self.workers.values():
            if w.state == "DEAD" or w.peer.closed:
                continue
            if node and not w.node_id.hex().startswith(node):
                continue
            if actor_wids is not None and w.worker_id not in actor_wids:
                continue
            if workers and not any(
                w.worker_id.hex().startswith(p) for p in workers
            ):
                continue
            out.append((f"worker:{w.worker_id.hex()[:8]}:pid{w.pid}", w.peer))
        if actor_wids is None and not workers:
            for n in self.nodes.values():
                if n.peer is None or n.peer.closed:
                    continue
                if node and not n.node_id.hex().startswith(node):
                    continue
                out.append((f"agent:{n.node_id.hex()[:8]}", n.peer))
            if not node:
                for i, d in enumerate(sorted(self.drivers, key=id)):
                    if not d.closed:
                        out.append((f"driver:{i}", d))
        return out

    def _include_self(self, node: Optional[str], actor: Optional[str],
                      workers: Optional[List[str]]) -> bool:
        if actor or workers:
            return False
        return not node or self.head_node_id.hex().startswith(node)

    async def rpc_profile_stacks(self, peer: rpc.Peer,
                                 node: Optional[str] = None,
                                 actor: Optional[str] = None,
                                 timeout_s: float = 10.0):
        """Cluster-wide structured stack dump: controller + agents +
        workers + drivers, merged and deduplicated. The controller's own
        leg is a lock-free snapshot (``profiling.dump_stacks`` touches no
        controller state), so dumping mid-scheduling-storm — or mid-
        deadlock — always returns."""
        from ray_tpu.util import profiling

        procs: Dict[str, Any] = {}
        if self._include_self(node, actor, None):
            procs["controller"] = profiling.dump_stacks()

        async def ask(name: str, p: rpc.Peer):
            try:
                procs[name] = await asyncio.wait_for(
                    p.call("dump_stacks"), timeout_s
                )
            except Exception as e:  # noqa: BLE001 — wedged/gone process
                procs[name] = f"<unavailable: {e}>"

        await asyncio.gather(
            *(ask(name, p) for name, p in self._profile_targets(node, actor))
        )
        return {"procs": procs, "merged": profiling.merge_stack_dumps(procs)}

    async def rpc_profile_cpu_all(self, peer: rpc.Peer,
                                  duration_s: float = 10.0,
                                  hz: Optional[float] = None,
                                  node: Optional[str] = None,
                                  workers: Optional[List[str]] = None):
        """Fan out the sampling CPU profiler: every target profiles
        itself concurrently for ``duration_s`` (samplers run on their own
        threads; nobody's control plane blocks), results merge into
        cluster-wide collapsed stacks + per-task CPU attribution."""
        from ray_tpu.util import profiling

        if hz is None:
            hz = float(self.config.profiling_sample_hz)
        duration_s = max(0.05, min(float(duration_s), 600.0))
        results: Dict[str, Any] = {}

        async def ask(name: str, p: rpc.Peer):
            try:
                results[name] = await asyncio.wait_for(
                    p.call("profile_cpu", duration_s, hz), duration_s + 15.0
                )
            except Exception as e:  # noqa: BLE001 — wedged/gone process
                results[name] = f"<unavailable: {e}>"

        legs = [
            ask(name, p)
            for name, p in self._profile_targets(node, None, workers)
        ]
        if self._include_self(node, None, workers):

            async def self_leg():
                results["controller"] = await profiling.sample_async(
                    duration_s, hz
                )

            legs.append(self_leg())
        await asyncio.gather(*legs)
        merged = profiling.merge_cpu_results(results)
        merged["hz"] = hz
        merged["duration_s"] = duration_s
        merged["ms_per_sample"] = 1000.0 / hz
        return merged

    async def rpc_profile_device_all(self, peer: rpc.Peer,
                                     workers: Optional[List[str]] = None,
                                     duration_s: float = 5.0,
                                     capture: Optional[str] = None):
        """Attach jax.profiler traces to already-running workers for
        ``duration_s`` (start → sleep → stop over their live RPC
        channels — no restart). Captures land in each worker's session
        ``profiles/`` root, listed by ``ray-tpu profile captures``."""
        capture = capture or f"ondemand-{int(time.time())}"
        duration_s = max(0.1, min(float(duration_s), 600.0))
        targets = [
            (name, p)
            for name, p in self._profile_targets(None, None, workers)
            if name.startswith("worker:")
        ]
        out: Dict[str, dict] = {}

        async def control(name: str, p: rpc.Peer, action: str):
            try:
                return await asyncio.wait_for(
                    p.call("profile_device", action, capture), 15.0
                )
            except Exception as e:  # noqa: BLE001 — wedged/gone worker
                return {"ok": False, "error": str(e)}

        starts = await asyncio.gather(
            *(control(name, p, "start") for name, p in targets)
        )
        started = []
        for (name, p), res in zip(targets, starts):
            out[name] = res
            if res.get("ok"):
                started.append((name, p))
        if started:
            await asyncio.sleep(duration_s)
            stops = await asyncio.gather(
                *(control(name, p, "stop") for name, p in started)
            )
            for (name, _p), res in zip(started, stops):
                out[name] = res
        return {"capture": capture, "duration_s": duration_s, "workers": out}

    async def rpc_profile_incidents(self, peer: rpc.Peer, limit: int = 100):
        """Incident capture bundles under this session (auto-written by
        the lockwatch/recompile-storm/SLO detectors)."""
        from ray_tpu.util import profiling

        return profiling.list_incidents(self.session_dir)[-max(1, limit):]

    async def rpc_get_incident(self, peer: rpc.Peer, incident_id: str):
        from ray_tpu.util import profiling

        return profiling.get_incident(incident_id, self.session_dir)

    # =================================================================
    # Object & memory observability (`ray-tpu memory`; reference: `ray
    # memory` / dashboard memory view over core-worker ref counting)
    # =================================================================
    async def _dump_memory_fanout(self, node: Optional[str], limit: int,
                                  timeout_s: float) -> Dict[str, Any]:
        """Every process answers ``rpc_dump_memory`` over its existing
        channel (the PR 9 profiling fan-out pattern): workers/drivers
        return their ref census, agents their store's per-object rows."""
        procs: Dict[str, Any] = {}

        async def ask(name: str, p: rpc.Peer):
            try:
                procs[name] = await asyncio.wait_for(
                    p.call("dump_memory", limit=limit), timeout_s
                )
            except Exception as e:  # noqa: BLE001 — wedged/gone process
                procs[name] = f"<unavailable: {e}>"

        await asyncio.gather(
            *(ask(name, p) for name, p in self._profile_targets(node, None))
        )
        return procs

    def _store_stats_by_node(self, procs: Dict[str, Any]) -> Dict[str, dict]:
        """Per-node store stats: the head's live, agents' from their
        fan-out dump (falling back to the last telemetry heartbeat)."""
        stores: Dict[str, dict] = {}
        agent_dumps = {
            name[len("agent:"):]: d
            for name, d in procs.items()
            if name.startswith("agent:") and isinstance(d, dict)
        }
        for nid, nrec in self.nodes.items():
            hexid = nid.hex()
            if nrec.peer is None:
                stores[hexid] = self.head_store.stats()
                continue
            dump = agent_dumps.get(hexid[:8])
            if dump is not None and dump.get("store"):
                stores[hexid] = dump["store"]
            else:
                stores[hexid] = (nrec.telemetry or {}).get("object_store", {})
        return stores

    def _store_object_index(self, procs: Dict[str, Any]) -> Dict[str, dict]:
        """oid hex -> store row (pinned/spilled/in_arena), merged across
        the head store and every agent dump — the spill/pin tier source
        for per-object attribution."""
        index: Dict[str, dict] = {}
        for row in self.head_store.object_rows():
            index[row["object_id"]] = row
        for name, d in procs.items():
            if name.startswith("agent:") and isinstance(d, dict):
                for row in d.get("objects", ()):
                    index.setdefault(row["object_id"], row)
        return index

    def _object_tier(self, orec: ObjectRecord,
                     store_row: Optional[dict]) -> str:
        if orec.state == "PENDING":
            return "pending"
        if orec.state == "FAILED":
            return "failed"
        if orec.inline is not None:
            return "inline"
        if store_row is not None and store_row.get("spilled"):
            return "spilled"
        return "shm"

    async def rpc_summarize_memory(self, peer, limit: int = 50,
                                   node: Optional[str] = None,
                                   timeout_s: float = 5.0):
        """Cluster-wide memory census rollup: controller object directory
        (size/tier/call-site/holders) merged with per-process ref
        censuses and per-node store stats. O(limit) call-site rows on the
        wire; totals are uncapped."""
        procs = await self._dump_memory_fanout(node, 1000, timeout_s)
        stores = self._store_stats_by_node(procs)
        store_index = self._store_object_index(procs)
        by_site: Dict[str, dict] = {}

        def site_row(site: str) -> dict:
            row = by_site.get(site)
            if row is None:
                row = by_site[site] = {
                    "objects": 0, "bytes": 0, "spilled_bytes": 0,
                    "local_refs": 0, "pins": 0,
                    "tiers": {},
                }
            return row

        totals = {
            "objects": len(self.objects),
            "inline_bytes": 0, "shm_bytes": 0, "spilled_bytes": 0,
            "open_refs": 0, "pins": 0, "pin_bytes": 0,
            "memory_store_entries": 0, "memory_store_bytes": 0,
        }
        for oid, orec in self.objects.items():
            srow = store_index.get(oid.hex())
            tier = self._object_tier(orec, srow)
            site = orec.callsite or "(unknown)"
            row = site_row(site)
            row["objects"] += 1
            row["bytes"] += orec.size
            row["tiers"][tier] = row["tiers"].get(tier, 0) + 1
            if tier == "inline":
                totals["inline_bytes"] += orec.size
            elif tier == "shm":
                totals["shm_bytes"] += orec.size
            elif tier == "spilled":
                totals["spilled_bytes"] += orec.size
                row["spilled_bytes"] += orec.size
        proc_rows: Dict[str, dict] = {}
        pin_pids: Set[int] = set()  # the pin registry is per-PROCESS:
        # two connections from one process (a driver + its cluster-admin
        # CoreWorker) must not double-count the same pins
        for name, d in procs.items():
            if name.startswith("agent:") or not isinstance(d, dict):
                if not isinstance(d, dict):
                    proc_rows[name] = {"error": str(d)}
                continue
            refs = d.get("refs", {})
            pins = d.get("pins", {})
            ms = d.get("memory_store", {})
            open_refs = 0
            for site, info in refs.items():
                open_refs += info.get("count", 0)
                row = site_row(site)
                row["local_refs"] += info.get("count", 0)
                row["pins"] += info.get("pinned", 0)
            totals["open_refs"] += open_refs
            pid = d.get("pid")
            if pid not in pin_pids:
                pin_pids.add(pid)
                totals["pins"] += pins.get("count", 0)
                totals["pin_bytes"] += pins.get("bytes", 0)
            totals["memory_store_entries"] += ms.get("entries", 0)
            totals["memory_store_bytes"] += ms.get("ready_bytes", 0)
            proc_rows[name] = {
                "open_refs": open_refs,
                "memory_store": ms,
                "pins": {k: pins.get(k, 0) for k in ("count", "bytes")},
            }
        keep = sorted(
            by_site.items(),
            key=lambda kv: (-kv[1]["bytes"],
                            -(kv[1]["objects"] + kv[1]["local_refs"])),
        )
        return {
            "totals": totals,
            "nodes": stores,
            "by_callsite": dict(keep[: max(1, limit)]),
            "truncated": len(keep) > limit,
            "procs": proc_rows,
            "leaks": sorted(
                self._leak_flags.values(), key=lambda r: -r.get("count", 0)
            ),
        }

    async def rpc_list_object_refs(self, peer, limit: int = 1000,
                                   node: Optional[str] = None,
                                   timeout_s: float = 5.0):
        """Per-object census rows (the `ray memory` table): directory
        objects with owner/call-site/tier/holders (newest ``limit``),
        plus owner-local memory-store objects invisible to the directory,
        attributed by the process fan-out."""
        import collections as _c

        procs = await self._dump_memory_fanout(node, limit, timeout_s)
        store_index = self._store_object_index(procs)
        # borrow/pin attribution per object from the process censuses
        holders_by_oid: Dict[str, List[str]] = {}
        local_rows: List[dict] = []
        for name, d in procs.items():
            if name.startswith("agent:") or not isinstance(d, dict):
                continue
            for row in d.get("objects", ()):
                hexid = row["object_id"]
                if row.get("local_only"):
                    if len(local_rows) < limit:
                        local_rows.append(
                            {
                                "object_id": hexid,
                                "tier": "memory_store",
                                "callsite": row.get("callsite", ""),
                                "creator": name,
                                "holders": [name],
                                "local_refs": row.get("count", 0),
                                "size": None,  # owner-private; size unknown
                                "state": "READY",
                                "pinned": bool(row.get("pinned")),
                            }
                        )
                else:
                    holders_by_oid.setdefault(hexid, []).append(name)
        # Memory-store rows keep their slots: the owner-local tier is the
        # one the directory can never show, so a full directory must not
        # silently squeeze it out of the capped reply.
        limit = max(1, limit)
        dir_limit = max(1, limit - len(local_rows))
        out = []
        for oid, orec in _c.deque(self.objects.items(), maxlen=dir_limit):
            hexid = oid.hex()
            srow = store_index.get(hexid)
            out.append(
                {
                    "object_id": hexid,
                    "state": orec.state,
                    "size": orec.size,
                    "tier": self._object_tier(orec, srow),
                    "callsite": orec.callsite,
                    "creator": orec.creator,
                    "holders": holders_by_oid.get(
                        hexid, sorted(orec.holders)
                    ),
                    "locations": [n.hex() for n in orec.locations],
                    "pinned": bool(srow and srow.get("pinned")),
                    "is_error": orec.is_error,
                }
            )
        return (out + local_rows)[:limit]

    async def rpc_summarize_objects(self, peer, limit: int = 100):
        """Controller-side object rollup (replaces the client pulling
        100k full rows to count them): uncapped totals by state/tier,
        call-site counts capped to the ``limit`` largest."""
        by_state: Dict[str, int] = {}
        by_tier: Dict[str, int] = {}
        sites: Dict[str, dict] = {}
        total_size = 0
        # Same tier rule as summarize_memory (_object_tier), with the
        # head store's spill view (local, no fan-out — agent-node spills
        # show as shm here; full fidelity lives in rpc_summarize_memory).
        spilled_here = self.head_store.spilled_ids()
        _SPILLED_ROW = {"spilled": True}
        for oid, orec in self.objects.items():
            by_state[orec.state] = by_state.get(orec.state, 0) + 1
            tier = self._object_tier(
                orec, _SPILLED_ROW if oid.hex() in spilled_here else None
            )
            by_tier[tier] = by_tier.get(tier, 0) + 1
            total_size += orec.size
            site = orec.callsite or "(unknown)"
            row = sites.setdefault(site, {"count": 0, "bytes": 0})
            row["count"] += 1
            row["bytes"] += orec.size
        keep = sorted(sites.items(), key=lambda kv: -kv[1]["bytes"])
        return {
            "total": len(self.objects),
            "total_size": total_size,
            "by_state": by_state,
            "by_tier": by_tier,
            "callsites": dict(keep[: max(1, limit)]),
            "truncated": len(keep) > limit,
        }

    def _memory_census_tick(self):
        """Per-telemetry-sweep census work: the Grafana "Memory" gauges,
        the open-ref growth (leak) detector, and the store-pressure
        incident trigger. The object-table pass is SHARDED (round 17):
        each sweep walks at most ``_CENSUS_CHUNK`` records against a
        key snapshot taken at cycle start, accumulating kinds/by_site
        across the cycle; gauges and the leak sweep publish once per
        completed cycle. Per-tick controller-loop work is thereby
        bounded regardless of table size — the old stride amortization
        still paid one full O(objects) stall whenever it did fire."""
        if not getattr(self.config, "memory_census", True):
            return
        m = _get_mem_metrics()
        for nid, nrec in self.nodes.items():
            # The head's heartbeat (built one line before this tick in
            # _head_telemetry_loop) already carries a fresh stats() dict —
            # don't pay the O(entries) store scan a second time per sweep.
            store = (nrec.telemetry or {}).get("object_store") or (
                self.head_store.stats() if nrec.peer is None else {}
            )
            tag = {"node": nid.hex()[:12]}
            m["store_used"].set(store.get("used", 0), tag)
            m["store_pinned"].set(store.get("pinned_bytes", 0), tag)
            m["store_spilled"].set(store.get("spilled_bytes", 0), tag)
            self._pressure_check_node(nid, store)
        self._census_tick_n += 1
        if self._census_cycle is None:
            # New cycle: snapshot the key list (a ref copy — milliseconds
            # even at envelope depth) so the shard walk stays stable
            # while the table churns underneath it.
            self._census_cycle = {
                "keys": list(self.objects),
                "pos": 0,
                "kinds": {"inline": 0, "shm": 0, "pending": 0, "failed": 0},
                "by_site": {},
            }
        cyc = self._census_cycle
        keys = cyc["keys"]
        pos = cyc["pos"]
        end = min(len(keys), pos + _CENSUS_CHUNK)
        kinds = cyc["kinds"]
        by_site: Dict[str, int] = cyc["by_site"]
        objects = self.objects
        for key in keys[pos:end]:
            orec = objects.get(key)
            if orec is None:
                continue  # freed since the cycle's snapshot
            if orec.state == "PENDING":
                kinds["pending"] += 1
            elif orec.state == "FAILED":
                kinds["failed"] += 1
            elif orec.inline is not None:
                kinds["inline"] += 1
            else:
                kinds["shm"] += 1
            site = orec.callsite or "(unknown)"
            by_site[site] = by_site.get(site, 0) + 1
        cyc["pos"] = end
        if end >= len(keys):
            for kind, n in kinds.items():
                m["refs_open"].set(n, {"kind": kind})  # ray-tpu: lint-ignore[RTL004] — fixed 4-value tier vocabulary
            self._leak_sweep(by_site)
            self._census_cycle = None

    def _leak_sweep(self, by_site: Dict[str, int]):
        """Flag call-sites whose open-object count rose monotonically
        across ``memory_leak_sweeps`` consecutive sweeps and sits above
        ``memory_leak_min_refs`` — the ref-hoarder signature. Vocabulary
        is bounded: client-side call-sites are interned under
        ``memory_callsite_cap`` and the trend table caps at 512 entries."""
        import collections as _c

        sweeps = max(2, int(getattr(self.config, "memory_leak_sweeps", 5)))
        floor = int(getattr(self.config, "memory_leak_min_refs", 32))
        trends = self._mem_trends
        for site, count in by_site.items():
            dq = trends.get(site)
            if dq is None:
                if len(trends) >= 512:
                    continue  # bounded vocabulary backstop
                dq = trends[site] = _c.deque(maxlen=sweeps)
            dq.append(count)
        for site in [s for s in trends if s not in by_site]:
            trends.pop(site, None)
            self._leak_flags.pop(site, None)
        for site, dq in trends.items():
            window = list(dq)
            cur = window[-1]
            rising = (
                len(window) == sweeps
                and cur >= floor
                and all(b > a for a, b in zip(window, window[1:]))
            )
            if rising:
                flag = self._leak_flags.get(site)
                if flag is None:
                    self._leak_flags[site] = {
                        "callsite": site,
                        "count": cur,
                        "growth": cur - window[0],
                        "first_flagged": time.time(),
                    }
                    _get_mem_metrics()["leak_flags"].inc(
                        1, {"callsite": site}  # ray-tpu: lint-ignore[RTL004] — bounded by the intern cap + trend-table cap
                    )
                    logger.warning(
                        "memory leak suspect: %s — open refs rising "
                        "monotonically over %d sweeps (now %d)",
                        site, sweeps, cur,
                    )
                    from ray_tpu.util.actuators import HealthSignal

                    self.health.observe(HealthSignal(
                        "memory_leak", key=site,
                        detail={"count": cur, "growth": cur - window[0]},
                    ))
                else:
                    flag["count"] = cur
                    flag["growth"] = cur - window[0]
            elif site in self._leak_flags and cur <= window[0]:
                self._leak_flags.pop(site, None)  # recovered

    def _pressure_check_node(self, nid: NodeID, store: dict):
        """Store-pressure incident trigger: occupancy past
        ``memory_incident_occupancy_pct`` or eviction-loop churn past
        ``memory_incident_spill_churn`` spills per sweep fires PR 9's
        incident machinery with a memory autopsy bundle."""
        pct = float(
            getattr(self.config, "memory_incident_occupancy_pct", 0.95)
        )
        churn = int(getattr(self.config, "memory_incident_spill_churn", 200))
        ops = int(store.get("spill_ops", 0) or 0)
        prev = self._spill_ops_prev.get(nid)
        self._spill_ops_prev[nid] = ops
        cap = store.get("capacity") or 0
        used = store.get("used", 0) or 0
        reason = None
        if pct > 0 and cap > 0 and used / cap >= pct:
            reason = "occupancy"
        elif churn > 0 and prev is not None and ops - prev >= churn:
            reason = "spill_churn"
        if reason is None:
            return
        # Health plane BEFORE the incident rate-limit pre-check: the
        # actuator registry has its own cooldown/budget, and a pressure
        # episode suppressed here (a capture fired recently) must still
        # reach the spill actuator.
        from ray_tpu.util.actuators import HealthSignal

        self.health.observe(HealthSignal(
            "memory_pressure", key=nid.hex(), target=nid.hex(),
            detail={
                "reason": reason,
                "occupancy": round(used / cap, 4) if cap else None,
                "spill_ops_delta": (ops - prev) if prev is not None else 0,
            },
        ))
        from ray_tpu.util import profiling

        # Pre-check the rate limit so a sustained-pressure store doesn't
        # spawn a capture thread per sweep (incident() re-checks it
        # atomically) — the slo_breach pattern.
        min_interval = float(
            self.config.profiling_incident_min_interval_s
        )
        if (
            time.time() - profiling._incident_last.get("memory_pressure", 0.0)
            < min_interval
        ):
            return
        autopsy = self._memory_autopsy(nid, reason, store)
        detail = {
            "node": nid.hex()[:12],
            "reason": reason,
            "occupancy": round(used / cap, 4) if cap else None,
            "spill_ops_delta": (ops - prev) if prev is not None else 0,
        }
        import threading as _t

        _t.Thread(
            target=profiling.incident,
            args=("memory_pressure", detail),
            kwargs={"extra_files": {
                "memory.json": json.dumps(autopsy, indent=1, default=str)
            }},
            daemon=True,
            name="memory-incident",
        ).start()

    def _memory_autopsy(self, nid: NodeID, reason: str, store: dict) -> dict:
        """The autopsy bundle body: top call-sites by resident bytes,
        per-node store stats, and the spill/delete queue depths — enough
        to answer "who filled the store" from the incident dir alone."""
        by_site: Dict[str, dict] = {}
        scan = len(self.objects) <= 300_000
        if scan:
            for orec in self.objects.values():
                if orec.state != "READY" or orec.inline is not None:
                    continue
                site = orec.callsite or "(unknown)"
                row = by_site.setdefault(site, {"objects": 0, "bytes": 0})
                row["objects"] += 1
                row["bytes"] += orec.size
        top = sorted(by_site.items(), key=lambda kv: -kv[1]["bytes"])[:20]
        nodes = {}
        for onid, nrec in self.nodes.items():
            if nrec.peer is None:
                nodes[onid.hex()[:12]] = self.head_store.stats()
            else:
                nodes[onid.hex()[:12]] = (
                    (nrec.telemetry or {}).get("object_store") or {}
                )
        return {
            "trigger_node": nid.hex()[:12],
            "reason": reason,
            "store": store,
            "spill_queue": {
                "deferred_deletes": store.get("deferred_deletes", 0),
                "num_spilled": store.get("num_spilled", 0),
                "spilled_bytes": store.get("spilled_bytes", 0),
                "spill_ops": store.get("spill_ops", 0),
            },
            "top_callsites": dict(top),
            "top_callsites_complete": scan,
            "leaks": list(self._leak_flags.values()),
            "nodes": nodes,
        }

    # =================================================================
    # Cluster log plane (core/log_plane.py; reference: the dashboard
    # StateHead logs API + log_monitor + GCS error-event aggregation)
    # =================================================================
    def _log_dir(self) -> str:
        return os.path.join(self.session_dir, "logs")

    def _worker_node_map(self) -> Dict[str, str]:
        """worker-id 8-hex prefix -> node hex (log filenames encode the
        worker; the controller's table supplies the node attribution)."""
        return {
            w.worker_id.hex()[:8]: w.node_id.hex()
            for w in self.workers.values()
        }

    def _attribute_file_node(self, filename: str, wmap: Dict[str, str],
                             fallback: Optional[str] = None) -> Optional[str]:
        stem = os.path.splitext(filename)[0]
        for prefix in ("worker-", "driver-"):
            if stem.startswith(prefix):
                wid = stem[len(prefix):]
                node = wmap.get(wid[:8])
                if node:
                    return node
        if filename.startswith(("controller", "driver-")):
            return self.head_node_id.hex()
        return fallback

    def _log_agent_targets(self, node: Optional[str]):
        out = []
        for n in self.nodes.values():
            if n.peer is None or n.peer.closed:
                continue
            if node and not n.node_id.hex().startswith(node):
                continue
            out.append(n)
        return out

    async def rpc_list_logs(self, peer, node: Optional[str] = None,
                            timeout_s: float = 10.0):
        """Cluster-wide log listing: the head's session log dir plus
        every agent's, merged and deduplicated by filename (single-host
        simulations share one dir; true multi-host nodes each contribute
        their own), each row attributed to the node whose worker wrote
        it."""
        from ray_tpu.core import log_plane

        per_node: Dict[str, list] = {}
        if not node or self.head_node_id.hex().startswith(node):
            # off-loop like the agents: listing stats every log file
            per_node[self.head_node_id.hex()] = await asyncio.to_thread(
                log_plane.list_local, self._log_dir()
            )

        async def ask(n: NodeRecord):
            try:
                res = await asyncio.wait_for(n.peer.call("list_logs"), timeout_s)
                per_node[n.node_id.hex()] = res.get("files", [])
            except Exception as e:  # noqa: BLE001 — wedged/gone agent
                logger.debug("list_logs on %s failed: %s",
                             n.node_id.hex()[:8], e)

        await asyncio.gather(*(ask(n) for n in self._log_agent_targets(node)))
        wmap = self._worker_node_map()
        rows: Dict[str, dict] = {}
        for node_hex, files in per_node.items():
            for f in files:
                name = f["filename"]
                if name in rows:
                    continue
                f = dict(f)
                f["node"] = self._attribute_file_node(name, wmap, node_hex)
                rows[name] = f
        out = sorted(rows.values(), key=lambda r: r["filename"])
        if node:
            out = [r for r in out
                   if r.get("node") and r["node"].startswith(node)]
        return out

    async def rpc_get_log(self, peer, filename: str, tail: int = 1000,
                          node: Optional[str] = None,
                          timeout_s: float = 10.0):
        """One log file's tail, wherever it lives: the head's dir first,
        then the agents (path-traversal guarded on every leg)."""
        from ray_tpu.core import log_plane

        if not node or self.head_node_id.hex().startswith(node):
            try:
                # off-loop: reading a rotation-capped file is up to
                # ~2x log_rotate_bytes of I/O
                return await asyncio.to_thread(
                    log_plane.read_local, self._log_dir(), filename, tail
                )
            except FileNotFoundError:
                pass
        last_err: Exception = FileNotFoundError(filename)
        for n in self._log_agent_targets(node):
            try:
                return await asyncio.wait_for(
                    n.peer.call("get_log", filename, tail), timeout_s
                )
            except ValueError:
                raise  # traversal attempt — do not keep probing
            except Exception as e:  # noqa: BLE001 — missing there / agent gone
                last_err = e
        raise last_err

    async def rpc_search_logs(self, peer, pattern: Optional[str] = None,
                              severity: Optional[str] = None,
                              task: Optional[str] = None,
                              actor: Optional[str] = None,
                              node: Optional[str] = None,
                              since: Optional[float] = None,
                              until: Optional[float] = None,
                              limit: int = 1000,
                              timeout_s: float = 10.0):
        """Cluster-wide structured log search (the `ray-tpu logs --grep/
        --task/--err` backend): regex + severity floor + time range +
        entity filters fan out to every node's sidecars over the
        existing channels (the PR 9/10 pattern), results merge bounded
        and time-ordered, deduplicated by (file, line) for shared-dir
        single-host nodes."""
        from ray_tpu.core import log_plane

        limit = max(1, min(int(limit), 10000))
        filters = dict(pattern=pattern, severity=severity, task=task,
                       actor=actor, node=node, since=since, until=until,
                       limit=limit)
        merged: Dict[tuple, dict] = {}

        def fold(records):
            for rec in records:
                merged.setdefault(
                    (rec.get("file", ""), rec.get("line", 0)), rec
                )

        if not node or self.head_node_id.hex().startswith(node):
            # off-loop like the agents: a regex scan over sidecars near
            # the rotation cap must not stall the scheduler loop
            fold(await asyncio.to_thread(
                log_plane.search_local, self._log_dir(), **filters
            ))

        async def ask(n: NodeRecord):
            try:
                fold(await asyncio.wait_for(
                    n.peer.call("search_logs", **filters), timeout_s
                ))
            except Exception as e:  # noqa: BLE001 — wedged/gone agent
                logger.debug("search_logs on %s failed: %s",
                             n.node_id.hex()[:8], e)

        await asyncio.gather(*(ask(n) for n in self._log_agent_targets(node)))
        wmap = self._worker_node_map()
        out = []
        for rec in merged.values():
            if rec.get("node") is None and rec.get("file"):
                rec["node"] = self._attribute_file_node(rec["file"], wmap)
                if node and not str(rec["node"] or "").startswith(node):
                    continue
            out.append(rec)
        out.sort(key=lambda r: (r.get("ts") or 0.0, r.get("file", ""),
                                r.get("line", 0)))
        return out[:limit]

    async def rpc_log_errors(self, peer, batch: List[dict]):
        """ERROR/exception records shipped by workers, agents, and
        drivers — folded into the bounded error-signature index."""
        for rec in batch:
            self._error_index.ingest(rec)
        return True

    async def rpc_summarize_errors(self, peer, limit: int = 50):
        """The error index: repeated failures collapsed by signature
        (exception type + interned top user frames) with counts, first/
        last seen, a sample traceback, and the lifecycle entity link."""
        return self._error_index.summarize(limit)

    async def rpc_log_follow(self, peer, filters: Optional[dict] = None):
        """Register this connection for live structured log delivery
        (``ray-tpu logs --follow``): matching records push as
        ``log_records`` notifies over the LogTailer→driver channel."""
        f = dict(filters or {})
        if f.pop("err", None):
            f.setdefault("severity", "ERROR")
        f = {k: v for k, v in f.items() if k in (
            "pattern", "severity", "task", "actor", "node") and v}
        self._log_followers[peer] = f
        self._ensure_record_tailer()
        return True

    async def rpc_log_unfollow(self, peer):
        self._log_followers.pop(peer, None)
        return True

    def _ensure_record_tailer(self):
        """Lazy structured tailer: worker sidecars only start being
        tailed once somebody follows (span sinks and raw logs are
        excluded by the pattern). Like the raw log-to-driver tailer
        above, this covers every worker logging into the session dir —
        all nodes on the single-host simulation; a true multi-host
        deployment would relay per-agent tailers (search/list DO fan
        out; follow is head-dir scoped)."""
        if self._record_tailer is not None:
            return
        from ray_tpu.core.log_monitor import LogTailer

        self._record_tailer = LogTailer(
            self._log_dir(), self._broadcast_records,
            pattern="worker-*.jsonl", start_at_end=True,
        )
        self._record_tailer.start()

    def _broadcast_records(self, batch):
        """Thread→loop bridge: parse tailed sidecar lines once, then fan
        matching records out to each follower by ITS filters."""
        if not self._log_followers or self._loop is None:
            return
        recs = []
        for source, line in batch:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            rec["file"] = source
            recs.append(rec)
        if not recs:
            return
        from ray_tpu.core import log_plane

        async def send():
            for peer, filters in list(self._log_followers.items()):
                try:
                    matched = [
                        r for r in recs
                        if log_plane.match_record(r, **filters)
                    ]
                except Exception as e:  # noqa: BLE001 — bad follower regex
                    logger.debug("follow filter failed: %s", e)
                    continue
                if matched:
                    await _notify_quiet(
                        peer, "log_records", matched, what="follower gone"
                    )

        asyncio.run_coroutine_threadsafe(send(), self._loop)

    def _error_spike_check(self):
        """Error-rate-spike trigger: >= log_error_spike_threshold ERROR
        records ingested within one telemetry sweep fires the PR 9
        incident machinery with the offending log tail attached."""
        threshold = int(getattr(self.config, "log_error_spike_threshold", 50))
        total = self._error_index.total
        delta = total - self._errors_prev_total
        self._errors_prev_total = total
        if threshold <= 0 or delta < threshold:
            return
        # Health plane before the incident rate-limit pre-check (same
        # rationale as memory_pressure): resolve the loudest signature and
        # the node it blames so the quarantine actuator has a target.
        try:
            top = self._error_index.summarize(limit=1)["signatures"]
            sig, row = next(iter(top.items())) if top else ("", {})
            nodes = row.get("nodes") or []
            from ray_tpu.util.actuators import HealthSignal

            self.health.observe(HealthSignal(
                "error_spike",
                key=nodes[0] if nodes else sig[:64],
                target=nodes[0] if nodes else "",
                detail={"signature": sig[:160], "errors_this_sweep": delta,
                        "count": row.get("count", 0)},
            ))
        except Exception as e:  # noqa: BLE001 — health must not break detection
            logger.debug("error-spike health observe failed: %s", e)
        from ray_tpu.core.log_plane import format_record
        from ray_tpu.util import profiling

        # Pre-check the rate limit so sustained error storms don't spawn
        # a capture thread per sweep (incident() re-checks atomically) —
        # the slo_breach/memory_pressure pattern.
        min_interval = float(self.config.profiling_incident_min_interval_s)
        if (
            time.time() - profiling._incident_last.get("error_spike", 0.0)
            < min_interval
        ):
            return
        tail = "\n".join(
            format_record(r) for r in self._error_index.recent_tail(100)
        )
        summary = self._error_index.summarize(limit=10)
        detail = {
            "errors_this_sweep": delta,
            "threshold": threshold,
            "top_signatures": {
                sig: row["count"]
                for sig, row in summary["signatures"].items()
            },
        }
        import threading as _t

        _t.Thread(
            target=profiling.incident,
            args=("error_spike", detail),
            kwargs={"extra_files": {"log_tail.txt": tail}},
            daemon=True,
            name="error-spike-incident",
        ).start()

    def _drain_spawn_events(self):
        """Fold worker SPAWNED events recorded by in-process spawns (the
        controller doubles as the head's agent) into the flight recorder.
        Agent-side spawns arrive through rpc_task_events instead."""
        from ray_tpu.core import node_agent as _na

        while True:
            try:
                ev = _na._lifecycle_events.popleft()
            except IndexError:
                return
            self.lifecycle.ingest(ev)

    async def rpc_task_events(self, peer: rpc.Peer, batch: List[dict]):
        """Batched task events from workers executing direct-push tasks
        (reference: TaskEventBuffer flushes to the GCS task manager) —
        plus driver-side SUBMITTED/WORKER_ASSIGNED and agent-side worker
        SPAWNED events, all folded into the flight recorder.

        Ingest is chunked: a 100k-task drain can land tens of thousands
        of events in one flush, and a single synchronous walk that size
        stalls the controller loop (and every lease/push RPC behind it)
        for ~100 ms. Yielding between chunks keeps loop_p50 flat while
        the recorder absorbs the same volume."""
        for i, ev in enumerate(batch):
            if i and i % 2000 == 0:
                await asyncio.sleep(0)
            self.lifecycle.ingest(ev)
        # The legacy ring keeps its pre-recorder semantics — worker
        # EXECUTION events only. Driver SUBMITTED/WORKER_ASSIGNED and
        # agent SPAWNED halves live in the flight recorder; letting them
        # into this buffer would halve the timeline's RUNNING→FINISHED
        # pairing window at the same task_event_buffer_size.
        self.events.extend(
            e for e in batch
            if e.get("kind") == "task"
            and e.get("state") in ("RUNNING", "FINISHED", "FAILED")
        )
        if len(self.events) > self.config.task_event_buffer_size:
            del self.events[: len(self.events) // 2]
        # Keep the state API's task view covering direct-push tasks the
        # controller never dispatched (reference: GcsTaskManager's
        # event-derived task table).
        wid = peer.meta.get("worker_id")
        w = self.workers.get(wid) if wid else None
        node_hex = w.node_id.hex() if w is not None else None
        for ev in batch:
            if ev.get("kind") != "task" or "task_id" not in ev:
                continue
            state = ev.get("state", "")
            if state not in ("RUNNING", "FINISHED", "FAILED"):
                # The task-row view stays EXECUTION-derived (worker
                # events only), as before the flight recorder: driver-
                # side SUBMITTED/WORKER_ASSIGNED halves ride a separate
                # flush channel and would race terminal rows backwards;
                # pre-execution states live in the lifecycle ring.
                continue
            cur = self._direct_task_rows.get(ev["task_id"])
            if (
                cur is not None
                and cur["state"] in ("FINISHED", "FAILED")
                and state == "RUNNING"
            ):
                continue  # late RUNNING flush must not regress a terminal row
            self._direct_task_rows[ev["task_id"]] = {
                "task_id": ev["task_id"],
                "name": ev.get("name", ""),
                "state": state,
                "type": ev.get("type", "NORMAL_TASK"),
                "node_id": node_hex,
            }
            self._direct_task_rows.move_to_end(ev["task_id"])
        while len(self._direct_task_rows) > 10000:
            self._direct_task_rows.popitem(last=False)
        return True

    async def rpc_get_actor_by_name(self, peer: rpc.Peer, name: str):
        actor_id = self.named_actors.get(name)
        if actor_id is None:
            return None
        actor = self.actors[actor_id]
        return {
            "actor_id": actor_id,
            "creation_spec": actor.creation_spec,
        }

    async def rpc_cancel_by_object(self, peer: rpc.Peer, oid: ObjectID, force: bool):
        orec = self.objects.get(oid)
        if orec is None or orec.creating_task is None:
            return False
        return await self.rpc_cancel_task(peer, orec.creating_task, force)

    async def rpc_cancel_task(self, peer: rpc.Peer, task_id: TaskID, force: bool):
        rec = self.tasks.get(task_id)
        if rec is None:
            return False
        if rec.state == "PENDING":
            rec.state = "FAILED"
            rec.retries_left = 0
            self.pending_tasks = [t for t in self.pending_tasks if t != task_id]
            self.lifecycle.record(
                *self._lc_key(rec.spec), "FAILED", reason="cancelled"
            )
            self._fail_task_objects(rec.spec, TaskCancelledError(task_id.hex()))
            self._unindex_deps(rec.spec)
            return True
        if rec.state in ("DISPATCHED", "RUNNING") and rec.worker_id:
            worker = self.workers.get(rec.worker_id)
            if worker is not None:
                rec.retries_left = 0
                if force:
                    await worker.peer.notify("exit")
                else:
                    await worker.peer.notify("cancel", task_id)
            return True
        return False

    # =================================================================
    # KV store (reference: gcs/gcs_server/gcs_kv_manager.cc)
    # =================================================================
    async def rpc_kv_put(self, peer, ns: str, key: bytes, value: bytes, overwrite: bool = True):
        table = self.kv.setdefault(ns, {})
        if not overwrite and key in table:
            return False
        table[key] = value
        self.journal.kv_put(ns, key, value)
        return True

    async def rpc_kv_get(self, peer, ns: str, key: bytes):
        return self.kv.get(ns, {}).get(key)

    async def rpc_kv_del(self, peer, ns: str, key: bytes):
        existed = self.kv.get(ns, {}).pop(key, None) is not None
        if existed:
            self.journal.kv_del(ns, key)
        return existed

    async def rpc_kv_keys(self, peer, ns: str, prefix: bytes):
        return [k for k in self.kv.get(ns, {}) if k.startswith(prefix)]

    # =================================================================
    # Placement groups
    # =================================================================
    async def rpc_pg_create(self, peer, bundles: List[Dict[str, float]], strategy: str, name: str):
        pg_id = PlacementGroupID.from_random()
        rs = [ResourceSet.from_dict(b) for b in bundles]
        self.pg_manager.create(pg_id, rs, strategy, name)
        self.journal.pg_create(pg_id.hex(), bundles, strategy, name)
        self._schedule_pump()
        return pg_id

    async def rpc_pg_wait_ready(self, peer, pg_id: PlacementGroupID, timeout: Optional[float]):
        deadline = None if timeout is None else time.monotonic() + timeout
        while not self.pg_manager.is_ready(pg_id):
            if pg_id not in self.pg_manager.groups:
                raise ValueError(f"placement group {pg_id.hex()} not found")
            if deadline is not None and time.monotonic() > deadline:
                return False
            self.pg_manager.retry_pending()
            await asyncio.sleep(0.02)
        return True

    async def rpc_pg_shrink(self, peer, pg_id: PlacementGroupID,
                            indices: List[int]):
        ok = self.pg_manager.shrink(pg_id, indices)
        if ok:
            # Journaled: a restarted controller must not resurrect the
            # retired bundles from the pg_create record.
            self.journal.pg_shrink(pg_id.hex(), indices)
        self._schedule_pump()
        return ok

    async def rpc_pg_remove(self, peer, pg_id: PlacementGroupID):
        self.pg_manager.remove(pg_id)
        self.journal.pg_remove(pg_id.hex())
        self._schedule_pump()
        return True

    async def rpc_pg_table(self, peer):
        return self.pg_manager.table()

    async def rpc_pg_bundle_nodes(self, peer, pg_id: PlacementGroupID):
        rec = self.pg_manager.groups.get(pg_id)
        if rec is None:
            return None
        return [n.hex() if n else None for n in rec.bundle_nodes]

    # =================================================================
    # Introspection / state API (reference: python/ray/util/state/api.py)
    # =================================================================
    async def rpc_cluster_resources(self, peer):
        total = ResourceSet()
        for n in self.cluster.nodes.values():
            total = total + n.total
        return total.to_dict()

    async def rpc_available_resources(self, peer):
        total = ResourceSet()
        for n in self.cluster.nodes.values():
            total = total + n.available
        return total.to_dict()

    def _node_row(self, nid: NodeID, node: NodeRecord, devstate: Dict[str, dict]) -> dict:
        res = self.cluster.nodes.get(nid)
        devices = []
        for payload in devstate.values():
            if (payload.get("node_id") or "") == nid.hex():
                pid = payload.get("pid")
                devices.extend({**d, "pid": pid} for d in payload.get("devices", ()))
        return {
            "node_id": nid.hex(),
            "state": node.state,
            "is_head": node.peer is None,
            "num_workers": len(node.workers),
            "agent_pid": node.agent_pid,
            "hostname": node.hostname,
            "provider_instance_id": node.provider_instance_id,
            "resources": res.to_dict() if res else {},
            "telemetry": node.telemetry,
            "devices": devices,
        }

    async def rpc_list_nodes(self, peer):
        devstate = self._live_device_state()
        return [
            self._node_row(nid, node, devstate)
            for nid, node in self.nodes.items()
        ]

    @staticmethod
    def _worker_row(w: WorkerRecord, hostname: str) -> dict:
        return {
            "worker_id": w.worker_id.hex(),
            "node_id": w.node_id.hex(),
            "state": w.state,
            "pid": w.pid,
            "hostname": hostname,
            "actor_id": w.actor_id.hex() if w.actor_id else None,
        }

    def _hostname_of(self, node_id: NodeID) -> str:
        node = self.nodes.get(node_id)
        return node.hostname if node is not None else "localhost"

    async def rpc_list_workers(self, peer):
        return [
            self._worker_row(w, self._hostname_of(w.node_id))
            for w in self.workers.values()
        ]

    # -- targeted gets (reference: the state API's get_* endpoints; a
    # point lookup must not pull a 100k-row list_* dump over the wire) --
    async def rpc_get_node(self, peer, node_id: str):
        try:
            nid = NodeID.from_hex(node_id)
        except (ValueError, TypeError):
            return None
        node = self.nodes.get(nid)
        if node is None:
            return None
        return self._node_row(nid, node, self._live_device_state())

    async def rpc_get_worker(self, peer, worker_id: str):
        try:
            wid = WorkerID.from_hex(worker_id)
        except (ValueError, TypeError):
            return None
        w = self.workers.get(wid)
        if w is None:
            return None
        return self._worker_row(w, self._hostname_of(w.node_id))

    async def rpc_get_task(self, peer, task_id: str):
        try:
            tid = TaskID.from_hex(task_id)
        except (ValueError, TypeError):
            return None
        rec = self.tasks.get(tid)
        if rec is not None:
            return {
                "task_id": tid.hex(),
                "name": rec.spec.name,
                "state": rec.state,
                "type": rec.spec.task_type.name,
                "node_id": rec.node_id.hex() if rec.node_id else None,
            }
        # direct-push tasks live only in the event-derived rows
        return self._direct_task_rows.get(task_id)

    async def rpc_get_actor(self, peer, actor_id: str):
        try:
            aid = ActorID.from_hex(actor_id)
        except (ValueError, TypeError):
            return None
        a = self.actors.get(aid)
        if a is None:
            return None
        return {
            "actor_id": a.actor_id.hex(),
            "state": a.state,
            "name": a.name,
            "num_restarts": a.num_restarts,
            "node_id": a.node_id.hex() if a.node_id else None,
            "death_reason": a.death_reason,
        }

    async def rpc_list_tasks(self, peer, limit: int = 1000):
        import collections as _c

        out = []
        seen = set()
        # deque(maxlen) keeps peak memory O(limit) even at 1M+ task
        # records — the status RPC must not materialize the full table.
        for tid, rec in _c.deque(self.tasks.items(), maxlen=limit):
            seen.add(tid.hex())
            out.append(
                {
                    "task_id": tid.hex(),
                    "name": rec.spec.name,
                    "state": rec.state,
                    "type": rec.spec.task_type.name,
                    "node_id": rec.node_id.hex() if rec.node_id else None,
                }
            )
        # direct-push tasks (event-derived rows; no TaskRecord exists)
        for tid_hex, row in _c.deque(self._direct_task_rows.items(), maxlen=limit):
            if tid_hex not in seen:
                out.append(row)
        return out[-limit:]

    async def rpc_summarize_tasks(self, peer, limit: int = 1000):
        """O(limit)-payload task rollup (reference: the state API's
        summarize_tasks backed by GcsTaskManager counters): counts by
        (name, state) capped to the ``limit`` busiest names, plus
        UNCAPPED totals by state — at 40k+ tasks the status RPC must not
        serialize the table."""
        by_name_state: Dict[Tuple[str, str], int] = {}
        by_state: Dict[str, int] = {}
        by_reason: Dict[str, int] = {}
        total = 0
        for rec in self.tasks.values():
            key = (rec.spec.name, rec.state)
            by_name_state[key] = by_name_state.get(key, 0) + 1
            by_state[rec.state] = by_state.get(rec.state, 0) + 1
            if rec.state == "PENDING" and rec.pending_reason:
                by_reason[rec.pending_reason] = (
                    by_reason.get(rec.pending_reason, 0) + 1
                )
            total += 1
        for row in self._direct_task_rows.values():
            key = (row.get("name", ""), row.get("state", ""))
            by_name_state[key] = by_name_state.get(key, 0) + 1
            by_state[key[1]] = by_state.get(key[1], 0) + 1
            total += 1
        # Cap to the busiest `limit` names (name count is user-bounded in
        # practice, but one misbehaving generator of unique names must
        # not blow up the reply).
        per_name: Dict[str, int] = {}
        for (name, _state), n in by_name_state.items():
            per_name[name] = per_name.get(name, 0) + n
        keep = set(sorted(per_name, key=per_name.get, reverse=True)[: max(0, limit)])
        names: Dict[str, Dict[str, int]] = {}
        for (name, state), n in sorted(by_name_state.items()):
            if name in keep:
                names.setdefault(name, {})[state] = n
        return {
            "tasks": names,
            "counts_by_state": by_state,
            "pending_reasons": by_reason,
            "total": total,
            "truncated": len(per_name) > len(keep),
        }

    def _control_plane_summary(self) -> dict:
        """Round-17 control-plane rollup for ``ray-tpu state``: batch-size
        histograms (how well batching amortizes the lease/push RPCs) and
        the scheduler's fast-path vs full-scan split (how often placement
        was a dict lookup + heap peek vs an O(nodes) walk)."""

        def counter(name: str) -> Dict[str, float]:
            e = self.metrics.get(name)
            if not e:
                return {}
            out: Dict[str, float] = {}
            for tags, v in e["series"].items():
                label = ",".join(f"{k}={val}" for k, val in tags) or "(all)"
                out[label] = out.get(label, 0) + v
            return out

        def hist(name: str):
            e = self.metrics.get(name)
            if not e:
                return None
            merged = bounds = None
            for _tags, payload in e["series"].items():
                st = payload["state"]
                bounds = payload.get("boundaries") or bounds
                merged = (
                    list(st) if merged is None
                    else [a + b for a, b in zip(merged, st)]
                )
            if merged is None or not bounds:
                return None
            count = int(merged[-1])
            total = merged[-2]
            def _lbl(b):
                return int(b) if float(b).is_integer() else b

            buckets = {}
            for i, b in enumerate(bounds):
                buckets[f"<={_lbl(b)}"] = merged[i]
            buckets[f">{_lbl(bounds[-1])}"] = merged[len(bounds)]
            return {
                "count": count,
                "sum": total,
                "avg": round(total / count, 2) if count else 0.0,
                "buckets": buckets,
            }

        return {
            "scheduler_fast_path_total": counter("scheduler_fast_path_total"),
            "scheduler_full_scan_total": sum(
                counter("scheduler_full_scan_total").values()
            ),
            "lease_batch_size": hist("lease_batch_size"),
            "task_push_batch_size": hist("task_push_batch_size"),
            "pubsub_channels": self.bus.channels(),
            "resource_deltas_published": sum(self._resource_seq.values()),
        }

    async def rpc_summarize_lifecycle(self, peer):
        """Flight-recorder rollup: per-(kind, state) transition counts +
        dwell p50/p95/p99, why-pending attribution counters, live
        pending attribution (see core/lifecycle.py), and the round-17
        control-plane section (batch sizes, scheduler fast-path split)."""
        from ray_tpu.util import metrics as _metrics

        self._drain_spawn_events()
        snap = self.lifecycle.snapshot()
        # Fold any counters/histograms still sitting in this process's
        # metric registry so the summary reflects work up to now, not up
        # to the last telemetry sweep.
        self.scheduler.drain_counters()
        records = _metrics.drain_records()
        if records:
            await self.rpc_metrics_report(None, records)
        snap["control_plane"] = self._control_plane_summary()
        return snap

    async def rpc_list_lifecycle_events(self, peer, limit: int = 10000):
        self._drain_spawn_events()
        return self.lifecycle.tail(limit)

    async def rpc_summarize_health(self, peer, limit: int = 50):
        """Self-healing plane summary: registered actuators, recent
        actions with outcomes, per-trigger signal counts, and live
        avoids (quarantined / throttled nodes)."""
        return self.health.snapshot(limit=limit)

    async def rpc_list_actors(self, peer):
        return [
            {
                "actor_id": a.actor_id.hex(),
                "state": a.state,
                "name": a.name,
                "num_restarts": a.num_restarts,
                "node_id": a.node_id.hex() if a.node_id else None,
                "death_reason": a.death_reason,
            }
            for a in self.actors.values()
        ]

    async def rpc_list_objects(self, peer, limit: int = 1000):
        import collections as _c

        out = []
        for oid, rec in _c.deque(self.objects.items(), maxlen=limit):
            out.append(
                {
                    "object_id": oid.hex(),
                    "state": rec.state,
                    "size": rec.size,
                    "is_error": rec.is_error,
                    "locations": [n.hex() for n in rec.locations],
                    "callsite": rec.callsite,
                    "creator": rec.creator,
                    "holders": len(rec.holders),
                }
            )
        return out

    async def rpc_list_events(self, peer, limit: int = 10000):
        return self.events[-limit:]

    # =================================================================
    # App metrics (reference: metrics agent, _private/metrics_agent.py:119;
    # workers flush deltas, the controller aggregates)
    # =================================================================
    async def rpc_metrics_report(self, peer, records: list):
        for name, mtype, desc, tags, payload in records:
            entry = self.metrics.setdefault(
                name, {"type": mtype, "description": desc, "series": {}}
            )
            series = entry["series"]
            if mtype == "counter":
                series[tags] = series.get(tags, 0.0) + payload
            elif mtype == "gauge":
                series[tags] = payload
            elif mtype == "histogram":
                cur = series.get(tags)
                if cur is None:
                    series[tags] = payload
                else:
                    cur["state"] = [a + b for a, b in zip(cur["state"], payload["state"])]

    async def rpc_metrics_snapshot(self, peer):
        snap = {
            name: {
                "type": e["type"],
                "description": e["description"],
                "series": [(list(k), v) for k, v in e["series"].items()],
            }
            for name, e in self.metrics.items()
        }
        # Derived cross-rank straggler gauge: max-min of the ranks' last
        # op latency per collective key. Computed at snapshot time (the
        # controller is the only place all ranks' series meet), so
        # Prometheus/Grafana see it like any reported gauge.
        skew = self._collective_skew()
        if skew:
            snap["collective_skew_ms"] = {
                "type": "gauge",
                "description": "Cross-rank skew (max-min last op latency) per collective",
                "series": [
                    ([["group", r["group"]], ["op", r["op"]]], r["skew_ms"])
                    for r in skew
                ],
            }
        return snap

    async def rpc_serve_report(self, peer, key: str, snapshot: Optional[dict]):
        """An LLM engine's periodic flight-recorder snapshot (reference
        shape: serve replicas pushing autoscaling/queue metrics to the
        serve controller). Keyed deployment/replica/engine; stale entries
        (dead replicas) are pruned on the next report. ``snapshot=None``
        is an idle heartbeat: nothing changed engine-side, just keep the
        stored snapshot alive."""
        if snapshot is None:
            cur = self.serve_state.get(key)
            if cur is not None:
                cur["ts"] = time.time()
            return
        # Stamp arrival with THIS clock: staleness pruning must not trust
        # the engine host's wall time (a skewed worker node would have
        # its live snapshots pruned as stale on arrival).
        snapshot["ts"] = time.time()
        self.serve_state[key] = snapshot
        cutoff = time.time() - 120.0
        for k in [k for k, v in self.serve_state.items()
                  if v.get("ts", 0) < cutoff]:
            del self.serve_state[k]

    async def rpc_serve_state(self, peer):
        # Filter on read too: after the last engine stops reporting
        # (deployment deleted, replica dead) nothing triggers the
        # report-side prune, and a dead engine's occupancy must not be
        # served as live state forever.
        cutoff = time.time() - 120.0
        return {k: v for k, v in self.serve_state.items()
                if v.get("ts", 0) >= cutoff}

    # =================================================================
    # Node/device telemetry (reference: raylet resource-usage heartbeats
    # + the dashboard reporter agent's host/GPU stats)
    # =================================================================
    async def rpc_node_telemetry(self, peer, node_id: NodeID, sample: dict):
        node = self.nodes.get(node_id)
        if node is None:
            return
        # Controller clock, same reason as rpc_serve_report: staleness
        # checks must not trust a skewed worker host's wall time.
        sample["ts"] = time.time()
        node.telemetry = sample

    async def rpc_device_telemetry(self, peer, key: str, payload: dict):
        """A worker/driver process's per-device HBM sample + compile
        snapshot. Keyed node/proc; dead processes stop reporting and age
        out (pruned on the next report and on read)."""
        payload["ts"] = time.time()
        self.device_state[key] = payload
        cutoff = time.time() - 60.0
        for k in [k for k, v in self.device_state.items()
                  if v.get("ts", 0) < cutoff]:
            del self.device_state[k]

    def _live_device_state(self) -> Dict[str, dict]:
        cutoff = time.time() - 60.0
        return {k: v for k, v in self.device_state.items()
                if v.get("ts", 0) >= cutoff}

    async def rpc_collective_skew(self, peer):
        return self._collective_skew()

    async def rpc_compile_state(self, peer):
        """Per-process compile-tracker snapshots (from device telemetry):
        {node_hex/proc: compile snapshot}."""
        return {
            k: v.get("compile", {})
            for k, v in self._live_device_state().items()
            if v.get("compile")
        }

    async def rpc_summarize_resources(self, peer):
        """Cluster resource rollup (reference: `ray status` /
        summarize_* in util/state/api.py): per-node host CPU/mem +
        object-store occupancy from the telemetry heartbeats, per-device
        HBM used/limit and compile activity from worker device reports,
        plus cluster-wide totals."""
        now = time.time()
        devstate = self._live_device_state()
        by_node: Dict[str, list] = {}
        for key, payload in devstate.items():
            node_hex = payload.get("node_id") or key.split("/")[0]
            by_node.setdefault(node_hex, []).append(payload)
        nodes_out = {}
        totals = {
            "mem_used_bytes": 0, "mem_total_bytes": 0,
            "hbm_used_bytes": 0, "hbm_limit_bytes": 0, "hbm_peak_bytes": 0,
            "object_store_used": 0, "object_store_capacity": 0,
            "num_devices": 0, "compiles": 0, "compile_seconds": 0.0,
            "active_storms": [],
        }
        for nid, node in self.nodes.items():
            res = self.cluster.nodes.get(nid)
            tel = node.telemetry or {}
            host = tel.get("host", {})
            store = tel.get("object_store", {})
            row = {
                "hostname": node.hostname,
                "is_head": node.peer is None,
                "state": node.state,
                "num_workers": len(node.workers),
                "host": host,
                "object_store": {
                    "used": store.get("used", 0),
                    "capacity": store.get("capacity", 0),
                    "num_objects": store.get("num_objects", 0),
                    "num_spilled": store.get("num_spilled", 0),
                    # memory-census columns: spill-dir disk usage, store-
                    # side pins, and the deferred-delete queue depth
                    "spilled_bytes": store.get("spilled_bytes", 0),
                    "pinned_slots": store.get("pinned_slots", 0),
                    "pinned_bytes": store.get("pinned_bytes", 0),
                    "deferred_deletes": store.get("deferred_deletes", 0),
                    "spill_ops": store.get("spill_ops", 0),
                },
                "resources": {
                    "total": res.total.to_dict() if res else {},
                    "available": res.available.to_dict() if res else {},
                },
                "telemetry_age_s": round(now - tel["ts"], 2) if "ts" in tel else None,
                "devices": [],
                "compile": {
                    "compiles": 0, "compile_seconds": 0.0,
                    "compiles_per_min": 0.0,
                    "storms_total": 0, "active_storms": [],
                },
            }
            for payload in by_node.get(nid.hex(), ()):
                pid = payload.get("pid")
                for d in payload.get("devices", ()):
                    row["devices"].append({**d, "pid": pid})
                comp = payload.get("compile") or {}
                row["compile"]["compiles"] += comp.get("compiles", 0)
                row["compile"]["compile_seconds"] += comp.get("compile_seconds", 0.0)
                row["compile"]["storms_total"] += comp.get("storms_total", 0)
                # compiles in the tracker's rolling window, normalized to
                # per-minute — the live "compiles/min" column of `status`
                window = comp.get("storm_window_s") or 60.0
                in_window = sum(
                    f.get("window_count", 0)
                    for f in (comp.get("functions") or {}).values()
                )
                row["compile"]["compiles_per_min"] = round(
                    row["compile"].get("compiles_per_min", 0.0)
                    + in_window * 60.0 / window, 1,
                )
                for name in (comp.get("active_storms") or {}):
                    row["compile"]["active_storms"].append(name)
            row["devices"].sort(key=lambda d: (d.get("pid") or 0, d["id"]))
            totals["mem_used_bytes"] += host.get("mem_used_bytes", 0)
            totals["mem_total_bytes"] += host.get("mem_total_bytes", 0)
            totals["object_store_used"] += row["object_store"]["used"]
            totals["object_store_capacity"] += row["object_store"]["capacity"]
            totals["hbm_used_bytes"] += sum(d["bytes_in_use"] for d in row["devices"])
            totals["hbm_limit_bytes"] += sum(d["bytes_limit"] for d in row["devices"])
            totals["hbm_peak_bytes"] += sum(
                d["peak_bytes_in_use"] for d in row["devices"]
            )
            totals["num_devices"] += len(row["devices"])
            totals["compiles"] += row["compile"]["compiles"]
            totals["compile_seconds"] += round(row["compile"]["compile_seconds"], 4)
            totals["active_storms"].extend(row["compile"]["active_storms"])
            nodes_out[nid.hex()] = row
        totals["collective_skew_ms"] = self._collective_skew()
        return {"nodes": nodes_out, "totals": totals}

    def _collective_skew(self) -> List[dict]:
        """Cross-rank skew (max - min of the last per-rank op latency)
        per collective key, derived from the ``collective_last_op_ms``
        gauge series every rank reports — the straggler view per
        ring/mesh. Sorted worst-first."""
        entry = self.metrics.get("collective_last_op_ms")
        if not entry:
            return []
        per_key: Dict[Tuple[str, str], Dict[str, float]] = {}
        for tags, value in entry["series"].items():
            t = dict(tags)
            key = (t.get("group", "?"), t.get("op", "?"))
            per_key.setdefault(key, {})[t.get("rank", "?")] = value
        out = []
        for (group, op), ranks in per_key.items():
            if len(ranks) < 2:
                continue
            mx, mn = max(ranks.values()), min(ranks.values())
            out.append(
                {
                    "group": group, "op": op,
                    "skew_ms": round(mx - mn, 3),
                    "max_ms": round(mx, 3), "min_ms": round(mn, 3),
                    "slowest_rank": max(ranks, key=ranks.get),
                    "ranks": len(ranks),
                }
            )
        out.sort(key=lambda r: -r["skew_ms"])
        return out

    # -- resource-view pubsub (round 17: push-on-change replaces
    # per-sweep polling; core/pubsub.py documents the delivery model) --
    def _resource_row(self, nid: NodeID, avoids) -> dict:
        res = self.cluster.nodes[nid]
        av = avoids.get(nid)
        return {
            "available": res.available.to_dict(),
            "total": res.total.to_dict(),
            "draining": res.draining,
            "avoid": ("hard" if av[1] else "soft") if av else None,
        }

    def _resource_snapshot(self) -> dict:
        avoids = self.cluster.avoids()
        nodes = {}
        for nid in self.cluster.nodes:
            row = self._resource_row(nid, avoids)
            row["seq"] = self._resource_seq.setdefault(nid, 0)
            nodes[nid.hex()] = row
        return {"snapshot": True, "nodes": nodes}

    def _avoid_snapshot(self) -> dict:
        avoids = self.cluster.avoids()
        return {
            "snapshot": True,
            "avoid": {
                nid.hex(): {"hard": hard, "deadline": dl}
                for nid, (dl, hard) in avoids.items()
            },
            "draining": [
                nid.hex() for nid, res in self.cluster.nodes.items() if res.draining
            ],
        }

    async def _broadcast_resource_deltas(self):
        """Drain the scheduler's dirty-node set into per-node seq'd
        deltas on RESOURCES_CHANNEL, coalesced to at most one publish
        per resource_broadcast_min_interval_ms; a full snapshot rides
        the same channel every resource_reconcile_interval_s so mirrors
        converge past any dropped/reordered deltas. Avoid/drain state
        pushes to AVOID_CHANNEL on the reconcile cadence (it also rides
        every resource delta, so agents gating on the resource mirror
        see it immediately)."""
        from ray_tpu.core import pubsub as _ps

        dirty = self.cluster.dirty_nodes
        if not (self.bus.has(_ps.RESOURCES_CHANNEL) or self.bus.has(_ps.AVOID_CHANNEL)):
            dirty.clear()  # nobody listening — don't let the set grow
            return
        now = time.monotonic()
        min_iv = self.config.resource_broadcast_min_interval_ms / 1000.0
        if dirty and now - self._last_resource_broadcast >= min_iv:
            self._last_resource_broadcast = now
            avoids = self.cluster.avoids()
            batch = list(dirty)
            dirty.clear()
            for nid in batch:
                seq = self._resource_seq.get(nid, 0) + 1
                self._resource_seq[nid] = seq
                if nid not in self.cluster.nodes:
                    # Seq floor is kept: a re-registered node continues
                    # the sequence so mirrors never mistake its first
                    # post-rejoin delta for a stale pre-removal one.
                    msg = {"node": nid.hex(), "seq": seq, "removed": True}
                else:
                    msg = self._resource_row(nid, avoids)
                    msg["node"] = nid.hex()
                    msg["seq"] = seq
                await self.bus.publish(_ps.RESOURCES_CHANNEL, msg)
        if now - self._last_resource_reconcile >= self.config.resource_reconcile_interval_s:
            self._last_resource_reconcile = now
            if self.bus.has(_ps.RESOURCES_CHANNEL):
                await self.bus.publish(_ps.RESOURCES_CHANNEL, self._resource_snapshot())
            if self.bus.has(_ps.AVOID_CHANNEL):
                await self.bus.publish(_ps.AVOID_CHANNEL, self._avoid_snapshot())

    async def _head_telemetry_loop(self):
        """The controller doubles as the head node's agent — sample the
        head host + its store on the same cadence the agents report."""
        interval = self.config.node_telemetry_interval_ms / 1000.0
        if interval <= 0:
            return
        from ray_tpu.core import node_telemetry
        from ray_tpu.core.memory_monitor import HostCpuSampler
        from ray_tpu.util import metrics as _metrics

        cpu = HostCpuSampler()
        cpu.sample()  # prime the delta
        while not self._shutdown.is_set():
            await asyncio.sleep(interval)
            self._drain_spawn_events()
            # Recorder metrics are throttle-flushed from record(); a
            # quiet cluster still syncs its last batch here.
            self.lifecycle.flush_metrics()
            node = self.nodes.get(self.head_node_id)
            if node is None:
                continue
            sample = node_telemetry.build_node_sample(cpu, self.head_store)
            sample["ts"] = time.time()
            node.telemetry = sample
            # Memory census sweep: Grafana gauges, the open-ref growth
            # (leak) detector, and the store-pressure incident trigger.
            try:
                self._memory_census_tick()
            except Exception:  # noqa: BLE001 — census must not kill telemetry
                logger.exception("memory census tick failed")
            # Log plane sweep: the controller's own captured ERROR
            # records feed the index in-process (it has no ship loop),
            # then the error-rate-spike detector runs over the sweep.
            try:
                from ray_tpu.core import log_plane as _lp

                for rec in _lp.drain_ship():
                    self._error_index.ingest(rec)
                self._error_spike_check()
            except Exception:  # noqa: BLE001 — log plane must not kill telemetry
                logger.exception("log plane sweep failed")
            # Health plane tick: expire avoids, refresh gauges, and scan
            # shipped compile snapshots for new recompile storms.
            try:
                self.health.tick()
            except Exception:  # noqa: BLE001 — health must not kill telemetry
                logger.exception("health tick failed")
            # Resource-view pubsub: coalesced dirty-node deltas plus the
            # periodic reconcile snapshot (round 17).
            try:
                await self._broadcast_resource_deltas()
            except Exception:  # noqa: BLE001 — pubsub must not kill telemetry
                logger.exception("resource delta broadcast failed")
            # Scheduler fast-path/full-scan counters accumulate as plain
            # ints on the decision path (a metrics inc per placement
            # would cost more than the fast path saves) — flush here.
            self.scheduler.drain_counters()
            # Metrics recorded IN the controller process (head-side
            # object transfers, chunk serving) have no CoreWorker flusher
            # — fold them straight into the aggregation.
            records = _metrics.drain_records()
            if records:
                await self.rpc_metrics_report(None, records)

    async def rpc_resource_demand(self, peer):
        """Unmet demand for the autoscaler: resource sets of tasks that are
        waiting for placement plus bundles of pending placement groups
        (reference: SchedulerResourceReporter feeding the autoscaler via
        GcsAutoscalerStateManager)."""
        import itertools

        demand = []
        # pending work lives in the intake list, the per-class FIFOs, and
        # the dep-parked set — all of it is unmet demand
        pending_views = itertools.chain(
            self.pending_tasks,
            *self._class_queues.values(),
            self._dep_parked,
        )
        def _with_labels(item: dict, strategy) -> dict:
            # label-constrained demand carries its hard expressions so the
            # autoscaler can pick a node TYPE whose labels satisfy them
            hard = (strategy.node_labels or {}).get("hard") if strategy else None
            if hard:
                item["_labels"] = hard
            return item

        for tid in pending_views:
            rec = self.tasks.get(tid)
            if rec is not None and rec.state == "PENDING":
                demand.append(_with_labels(
                    rec.spec.resources.to_dict(), rec.spec.scheduling_strategy
                ))
        for req in self._lease_reqs:
            # parked worker-lease requests are unmet task demand too
            demand.append(_with_labels(req.demand.to_dict(), req.strategy))
        pg_demand = []
        for pg in self.pg_manager.pending_records():
            pg_demand.append(
                {"strategy": pg.strategy, "bundles": [b.to_dict() for b in pg.bundles]}
            )
        return {"tasks": demand, "placement_groups": pg_demand}

    # =================================================================
    # Streaming generators
    # =================================================================
    async def rpc_stream_item(self, peer, task_id: TaskID, index: int):
        rec = self.tasks.get(task_id)
        if rec is None:
            return False
        rec.stream_count = max(rec.stream_count, index + 1)
        for fut in rec.stream_waiters:
            if not fut.done():
                fut.set_result(True)
        rec.stream_waiters.clear()
        return True

    async def rpc_stream_next(self, peer, task_id: TaskID, index: int):
        """Block until item `index` exists; "item" when available, None at
        end-of-stream."""
        while True:
            rec = self.tasks.get(task_id)
            if rec is None:
                return None
            if index < rec.stream_count:
                return "item"
            if rec.stream_done or rec.state in ("FAILED", "FINISHED"):
                return "item" if index < rec.stream_count else None
            fut = asyncio.get_running_loop().create_future()
            rec.stream_waiters.append(fut)
            await fut

    async def rpc_drain_node(self, peer, node_id: NodeID, timeout_s: float = 300.0):
        """Graceful drain (reference: NodeManager drain / rpc::DrainNode +
        `ray drain-node`): stop placing work on the node, let running work
        finish (up to ``timeout_s``), then retire it. Actors with
        max_restarts left restart elsewhere through the normal death path.
        Returns immediately; drain progresses in the background."""
        node = self.nodes.get(node_id)
        if node is None or node.state != "ALIVE":
            raise ValueError(f"node {node_id.hex()} not alive")
        if node.peer is None:
            raise ValueError("cannot drain the head node")
        node.state = "DRAINING"
        self.cluster.set_draining(node_id, True)
        self.lifecycle.record("node", node_id.hex(), "DRAINING")
        await self._publish_death("node", node_id.hex(), "DRAINING")

        # Preempt restartable actors right away (reference: preemption
        # flagging, actor_task_submitter.h:67): their death path restarts
        # them on schedulable nodes and max_task_retries resubmits
        # in-flight methods. Non-restartable actors ride out the drain.
        for wid in list(node.workers):
            w = self.workers.get(wid)
            if w is not None and w.state == "ACTOR" and w.actor_id is not None:
                actor = self.actors.get(w.actor_id)
                if actor is not None and actor.restarts_left > 0:
                    try:
                        await w.peer.notify("exit")
                    except Exception:
                        pass

        async def finish_drain():
            # Wait for in-flight plain-task work to finish (actor-method
            # streams can arrive indefinitely and must not starve the
            # drain; their actors were preempted above or accept the cut).
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                busy = [
                    w
                    for wid in node.workers
                    if (w := self.workers.get(wid)) is not None
                    and w.state == "LEASED"
                    and w.running
                ]
                if not busy:
                    break
                await asyncio.sleep(0.2)
            rec = self.nodes.get(node_id)
            if rec is not None and rec.state == "DRAINING":
                try:
                    await rec.peer.notify("exit")
                except Exception:
                    pass

        # Keep a strong ref: the loop holds tasks weakly (same pitfall the
        # memory-monitor task documents below).
        task = asyncio.get_running_loop().create_task(finish_drain())
        self._drain_tasks.add(task)
        task.add_done_callback(self._drain_tasks.discard)
        return True

    async def rpc_ping(self, peer):
        return "pong"

    async def rpc_shutdown_cluster(self, peer):
        self._shutdown.set()
        return True

    # =================================================================
    def _lc_key(self, spec: TaskSpec) -> Tuple[str, str]:
        """Flight-recorder entity for a spec: actor-creation tasks chart
        the ACTOR's chain (SUBMITTED → ... → ALIVE), everything else the
        task's."""
        if spec.task_type == TaskType.ACTOR_CREATION_TASK and spec.actor_id:
            return "actor", spec.actor_id.hex()
        return "task", spec.task_id.hex()

    def _event(self, kind: str, spec: TaskSpec, state: str):
        self.events.append(
            {
                "ts": time.time(),
                "kind": kind,
                "task_id": spec.task_id.hex(),
                "name": spec.name,
                "state": state,
            }
        )
        if len(self.events) > self.config.task_event_buffer_size:
            del self.events[: len(self.events) // 2]
        if kind == "task" and spec.task_type == TaskType.ACTOR_CREATION_TASK:
            # A creation task's chain is charted under the ACTOR entity
            # (_lc_key: SUBMITTED/QUEUED/CREATING → ALIVE closes nothing);
            # a lone task.FINISHED here would inflate task counts with no
            # matching task.SUBMITTED. Legacy self.events keeps the row.
            return
        eid = (
            spec.actor_id.hex()
            if kind == "actor" and spec.actor_id
            else spec.task_id.hex()
        )
        self.lifecycle.record(kind, eid, state, name=spec.name)

    # =================================================================
    def _oom_candidates(self, head_only: bool, node_id: Optional[NodeID] = None):
        """KillCandidates among a node's workers (reference:
        worker_killing_policy candidate assembly)."""
        from ray_tpu.core.memory_monitor import KillCandidate

        candidates = []
        for w in self.workers.values():
            node = self.nodes.get(w.node_id)
            if node is None:
                continue
            if head_only and node.peer is not None:
                continue
            if node_id is not None and w.node_id != node_id:
                continue
            if w.state == "LEASED" and w.running:
                tid = next(iter(w.running))
                rec = self.tasks.get(tid)
                if rec is None:
                    continue
                candidates.append(
                    KillCandidate(
                        worker_id=w.worker_id.hex(),
                        pid=w.pid,
                        is_retriable=rec.retries_left > 0,
                        start_time=rec.submitted_at,
                        owner_id=rec.spec.owner_id.hex() if rec.spec.owner_id else "",
                    )
                )
            elif w.state == "ACTOR" and w.actor_id is not None:
                actor = self.actors.get(w.actor_id)
                if actor is None:
                    continue
                candidates.append(
                    KillCandidate(
                        worker_id=w.worker_id.hex(),
                        pid=w.pid,
                        is_retriable=actor.restarts_left > 0,
                        # Actors rank as oldest: tasks die before actors.
                        start_time=0.0,
                        owner_id=actor.creation_spec.owner_id.hex()
                        if actor.creation_spec.owner_id
                        else "",
                    )
                )
        return candidates

    async def _direct_oom_candidates(self, head_only: bool, node_id: Optional[NodeID] = None):
        """Candidates among DIRECT-pool workers, whose running tasks the
        controller never sees — ask each worker what it's executing
        (rpc_current_task). OOM is rare; a per-incident fan-out beats
        per-task tracking traffic."""
        from ray_tpu.core.memory_monitor import KillCandidate

        targets = []
        for w in self.workers.values():
            node = self.nodes.get(w.node_id)
            if node is None:
                continue
            if head_only and node.peer is not None:
                continue
            if node_id is not None and w.node_id != node_id:
                continue
            if w.state == "DIRECT" or (
                w.state == "LEASED" and not w.running and w.actor_id is None
            ):
                targets.append(w)

        async def ask(w):
            try:
                info = await asyncio.wait_for(w.peer.call("current_task"), 0.5)
            except Exception:  # noqa: BLE001 — dying worker
                return None
            if not info:
                return None
            return KillCandidate(
                worker_id=w.worker_id.hex(),
                pid=w.pid,
                is_retriable=bool(info.get("retriable")),
                start_time=float(info.get("start", time.time())),
                owner_id=info.get("owner", ""),
            )

        results = await asyncio.gather(*(ask(w) for w in targets))
        return [c for c in results if c is not None]

    def _oom_policy(self):
        from ray_tpu.core.memory_monitor import POLICIES

        policy = POLICIES.get(self.config.worker_killing_policy)
        if policy is None:
            logger.error(
                "unknown worker_killing_policy %r; using retriable_fifo",
                self.config.worker_killing_policy,
            )
            policy = POLICIES["retriable_fifo"]
        return policy

    async def rpc_node_over_memory(self, peer: rpc.Peer, node_id: NodeID):
        """A node agent's memory monitor crossed the threshold: pick a
        victim among THAT node's workers (the policies need task/actor
        context only the controller has) and return its pid for the
        agent to SIGKILL locally (reference: each raylet runs its own
        MemoryMonitor; victim choice is worker_killing_policy)."""
        candidates = self._oom_candidates(False, node_id)
        candidates += await self._direct_oom_candidates(False, node_id)
        victim = self._oom_policy()(candidates)
        if victim is None:
            return None
        w = self.workers.get(WorkerID.from_hex(victim.worker_id))
        if w is None:
            return None
        logger.warning(
            "node %s over memory: killing worker %s (pid %s, policy %s)",
            node_id.hex()[:8], victim.worker_id[:8], victim.pid,
            self.config.worker_killing_policy,
        )
        w.oom_marked = True
        # Belt-and-braces: also ask the worker to exit — if the agent's
        # SIGKILL fails (permission, races), the worker still dies and
        # the oom_marked flag stays truthful about the death cause.
        await _notify_quiet(w.peer, "exit", what="OOM kill fallback")
        return victim.pid

    async def _memory_monitor_loop(self):
        """Kill workers when the HEAD host's memory crosses the threshold
        (reference: memory_monitor.h polling + worker_killing_policy
        victim choice). Non-head nodes run the same monitor in their
        agent, reporting through rpc_node_over_memory — on single-host
        simulations the agents' monitors see the same memory, so the
        head-only filter here avoids double-killing."""
        from ray_tpu.core.memory_monitor import MemoryMonitor

        monitor = MemoryMonitor(threshold=self.config.memory_usage_threshold)
        policy = self._oom_policy()
        interval = self.config.memory_monitor_refresh_ms / 1000.0
        while not self._shutdown.is_set():
            await asyncio.sleep(interval)
            if not monitor.should_kill():
                continue
            candidates = self._oom_candidates(head_only=True)
            candidates += await self._direct_oom_candidates(head_only=True)
            victim = policy(candidates)
            if victim is None:
                continue
            wid = WorkerID.from_hex(victim.worker_id)
            w = self.workers.get(wid)
            if w is None:
                continue
            logger.warning(
                "memory monitor killing worker %s (pid %s, policy %s)",
                victim.worker_id[:8],
                victim.pid,
                self.config.worker_killing_policy,
            )
            w.oom_marked = True
            try:
                os.kill(victim.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                await _notify_quiet(w.peer, "exit", what="OOM SIGKILL fallback")

    async def _restore_persisted(self):
        """Re-create journaled PGs and detached actors after a restart
        (reference: GCS restart restores actor/PG tables, then the actor
        manager reschedules; gcs_actor_manager.cc restart path)."""
        for pg_hex, pg in self._restored.pgs.items():
            pg_id = PlacementGroupID.from_hex(pg_hex)
            rs = [ResourceSet.from_dict(b) for b in pg["bundles"]]
            self.pg_manager.create(pg_id, rs, pg["strategy"], pg["name"])
            if pg.get("retired"):
                self.pg_manager.shrink(pg_id, pg["retired"])
        for actor_hex, spec in self._restored.actors.items():
            if spec.dependencies:
                # Arg objects died with the old cluster; without lineage for
                # them the actor cannot be re-created faithfully.
                logger.warning("cannot restore detached actor %s: has object deps", actor_hex)
                self.journal.actor_dead(actor_hex)
                continue
            await self.rpc_create_actor(None, spec, _journal=False)
        if self._restored.pgs or self._restored.actors:
            self._schedule_pump()

    def _broadcast_logs(self, batch):
        """Thread→loop bridge: fan worker-log lines out to drivers
        (reference: log_monitor publish + driver print_to_stdstream)."""
        # Runs on the log-tailer THREAD; ``drivers`` is loop-owned. The
        # emptiness peek here is only an optimization (skip scheduling a
        # coroutine when nobody listens), so take it as an atomic
        # snapshot — the authoritative read happens in send() on the
        # loop. ConcSan flagged the bare read (owner_thread finding).
        if not snapshot(self.drivers) or self._loop is None:
            return

        async def send():
            for peer in list(self.drivers):
                await _notify_quiet(peer, "log_batch", batch, what="driver gone")

        asyncio.run_coroutine_threadsafe(send(), self._loop)

    async def run(self, port: int = 0):
        from ray_tpu.utils.net import bind_host

        # Loopback unless RAY_TPU_NODE_IP opts into multi-host (agents on
        # other hosts must reach the control plane).
        server, self.port = await rpc.serve(self, host=bind_host(), port=port)
        self._loop = asyncio.get_running_loop()
        # The controller's own incident captures (store pressure, lock
        # watchdog) resolve the session via this env hint — the spawned
        # controller process otherwise has no session marker (workers get
        # it from spawn_worker).
        os.environ.setdefault("RAY_TPU_SESSION_DIR", self.session_dir)
        # Profiling: continuous incident sampler (off unless configured)
        # + flight-recorder tail so controller incident bundles carry the
        # scheduler context alongside stacks/samples.
        from ray_tpu.util import profiling

        profiling.ensure_continuous(
            hz=self.config.profiling_continuous_hz,
            ring_s=self.config.profiling_ring_s,
        )
        profiling.set_recorder_tail_provider(lambda: self.lifecycle.tail(500))
        if self.config.log_structured:
            # Controller leg of the log plane: scheduler warnings/errors
            # become structured records (handler-only; streams already
            # land in controller.log) and feed the error index via the
            # telemetry sweep.
            from ray_tpu.core import log_plane

            log_plane.install(
                self.session_dir,
                node_id=self.head_node_id.hex(),
                proc="controller",
                capture_streams=False,
                rotate_bytes=self.config.log_rotate_bytes,
            )
        self._log_tailer = None
        if self.config.log_to_driver:
            from ray_tpu.core.log_monitor import LogTailer

            # One tailer on the session log dir covers every worker that
            # logs into this session (all nodes are host-local processes;
            # a true multi-host deployment runs a tailer per node agent).
            self._log_tailer = LogTailer(
                os.path.join(self.session_dir, "logs"), self._broadcast_logs
            )
            self._log_tailer.start()
        await self._restore_persisted()
        if self.config.memory_monitor_refresh_ms > 0:
            # Keep a strong ref: the loop holds tasks weakly and an
            # unreferenced monitor could be garbage-collected mid-run.
            self._monitor_task = asyncio.get_running_loop().create_task(
                self._memory_monitor_loop()
            )
        if self.config.object_auto_gc:
            self._gc_task = asyncio.get_running_loop().create_task(
                self._gc_sweep_loop()
            )
        if self.config.node_telemetry_interval_ms > 0:
            # Strong ref (loop holds tasks weakly, same as the monitor).
            self._telemetry_task = asyncio.get_running_loop().create_task(
                self._head_telemetry_loop()
            )
        if self.config.dashboard_port >= 0:
            from ray_tpu.core.http_gateway import start_http_gateway

            self.dashboard_port = start_http_gateway(
                self, asyncio.get_running_loop(), self.config.dashboard_port
            )
            with open(os.path.join(self.session_dir, "dashboard_port"), "w") as f:
                f.write(str(self.dashboard_port))
        with open(os.path.join(self.session_dir, "controller_port"), "w") as f:
            f.write(str(self.port))
        if self._head_prestart:
            await self._request_workers(self.nodes[self.head_node_id], self._head_prestart)
        await self._shutdown.wait()
        if self._log_tailer is not None:
            self._log_tailer.stop()
        if self._record_tailer is not None:
            self._record_tailer.stop()
        # Teardown: tell everyone to exit.
        for w in list(self.workers.values()):
            await _notify_quiet(w.peer, "exit", what="cluster teardown")
        for n in self.nodes.values():
            if n.peer is not None:
                await _notify_quiet(n.peer, "exit", what="cluster teardown")
        await asyncio.sleep(0.1)
        server.close()
        self.head_store.destroy()


def _default_store_bytes() -> int:
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    kb = int(line.split()[1])
                    return min(int(kb * 1024 * 0.3), 16 * 1024**3)
    except (OSError, ValueError, IndexError):
        pass  # no /proc/meminfo (macOS) or unparseable — use the default
    return 2 * 1024**3


def main():
    from ray_tpu.util import chaos, lockwatch

    lockwatch.maybe_install()  # RAY_TPU_LOCKWATCH=1: watch locks created from here on
    chaos.install_fault_plan_from_env()  # RAY_TPU_FAULT_PLAN: deterministic chaos
    parser = argparse.ArgumentParser()
    parser.add_argument("--session-dir", required=True)
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--resources", default="{}")
    parser.add_argument("--config", default="{}")
    parser.add_argument("--owned", action="store_true")
    args = parser.parse_args()
    logging.basicConfig(
        level=logging.INFO,
        format="[controller] %(levelname)s %(message)s",
    )
    cfg = Config.from_env().apply_overrides(json.loads(args.config))
    set_config(cfg)
    os.makedirs(args.session_dir, exist_ok=True)
    ctrl = Controller(args.session_dir, json.loads(args.resources), cfg, owned=args.owned)

    loop = asyncio.new_event_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, ctrl._shutdown.set)
    try:
        loop.run_until_complete(ctrl.run(args.port))
    finally:
        loop.close()


if __name__ == "__main__":
    main()
